"""The device submission engine: dynamic micro-batching for the
RS/PoDR2 hot paths.

Every off-chain actor in the reference ecosystem hits the device
through its own tiny synchronous call — OSS gateways encode uploads,
miners prove challenges, TEEs tag and verify — leaving the accelerator
idle between calls and recompiling on every new shape. This engine is
the serving layer between all of them and the ``ErasureCodec`` /
``AuditBackend`` gates (ops/rs.py, ops/audit_backend.py):

- callers ``submit_*`` and get a future back; per-op-class bounded
  queues hold the requests (policy.py: explicit backpressure, class
  priority, deadlines);
- one batcher thread drains a class on a size-or-deadline trigger,
  coalesces coalescible requests (same op, geometry and round
  parameters) into a single device batch, pads the batch to a shape
  bucket (buckets.py: compile-once program cache), launches it, and
  slices results back per request;
- everything observable lands in stats.py (queue depth, batch
  occupancy, pad waste, per-class latency percentiles), exported via
  node/metrics.py and the ``cess_engineStats`` RPC.

Zero-copy handoff: submits accept ``jax.Array`` payloads and keep
them ON DEVICE — coalescing concatenates resident inputs with
``jnp.concatenate``, padding pads with device zeros, and each
request's result slice comes back as a ``jax.Array``. Host (numpy)
submitters keep getting numpy back, even when a batch mixes both. So
``StoragePipeline -> engine -> device`` is one H2D copy total for the
concat-coalesced classes (encode / repair / tag / verify), provided
the payloads live on the backend's device. The stacked classes
(prove / verify_agg) assemble their [R, F, ...] mission batches
host-side — their callers are host agents and their payloads are
KiB-scale proofs, not fragment bytes.

Protocol determinism is the hard constraint: engine-mediated results
are bit-identical to the direct calls. That falls out of two facts —
every coalesced op is row-independent (vmap / per-row GF matrix
apply), and padding adds zero rows (or zero aggregation coefficients,
whose terms are exact modular zeros) that are sliced off afterward.
tests/test_serve.py pins both.

The direct synchronous path remains the default everywhere (the
trait-gate philosophy): an engine is used only where one is explicitly
configured (StoragePipeline(engine=...), MinerAgent(engine=...),
TeeAgent(engine=...), ``node.cli --engine``).

Resilience (opt-in, cess_tpu/resilience): constructed with a
``ResilienceConfig`` the engine additionally
- retries saturated blocking submits with deterministic backoff inside
  the request's ONE deadline budget (retry.py);
- isolates batch failures — a device error against a coalesced batch
  re-runs the members individually once, so a poisoned request cannot
  fail its batch-mates (``cess_resilience_batch_requeues``);
- health-gates each backend: a breaker tripped by the error window
  transparently serves batches on the CPU reference codec/audit
  backend (bit-identical results by construction) and probes its way
  back (health.py);
- exposes it all as ``cess_resilience_*`` gauges beside the
  ``cess_engine_*`` family.
The ``engine.dispatch`` fault site (resilience/faults.py) sits on
every non-degraded device attempt, so seeded chaos plans can drive
all of the above deterministically in tier-1.

SLO + adaptive control (opt-in, ISSUE 6): built with an
``obs.SloBoard`` (``slo=``) every resolved/failed/expired request
feeds the board's burn-rate windows and per-tenant accounting (every
submit takes an optional ``tenant=`` tag, threaded down from the
gateway/miner/TEE agents), and the batcher's drain anchor becomes
WEIGHTED-FAIR across tenants (deficit on served device rows) so one
heavy uploader cannot starve another tenant's traffic inside a class.
With an ``AdaptiveBatchPolicy`` (``adaptive=``) the batching knobs
(max_delay / request / row budgets) are read PER CLASS from the live
latency signal instead of the static policy constants, and with an
``AdmissionController`` (``admission=``; auto-built by
:func:`make_engine` when both are present) sheddable submits are
SLO-gated (``EngineShed``) and a burning protected class latches the
codec breaker open (``HealthMonitor.hold_open``) so bulk load
degrades to the CPU reference while the device serves the protected
class. All three attributes default to None and every hook on the
disabled path is one attribute load + a None check — no SLO or
tenant object is allocated (the NOOP_SPAN contract,
tests/test_slo.py pins it).
"""
from __future__ import annotations

import collections
import contextlib
import dataclasses
import hashlib
import threading
import time
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import flight as _flight
from ..obs import trace
from ..resilience import faults
from ..resilience.retry import Budget
from .buckets import ProgramCache, bucket_rows
from .policy import (CLASSES, AdmissionPolicy, EngineClosed,
                     EngineSaturated, EngineShed, EngineTimeout)
from .stats import EngineStats


class EngineFuture:
    """Result handle for a submitted request (threading-based: the
    engine serves plain synchronous agents, not an event loop)."""

    __slots__ = ("_event", "_value", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._value: Any = None
        self._exc: BaseException | None = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: float | None = None):
        """Block until resolved. Raises the request's failure
        (EngineTimeout on deadline cancellation, the op's error on a
        batch failure) or EngineTimeout if ``timeout`` elapses first."""
        if not self._event.wait(timeout):
            raise EngineTimeout(f"no result within {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._value

    # engine-internal
    def _resolve(self, value) -> None:
        self._value = value
        self._event.set()

    def _reject(self, exc: BaseException) -> None:
        self._exc = exc
        self._event.set()


@dataclasses.dataclass
class _Request:
    cls: str                 # op class (policy.CLASSES)
    key: tuple               # coalescing key: op + geometry + round aux
    rows: int                # device rows this request contributes
    arrays: dict             # normalized numpy payloads
    aux: dict                # shared parameters (idx/nu/present/...)
    enqueue_t: float
    deadline: float | None
    future: EngineFuture
    squeeze: bool = False    # 2-D submit: drop the batch axis on return
    device: bool = False     # jax.Array payload: result stays on device
    # request-scoped trace span (cess_tpu/obs): covers queue-wait ->
    # batch membership -> device dispatch -> resolve; the NOOP
    # singleton when no tracer is armed (every touch is then a no-op)
    span: Any = trace.NOOP_SPAN
    # per-tenant accounting tag (obs/slo.py): None when untagged or
    # when no SLO board is configured — a bare field default, nothing
    # allocated on the disabled path
    tenant: str | None = None


def _round_digest(num_blocks: int, idx, nu) -> bytes:
    """Coalescing identity of a challenge round's derived parameters."""
    h = hashlib.sha256(num_blocks.to_bytes(8, "little"))
    h.update(np.asarray(idx).tobytes())
    h.update(np.asarray(nu).tobytes())
    return h.digest()[:16]


def _norm(arr, dtype):
    """Normalize a payload WITHOUT forcing it off its device: jax
    arrays stay jax (dtype-cast on device when needed), everything
    else becomes a contiguous numpy array."""
    if isinstance(arr, jax.Array):
        return arr if arr.dtype == dtype else arr.astype(dtype)
    return np.ascontiguousarray(np.asarray(arr, dtype=dtype))


def _concat_rows(arrs: list):
    """Coalesce request payloads along axis 0 — ON DEVICE when any
    contributor is device-resident (one H2D per host contributor,
    zero for resident ones), plain numpy otherwise."""
    if any(isinstance(a, jax.Array) for a in arrs):
        if len(arrs) == 1:
            return arrs[0]
        return jnp.concatenate([jnp.asarray(a) for a in arrs], axis=0)
    return np.concatenate(arrs, axis=0)


def _pad_axis0(arr, rows: int):
    if arr.shape[0] == rows:
        return arr
    if isinstance(arr, jax.Array):
        pad = jnp.zeros((rows - arr.shape[0],) + arr.shape[1:], arr.dtype)
        return jnp.concatenate([arr, pad], axis=0)
    pad = np.zeros((rows - arr.shape[0],) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)


class SubmissionEngine:
    """See module docstring. Construct via :func:`make_engine` or pass
    an ``ErasureCodec`` (ops/rs.py gate) and optionally an
    ``AuditBackend`` (ops/audit_backend.py gate) directly."""

    # op class -> which backend's health breaker gates it
    _BACKEND_OF = {"encode": "codec", "repair": "codec", "decode": "codec",
                   "tag": "audit", "verify_batch": "audit",
                   "verify_agg": "audit", "prove": "audit"}

    def __init__(self, codec=None, audit=None,
                 policy: AdmissionPolicy | None = None,
                 resilience=None, tracer=None, slo=None, adaptive=None,
                 admission=None, pool=None, profile=None):
        if codec is None and audit is None:
            raise ValueError("engine needs a codec and/or audit backend")
        self.codec = codec
        self.audit = audit
        # request-scoped tracing (cess_tpu/obs): an explicitly passed
        # Tracer pins this engine to it; otherwise the process-armed
        # tracer (obs.trace.arm) is consulted per request. None + not
        # armed = every hook is the no-op singleton.
        self.tracer = tracer
        self.policy = policy or AdmissionPolicy()
        self.stats = EngineStats()
        self.programs = ProgramCache(self.stats)
        # SLO + adaptive control (ISSUE 6, see module doc). All three
        # default None: the disabled submit/batch paths are one
        # attribute load + None check each, allocating nothing.
        self.slo = slo                    # obs.SloBoard
        self.adaptive = adaptive          # serve.adaptive.AdaptiveBatchPolicy
        self.admission = admission        # serve.adaptive.AdmissionController
        self.stats.slo = slo
        self.stats.adaptive = adaptive
        # continuous profiling (obs/profile.py, ISSUE 13, opt-in): a
        # ProfilePlane accounts every dispatch's stage breakdown and
        # pad bill and (baseline-anchored) watches for throughput
        # regressions. None = one attribute load + None check on the
        # account path; the program cache times builds into it.
        self.profile = profile
        self.stats.profile = profile
        if profile is not None:
            self.programs.profile = profile
        # per-(class, tenant) served device rows: the weighted-fair
        # drain's deficit counters (engine-lock guarded, only ever
        # populated when a board is configured)
        self._tenant_rows: dict[str, dict[str, int]] = {}
        # resilience (cess_tpu/resilience, opt-in): CPU reference
        # fallbacks compute bit-identical bytes, so a tripped breaker
        # changes WHERE a batch runs, never what it returns
        self.resilience = resilience
        self.monitors: dict[str, Any] = {}
        self._fallback_codec = None
        self._fallback_audit = None
        if resilience is not None:
            self.stats.resilience = resilience.stats
            if codec is not None:
                if hasattr(codec, "fold_symbol"):
                    # regenerating codec: degrade onto ITS reference
                    # twin so the symbol surface survives a breaker
                    # trip (same bytes, host placement)
                    from ..ops.regen import RegenReference

                    self._fallback_codec = RegenReference(codec.k,
                                                          codec.m)
                else:
                    from ..ops import rs as _rs

                    self._fallback_codec = _rs.make_codec(
                        codec.k, codec.m, backend="cpu")
                self.monitors["codec"] = resilience.monitor()
            if audit is not None:
                from ..ops import audit_backend as _ab

                self._fallback_audit = _ab.make_audit_backend(audit.key,
                                                              "cpu")
                self.monitors["audit"] = resilience.monitor()
            for name, mon in self.monitors.items():
                mon.name = name   # black-box journal identity
                resilience.stats.register_monitor(name, mon)
        if admission is not None:
            # after the monitors exist: the controller latches the
            # codec breaker for its degrade response (no resilience =
            # no breaker = shed-only admission)
            admission.bind(self)
        # multi-chip serving plane (serve/pool.py, opt-in): a
        # DevicePool routes drained batches across per-device worker
        # lanes. None = the single-device dispatch path, byte-for-byte
        # the PR-1 behavior (one attribute load + None check per
        # drained batch). Bound after the per-backend monitors exist —
        # bind() builds each lane's per-(backend, device) breakers
        # from the same monitor factory, plus lane-pinned audit views.
        self.pool = pool
        self.stats.pool = pool
        if pool is not None:
            pool.bind(self)
        self._queues: dict[str, collections.deque[_Request]] = {
            c: collections.deque() for c in CLASSES}
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._closed = False
        self._flushing = 0       # active flush() calls force draining
        self._inflight = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cess-submission-engine")
        self._thread.start()

    # ------------------------------------------------------------------
    # submission API — each submit_* returns an EngineFuture; the
    # same-named plain method is the blocking convenience form.
    # ------------------------------------------------------------------

    # -- encode (ErasureCodec) ----------------------------------------
    def submit_encode(self, data, timeout: float | None = None,
                      tenant: str | None = None) -> EngineFuture:
        """data [B, k, n] (or [k, n]) uint8 -> future of [B, k+m, n]."""
        self._need_codec()
        data, squeeze = self._norm_shards(data, self.codec.k)
        key = ("encode", data.shape[1], data.shape[2])
        return self._submit("encode", key, data.shape[0],
                            {"data": data}, {}, timeout, squeeze,
                            tenant=tenant)

    def encode(self, data, timeout: float | None = None,
               tenant: str | None = None) -> np.ndarray:
        return self._blocking("encode", self.submit_encode, data,
                              timeout=timeout, tenant=tenant)

    # -- decode / repair (ErasureCodec) --------------------------------
    def submit_reconstruct(self, survivors, present, missing=None,
                           timeout: float | None = None,
                           tenant: str | None = None) -> EngineFuture:
        """survivors [B, k, n] (or [k, n]) rows ordered as ``present``
        -> future of the recovered [B, len(missing), n] shards."""
        self._need_codec()
        present = tuple(present)
        if missing is None:
            missing = tuple(i for i in range(self.codec.k + self.codec.m)
                            if i not in present)
        survivors, squeeze = self._norm_shards(survivors, len(present))
        key = ("repair", "reconstruct", present, tuple(missing),
               survivors.shape[2])
        return self._submit("repair", key, survivors.shape[0],
                            {"survivors": survivors},
                            {"present": present, "missing": tuple(missing)},
                            timeout, squeeze, tenant=tenant)

    def reconstruct(self, survivors, present, missing=None,
                    timeout: float | None = None,
                    tenant: str | None = None) -> np.ndarray:
        return self._blocking("repair", self.submit_reconstruct,
                              survivors, present, missing,
                              timeout=timeout, tenant=tenant)

    def submit_decode_data(self, survivors, present,
                           timeout: float | None = None,
                           tenant: str | None = None) -> EngineFuture:
        self._need_codec()
        present = tuple(present)
        survivors, squeeze = self._norm_shards(survivors, len(present))
        key = ("repair", "decode", present, (), survivors.shape[2])
        return self._submit("repair", key, survivors.shape[0],
                            {"survivors": survivors},
                            {"present": present}, timeout, squeeze,
                            tenant=tenant)

    def decode_data(self, survivors, present,
                    timeout: float | None = None,
                    tenant: str | None = None) -> np.ndarray:
        return self._blocking("repair", self.submit_decode_data,
                              survivors, present, timeout=timeout,
                              tenant=tenant)

    def submit_repair_symbol(self, pairs, coeff: int,
                             timeout: float | None = None,
                             tenant: str | None = None) -> EngineFuture:
        """pairs [B, 2, n] (or [2, n]) uint8 (accumulator, fragment)
        rows -> future of the folded [B, 1, n] partial sums
        (acc ^ coeff*fragment) — the helper hop of the regenerating
        repair chain (ops/regen.py). Needs a codec with the symbol
        surface (``make_engine(..., rs_backend="regen")``); a
        breaker-degraded batch serves from the host twin."""
        self._need_codec()
        if not hasattr(self.codec, "fold_symbol"):
            raise ValueError(
                "repair symbols need a regenerating codec; build the "
                "engine with rs_backend='regen'")
        coeff = int(coeff)
        pairs, squeeze = self._norm_shards(pairs, 2)
        key = ("repair", "symbol", (coeff,), (), pairs.shape[2])
        return self._submit("repair", key, pairs.shape[0],
                            {"survivors": pairs}, {"coeff": coeff},
                            timeout, squeeze, tenant=tenant)

    def repair_symbol(self, pairs, coeff: int,
                      timeout: float | None = None,
                      tenant: str | None = None) -> np.ndarray:
        return self._blocking("repair", self.submit_repair_symbol,
                              pairs, coeff, timeout=timeout,
                              tenant=tenant)

    # -- tag (AuditBackend, TEE role) ----------------------------------
    def submit_tag(self, fragment_ids, fragments,
                   timeout: float | None = None,
                   tenant: str | None = None) -> EngineFuture:
        """ids [F, 2] uint32, fragments [F, bytes] uint8 -> future of
        tags [F, blocks, limbs]."""
        self._need_audit()
        ids = _norm(fragment_ids, np.uint32)
        frags = _norm(fragments, np.uint8)
        if ids.ndim != 2 or ids.shape[1] != 2 or frags.ndim != 2 \
                or ids.shape[0] != frags.shape[0]:
            raise ValueError("expected ids [F, 2] and fragments [F, bytes]")
        key = ("tag", frags.shape[1])
        return self._submit("tag", key, frags.shape[0],
                            {"ids": ids, "fragments": frags}, {}, timeout,
                            tenant=tenant)

    def tag_fragments(self, fragment_ids, fragments,
                      timeout: float | None = None,
                      tenant: str | None = None) -> np.ndarray:
        return self._blocking("tag", self.submit_tag, fragment_ids,
                              fragments, timeout=timeout, tenant=tenant)

    # -- prove (miner role) --------------------------------------------
    def submit_prove_aggregate(self, fragments, tags, idx, nu, r,
                               sectors: int | None = None,
                               timeout: float | None = None,
                               tenant: str | None = None) -> EngineFuture:
        """One miner's aggregated proof over its held set: fragments
        [F, bytes], tags [F, blocks, limbs], coefficients r [F] ->
        future of (mu [sectors], sigma [limbs]). Requests from miners
        answering the SAME round (same idx/nu) coalesce into one
        F-padded vmap batch; r's zero padding contributes exact
        modular zeros to the fold, so results are bit-identical."""
        self._need_audit()
        from ..ops import podr2

        frags = np.ascontiguousarray(np.asarray(fragments, dtype=np.uint8))
        tag_arr = np.ascontiguousarray(np.asarray(tags, dtype=np.uint32))
        r_arr = np.ascontiguousarray(np.asarray(r, dtype=np.uint32))
        idx = np.asarray(idx)
        nu = np.asarray(nu)
        if frags.ndim != 2 or tag_arr.ndim != 3 or r_arr.ndim != 1 \
                or not frags.shape[0] == tag_arr.shape[0] == r_arr.shape[0]:
            raise ValueError("expected fragments [F, bytes], tags "
                             "[F, blocks, limbs], r [F]")
        sectors = podr2.SECTORS if sectors is None else sectors
        key = ("prove", frags.shape[1], tag_arr.shape[1],
               tag_arr.shape[2], sectors,
               _round_digest(tag_arr.shape[1], idx, nu))
        return self._submit("prove", key, frags.shape[0],
                            {"fragments": frags, "tags": tag_arr,
                             "r": r_arr},
                            {"idx": idx, "nu": nu, "sectors": sectors},
                            timeout, tenant=tenant)

    def prove_aggregate(self, fragments, tags, idx, nu, r,
                        sectors: int | None = None,
                        timeout: float | None = None,
                        tenant: str | None = None):
        return self._blocking("prove", self.submit_prove_aggregate,
                              fragments, tags, idx, nu, r, sectors,
                              timeout=timeout, tenant=tenant)

    # -- verify (TEE role) ---------------------------------------------
    def submit_verify_batch(self, fragment_ids, num_blocks, idx, nu,
                            mu, sigma,
                            timeout: float | None = None,
                            tenant: str | None = None) -> EngineFuture:
        """Per-fragment checks: ids [F, 2], mu [F, sectors], sigma
        [F, limbs] -> future of bool [F]. Coalesces along F across
        requests of the same round."""
        self._need_audit()
        ids = _norm(fragment_ids, np.uint32)
        mu = _norm(mu, np.uint32)
        sigma = _norm(sigma, np.uint32)
        idx = np.asarray(idx)
        nu = np.asarray(nu)
        if ids.ndim != 2 or mu.ndim != 2 or sigma.ndim != 2 \
                or not ids.shape[0] == mu.shape[0] == sigma.shape[0]:
            raise ValueError("expected ids [F, 2], mu [F, s], sigma "
                             "[F, limbs]")
        key = ("verify_batch", num_blocks, mu.shape[1], sigma.shape[1],
               _round_digest(num_blocks, idx, nu))
        return self._submit("verify", key, ids.shape[0],
                            {"ids": ids, "mu": mu, "sigma": sigma},
                            {"idx": idx, "nu": nu,
                             "num_blocks": num_blocks}, timeout,
                            tenant=tenant)

    def verify_batch(self, fragment_ids, num_blocks, idx, nu, mu, sigma,
                     timeout: float | None = None,
                     tenant: str | None = None) -> np.ndarray:
        return self._blocking("verify", self.submit_verify_batch,
                              fragment_ids, num_blocks, idx, nu, mu,
                              sigma, timeout=timeout, tenant=tenant)

    def submit_verify_aggregate(self, fragment_ids, num_blocks, idx, nu,
                                r, mu, sigma,
                                timeout: float | None = None,
                                tenant: str | None = None) -> EngineFuture:
        """One aggregated-proof check (TeeAgent's per-mission verify):
        ids [F, 2], r [F], mu [sectors], sigma [limbs] -> future of
        bool. Missions of the same round coalesce: each mission's owed
        set is padded to a shared F bucket with r = 0 rows (exact
        modular zeros in the fold) and the checks run as one vmap."""
        self._need_audit()
        ids = np.ascontiguousarray(np.asarray(fragment_ids,
                                              dtype=np.uint32)).reshape(-1, 2)
        r_arr = np.ascontiguousarray(np.asarray(r, dtype=np.uint32))
        mu = np.ascontiguousarray(np.asarray(mu, dtype=np.uint32))
        sigma = np.ascontiguousarray(np.asarray(sigma, dtype=np.uint32))
        idx = np.asarray(idx)
        nu = np.asarray(nu)
        if r_arr.ndim != 1 or ids.shape[0] != r_arr.shape[0] \
                or mu.ndim != 1 or sigma.ndim != 1:
            raise ValueError("expected ids [F, 2], r [F], mu [s], "
                             "sigma [limbs]")
        key = ("verify_agg", num_blocks, mu.shape[0], sigma.shape[0],
               _round_digest(num_blocks, idx, nu))
        return self._submit("verify", key, ids.shape[0],
                            {"ids": ids, "r": r_arr, "mu": mu,
                             "sigma": sigma},
                            {"idx": idx, "nu": nu,
                             "num_blocks": num_blocks}, timeout,
                            tenant=tenant)

    def verify_aggregate(self, fragment_ids, num_blocks, idx, nu, r, mu,
                         sigma, timeout: float | None = None,
                         tenant: str | None = None) -> bool:
        return bool(self._blocking(
            "verify", self.submit_verify_aggregate, fragment_ids,
            num_blocks, idx, nu, r, mu, sigma, timeout=timeout,
            tenant=tenant))

    # ------------------------------------------------------------------
    # lifecycle / introspection
    # ------------------------------------------------------------------
    def warm_repair(self, patterns, n: int, buckets=(1, 2)) -> None:
        """Pre-compile + pre-stage the repair-class programs for the
        given erasure patterns so a restoral-market claim pays kernel
        time, never compile/staging time (the warm path behind the
        fragment_repair_warm_p99_ms bench metric).

        patterns: iterable of (present, missing) row tuples;
        n: shard byte width; buckets: row-bucket sizes to warm. The
        default covers a solo claim (bucket 1) AND two same-pattern
        claims coalescing in the batching window (bucket 2) — wider
        coalescence pads to a bucket that was never warmed and pays
        one cold compile; pass more buckets when many miners race the
        same restoral order (each warmed bucket costs one AOT compile
        per pattern at warm time).

        Populates the engine program cache under the exact keys
        ``_op_repair`` will look up, and — when the codec supports it
        (TPUCodec.warm_reconstruct) — AOT-compiles the underlying
        reconstruct program with its decode matrix baked in."""
        self._need_codec()
        warm = getattr(self.codec, "warm_reconstruct", None)
        pool = self.pool
        lanes = pool.lanes if pool is not None else ()
        for present, missing in patterns:
            present, missing = tuple(present), tuple(missing)
            for b in buckets:
                bucket = bucket_rows(b)
                # the warm key must carry the same cost-model meta
                # _op_repair's lookup appends, or the warmed entry
                # never hits
                meta = self._codec_meta(self.codec, "repair", present,
                                        missing,
                                        (bucket, len(present), n))
                if warm is not None:
                    warm(present, missing,
                         (bucket, len(present), n))
                self.programs.get(
                    ("repair", present, missing, n, bucket) + meta,
                    lambda p=present, mi=missing:
                        (lambda a: self.codec.reconstruct(a, p, mi)))
                # pool path: pre-populate EVERY lane's slice of the
                # cache under the device-component keys _op_repair
                # will look up, and AOT-compile per lane device — a
                # repair storm fans out across lanes without any lane
                # paying compile/staging time (and a program warmed
                # for device 0 is never handed a lane-3 batch)
                for lane in lanes:
                    if warm is not None:
                        warm(present, missing,
                             (bucket, len(present), n),
                             device=lane.device)
                    self.programs.get(
                        self._key(("repair", present, missing, n,
                                   bucket), False, lane) + meta,
                        lambda p=present, mi=missing:
                            (lambda a: self.codec.reconstruct(a, p,
                                                              mi)))
        # regen leg: when the codec carries the symbol surface
        # (RegenCodec.warm_fold), warm the helper-fold programs for
        # every coefficient the single-missing patterns can ask for —
        # same base + per-lane key discipline as the reconstructs, so
        # a symbol chain fanned across lanes never pays compile time
        warm_fold = getattr(self.codec, "warm_fold", None)
        if warm_fold is None:
            return
        from ..ops import regen

        coeffs: set[int] = set()
        for present, missing in patterns:
            present, missing = tuple(present), tuple(missing)
            if len(missing) != 1:
                continue
            coeffs.update(regen.repair_coeffs(
                self.codec.k, self.codec.m, present, missing))
        coeffs.discard(0)
        for c in sorted(coeffs):
            for b in buckets:
                bucket = bucket_rows(b)
                meta = self._codec_meta(self.codec, "symbol", (c,), (),
                                        (bucket, 2, n))
                warm_fold(c, (bucket, 2, n))
                self.programs.get(
                    ("symbol", c, n, bucket) + meta,
                    lambda cc=c:
                        (lambda a: self.codec.fold_symbol(a, cc)))
                for lane in lanes:
                    warm_fold(c, (bucket, 2, n), device=lane.device)
                    self.programs.get(
                        self._key(("symbol", c, n, bucket), False,
                                  lane) + meta,
                        lambda cc=c:
                            (lambda a: self.codec.fold_symbol(a, cc)))

    def attach_stream(self, stream_stats) -> None:
        """Register a streaming driver's StreamStats so its per-stage
        occupancy/stall counters ride the ``cess_engine_*`` metrics
        surface (serve/stream.py). Attach ONE long-lived driver per
        stream source and detach it when the source is done — the
        exported stream gauges are summed over every attached driver,
        so abandoned registrations dilute the bound-where signal."""
        with self._lock:
            self.stats.streams.append(stream_stats)

    def detach_stream(self, stream_stats) -> None:
        """Unregister a driver's StreamStats (identity match); its
        counters stop contributing to the merged gauges. Unknown stats
        objects are ignored (idempotent)."""
        with self._lock:
            try:
                self.stats.streams.remove(stream_stats)
            except ValueError:
                pass

    def stats_snapshot(self) -> dict:
        with self._lock:
            return self.stats.snapshot(
                {c: len(q) for c, q in self._queues.items()})

    def stats_metrics(self) -> dict[str, float]:
        with self._lock:
            return self.stats.metrics(
                {c: len(q) for c, q in self._queues.items()})

    def stats_histograms(self) -> dict:
        """Latency histogram families for the /metrics exposition
        (name -> obs.prom.Histogram); rendering snapshots each one
        consistently, so no engine lock is needed here."""
        return self.stats.histograms()

    def labeled_series(self) -> list:
        """Labeled exposition series — ``(family, kind, labels,
        value)`` — from the SLO board (``cess_slo_*`` per-class gauges,
        ``cess_tenant_*`` counters); empty without one. node/metrics.py
        renders these beside the flat gauges with escaped label
        values."""
        return [] if self.slo is None else self.slo.series()

    def labeled_histograms(self) -> list:
        """Labeled histogram families — ``(family, labels,
        Histogram)`` — the per-tenant latency distributions; empty
        without an SLO board."""
        return [] if self.slo is None else self.slo.tenant_histograms()

    def flush(self, timeout: float | None = None) -> bool:
        """Force-drain everything queued and wait until it resolves
        (no waiting out the coalescing delay). Returns False if the
        timeout elapses first; queued work keeps draining regardless."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            self._flushing += 1
            self._cond.notify_all()
            try:
                while any(self._queues.values()) or self._inflight:
                    left = None if deadline is None \
                        else deadline - time.monotonic()
                    if left is not None and left <= 0:
                        return False
                    self._cond.wait(left)
            finally:
                self._flushing -= 1
        return True

    def close(self, timeout: float | None = 5.0) -> None:
        """Drain pending requests, then stop the batcher. Subsequent
        submits raise EngineClosed.

        If the drain outlives ``timeout``, every request still QUEUED
        (not yet handed to the device) is rejected with EngineClosed so
        no caller blocks forever on a future that will never fire —
        the no-silent-drops contract extends to shutdown. A batch
        already in flight still resolves if the process lives on."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)
        if self.pool is not None:
            # the batcher drained what it will drain; the lane workers
            # finish their pending batches, then stop
            self.pool.close(timeout)
        if self._thread.is_alive():
            with self._cond:
                for cls, q in self._queues.items():
                    while q:
                        r = q.popleft()
                        self.stats.classes[cls].failed += 1
                        r.future._reject(EngineClosed(
                            "engine shut down before this request ran"))
                        r.span.set(outcome="closed").finish()

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _need_codec(self) -> None:
        if self.codec is None:
            raise ValueError("engine has no ErasureCodec configured")

    def _need_audit(self) -> None:
        if self.audit is None:
            raise ValueError("engine has no AuditBackend configured")

    def _blocking(self, cls: str, submit, *args,
                  timeout: float | None = None,
                  tenant: str | None = None):
        """The blocking convenience form behind encode()/tag_fragments()
        /... — without resilience it is submit().result() verbatim.
        With it, EngineSaturated submits retry under the configured
        backoff policy inside ONE deadline budget: every attempt's
        queue deadline and wait are the budget's REMAINING time, so
        retrying can never extend the caller's deadline. EngineShed is
        deliberately NOT retried — shed load must stop offering, not
        back off and re-offer (policy.py)."""
        res = self.resilience
        if res is None:
            return submit(*args, timeout=timeout,
                          tenant=tenant).result()
        if timeout is None:
            timeout = self.policy.default_timeout
        budget = Budget(timeout)

        def attempt(b):
            left = b.remaining()
            return submit(*args, timeout=left, tenant=tenant).result(left)

        return res.retry.call(attempt, retry_on=(EngineSaturated,),
                              budget=budget, token=cls,
                              stats=res.stats, cls=cls)

    @staticmethod
    def _norm_shards(data, rows: int):
        arr = _norm(data, np.uint8)
        squeeze = arr.ndim == 2
        if squeeze:
            arr = arr[None]
        if arr.ndim != 3 or arr.shape[1] != rows:
            raise ValueError(f"expected [B, {rows}, n] shards, got "
                             f"{arr.shape}")
        return arr, squeeze

    def _tracer_now(self):
        """The tracer serving this call: the engine's pinned one, else
        whatever is process-armed (obs.trace) — None when tracing is
        off, and every span hook then touches the no-op singleton."""
        return self.tracer if self.tracer is not None \
            else trace.armed_tracer()

    def _submit(self, cls: str, key: tuple, rows: int, arrays: dict,
                aux: dict, timeout: float | None,
                squeeze: bool = False,
                tenant: str | None = None) -> EngineFuture:
        if rows < 1:
            raise ValueError(f"empty {cls} request (0 rows)")
        now = time.monotonic()
        if timeout is None:
            timeout = self.policy.default_timeout
        # SLO-gated admission (serve/adaptive.py): consulted BEFORE
        # anything is queued or allocated — a shed is an explicit
        # EngineShed the caller acts on, never a silent drop. One
        # attribute load + None check when no controller is configured.
        adm = self.admission
        if adm is not None:
            reason = adm.admit(cls, timeout, tenant,
                               queued=len(self._queues[cls]))
            if reason is not None:
                with self._lock:
                    self.stats.classes[cls].shed += 1
                # a shed is an anomaly the flight recorder must keep:
                # a marker span (tail-sampling pins on outcome="shed")
                # plus a journal note — both OUTSIDE the engine lock
                # (the shed-storm bundle reads stats_snapshot()).
                tracer = self._tracer_now()
                if tracer is not None:
                    with tracer.start(f"engine.{cls}", sys="engine",
                                      cls=cls, rows=rows, op=key[0],
                                      outcome="shed",
                                      reason=reason) as sp:
                        if tenant is not None:
                            sp.set(tenant=tenant)
                _flight.note("engine", "shed", cls=cls, reason=reason,
                             tenant=tenant)
                raise EngineShed(f"{cls} request shed: {reason}")
        fut = EngineFuture()
        device = any(isinstance(a, jax.Array) for a in arrays.values())
        req = _Request(cls=cls, key=key, rows=rows, arrays=arrays,
                       aux=aux, enqueue_t=now,
                       deadline=None if timeout is None else now + timeout,
                       future=fut, squeeze=squeeze, device=device,
                       tenant=tenant)
        tracer = self._tracer_now()
        if tracer is not None:
            # the request span outlives this frame (the batcher thread
            # finishes it when the future resolves), so no with-block
            # can own it — every exit path below closes it explicitly
            req.span = tracer.start(  # cesslint: disable=span-balance — finished at resolve/reject/expire/close (cross-thread span)
                f"engine.{cls}", sys="engine", cls=cls, rows=rows,
                op=key[0])
            if tenant is not None:
                req.span.set(tenant=tenant)
        saturated = False
        with self._cond:
            if self._closed:
                req.span.set(outcome="closed").finish()
                raise EngineClosed("engine is shut down")
            st = self.stats.classes[cls]
            if len(self._queues[cls]) >= self.policy.queue_cap:
                st.saturated += 1
                saturated = True
            else:
                st.submitted += 1
                self._queues[cls].append(req)
                self._cond.notify_all()
        if saturated:
            # span finish + journal note outside the engine lock: the
            # recorder's listeners (incident bundles) read engine
            # snapshots and must never nest under _cond
            req.span.set(outcome="saturated").finish()
            _flight.note("engine", "saturated", cls=cls)
            raise EngineSaturated(
                f"{cls} queue full ({self.policy.queue_cap})")
        return fut

    # -- batcher thread -------------------------------------------------
    def _run(self) -> None:
        while True:
            batch: list[_Request] = []
            breaches: list[tuple] = []
            with self._cond:
                while True:
                    now = time.monotonic()
                    self._expire(now, breaches)
                    if breaches:
                        break
                    cls = self._ready_class(now)
                    if cls is not None:
                        batch = self._drain(cls)
                        self._inflight += 1
                        break
                    if self._closed:
                        self._cond.notify_all()
                        return
                    self._cond.wait(self._wake_timeout(now))
            if breaches:
                # deadline breaches burn the SLO error budget — fed
                # OUTSIDE the engine lock (board listeners may take
                # breaker locks; same discipline as _account_batch).
                # No batch was drained, so re-enter straight away.
                slo = self.slo
                for bcls, lat, tenant, rows in breaches:
                    slo.observe(bcls, lat, ok=False, tenant=tenant,
                                rows=rows)
                continue
            pool = self.pool
            if pool is not None:
                # multi-chip path: hand the drained batch to the
                # device-pool scheduler — the chosen lane's worker
                # runs it and settles the in-flight count via
                # _batch_done. One attribute load + None check is the
                # whole cost of this seam on the single-device path.
                try:
                    pool.dispatch(batch)
                except BaseException as e:
                    _flight.note("engine", "escape", error=repr(e))
                    self._batch_done()
                    raise
                continue
            try:
                if batch:
                    try:
                        self._run_batch(batch)
                    except BaseException as e:
                        # an exception ESCAPING the batch runner (member
                        # failures are isolated inside it) would kill
                        # the batcher thread — exactly the black-box
                        # moment: journal it before the thread dies so
                        # the incident bundle carries the cause
                        _flight.note("engine", "escape", error=repr(e))
                        raise
            finally:
                with self._cond:
                    self._inflight -= 1
                    self._cond.notify_all()

    def _batch_done(self) -> None:
        """Settle one drained batch's in-flight count — the pool path's
        lane workers call this once the batch's futures are resolved
        (the inline path settles in _run's finally)."""
        with self._cond:
            self._inflight -= 1
            self._cond.notify_all()

    def _knobs(self, cls: str) -> tuple[float, int, int]:
        """(max_delay, max_batch_requests, max_batch_rows) for this
        class: the live AdaptiveBatchPolicy values when one is
        configured, else the static policy constants — the one seam
        through which adaptive control steers the batcher."""
        ad = self.adaptive
        if ad is not None:
            return ad.knobs(cls)
        pol = self.policy
        return pol.max_delay, pol.max_batch_requests, pol.max_batch_rows

    def _expire(self, now: float, breaches: list | None = None) -> None:
        """Cancel EVERY queued request whose deadline passed, in every
        class (lock held). Running before readiness checks means a dead
        request in a quiet class cancels promptly even while other
        classes carry traffic, never trips a spurious drain trigger,
        and stops counting against its queue's cap. A timed-out
        request IS an SLO breach (the budget burns whether the device
        ran or not), but the board must never be fed under the engine
        lock — breaches are collected into ``breaches`` for the
        caller to observe after releasing it."""
        slo = self.slo
        for cls, q in self._queues.items():
            if not any(r.deadline is not None and r.deadline <= now
                       for r in q):
                continue
            st = self.stats.classes[cls]
            keep = []
            for r in q:
                if r.deadline is not None and r.deadline <= now:
                    st.timeouts += 1
                    r.future._reject(EngineTimeout(
                        f"{cls} request deadline expired before "
                        "batching"))
                    r.span.set(outcome="timeout").finish()
                    if slo is not None and breaches is not None:
                        breaches.append((cls, now - r.enqueue_t,
                                         r.tenant, r.rows))
                else:
                    keep.append(r)
            q.clear()
            q.extend(keep)

    def _ready_class(self, now: float) -> str | None:
        """Class to drain now, or None to keep waiting.

        A drain happens when ANY class trips a trigger — size
        (requests or rows), deadline (oldest waited its class's
        max_delay), an active flush, or engine shutdown (drain
        everything). Once the device is going to be fed, the
        HIGHEST-PRIORITY non-empty class goes first regardless of
        which class tripped: a just-arrived challenge verification
        preempts the bulk encode whose delay expired (policy.py).
        Expired requests are gone already (_expire runs first), so
        deadlines never trigger drains."""
        first_nonempty = None
        for cls in CLASSES:               # priority order
            q = self._queues[cls]
            if not q:
                continue
            if first_nonempty is None:
                first_nonempty = cls
            max_delay, max_reqs, max_rows = self._knobs(cls)
            if (self._closed or self._flushing
                    or len(q) >= max_reqs
                    or q[0].enqueue_t + max_delay <= now
                    or sum(r.rows for r in q) >= max_rows):
                return first_nonempty
        return None

    def _wake_timeout(self, now: float) -> float | None:
        wake = None
        for cls, q in self._queues.items():
            if not q:
                continue
            max_delay = self._knobs(cls)[0]
            for r in q:
                t = r.enqueue_t + max_delay
                if r.deadline is not None:
                    t = min(t, r.deadline)
                wake = t if wake is None else min(wake, t)
        if wake is None:
            return None
        return max(wake - now, 0.0)

    # ops that pad every request's OWN row axis to the batch-wide
    # bucket (stacked, not concatenated): cap the bucket spread so one
    # huge request cannot multiply the device work of its small peers
    _STACKED_OPS = ("prove", "verify_agg")
    PAD_SPREAD = 4

    def _anchor_index(self, cls: str, q) -> int:
        """Which queued request anchors the next batch. Without tenant
        accounting: the oldest (index 0, the PR-1 behavior). With an
        SLO board: weighted-fair across the tenants present in the
        queue — the anchor is the OLDEST request of the tenant with
        the smallest served-device-rows deficit counter, so a heavy
        uploader's backlog cannot indefinitely pre-empt another
        tenant's differently-keyed work inside the same class (ties
        break lexicographically: deterministic). Lock held."""
        if self.slo is None or len(q) < 2:
            return 0
        served = self._tenant_rows.get(cls, {})
        first_of: dict[str, int] = {}
        for i, r in enumerate(q):
            t = self._fair_key(r.tenant, served)
            if t not in first_of:
                first_of[t] = i
        if len(first_of) < 2:
            return 0
        tenant = min(first_of, key=lambda t: (served.get(t, 0), t))
        return first_of[tenant]

    def _fair_key(self, tenant: "str | None", served: dict) -> str:
        """Deficit-counter key for a tenant: its own name while
        in-cap, the board's shared overflow bucket once the board's
        ``max_tenants`` distinct names exist (same cap and same
        bucket as the ``cess_tenant_*`` exposition, so the scrape can
        explain the scheduler's grouping). The ONE aliasing rule for
        both sides — _account_batch charges served rows under it and
        _anchor_index reads deficits through it; a divergence inverts
        fairness (an over-cap tenant whose charges land in the
        overflow but whose raw name reads 0 anchors every drain)."""
        t = tenant or ""
        if t not in served and len(served) >= self.slo.max_tenants:
            from ..obs.slo import OVERFLOW

            return OVERFLOW
        return t

    def _drain(self, cls: str) -> list[_Request]:
        """Pop one coalescible batch (lock held): take queued requests
        sharing the ANCHOR request's key up to the size budgets;
        others stay queued in order. The anchor is the oldest request
        (or the fair-queued tenant's oldest — _anchor_index). Expired
        requests are already gone (_expire runs under the same lock
        hold)."""
        q = self._queues[cls]
        if not q:
            return []
        idx = self._anchor_index(cls, q)
        first = q[idx]
        stacked = first.key[0] in self._STACKED_OPS
        anchor_bucket = bucket_rows(first.rows)
        _, max_reqs, max_rows = self._knobs(cls)
        batch, rest, rows = [first], [], first.rows
        for i, r in enumerate(q):
            if i == idx:
                continue
            fits = (r.key == first.key
                    and len(batch) < max_reqs
                    and rows + r.rows <= max_rows)
            if fits and stacked:
                b = bucket_rows(r.rows)
                fits = (b <= self.PAD_SPREAD * anchor_bucket
                        and anchor_bucket <= self.PAD_SPREAD * b)
            if fits:
                batch.append(r)
                rows += r.rows
            else:
                rest.append(r)
        q.clear()
        q.extend(rest)
        return batch

    def _device_annotation(self, tracer, op: str):
        """Optional XLA-profile alignment: with jax_annotations on,
        each device batch dispatch runs inside a
        jax.profiler.TraceAnnotation scope named like the framework
        span, so a captured XLA profile lines up with the trace."""
        if tracer is None or not tracer.jax_annotations:
            return contextlib.nullcontext()
        annotation = getattr(jax.profiler, "TraceAnnotation", None)
        if annotation is None:
            return contextlib.nullcontext()
        return annotation(f"cess:{op}")

    def _run_batch(self, batch: list[_Request], lane=None,
                   tried=None) -> bool:
        """Run one coalesced batch. ``lane`` is None on the inline
        single-device path; on the pool path it is the DeviceLane
        whose worker is running this batch — breaker gating then uses
        the lane's per-(backend, device) monitor, dispatch pins to the
        lane's device, and a denied/failed lane DRAINS the batch to a
        healthy sibling (``tried`` carries the lane indices that
        already failed it). Returns True when the batch was handed
        off that way — its futures are then the sibling's to settle."""
        cls = batch[0].cls
        op = batch[0].key[0]
        runner: Callable = getattr(self, f"_op_{op}")
        res = self.resilience
        mons = self.monitors if lane is None else lane.monitors
        mon = mons.get(self._BACKEND_OF.get(op))
        # breaker open (and no probe due): drain to a healthy sibling
        # lane when there is one, else serve on the CPU fallback
        degraded = res is not None and res.fallback \
            and mon is not None and not mon.allow()
        if degraded and lane is not None and self.pool.requeue(
                batch, lane, tried if tried is not None else set()):
            return True
        if degraded:
            res.stats.note_degraded(cls)
        tracer = self._tracer_now()
        bspan = trace.NOOP_SPAN
        if tracer is not None:
            # the coalesced-batch span: parented to its first member's
            # request span (the link that makes occupancy/pad-waste
            # attributable per request); closed on every path below
            bspan = tracer.start(  # cesslint: disable=span-balance — finished on both the success and error paths below
                "engine.batch", sys="engine", parent=batch[0].span,
                op=op, cls=cls, members=len(batch),
                rows=sum(r.rows for r in batch), degraded=degraded)
            for r in batch:
                r.span.event("batched", batch_span=bspan.span_id,
                             members=len(batch))
        t0 = time.monotonic()
        try:
            # current=True: the device span is the batcher thread's
            # active span for the dispatch, so fault-injection firings
            # (faults.inject below) annotate it via obs.event
            with self._device_annotation(tracer, op), \
                    self._lane_placement(lane, degraded), \
                    (trace.NOOP_SPAN if tracer is None else tracer.start(
                        f"device.{op}", sys="device", parent=bspan,
                        current=True, op=op, degraded=degraded,
                        backend="cpu-fallback" if degraded else "primary",
                        **({} if lane is None
                           else {"device": lane.index}))):
                if not degraded:
                    faults.inject("engine.dispatch")   # chaos seam
                    if lane is not None:
                        # per-lane seam: chaos plans kill ONE lane's
                        # dispatch while its siblings stay healthy
                        faults.inject(f"engine.dispatch.d{lane.index}")
                        # per-class lane seam: a plan can trip ONE
                        # class's dispatches on one lane (the repair
                        # storm trips repair lane 0 mid-storm while
                        # the same lane keeps serving uploads)
                        faults.inject(
                            f"engine.dispatch.{cls}.d{lane.index}")
                # two-arg call off the pool path: the (batch, degraded)
                # runner signature is a public monkeypatch seam
                results, device_rows = (
                    runner(batch, degraded) if lane is None
                    else runner(batch, degraded, lane))
        except Exception as e:        # op failure
            if mon is not None and not degraded:
                mon.record_error()
            bspan.set(error=repr(e)).finish()
            if lane is not None and not degraded and self.pool.requeue(
                    batch, lane, tried if tried is not None else set()):
                # member isolation preserved: the batch moves WHOLE to
                # a healthy sibling; salvage (solo re-runs / CPU
                # degradation) only runs once every sibling failed it
                return True
            if res is not None and self._salvage_batch(runner, batch, e,
                                                       mon, degraded,
                                                       lane):
                return False
            with self._lock:
                self.stats.classes[cls].failed += len(batch)
            fail_t = time.monotonic()
            for r in batch:
                r.future._reject(e)
                r.span.set(outcome="error", error=repr(e)).finish()
                self._observe_failure(r, fail_t)
            return False
        if mon is not None and not degraded:
            mon.record_success(time.monotonic() - t0)
        self._account_batch(batch, device_rows, bspan, lane=lane, t0=t0)
        bspan.finish()
        for r, out in zip(batch, results):
            r.future._resolve(out)
            if r.span is not trace.NOOP_SPAN:
                r.span.set(outcome="ok").finish()
        return False

    def _observe_failure(self, r: _Request, now: float) -> None:
        """Feed one rejected request into the SLO windows (failures
        burn the error budget). One None check on the disabled path."""
        slo = self.slo
        if slo is not None:
            slo.observe(r.cls, now - r.enqueue_t, ok=False,
                        tenant=r.tenant, rows=r.rows)

    def _account_batch(self, batch: list[_Request], device_rows: int,
                       batch_span=trace.NOOP_SPAN, lane=None,
                       t0: float | None = None) -> None:
        done = time.monotonic()
        real_rows = sum(r.rows for r in batch)
        cls = batch[0].cls
        with self._lock:
            st = self.stats.classes[cls]
            st.batches += 1
            st.batched_requests += len(batch)
            st.rows += real_rows
            st.padded_rows += max(device_rows - real_rows, 0)
            st.completed += len(batch)
            for r in batch:
                lat = done - r.enqueue_t
                st.latencies.append(lat)
                st.hist.observe(lat)
            if self.slo is not None:
                # the weighted-fair drain's deficit counters (bounded:
                # past the cap a new tenant shares the overflow bucket)
                served = self._tenant_rows.setdefault(cls, {})
                for r in batch:
                    t = self._fair_key(r.tenant, served)
                    served[t] = served.get(t, 0) + r.rows
        # SLO + adaptive feeds OUTSIDE the engine lock (board and
        # policy own their locks; listeners may touch breaker locks) —
        # and only when armed: the disabled path pays one attribute
        # load + None check per batch, allocating nothing (the
        # zero-cost-when-off contract, cess_tpu/obs)
        slo = self.slo
        if slo is not None:
            for r in batch:
                slo.observe(cls, done - r.enqueue_t, ok=True,
                            tenant=r.tenant, rows=r.rows)
        ad = self.adaptive
        if ad is not None:
            occ = len(batch)
            for r in batch:
                ad.note(cls, done - r.enqueue_t, occ)
        prof = self.profile
        if prof is not None:
            # continuous profiling feed (obs/profile.py): the byte
            # count and queue-wait sums are only computed when armed
            prof.on_batch(
                cls, device_rows,
                0 if lane is None else lane.index,
                rows=real_rows,
                padded=max(device_rows - real_rows, 0),
                requests=len(batch),
                nbytes=sum(a.nbytes for r in batch
                           for a in r.arrays.values()),
                queue_s=sum(done - r.enqueue_t for r in batch),
                dispatch_s=0.0 if t0 is None else done - t0)
        # span attribution only when the spans are real: the disabled
        # path must not pay the round()s / kwargs dicts per request
        if batch_span is not trace.NOOP_SPAN:
            pad = max(device_rows - real_rows, 0)
            pad_waste = pad / device_rows if device_rows else 0.0
            batch_span.set(device_rows=device_rows,
                           pad_waste=round(pad_waste, 4))
            for r in batch:
                r.span.set(occupancy=len(batch),
                           pad_waste=round(pad_waste, 4),
                           batch_span=batch_span.span_id,
                           latency_s=round(done - r.enqueue_t, 6))

    def _salvage_batch(self, runner: Callable, batch: list[_Request],
                       primary_exc: BaseException, mon,
                       degraded: bool, lane=None) -> bool:
        """A batch op failed with resilience configured: isolate the
        members — re-run each ALONE once (one poisoned request must
        not fail its batch-mates), then, if the device attempt failed
        and fallback is allowed, serve the member on the CPU reference
        backend. Resolves or rejects every future; returns True (the
        caller is done with the batch)."""
        res = self.resilience
        cls = batch[0].cls
        tracer = self._tracer_now()
        if len(batch) > 1:
            res.stats.note_batch_requeues(len(batch))
        # solo re-runs use the primary backend only while the breaker
        # is closed (or the failed batch was already degraded): when
        # the failure WAS a recovery probe against an open breaker,
        # re-probing the known-bad device once per member would
        # amplify the outage latency by the batch size — members go
        # straight to the fallback instead
        solo = len(batch) > 1 \
            and (degraded or mon is None or mon.state == "closed")
        for r in batch:
            out = None
            exc = primary_exc
            if solo:
                r.span.event("salvage.solo")
                try:
                    with self._lane_placement(lane, degraded):
                        if not degraded:
                            faults.inject("engine.dispatch")
                            if lane is not None:
                                faults.inject(
                                    f"engine.dispatch.d{lane.index}")
                        out, rows = (runner([r], degraded)
                                     if lane is None
                                     else runner([r], degraded, lane))
                except Exception as e:  # noqa: BLE001 — per-member isolation
                    exc = e
                    if mon is not None and not degraded:
                        mon.record_error()
                else:
                    if mon is not None and not degraded:
                        mon.record_success(0.0)
            if out is None and not degraded and res.fallback \
                    and mon is not None:
                try:
                    with (trace.NOOP_SPAN if tracer is None
                          else tracer.start("resilience.fallback",
                                            sys="resilience",
                                            parent=r.span,
                                            current=True, cls=cls)):
                        out, rows = (runner([r], True) if lane is None
                                     else runner([r], True, lane))
                    res.stats.note_fallback(cls)
                except Exception as e:  # noqa: BLE001 — fallback is best-effort
                    exc = e
            if out is None:
                with self._lock:
                    self.stats.classes[cls].failed += 1
                r.future._reject(exc)
                r.span.set(outcome="error", error=repr(exc)).finish()
                self._observe_failure(r, time.monotonic())
            else:
                self._account_batch([r], rows, lane=lane)
                r.future._resolve(out[0])
                r.span.set(outcome="ok").finish()
        return True

    # -- op runners (batcher thread only) -------------------------------
    def _split_rows(self, batch: list[_Request], out) -> list:
        """Slice a batch result back per request. Device submitters get
        ``jax.Array`` slices (no host materialization anywhere on their
        path); an all-host batch is fetched ONCE and sliced as numpy.

        The result is synced BEFORE futures resolve: zero-copy means
        no D2H transfer, not fire-and-forget — a future must mean
        "this batch actually completed", the per-class latency
        percentiles must measure enqueue->completion (not async
        dispatch), and a device-side execution failure must reject the
        batch through _run_batch's error path instead of resolving
        futures with poisoned arrays."""
        if isinstance(out, jax.Array):
            jax.block_until_ready(out)
            if not any(r.device for r in batch):
                out = np.asarray(out)
        results, off = [], 0
        for r in batch:
            piece = out[off:off + r.rows]
            if r.device and not isinstance(piece, jax.Array):
                piece = jnp.asarray(piece)
            elif not r.device and isinstance(piece, jax.Array):
                piece = np.asarray(piece)
            results.append(piece[0] if r.squeeze else piece)
            off += r.rows
        return results

    def _rs_backend(self, degraded: bool):
        """The ErasureCodec serving this batch: the configured device
        gate, or the CPU reference when the breaker degraded it. The
        codec is shared across pool lanes — lane placement comes from
        the _lane_placement default-device scope, not the gate."""
        return self._fallback_codec if degraded else self.codec

    def _audit_backend(self, degraded: bool, lane=None):
        """The AuditBackend serving this batch. Unlike the codec, an
        AuditBackend pins every op to ITS OWN device
        (ops/audit_backend.py ``_on``), so the pool path must use the
        lane's own view — the shared gate would collapse every audit
        batch back onto one chip."""
        if degraded:
            return self._fallback_audit
        if lane is not None and lane.audit is not None:
            return lane.audit
        return self.audit

    @staticmethod
    def _lane_placement(lane, degraded: bool):
        """Device scope for a batch dispatch: the lane's device on the
        pool path, JAX's default placement otherwise (and always for
        degraded batches — the CPU fallback gates pin themselves)."""
        if lane is None or degraded:
            return contextlib.nullcontext()
        return jax.default_device(lane.device)

    @staticmethod
    def _codec_meta(codec, kind, present=(), missing=(), shape=()) -> tuple:
        """Cost-model attribution components for a program-cache key:
        codecs that auto-select a lowering (TPUCodec.program_meta,
        strategy="xor"/"auto") report which strategy serves this
        (kind, pattern, shape) plus the estimate that picked it, so
        OpProfiler/CompileLedger keep the programs apart. Zero-cost
        seam: one load + None check, and default-strategy codecs
        return () — cache keys grow only when the selector is armed."""
        meta = getattr(codec, "program_meta", None)
        if meta is None:
            return ()
        return meta(kind, present=present, missing=missing, shape=shape)

    @staticmethod
    def _key(key: tuple, degraded: bool, lane=None) -> tuple:
        """Degraded programs cache under their own keys — a breaker
        flip must never hand a device program a CPU batch or vice
        versa. On the pool path the key grows a device component for
        the same reason: a program compiled (AOT-warmed) for lane 0's
        device must never be handed a batch placed on lane 3
        (degraded keys stay device-free — the CPU fallback program is
        one program, shared by every lane)."""
        if degraded:
            return key + ("cpu-fallback",)
        if lane is not None:
            return key + (("device", lane.index),)
        return key

    def _op_encode(self, batch, degraded=False, lane=None):
        codec = self._rs_backend(degraded)
        data = _concat_rows([r.arrays["data"] for r in batch])
        total = data.shape[0]
        bucket = bucket_rows(total)
        _, k, n = data.shape
        meta = self._codec_meta(codec, "encode", shape=(bucket, k, n))
        prog = self.programs.get(self._key(("encode", k, n, bucket),
                                           degraded, lane) + meta,
                                 lambda: codec.encode)
        out = prog(_pad_axis0(data, bucket))[:total]
        return self._split_rows(batch, out), bucket

    def _op_repair(self, batch, degraded=False, lane=None):
        codec = self._rs_backend(degraded)
        kind = batch[0].key[1]
        aux = batch[0].aux
        surv = _concat_rows([r.arrays["survivors"] for r in batch])
        total = surv.shape[0]
        bucket = bucket_rows(total)
        n = surv.shape[2]
        if kind == "reconstruct":
            present, missing = aux["present"], aux["missing"]
            meta = self._codec_meta(codec, "repair", present, missing,
                                    (bucket, len(present), n))
            prog = self.programs.get(
                self._key(("repair", present, missing, n, bucket),
                          degraded, lane) + meta,
                lambda: (lambda a: codec.reconstruct(a, present,
                                                     missing)))
        elif kind == "symbol":
            coeff = aux["coeff"]
            fold = getattr(codec, "fold_symbol", None)
            if fold is None:
                # breaker-degraded (or plain-reference fallback) codec:
                # serve the fold from the host twin — the chain stays
                # bit-identical, only the placement degrades
                from ..ops import regen

                fold = regen.fold_symbol_pairs
                meta = ()
            else:
                meta = self._codec_meta(codec, "symbol", (coeff,), (),
                                        (bucket, 2, n))
            prog = self.programs.get(
                self._key(("symbol", coeff, n, bucket), degraded,
                          lane) + meta,
                lambda f=fold, c=coeff: (lambda a: f(a, c)))
        else:
            present = aux["present"]
            meta = self._codec_meta(codec, "decode", present, (),
                                    (bucket, len(present), n))
            prog = self.programs.get(
                self._key(("decode", present, n, bucket), degraded,
                          lane) + meta,
                lambda: (lambda a: codec.decode_data(a, present)))
        out = prog(_pad_axis0(surv, bucket))[:total]
        return self._split_rows(batch, out), bucket

    def _op_tag(self, batch, degraded=False, lane=None):
        audit = self._audit_backend(degraded, lane)
        ids = _concat_rows([r.arrays["ids"] for r in batch])
        frags = _concat_rows([r.arrays["fragments"] for r in batch])
        total = frags.shape[0]
        bucket = bucket_rows(total)
        nbytes = frags.shape[1]
        prog = self.programs.get(self._key(("tag", nbytes, bucket),
                                           degraded, lane),
                                 lambda: audit.tag_fragments)
        out = prog(_pad_axis0(ids, bucket),
                   _pad_axis0(frags, bucket))[:total]
        return self._split_rows(batch, out), bucket

    def _op_verify_batch(self, batch, degraded=False, lane=None):
        audit = self._audit_backend(degraded, lane)
        aux = batch[0].aux
        ids = _concat_rows([r.arrays["ids"] for r in batch])
        mu = _concat_rows([r.arrays["mu"] for r in batch])
        sigma = _concat_rows([r.arrays["sigma"] for r in batch])
        total = ids.shape[0]
        bucket = bucket_rows(total)
        num_blocks, idx, nu = (aux["num_blocks"], aux["idx"], aux["nu"])
        prog = self.programs.get(
            self._key(("verify_batch", batch[0].key, bucket), degraded,
                      lane),
            lambda: (lambda i, u, s: audit.verify_batch(
                i, num_blocks, idx, nu, u, s)))
        out = prog(_pad_axis0(ids, bucket),
                   _pad_axis0(mu, bucket),
                   _pad_axis0(sigma, bucket))[:total]
        return self._split_rows(batch, out), bucket

    def _op_verify_agg(self, batch, degraded=False, lane=None):
        from ..ops import podr2

        aux = batch[0].aux
        fb = bucket_rows(max(r.rows for r in batch))
        rb = bucket_rows(len(batch))
        ids = np.zeros((rb, fb, 2), dtype=np.uint32)
        rs = np.zeros((rb, fb), dtype=np.uint32)
        mu = np.zeros((rb,) + batch[0].arrays["mu"].shape, np.uint32)
        sigma = np.zeros((rb,) + batch[0].arrays["sigma"].shape,
                         np.uint32)
        for i, r in enumerate(batch):
            ids[i, :r.rows] = r.arrays["ids"]
            rs[i, :r.rows] = r.arrays["r"]
            mu[i] = r.arrays["mu"]
            sigma[i] = r.arrays["sigma"]
        num_blocks, idx, nu = (aux["num_blocks"], aux["idx"], aux["nu"])
        audit = self._audit_backend(degraded, lane)

        def build():
            fn = jax.vmap(lambda i, rr, u, s: podr2.verify_aggregate(
                audit.key, i, num_blocks, idx, nu, rr, u, s))

            def run(i, rr, u, s):
                with jax.default_device(audit.device):
                    return fn(i, rr, u, s)
            return run

        prog = self.programs.get(
            self._key(("verify_agg", batch[0].key, fb, rb), degraded,
                      lane),
            build)
        out = np.asarray(prog(ids, rs, mu, sigma))
        results = [bool(out[i]) for i in range(len(batch))]
        return results, rb * fb

    def _op_prove(self, batch, degraded=False, lane=None):
        from ..ops import podr2

        aux = batch[0].aux
        fb = bucket_rows(max(r.rows for r in batch))
        rb = bucket_rows(len(batch))
        nbytes = batch[0].arrays["fragments"].shape[1]
        blocks, limbs = batch[0].arrays["tags"].shape[1:]
        frags = np.zeros((rb, fb, nbytes), dtype=np.uint8)
        tags = np.zeros((rb, fb, blocks, limbs), dtype=np.uint32)
        rs = np.zeros((rb, fb), dtype=np.uint32)
        for i, r in enumerate(batch):
            frags[i, :r.rows] = r.arrays["fragments"]
            tags[i, :r.rows] = r.arrays["tags"]
            rs[i, :r.rows] = r.arrays["r"]
        idx, nu, sectors = aux["idx"], aux["nu"], aux["sectors"]
        audit = self._audit_backend(degraded, lane)

        def build():
            fn = jax.vmap(lambda f, t, rr: podr2.prove_aggregate(
                f, t, idx, nu, rr, sectors))

            def run(f, t, rr):
                with jax.default_device(audit.device):
                    return fn(f, t, rr)
            return run

        prog = self.programs.get(
            self._key(("prove", batch[0].key, fb, rb), degraded, lane),
            build)
        mu, sigma = prog(frags, tags, rs)
        mu = np.asarray(mu)
        sigma = np.asarray(sigma)
        results = [(mu[i], sigma[i]) for i in range(len(batch))]
        return results, rb * fb


def make_engine(k: int | None = None, m: int | None = None, *,
                rs_backend: str = "cpu", strategy: str | None = None,
                podr2_key=None, audit_backend: str = "cpu",
                policy: AdmissionPolicy | None = None,
                resilience=None, tracer=None, slo=None, adaptive=None,
                admission=None, pool=None,
                profile=None) -> SubmissionEngine:
    """Build an engine over the two trait gates.

    k/m select the ErasureCodec geometry (None = no codec: the engine
    serves only audit classes); podr2_key enables the audit classes
    (None = no AuditBackend: tag/prove/verify submits raise).
    resilience: optional cess_tpu.resilience.ResilienceConfig — retry
    on saturation, batch-failure isolation, and health-gated CPU
    degradation (see the module doc's Resilience paragraph).
    tracer: optional cess_tpu.obs.Tracer — request-scoped spans for
    every submit (queue-wait -> batch -> device dispatch -> resolve);
    without one the engine still honors a process-armed tracer
    (obs.trace.arm), and with neither every hook is a no-op.
    slo: optional cess_tpu.obs.SloBoard — burn-rate SLO monitors +
    per-tenant accounting + weighted-fair dequeue (module doc's SLO
    paragraph). adaptive: an AdaptiveBatchPolicy (serve/adaptive.py),
    or True to build one seeded from ``policy`` and steered by the
    board's targets. admission: an AdmissionController; auto-built
    when both ``slo`` and ``adaptive`` are present (pass your own to
    customize the protect/shed classes, or ``False`` to disable).
    pool: the multi-chip serving plane (serve/pool.py) — a built
    DevicePool, or True (all local devices) / a device count N (the
    ``--pool[=N]`` CLI form). None/0/False = the single-device
    dispatch path, unchanged.
    profile: optional cess_tpu.obs.profile.ProfilePlane — continuous
    performance profiling: per-(class, bucket, device) stage
    breakdowns, the unified pad ledger, program-cache compile events
    and (when built with a bench baseline) the perf-regression
    watchdog. None = the account path pays one attribute load + None
    check per batch.
    """
    codec = None
    if k is not None:
        from ..ops import rs

        codec = rs.make_codec(k, m, backend=rs_backend, strategy=strategy)
    audit = None
    if podr2_key is not None:
        from ..ops import audit_backend as ab

        audit = ab.make_audit_backend(podr2_key, audit_backend)
    if adaptive is True:
        if slo is None:
            # the node.cli refusal, enforced at the API layer too: a
            # tuner with no board has no targets to steer toward and
            # would silently never adjust a knob (pass an explicit
            # AdaptiveBatchPolicy(targets=...) for a board-less tuner)
            raise ValueError("adaptive=True needs an slo= board "
                             "(its targets steer the knob tuner)")
        from .adaptive import AdaptiveBatchPolicy

        adaptive = AdaptiveBatchPolicy(policy, board=slo)
    if admission is None and slo is not None and adaptive is not None:
        from .adaptive import AdmissionController

        admission = AdmissionController(slo, adaptive)
    if pool and not hasattr(pool, "bind"):
        # True = every local device; an int = the first N of them
        from .pool import DevicePool

        pool = DevicePool(n=None if pool is True else int(pool))
    return SubmissionEngine(codec, audit, policy, resilience=resilience,
                            tracer=tracer, slo=slo, adaptive=adaptive,
                            admission=admission or None,
                            pool=pool or None, profile=profile)
