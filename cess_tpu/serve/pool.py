"""Multi-chip serving plane: the device-pool scheduler that shards the
submission engine across the mesh.

The data plane has scaled past one chip for a while —
``parallel/mesh.py`` runs the fused encode+tag program over an
8-device (seg, byte) mesh — but the serving plane was still a
single-device service: every batch the engine drained dispatched to
ONE device, so ``stream_encode_tag_GiBps`` and
``podr2_100k_tag_verify_frags_per_s`` were per-chip ceilings, not
fleet numbers. :class:`DevicePool` turns the engine into a fleet
service:

- each device gets a :class:`DeviceLane` — its own worker thread, its
  own per-(backend, device) ``HealthMonitor`` breakers (named
  ``codec.d<i>`` / ``audit.d<i>`` beside the engine's per-backend
  ones), its own ``AuditBackend`` view pinned to the lane device, and
  its own slice of the program cache (``SubmissionEngine._key`` grows
  a ``("device", i)`` component on the pool path, so a program
  compiled for device 0 is never handed a batch placed on device 3);
- placement is deficit-weighted on in-flight device rows: the
  least-loaded lane wins, ties break by device index — deterministic,
  no wallclock, no entropy, the same discipline as the engine's
  weighted-fair drain anchor. Every placement appends to a bounded
  count-sequenced log, the replay witness (same offered sequence =>
  same log);
- a lane whose dispatch fails (or whose breaker denies admission)
  DRAINS its batch to a healthy sibling instead of degrading: the
  batch is requeued whole (member isolation preserved — the engine's
  salvage machinery only runs once every sibling has been tried), so
  one sick chip degrades to CPU only when the whole pool is sick.
  While a lane's breaker is open, every ``probe_every``-th placement
  for that op class is offered back to it as a recovery probe (its
  own breaker decides whether to admit it) — without this, avoiding
  open lanes would make every trip permanent;
- ``StreamingIngest`` placement: :meth:`DevicePool.stream_entry`
  builds the (program, put, put_ids) triple against the pool's
  (n_lanes, 1) mesh, so each staged batch's sharded ``device_put``
  fans segments across every lane in one transfer.

Determinism contract: the pool changes WHERE a batch runs, never what
it computes — the GF(2^8)/PoDR2 programs are platform- and
topology-deterministic (tests/test_pool.py pins pool == single-device
== direct, byte for byte). The zero-cost contract holds too: an
engine built without ``pool=`` takes the exact PR-1 dispatch path
(one attribute load + None check per drained batch).

Thread-safety: every pool/lane counter is guarded by the one pool
lock; breaker state lives in the monitors (their own locks). Flight
journal notes (``pool.requeue`` / ``pool.escape``) always fire with
the pool lock released — incident listeners snapshot the engine.
"""
from __future__ import annotations

import collections
import threading
from typing import Any

import jax

from ..obs import flight as _flight

PLACEMENT_LOG = 4096     # bounded placement-log window (replay witness)


class DeviceLane:
    """One device's worker lane inside the pool: the device handle,
    its per-(backend, device) breakers, its pinned AuditBackend view,
    a pending-batch queue and the load/served counters placement reads.
    All mutable fields are guarded by the owning pool's lock."""

    __slots__ = ("index", "device", "audit", "monitors", "pending",
                 "thread", "batches", "rows", "requeues",
                 "inflight_rows")

    def __init__(self, index: int, device):
        self.index = index
        self.device = device
        self.audit = None                   # lane-pinned AuditBackend
        self.monitors: dict[str, Any] = {}  # backend -> HealthMonitor
        self.pending: collections.deque = collections.deque()
        self.thread: threading.Thread | None = None
        self.batches = 0          # batches this lane completed
        self.rows = 0             # real rows across those batches
        self.requeues = 0         # batches received from a sick sibling
        self.inflight_rows = 0    # placement's deficit counter

    def breaker_state(self, backend: str | None) -> str:
        """This lane's breaker state for an op class's backend —
        "closed" when unmonitored (no resilience configured)."""
        mon = self.monitors.get(backend)
        return "closed" if mon is None else mon.state


class DevicePool:
    """See module doc. Construct over explicit devices (or the first
    ``n`` of ``jax.devices()``; ``n`` of 0/None means all), then pass
    to ``make_engine(pool=...)`` — the engine binds the pool, which
    builds the per-lane breakers and starts the lane workers."""

    def __init__(self, devices=None, n: int | None = None,
                 probe_every: int = 8):
        if devices is None:
            devices = jax.devices()
        devices = list(devices)
        if n:
            devices = devices[:n]
        if not devices:
            raise ValueError("DevicePool needs at least one device")
        if probe_every < 1:
            raise ValueError("probe_every must be >= 1")
        self.lanes = [DeviceLane(i, d) for i, d in enumerate(devices)]
        self.probe_every = probe_every
        self._mu = threading.Lock()
        self._cond = threading.Condition(self._mu)
        self._engine = None
        self._closed = False
        self._seq = 0             # placement sequence (count, not time)
        # the replay witness: (seq, op, members, rows, lane, reason)
        self._log: collections.deque = \
            collections.deque(maxlen=PLACEMENT_LOG)
        self._probe_tick: dict[str, int] = {}   # op -> placements seen

    @property
    def n_devices(self) -> int:
        return len(self.lanes)

    def devices(self) -> list:
        return [lane.device for lane in self.lanes]

    # -- engine binding ------------------------------------------------------
    def bind(self, engine) -> None:
        """Wire the pool into an engine (SubmissionEngine.__init__):
        per-lane breakers from the engine's resilience monitor factory
        (registered as ``<backend>.d<i>`` beside the engine's own), a
        lane-pinned AuditBackend view per lane, and one worker thread
        per lane. A pool serves exactly one engine."""
        with self._mu:
            if self._engine is not None:
                raise ValueError("DevicePool already bound to an engine")
            if self._closed:
                raise ValueError("DevicePool is shut down")
            self._engine = engine
        res = engine.resilience
        if res is not None:
            for lane in self.lanes:
                for backend in engine.monitors:
                    mon = res.monitor()
                    mon.name = f"{backend}.d{lane.index}"
                    lane.monitors[backend] = mon
                    res.stats.register_monitor(mon.name, mon)
        if engine.audit is not None:
            from ..ops.audit_backend import AuditBackend

            for lane in self.lanes:
                # same key, lane device: AuditBackend pins every op to
                # its own device, so without a per-lane view all audit
                # batches would collapse back onto one chip
                lane.audit = AuditBackend(engine.audit.key, lane.device)
        for lane in self.lanes:
            lane.thread = threading.Thread(
                target=self._worker, args=(lane,), daemon=True,
                name=f"cess-pool-lane-{lane.index}")
            lane.thread.start()

    # -- placement -----------------------------------------------------------
    def dispatch(self, batch) -> None:
        """Place one drained batch on a lane (engine batcher thread).
        The engine already counted it in-flight; the lane worker
        settles it via ``engine._batch_done``."""
        op = batch[0].key[0]
        rows = sum(r.rows for r in batch)
        with self._cond:
            if self._closed or self._engine is None:
                raise RuntimeError("device pool is not serving")
            lane, reason = self._place_locked(op, rows, frozenset())
            lane.inflight_rows += rows
            self._seq += 1
            self._log.append((self._seq, op, len(batch), rows,
                              lane.index, reason))
            lane.pending.append((batch, set()))
            self._cond.notify_all()

    def requeue(self, batch, lane: DeviceLane, tried: set) -> bool:
        """Drain a failing lane's in-flight batch to a healthy sibling
        (engine._run_batch's pool path, on dispatch failure or breaker
        denial). ``tried`` accumulates the lane indices that already
        failed this batch so it can never bounce forever. Returns False
        when no healthy untried sibling exists — the caller falls back
        to the engine's salvage/degrade machinery."""
        eng = self._engine
        tried.add(lane.index)
        op = batch[0].key[0]
        rows = sum(r.rows for r in batch)
        backend = eng._BACKEND_OF.get(op) if eng is not None else None
        with self._cond:
            if self._closed:
                return False
            sibs = [ln for ln in self.lanes
                    if ln.index not in tried
                    and ln.breaker_state(backend) == "closed"]
            if not sibs:
                return False
            target = self._least_loaded(sibs)
            target.inflight_rows += rows
            target.requeues += 1
            self._seq += 1
            self._log.append((self._seq, op, len(batch), rows,
                              target.index, "requeue"))
            target.pending.append((batch, tried))
            self._cond.notify_all()
        # journal with the pool lock released (incident listeners read
        # engine/pool snapshots): the drain is exactly the black-box
        # moment a postmortem wants on the timeline
        _flight.note("pool", "requeue", op=op, rows=rows,
                     src=lane.index, dst=target.index)
        return True

    def _place_locked(self, op: str, rows: int, tried) -> tuple:
        """Pick the lane for a fresh placement (pool lock held).
        Deficit-weighted on in-flight device rows: least-loaded wins,
        ties by device index — no wallclock, no entropy. Lanes whose
        breaker for the op's backend is open are avoided, except that
        every ``probe_every``-th placement per op class is offered to
        the least-loaded open lane as a recovery probe (its breaker
        decides whether to admit); held lanes (SLO vacate) are never
        probed. With every breaker open the least-loaded open lane is
        picked anyway — its denial path degrades to CPU."""
        eng = self._engine
        backend = eng._BACKEND_OF.get(op) if eng is not None else None
        lanes = [ln for ln in self.lanes if ln.index not in tried]
        healthy = [ln for ln in lanes
                   if ln.breaker_state(backend) == "closed"]
        tripped = [ln for ln in lanes
                   if ln.breaker_state(backend) == "open"]
        if healthy and tripped:
            tick = self._probe_tick.get(op, 0) + 1
            self._probe_tick[op] = tick
            if tick % self.probe_every == 0:
                return self._least_loaded(tripped), "probe"
        if healthy:
            return self._least_loaded(healthy), "least-loaded"
        return self._least_loaded(lanes), "all-open"

    @staticmethod
    def _least_loaded(lanes: list) -> DeviceLane:
        return min(lanes, key=lambda ln: (ln.inflight_rows, ln.index))

    # -- lane workers --------------------------------------------------------
    def _worker(self, lane: DeviceLane) -> None:
        while True:
            with self._cond:
                while not lane.pending and not self._closed:
                    self._cond.wait()
                if not lane.pending:
                    return            # closed and drained
                batch, tried = lane.pending.popleft()
            rows = sum(r.rows for r in batch)
            handed_off = False
            try:
                # the engine's batch runner does everything — breaker
                # gating, device placement, salvage, future resolution.
                # A truthy return means the batch was requeued to a
                # sibling: it is no longer this lane's (or, for
                # engine accounting, this dispatch's) responsibility.
                handed_off = bool(self._engine._run_batch(
                    batch, lane=lane, tried=tried))
            except BaseException as e:
                # an escape would kill this lane's worker — journal the
                # black-box moment first (same contract as the engine
                # batcher's escape note)
                _flight.note("pool", "escape", lane=lane.index,
                             error=repr(e))
                raise
            finally:
                with self._cond:
                    lane.inflight_rows -= rows
                    if not handed_off:
                        lane.batches += 1
                        lane.rows += rows
                if not handed_off:
                    self._engine._batch_done()

    # -- StreamingIngest placement -------------------------------------------
    def stream_entry(self, pipeline, batch: int,
                     pair_ids: bool = False) -> dict:
        """The (program, put, put_ids) kwargs that point a
        StreamingIngest at this pool's mesh: each staged batch's
        sharded ``device_put`` fans the segment axis across every
        lane in one transfer (parallel/mesh.py pool_stream_entry).
        ``batch`` must be divisible by the lane count."""
        from ..parallel.mesh import pool_stream_entry

        return pool_stream_entry(pipeline, self.devices(), batch,
                                 pair_ids)

    # -- introspection / lifecycle -------------------------------------------
    def placement_log(self) -> tuple:
        """The bounded placement log — ``(seq, op, members, rows,
        lane, reason)`` rows, count-sequenced. Same seed + same offered
        sequence reproduces it row for row (tests/test_pool.py)."""
        with self._mu:
            return tuple(self._log)

    def snapshot(self) -> dict:
        with self._mu:
            lanes = []
            for lane in self.lanes:
                lanes.append({
                    "device": lane.index,
                    "platform": getattr(lane.device, "platform", "?"),
                    "batches": lane.batches,
                    "rows": lane.rows,
                    "requeues": lane.requeues,
                    "inflight_rows": lane.inflight_rows,
                    "breakers": {b: m.state
                                 for b, m in lane.monitors.items()},
                })
            return {"n_devices": len(self.lanes),
                    "placements": self._seq,
                    "lanes": lanes}

    def metrics(self) -> dict[str, float]:
        """Flat per-device gauges for the ``/metrics`` exposition —
        the ``cess_engine_device_*`` family (merged by
        EngineStats.metrics)."""
        snap = self.snapshot()
        out = {"cess_engine_device_count": float(snap["n_devices"]),
               "cess_engine_device_placements": float(snap["placements"])}
        for lane in snap["lanes"]:
            i = lane["device"]
            for name in ("batches", "rows", "requeues", "inflight_rows"):
                out[f"cess_engine_device_{i}_{name}"] = float(lane[name])
            for backend, state in lane["breakers"].items():
                out[f"cess_engine_device_{i}_{backend}_open"] = \
                    0.0 if state == "closed" else 1.0
        return out

    def close(self, timeout: float | None = 5.0) -> None:
        """Stop the lane workers after they drain their pending
        batches (SubmissionEngine.close calls this)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        for lane in self.lanes:
            t = lane.thread
            if t is not None:
                t.join(timeout)
