"""Double-buffered host->device streaming driver for the flagship
encode+tag workload.

Every BASELINE metric is measured device-resident, but the real
OSS-gateway workload (SURVEY.md §3.2) ingests a STREAM of 16 MiB
segments from the host. Round-tripping each batch through the host
between encode and tag, and serializing transfer against compute,
throws away exactly the throughput the kernels won — erasure-coding
pipelines live or die on transfer/compute overlap once the kernel is
fast (PAPERS: "Accelerating XOR-based Erasure Coding using Program
Optimization Techniques"), and ragged batched TPU streams need
dedicated staging to keep the chip busy (PAPERS: "Ragged Paged
Attention ... for TPU").

:class:`StreamingIngest` drives the pipeline's FUSED encode+tag
program (models/pipeline.py ``fused_program``: one jitted call, the
segment buffer donated) over a host byte stream:

- each batch is staged ONCE with ``jax.device_put`` (one H2D copy from
  host bytes to device tags — the fused program never materializes an
  intermediate on the host);
- dispatch is asynchronous, so staging batch i+1 overlaps the device
  computing batch i (double buffering falls out of async dispatch +
  a bounded in-flight window: at most ``depth`` batches are enqueued
  before the driver blocks on the oldest);
- the ragged final batch is padded with zero segments to the SAME
  program shape (no tail recompile; every pipeline op is
  row-independent, so the pad rows are sliced off bit-exactly);
- every stage is counted in :class:`~cess_tpu.serve.stats.StreamStats`
  (staging time, dispatch time, stall time, pad waste) and exported
  through the engine's ``cess_engine_stream_*`` metrics when attached
  (SubmissionEngine.attach_stream).

Results are bit-identical to the direct per-step path
(``encode_step`` -> ``tag_step``) — tests/test_stream.py pins this on
both MAC limb widths, including the ragged tail.

For multi-chip meshes, cess_tpu/parallel/mesh.py ``stream_entry``
builds the (program, put, put_ids) triple that shards each staged
batch over (seg, byte); the driver is topology-agnostic.
"""
from __future__ import annotations

import collections
import time
from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from ..obs import flight as _flight
from ..obs import trace
from ..resilience import faults
from .engine import _pad_axis0
from .stats import StreamStats


def _as_host_array(source):
    """Coerce a whole-source 2-D array-like (jax.Array included) to a
    host ndarray in ONE fetch; anything else (a chunk iterable) passes
    through untouched. Shared by run()'s validation and _rebatch so
    the two paths can never accept different source types — and so a
    device-resident source is never iterated row-by-row (one blocking
    D2H per segment)."""
    if not isinstance(source, np.ndarray) \
            and getattr(source, "ndim", None) == 2:
        return np.asarray(source)
    return source


def _rebatch(source, batch: int) -> Iterator[np.ndarray]:
    """Yield [<=batch, seg] host chunks from an array or an iterable of
    row chunks (a network receive loop hands arbitrary-sized pieces)."""
    source = _as_host_array(source)
    if isinstance(source, np.ndarray):
        for start in range(0, source.shape[0], batch):
            yield source[start:start + batch]
        return
    pending: list[np.ndarray] = []
    rows = 0
    for piece in source:
        piece = np.asarray(piece)
        if piece.ndim == 1:
            piece = piece[None]
        pending.append(piece)
        rows += piece.shape[0]
        while rows >= batch:
            buf = np.concatenate(pending, axis=0) if len(pending) > 1 \
                else pending[0]
            yield buf[:batch]
            rest = buf[batch:]
            pending = [rest] if rest.shape[0] else []
            rows = rest.shape[0]
    if rows:
        yield np.concatenate(pending, axis=0) if len(pending) > 1 \
            else pending[0]


class StreamingIngest:
    """See module doc. One instance per stream source; safe to reuse
    for consecutive runs (counters accumulate across runs).

    pipeline: the StoragePipeline whose fused program to drive.
    batch:    segments per device batch (the compiled shape).
    depth:    in-flight window — batches enqueued on the device before
              the driver blocks on the oldest (2 = classic double
              buffering: one computing, one staged).
    program:  override the device program (fn(segments, ids) -> dict
              with "fragments"/"tags") — the mesh entry passes its
              shard_map'd step here.
    put / put_ids: override staging (default jax.device_put) — the
              mesh entry passes sharded placements.
    pool:     optional DevicePool (serve/pool.py) — device-aware
              placement: the driver derives its (program, put,
              put_ids) from ``pool.stream_entry``'s mesh over the
              pool's lanes, so each staged batch's sharded
              ``device_put`` fans the segment axis across every lane
              in one transfer. Explicit program/put overrides win;
              ``batch`` must be divisible by the lane count.
    engine:   optional SubmissionEngine to export stats through.
    tenant:   optional per-tenant accounting tag (obs/slo.py): with an
              attached engine carrying an SLO board, each staged batch
              is charged to this tenant under the ``stream`` class —
              the gateway ingest path's contribution to the same
              accounting its engine submits carry.
    """

    def __init__(self, pipeline, batch: int, *, depth: int = 2,
                 program=None, put=None, put_ids=None, stats=None,
                 engine=None, tenant: str | None = None, pool=None):
        if batch < 1 or depth < 1:
            raise ValueError(f"bad stream shape: batch={batch}, "
                             f"depth={depth}")
        if pool is not None and program is None:
            # device-aware placement: shard the staged batches over
            # the pool's lanes (the single-device default otherwise)
            entry = pool.stream_entry(pipeline, batch)
            program = entry["program"]
            put = put or entry["put"]
            put_ids = put_ids or entry["put_ids"]
        self.pipeline = pipeline
        self.batch = batch
        self.depth = depth
        self.stats = stats or StreamStats()
        self.tenant = tenant
        self._program = program
        self._put = put or jax.device_put
        self._put_ids = put_ids or self._put
        self._engine = engine
        if engine is not None:
            engine.attach_stream(self.stats)

    def detach(self) -> None:
        """Stop contributing to the attached engine's merged
        cess_engine_stream_* gauges (call when this stream source is
        done; idempotent, no-op without an engine). Construct ONE
        driver per long-lived source rather than one per request —
        attachments are summed, not replaced."""
        if self._engine is not None:
            self._engine.detach_stream(self.stats)
            self._engine = None

    # ------------------------------------------------------------------
    def run(self, segments, fragment_ids=None) -> Iterator[dict]:
        """Stream host segments through the device; yield per-batch
        ``{"fragments", "tags", "rows"}`` dicts of DEVICE arrays
        (ragged tail already sliced to its real rows). Each yielded
        batch is complete on device (the in-flight throttle blocks
        before yielding), so consumers never observe partial results.

        segments: [N, segment_size] uint8 host array, or an iterable
        of row chunks (rebatched internally). fragment_ids: optional
        [N, k+m] or [N, k+m, 2] array (requires an array source); None
        uses the bench/demo arange over the global row index — exactly
        the default the direct path would use over the whole array.

        Input validation happens HERE, at call time (run() is a plain
        method delegating to an inner generator), so a bad call fails
        at its own site rather than at the consumer's first next().
        """
        if fragment_ids is not None:
            segments = _as_host_array(segments)
            if not isinstance(segments, np.ndarray) \
                    or segments.ndim != 2:
                # a generator/chunked source cannot be lined up with a
                # pre-shaped id array — reject loudly instead of the
                # opaque shape errors np coercion would produce
                raise ValueError(
                    "fragment_ids requires an [N, segment_size] array "
                    "segment source, not a chunked/iterator source")
            fragment_ids = np.asarray(fragment_ids)
            if fragment_ids.shape[0] != segments.shape[0]:
                raise ValueError("fragment_ids rows != segments rows")
        return self._run(segments, fragment_ids)

    def _tracer_now(self):
        """Tracer serving this run: the attached engine's pinned one,
        else the process-armed tracer (obs.trace), else None."""
        if self._engine is not None and self._engine.tracer is not None:
            return self._engine.tracer
        return trace.armed_tracer()

    @staticmethod
    def _step_annotation(tracer, step: int):
        """XLA-profile alignment for the streamed path: each batch
        dispatch runs under a jax.profiler.StepTraceAnnotation, so the
        profiler's per-step view matches the driver's batch spans."""
        if tracer is None or not tracer.jax_annotations:
            return None
        annotation = getattr(jax.profiler, "StepTraceAnnotation", None)
        return None if annotation is None \
            else annotation("cess_stream", step_num=step)

    def _run(self, segments, fragment_ids) -> Iterator[dict]:
        cfg = self.pipeline.config
        rows = cfg.k + cfg.m
        program = self._program or self.pipeline.fused_program()
        st = self.stats
        t_run = time.perf_counter()
        inflight: collections.deque = collections.deque()
        run_span = trace.NOOP_SPAN
        batches = stalls = 0

        def drain_one():
            nonlocal stalls
            out, real = inflight.popleft()
            t0 = time.perf_counter()
            jax.block_until_ready(out["tags"])
            stall = time.perf_counter() - t0
            st.stall_s += stall
            stalls += 1
            if run_span is not trace.NOOP_SPAN:
                run_span.event("stall", s=round(stall, 6))
            if real < self.batch:
                out = {k: v[:real] for k, v in out.items()}
            out["rows"] = real
            return out

        try:
            tracer = self._tracer_now()
            if tracer is not None:
                run_span = tracer.start("stream.run", sys="stream",
                                        batch=self.batch,
                                        depth=self.depth)
            seg_off = 0
            for chunk in _rebatch(segments, self.batch):
                # enforce the in-flight window BEFORE staging the next
                # batch: at most ``depth`` batches are ever enqueued
                # (depth=2 = one computing + one staged), which is what
                # bounds in-flight device memory
                while len(inflight) >= self.depth:
                    yield drain_one()
                chunk = np.ascontiguousarray(chunk, dtype=np.uint8)
                real = chunk.shape[0]
                pad = 0
                if real < self.batch:          # ragged tail: pad, reuse
                    chunk = _pad_axis0(chunk, self.batch)
                    pad = self.batch - real
                    st.padded_segments += pad
                if fragment_ids is None:
                    ids = np.arange(seg_off * rows,
                                    (seg_off + self.batch) * rows,
                                    dtype=np.int32)
                else:
                    ids = _pad_axis0(fragment_ids[seg_off:seg_off + real],
                                     self.batch)
                bspan = trace.NOOP_SPAN if tracer is None \
                    else tracer.start("stream.batch", sys="stream",
                                      parent=run_span, rows=real,
                                      pad=pad)
                try:
                    bt0 = t0 = time.perf_counter()
                    faults.inject("stream.h2d")   # chaos seam: staging
                    dev = self._put(chunk)
                    ids_dev = self._put_ids(ids)
                    h2d = time.perf_counter() - t0
                    st.h2d_s += h2d
                    t0 = time.perf_counter()
                    faults.inject("stream.dispatch")  # chaos: launch
                    ann = self._step_annotation(tracer, st.batches)
                    if ann is None:
                        out = program(dev, ids_dev)
                    else:
                        with ann:
                            out = program(dev, ids_dev)
                except BaseException as e:
                    # a staging/dispatch failure (fault injection, OOM)
                    # must still land the batch span in the ring, error
                    # attached — a traced chaos run shows WHICH batch
                    # died, not a silent hole in the export — and burn
                    # the stream SLO's error budget like any engine
                    # failure (_observe_failure): a stream that died
                    # must not scrape as a clean SLO
                    if bspan is not trace.NOOP_SPAN:
                        bspan.set(error=repr(e)).finish()
                    eng = self._engine
                    if eng is not None and eng.slo is not None:
                        eng.slo.observe("stream",
                                        time.perf_counter() - bt0,
                                        ok=False, tenant=self.tenant,
                                        rows=real)
                    # black-box journal: the exception is about to
                    # escape the stream driver — an incident trigger
                    _flight.note("stream", "escape", error=repr(e))
                    raise
                dispatch = time.perf_counter() - t0
                st.dispatch_s += dispatch
                st.hist.observe(h2d + dispatch)
                # SLO/tenant feed (obs/slo.py): streamed batches ride
                # the attached engine's board under the "stream" class
                # (targetable like any op class); one attribute chain
                # + None check when no board is configured
                eng = self._engine
                if eng is not None and eng.slo is not None:
                    eng.slo.observe("stream", h2d + dispatch,
                                    tenant=self.tenant, rows=real)
                # continuous-profiling feed (obs/profile.py): the
                # ragged tail's pad rides the SAME PadLedger as the
                # engine's bucket padding — one end-to-end pad bill
                if eng is not None and eng.profile is not None:
                    eng.profile.on_stream(
                        batch=self.batch, rows=real,
                        nbytes=real * cfg.segment_size,
                        h2d_s=h2d, dispatch_s=dispatch)
                if bspan is not trace.NOOP_SPAN:
                    bspan.finish(h2d_s=round(h2d, 6),
                                 dispatch_s=round(dispatch, 6))
                st.batches += 1
                batches += 1
                st.segments += real
                st.bytes_in += real * cfg.segment_size
                seg_off += self.batch
                inflight.append((out, real))
            while inflight:
                yield drain_one()
        finally:
            st.wall_s += time.perf_counter() - t_run
            if run_span is not trace.NOOP_SPAN:
                run_span.finish(batches=batches, stalls=stalls)

    def ingest(self, segments, fragment_ids=None) -> dict:
        """Run the whole stream and concatenate the per-batch device
        results — the convenience form for callers that want the full
        ``forward``-shaped output without managing the generator."""
        outs = list(self.run(segments, fragment_ids))
        if not outs:
            raise ValueError("empty segment stream")
        return {"fragments": jnp.concatenate([o["fragments"]
                                              for o in outs], axis=0),
                "tags": jnp.concatenate([o["tags"] for o in outs],
                                        axis=0)}
