"""Trace-driven adaptive control: batching knobs tuned from the live
latency signal, and SLO-gated admission.

PR 1 fixed the batching constants (`AdmissionPolicy`: one max_delay,
one row budget, for every class); PR 5 made the cost of those
constants visible (queue-wait, occupancy, pad-waste per request).
This module closes the loop — the continuous-batching insight from
LLM serving (admit-until-deadline, PAPERS.md Ragged Paged Attention)
applied to the RS/PoDR2 classes:

- :class:`AdaptiveBatchPolicy` owns PER-CLASS batching knobs
  (max_delay / max_batch_requests / max_batch_rows) seeded from the
  static policy and adjusted AIMD-style from the live observations.
  Occupancy-targeting: when a class's p99 clears its target with
  headroom AND batches are running under-occupied, the coalescing
  delay GROWS (more batching, better device efficiency); the moment
  p99 crosses the target the delay shrinks multiplicatively (latency
  wins). Updates advance on observation count — no wall clock — so
  replayed workloads adapt identically given identical latencies.
- :class:`AdmissionController` extends the PR-4 breaker from "device
  broken" to "SLO at risk": registered as a listener on the SLO board
  (obs/slo.py), a *protected* class entering ``burning`` makes the
  controller (a) SHED sheddable-class submits (`EngineShed` — explicit
  backpressure, same family as EngineSaturated) and (b) latch the
  codec breaker open (`HealthMonitor.hold_open`) so surviving bulk
  load serves on the bit-identical CPU reference path, freeing the
  device for the protected class. Both release when the protected
  class recovers to ``ok`` (hysteresis: ``warn`` keeps protection).
  Independent of burn state, admission is deadline-aware: a sheddable
  request whose deadline is already below the class's live p99
  estimate is rejected at submit instead of timing out in the queue
  (the engine never spends queue slots on work it cannot deliver).
  ``attach_fleet`` widens the trigger set from the local board to a
  FleetBoard global view (obs/fleet.py): a quorum of the fleet burning
  on a protected class engages the identical response, which is how a
  multi-host deployment turns the federated SLO picture into
  backpressure at every gateway.

Both objects are opt-in (`make_engine(slo=..., adaptive=...)`,
``node.cli --slo --adaptive``) and cost nothing when absent: the
engine's disabled paths are one attribute load + None check, exactly
the NOOP_SPAN / faults contract.

Lock order (cesslint lock-discipline scans this package): the engine
lock may nest over this module's locks (knob reads from the batcher,
admission checks from submitters) and this module's locks may nest
over a HealthMonitor's — never the reverse on either edge.
"""
from __future__ import annotations

import collections
import threading

from ..obs import flight as _flight
from .policy import AdmissionPolicy


class AdaptiveBatchPolicy:
    """Per-class batching knobs, latency/occupancy-tuned. See module
    doc.

    policy:        the static AdmissionPolicy supplying seeds + caps.
    board:         optional obs.SloBoard — classes with an SLO target
                   adapt toward (headroom * p99 objective); others
                   stay on the static constants.
    targets:       explicit {cls: p99_seconds} overrides (take
                   precedence over board targets).
    update_every:  observations of a class between knob updates.
    window:        latency/occupancy observations retained per class.
    min_delay_s:   floor the coalescing delay can shrink to.
    delay_cap_s:   ceiling it can grow to (default 8x the static).
    headroom:      fraction of the target the p99 estimate must stay
                   under before the delay may grow.
    occupancy_target: mean batch occupancy below which growing the
                   delay is worthwhile (more coalescing wanted).
    """

    def __init__(self, policy: AdmissionPolicy | None = None, *,
                 board=None, targets: dict | None = None,
                 update_every: int = 16, window: int = 128,
                 min_delay_s: float = 5e-4,
                 delay_cap_s: float | None = None,
                 shrink: float = 0.5, grow: float = 1.25,
                 headroom: float = 0.25, occupancy_target: float = 4.0,
                 min_rows: int = 8, max_adjustments: int = 256):
        if update_every < 1 or window < update_every:
            raise ValueError("invalid adaptive update bounds")
        if not 0 < shrink < 1 or grow <= 1 or not 0 < headroom < 1:
            raise ValueError("invalid adaptive gain bounds")
        self.policy = policy or AdmissionPolicy()
        self.board = board
        self.targets = dict(targets or {})
        self.update_every = update_every
        self.window = window
        self.min_delay_s = min_delay_s
        self.delay_cap_s = delay_cap_s \
            if delay_cap_s is not None else self.policy.max_delay * 8
        self.shrink = shrink
        self.grow = grow
        self.headroom = headroom
        self.occupancy_target = occupancy_target
        self.min_rows = min_rows
        self._mu = threading.Lock()
        self._classes: dict[str, dict] = {}
        self._adjustments: collections.deque = collections.deque(
            maxlen=max_adjustments)

    def target_for(self, cls: str) -> float | None:
        """The p99 objective steering this class, or None (static)."""
        if cls in self.targets:
            return self.targets[cls]
        if self.board is not None:
            for t in self.board.targets:
                if t.cls == cls:
                    return t.p99_s
        return None

    def _state_locked(self, cls: str) -> dict:
        st = self._classes.get(cls)
        if st is None:
            pol = self.policy
            st = self._classes[cls] = {
                "delay": pol.max_delay,
                "reqs": pol.max_batch_requests,
                "rows": pol.max_batch_rows,
                "lats": collections.deque(maxlen=self.window),
                "occs": collections.deque(maxlen=self.window),
                "count": 0,
                "p99": 0.0,
                "adjustments": 0,
            }
        return st

    # -- the engine's read side (batcher thread, under the engine lock) ------
    def knobs(self, cls: str) -> tuple[float, int, int]:
        """(max_delay, max_batch_requests, max_batch_rows) for this
        class right now."""
        with self._mu:
            st = self._state_locked(cls)
            return st["delay"], st["reqs"], st["rows"]

    def p99_est(self, cls: str) -> float:
        """Live p99 estimate from the class's window (0.0 until the
        first update) — the deadline-aware admission signal."""
        with self._mu:
            st = self._classes.get(cls)
            return 0.0 if st is None else st["p99"]

    # -- the engine's write side (batcher thread, outside the lock) ----------
    def note(self, cls: str, latency_s: float, occupancy: int = 1) -> None:
        """One resolved request's submit->resolve latency + its batch
        occupancy; every ``update_every``-th observation of a targeted
        class re-tunes the knobs."""
        adjusted = None
        with self._mu:
            st = self._state_locked(cls)
            st["lats"].append(latency_s)
            st["occs"].append(occupancy)
            st["count"] += 1
            if st["count"] % self.update_every:
                return
            lats = sorted(st["lats"])
            st["p99"] = lats[min(len(lats) - 1,
                                 int(0.99 * len(lats)))]
            target = self.target_for(cls)
            if target is None:
                return
            occ = sum(st["occs"]) / len(st["occs"])
            pol = self.policy
            delay, rows = st["delay"], st["rows"]
            if st["p99"] > target:
                # over target: multiplicative backoff — smaller
                # batches sooner beats fuller batches later
                delay = max(self.min_delay_s, delay * self.shrink)
                rows = max(self.min_rows, rows // 2)
            elif st["p99"] < target * (1.0 - self.headroom) \
                    and occ < self.occupancy_target:
                # comfortable headroom AND under-occupied batches:
                # trade some of the slack for coalescence
                delay = min(self.delay_cap_s, delay * self.grow)
                rows = min(pol.max_batch_rows, rows * 2)
            if (delay, rows) != (st["delay"], st["rows"]):
                st["delay"], st["rows"] = delay, rows
                st["adjustments"] += 1
                adjusted = (cls, st["count"], round(st["p99"], 6),
                            round(delay, 6), rows)
                self._adjustments.append(adjusted)
        if adjusted is not None:
            # journal the knob change OUTSIDE self._mu (listener
            # bundles read snapshot(), which takes it)
            _flight.note("adaptive", "adjust", cls=adjusted[0],
                         count=adjusted[1], p99=adjusted[2],
                         delay=adjusted[3], rows=adjusted[4])

    # -- introspection -------------------------------------------------------
    def adjustment_log(self) -> tuple:
        """(cls, observation_count, p99_est, new_delay, new_rows) per
        knob change, newest ``max_adjustments`` kept."""
        with self._mu:
            return tuple(self._adjustments)

    def snapshot(self) -> dict:
        with self._mu:
            out = {}
            for cls, st in self._classes.items():
                out[cls] = {
                    "delay_s": round(st["delay"], 6),
                    "max_batch_requests": st["reqs"],
                    "max_batch_rows": st["rows"],
                    "p99_est_s": round(st["p99"], 6),
                    "target_s": self.target_for(cls),
                    "observations": st["count"],
                    "adjustments": st["adjustments"],
                }
            return out

    def metrics(self) -> dict[str, float]:
        """Flat gauges merged into the cess_engine_* exposition."""
        out = {}
        for cls, st in self.snapshot().items():
            out[f"cess_adaptive_{cls}_delay_s"] = float(st["delay_s"])
            out[f"cess_adaptive_{cls}_max_batch_rows"] = \
                float(st["max_batch_rows"])
            out[f"cess_adaptive_{cls}_p99_est_s"] = \
                float(st["p99_est_s"])
            out[f"cess_adaptive_{cls}_adjustments_total"] = \
                float(st["adjustments"])
        return out


class AdmissionController:
    """SLO-gated, deadline-aware admission. See module doc.

    board:    the obs.SloBoard whose transitions drive protection.
    adaptive: optional AdaptiveBatchPolicy supplying the live p99
              estimate for the deadline check.
    protect:  classes whose ``burning`` state engages protection.
    shed:     classes rejected (EngineShed) while protection is
              engaged — bulk load the protected classes outrank.
    degrade:  latch the engine's codec breaker open while engaged
              (surviving sheddable batches serve on the bit-identical
              CPU reference), when the engine has one (resilience
              configured); shed-only otherwise.
    """

    def __init__(self, board, adaptive: AdaptiveBatchPolicy | None = None,
                 *, protect: tuple = ("verify",),
                 shed: tuple = ("encode",), degrade: bool = True):
        self.board = board
        self.adaptive = adaptive
        self.protect = tuple(protect)
        self.shed = tuple(shed)
        self.degrade = degrade
        self._mu = threading.Lock()
        self._burning: set[str] = set()
        self._engaged = False
        self._monitors: list = []
        self._holds = 0
        self._releases = 0
        self._sheds: dict[str, dict[str, int]] = {}
        self._fleet_view: str | None = None
        board.add_listener(self._on_transition)

    def attach_fleet(self, fleet_board, *, view: str = "quorum") -> None:
        """Extend protection fleet-wide: subscribe to an
        obs.fleet.FleetBoard so a ``burning`` transition of the chosen
        global view (``quorum`` by default — a strict majority of nodes
        burning; ``worst`` for any single node) on a protected class
        engages the same shed/degrade response as a local transition.
        Fleet triggers are tracked as ``fleet:<cls>`` keys alongside the
        local ones, so protection releases only when BOTH the local
        board and the fleet view have recovered to ``ok``."""
        self._fleet_view = view
        fleet_board.add_listener(self._on_fleet_transition)

    def bind(self, engine) -> None:
        """Attach to an engine: grab the breakers the degrade response
        latches (the codec backend gates the sheddable bulk classes).
        Called by the engine constructor."""
        mon = engine.monitors.get("codec")
        self._monitors = [mon] if (self.degrade and mon is not None) \
            else []

    # -- the SLO board's listener seam ---------------------------------------
    def _on_transition(self, cls: str, old: str, new: str) -> None:
        if cls not in self.protect:
            return
        self._apply(cls, new, f"slo:{cls}")

    # -- the fleet board's listener seam (attach_fleet) ----------------------
    def _on_fleet_transition(self, cls: str, view: str, old: str,
                             new: str) -> None:
        if view != self._fleet_view or cls not in self.protect:
            return
        self._apply(f"fleet:{cls}", new, f"fleet:{cls}")

    def _apply(self, key: str, new: str, hold_reason: str) -> None:
        engage = release = False
        with self._mu:
            if new == "burning":
                self._burning.add(key)
                if not self._engaged:
                    self._engaged = engage = True
                    self._holds += 1
            elif new == "ok":
                self._burning.discard(key)
                if self._engaged and not self._burning:
                    self._engaged = False
                    release = True
                    self._releases += 1
        # breaker calls OUTSIDE this lock (lock order: controller ->
        # monitor, and never while more than one is held)
        if engage:
            for mon in self._monitors:
                mon.hold_open(hold_reason)
        if release:
            for mon in self._monitors:
                mon.release()

    # -- the engine's submit seam --------------------------------------------
    def admit(self, cls: str, timeout_s: float | None,
              tenant: str | None = None,
              queued: "int | None" = None) -> str | None:
        """None to admit, or the shed reason. Consulted by the engine
        before a sheddable request is queued. ``queued`` is the
        class's current backlog depth (None = unknown: assume one)."""
        if cls not in self.shed:
            return None
        reason = None
        with self._mu:
            if self._engaged:
                reason = "slo-burning"
        if reason is None and self.adaptive is not None \
                and timeout_s is not None \
                and (queued is None or queued > 0):
            # deadline-aware: the class's live p99 already exceeds
            # this request's whole budget — queueing it only converts
            # a fast rejection into a slow EngineTimeout. Only with a
            # BACKLOG, though: p99_est is refreshed by served requests
            # alone, so shedding on an idle class would let a stale
            # spike estimate reject everything forever (the served
            # request is also what ages the estimate back down)
            est = self.adaptive.p99_est(cls)
            if est > timeout_s:
                reason = "deadline-unmeetable"
        if reason is not None:
            with self._mu:
                per = self._sheds.setdefault(cls, {})
                per[reason] = per.get(reason, 0) + 1
            self.board.note_shed(cls, tenant)
        return reason

    @property
    def engaged(self) -> bool:
        with self._mu:
            return self._engaged

    def snapshot(self) -> dict:
        with self._mu:
            return {
                "engaged": self._engaged,
                "burning": sorted(self._burning),
                "holds": self._holds,
                "releases": self._releases,
                "sheds": {cls: dict(r)
                          for cls, r in sorted(self._sheds.items())},
                "protect": list(self.protect),
                "shed_classes": list(self.shed),
                "degrade": bool(self._monitors),
                "fleet_view": self._fleet_view,
            }
