"""Engine counters: queue depth, batch occupancy, pad waste, latency.

The serving layer is only tunable if its behavior is visible — the
reference threads a Prometheus registry through every subsystem
(node/src/service.rs:109-151), and the engine exports through the same
surface: ``node/metrics.py`` merges :meth:`EngineStats.metrics` into
the ``/metrics`` exposition when a node has an engine attached, and
the RPC debug endpoint ``cess_engineStats`` serves the raw snapshot.

Everything here is updated under the engine lock by design (the
batcher and submitters already hold it at every recording site), so
the counters need no locking of their own.
"""
from __future__ import annotations

import collections

from ..obs import prom
from . import policy

LATENCY_WINDOW = 512     # per-class sliding window for percentiles


class ClassStats:
    __slots__ = ("submitted", "completed", "failed", "timeouts",
                 "saturated", "shed", "batches", "batched_requests",
                 "rows", "padded_rows", "latencies", "hist")

    def __init__(self):
        self.submitted = 0          # requests admitted to the queue
        self.completed = 0          # futures resolved with a result
        self.failed = 0             # futures resolved with an op error
        self.timeouts = 0           # cancelled: deadline expired queued
        self.saturated = 0          # rejected at submit: queue full
        self.shed = 0               # rejected by SLO-gated admission
        self.batches = 0            # device batches launched
        self.batched_requests = 0   # requests across those batches
        self.rows = 0               # real rows across those batches
        self.padded_rows = 0        # pad rows added to reach buckets
        self.latencies = collections.deque(maxlen=LATENCY_WINDOW)
        # real Prometheus histogram of the same submit->resolve
        # latencies: unlike the sliding-window percentiles above this
        # is mergeable across nodes/scrapes, rendered as cumulative
        # _bucket{le=...}/_sum/_count lines by node/metrics.py
        self.hist = prom.Histogram(prom.LATENCY_BUCKETS_S)

    # -- derived -----------------------------------------------------------
    @property
    def occupancy(self) -> float:
        """Mean requests coalesced per device batch."""
        return self.batched_requests / self.batches if self.batches else 0.0

    @property
    def pad_waste(self) -> float:
        """Fraction of device rows that were padding."""
        total = self.rows + self.padded_rows
        return self.padded_rows / total if total else 0.0

    def percentile(self, q: float) -> float:
        """q in [0, 1] over the sliding submit->resolve latency window."""
        if not self.latencies:
            return 0.0
        xs = sorted(self.latencies)
        return xs[min(len(xs) - 1, int(q * len(xs)))]


class StreamStats:
    """Per-stage counters for the double-buffered streaming driver
    (serve/stream.py). One instance per driver; attach to an engine
    (SubmissionEngine.attach_stream) to export through the same
    ``cess_engine_*`` exposition, prefixed ``cess_engine_stream_``.

    Reading the two stage clocks against wall time tells you where the
    streamed workload is bound:
    - ``stall_s`` is host time spent BLOCKED on device results (the
      in-flight throttle + final drain) — a high stall fraction means
      the device is saturated: good occupancy, compute-bound.
    - ``h2d_s`` is host time spent staging bytes to the device — a
    high h2d fraction with near-zero stall means the transfer side
    cannot keep the chip busy: transfer-bound, the overlap is the
    only thing hiding it.
    """

    _COUNTERS = ("batches", "segments", "padded_segments", "bytes_in",
                 "h2d_s", "dispatch_s", "stall_s", "wall_s")
    __slots__ = _COUNTERS + ("hist",)

    def __init__(self):
        self.batches = 0           # device batches dispatched
        self.segments = 0          # real segments ingested
        self.padded_segments = 0   # zero rows added to the ragged tail
        self.bytes_in = 0          # host bytes staged (real, not pad)
        self.h2d_s = 0.0           # host time in device_put staging
        self.dispatch_s = 0.0      # host time dispatching the program
        self.stall_s = 0.0         # host time blocked on device results
        self.wall_s = 0.0          # wall time of completed run() calls
        # per-batch host time (staging + dispatch) histogram — the
        # mergeable form beside the aggregate stage clocks above
        self.hist = prom.Histogram(prom.LATENCY_BUCKETS_S)

    def raw(self) -> dict:
        return {name: getattr(self, name) for name in self._COUNTERS}

    def snapshot(self) -> dict:
        return stream_gauges(self.raw())

    def metrics(self) -> dict[str, float]:
        return {f"cess_engine_stream_{k}": float(v)
                for k, v in self.snapshot().items()}


def stream_gauges(raw: dict) -> dict:
    """Derived per-stage gauges from raw StreamStats counters (shared
    by a single driver's snapshot and the engine's cross-stream sum)."""
    out = dict(raw)
    wall = raw["wall_s"]
    out["stall_frac"] = round(raw["stall_s"] / wall, 4) if wall else 0.0
    out["h2d_frac"] = round(raw["h2d_s"] / wall, 4) if wall else 0.0
    for k in ("h2d_s", "dispatch_s", "stall_s", "wall_s"):
        out[k] = round(out[k], 6)
    return out


class EngineStats:
    """One ClassStats per op class + engine-wide program-cache counts
    (+ any attached streaming drivers' stage counters)."""

    def __init__(self):
        self.classes = {c: ClassStats() for c in policy.CLASSES}
        self.programs_built = 0     # program-cache misses (compiles)
        self.programs_reused = 0    # program-cache hits
        self.streams: list[StreamStats] = []   # attached stream drivers
        # ResilienceStats (cess_tpu/resilience/stats.py) when the
        # engine is resilience-configured — duck-typed (snapshot()/
        # metrics()) so this module never imports the package
        self.resilience = None
        # SloBoard (obs/slo.py) / AdaptiveBatchPolicy (serve/
        # adaptive.py) when configured — same duck-typed contract;
        # the board's LABELED families render via the engine's
        # labeled_series()/labeled_histograms(), not these flat dicts
        self.slo = None
        self.adaptive = None
        # DevicePool (serve/pool.py) when the engine serves the
        # multi-chip plane — duck-typed (snapshot()/metrics()) like
        # the attachments above; exports the cess_engine_device_*
        # per-lane family
        self.pool = None
        # ProfilePlane (obs/profile.py) when the engine is profiled —
        # same duck-typed contract; exports the cess_profile_* family
        self.profile = None

    def snapshot(self, queue_depths: dict[str, int] | None = None) -> dict:
        """JSON-shaped dump for the RPC debug endpoint."""
        depths = queue_depths or {}
        out: dict = {"programs_built": self.programs_built,
                     "programs_reused": self.programs_reused,
                     "classes": {}}
        for cls, st in self.classes.items():
            out["classes"][cls] = {
                "queue_depth": depths.get(cls, 0),
                "submitted": st.submitted,
                "completed": st.completed,
                "failed": st.failed,
                "timeouts": st.timeouts,
                "saturated": st.saturated,
                "shed": st.shed,
                "batches": st.batches,
                "batch_occupancy": round(st.occupancy, 4),
                "pad_waste": round(st.pad_waste, 4),
                "latency_p50": round(st.percentile(0.50), 6),
                "latency_p99": round(st.percentile(0.99), 6),
            }
        if self.streams:
            out["streams"] = [s.snapshot() for s in self.streams]
        if self.resilience is not None:
            out["resilience"] = self.resilience.snapshot()
        if self.slo is not None:
            out["slo"] = self.slo.snapshot()
        if self.adaptive is not None:
            out["adaptive"] = self.adaptive.snapshot()
        if self.pool is not None:
            out["devices"] = self.pool.snapshot()
        if self.profile is not None:
            out["profile"] = self.profile.snapshot()
        return out

    def metrics(self, queue_depths: dict[str, int] | None = None
                ) -> dict[str, float]:
        """Flat Prometheus-style gauges (merged by node/metrics.py)."""
        snap = self.snapshot(queue_depths)
        out = {"cess_engine_programs_built": snap["programs_built"],
               "cess_engine_programs_reused": snap["programs_reused"]}
        for cls, st in snap["classes"].items():
            for name, val in st.items():
                out[f"cess_engine_{cls}_{name}"] = val
        if self.streams:
            # sum RAW counters across attached drivers, then derive —
            # adding per-driver fractions would be meaningless
            totals = self.streams[0].raw()
            for s in self.streams[1:]:
                for k, v in s.raw().items():
                    totals[k] += v
            for name, val in stream_gauges(totals).items():
                out[f"cess_engine_stream_{name}"] = float(val)
        if self.resilience is not None:
            # cess_resilience_* rides the same exposition (ISSUE 4:
            # retry/abandon/breaker gauges beside the engine family)
            out.update(self.resilience.metrics())
        if self.adaptive is not None:
            # cess_adaptive_* per-class knob/estimate gauges (ISSUE 6)
            out.update(self.adaptive.metrics())
        if self.pool is not None:
            # cess_engine_device_* per-lane placement/load/breaker
            # gauges (the multi-chip serving plane, serve/pool.py)
            out.update(self.pool.metrics())
        if self.profile is not None:
            # cess_profile_* continuous-profiling gauges (ISSUE 13)
            out.update(self.profile.metrics())
        return out

    def histograms(self) -> dict[str, prom.Histogram]:
        """Histogram families for the text exposition: one
        submit->resolve latency family per op class, plus the summed
        per-batch stream staging+dispatch family when drivers are
        attached (per-driver histograms share bounds, so the merge is
        exact — node/metrics.py renders these with
        ``# TYPE ... histogram``)."""
        out = {f"cess_engine_{cls}_latency_seconds": st.hist
               for cls, st in self.classes.items()}
        if self.streams:
            merged = prom.Histogram(prom.LATENCY_BUCKETS_S)
            for s in self.streams:
                merged.merge(s.hist)
            out["cess_engine_stream_batch_seconds"] = merged
        return out
