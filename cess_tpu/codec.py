"""Canonical deterministic binary codec — the framework's SCALE analog.

The reference serializes everything (extrinsics, blocks, storage) with
SCALE (parity-scale-codec). This framework needs the same property — a
byte-exact, deterministic encoding shared by signing payloads, the
gossip wire, and the on-disk block/state stores — without depending on
Python ``repr`` or pickle (non-canonical / unsafe to decode from
peers).

Encoding: 1-byte tag + payload. Lengths and ints are LEB128 varints
(ints zigzag-encoded, arbitrary precision). Dicts sort entries by
encoded key bytes; sets sort encoded items — so logically equal values
encode identically. Dataclasses are encoded by registered name + field
values in declaration order; decoding an unregistered name is an error
(no arbitrary-object construction from untrusted bytes, unlike pickle).

numpy arrays encode as (dtype, shape, raw bytes) — required for the
PoDR2 proof blobs whose wire size the chain's SIGMA_MAX cap measures.
"""
from __future__ import annotations

import dataclasses
from typing import Any

import numpy as np

_NONE, _FALSE, _TRUE, _INT, _BYTES, _STR, _TUPLE, _LIST, _DICT, _SET, \
    _DATACLASS, _NDARRAY = range(12)

_REGISTRY: dict[str, type] = {}


def register(cls):
    """Class decorator: make a dataclass codec-encodable by name."""
    name = cls.__name__
    if name in _REGISTRY and _REGISTRY[name] is not cls:
        raise ValueError(f"codec name collision: {name}")
    _REGISTRY[name] = cls
    return cls


class CodecError(ValueError):
    pass


# Nesting bound for both encode and decode: a 2 KiB blob (SIGMA_MAX)
# can otherwise nest ~1024 one-element tuples and blow the Python
# recursion limit — RecursionError from a peer-supplied proof must not
# crash the TEE worker or prevent block-log replay. 32 is far above any
# legitimate protocol structure (extrinsics nest ~4 deep).
MAX_DEPTH = 32


# -- varints -----------------------------------------------------------------
def _write_uvarint(out: bytearray, n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return


def _write_varint(out: bytearray, n: int) -> None:
    _write_uvarint(out, (n << 1) ^ (n >> (n.bit_length() + 1)) if n < 0
                   else n << 1)


def _read_uvarint(data: bytes, pos: int) -> tuple[int, int]:
    n = shift = 0
    while True:
        if pos >= len(data):
            raise CodecError("truncated varint")
        b = data[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def _read_varint(data: bytes, pos: int) -> tuple[int, int]:
    u, pos = _read_uvarint(data, pos)
    return (u >> 1) ^ -(u & 1), pos


# -- encode ------------------------------------------------------------------
def _encode_one(obj: Any, depth: int) -> bytes:
    out = bytearray()
    _encode_into(out, obj, depth)
    return bytes(out)


def _encode_into(out: bytearray, obj: Any, depth: int = 0) -> None:
    if depth > MAX_DEPTH:
        raise CodecError("nesting too deep")
    if obj is None:
        out.append(_NONE)
    elif obj is True:
        out.append(_TRUE)
    elif obj is False:
        out.append(_FALSE)
    elif isinstance(obj, int) and not isinstance(obj, bool):
        out.append(_INT)
        _write_varint(out, obj)
    elif isinstance(obj, (bytes, bytearray)):
        out.append(_BYTES)
        _write_uvarint(out, len(obj))
        out.extend(obj)
    elif isinstance(obj, str):
        raw = obj.encode("utf-8")
        out.append(_STR)
        _write_uvarint(out, len(raw))
        out.extend(raw)
    elif isinstance(obj, np.ndarray):
        out.append(_NDARRAY)
        dt = np.dtype(obj.dtype).str.encode()
        _write_uvarint(out, len(dt))
        out.extend(dt)
        _write_uvarint(out, obj.ndim)
        for d in obj.shape:
            _write_uvarint(out, d)
        raw = np.ascontiguousarray(obj).tobytes()
        _write_uvarint(out, len(raw))
        out.extend(raw)
    elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        name = type(obj).__name__
        if _REGISTRY.get(name) is not type(obj):
            raise CodecError(f"unregistered dataclass: {name}")
        out.append(_DATACLASS)
        raw = name.encode()
        _write_uvarint(out, len(raw))
        out.extend(raw)
        fields = dataclasses.fields(obj)
        _write_uvarint(out, len(fields))
        for f in fields:
            _encode_into(out, getattr(obj, f.name), depth + 1)
    elif isinstance(obj, tuple):
        out.append(_TUPLE)
        _write_uvarint(out, len(obj))
        for item in obj:
            _encode_into(out, item, depth + 1)
    elif isinstance(obj, list):
        out.append(_LIST)
        _write_uvarint(out, len(obj))
        for item in obj:
            _encode_into(out, item, depth + 1)
    elif isinstance(obj, dict):
        entries = sorted((_encode_one(k, depth + 1), _encode_one(v, depth + 1))
                         for k, v in obj.items())
        out.append(_DICT)
        _write_uvarint(out, len(entries))
        for ek, ev in entries:
            out.extend(ek)
            out.extend(ev)
    elif isinstance(obj, (set, frozenset)):
        entries = sorted(_encode_one(i, depth + 1) for i in obj)
        out.append(_SET)
        _write_uvarint(out, len(entries))
        for e in entries:
            out.extend(e)
    else:
        raise CodecError(f"unencodable type: {type(obj).__name__}")


def encode(obj: Any) -> bytes:
    out = bytearray()
    _encode_into(out, obj)
    return bytes(out)


# -- decode ------------------------------------------------------------------
def _read_raw(data: bytes, pos: int) -> tuple[bytes, int]:
    n, pos = _read_uvarint(data, pos)
    if pos + n > len(data):
        raise CodecError("truncated payload")
    return data[pos:pos + n], pos + n


def _decode_at(data: bytes, pos: int,
               depth: int = 0) -> tuple[Any, int]:
    if depth > MAX_DEPTH:
        raise CodecError("nesting too deep")
    if pos >= len(data):
        raise CodecError("truncated value")
    tag = data[pos]
    pos += 1
    if tag == _NONE:
        return None, pos
    if tag == _TRUE:
        return True, pos
    if tag == _FALSE:
        return False, pos
    if tag == _INT:
        return _read_varint(data, pos)
    if tag == _BYTES:
        return _read_raw(data, pos)
    if tag == _STR:
        raw, pos = _read_raw(data, pos)
        return raw.decode("utf-8"), pos
    if tag == _NDARRAY:
        dt, pos = _read_raw(data, pos)
        ndim, pos = _read_uvarint(data, pos)
        shape = []
        for _ in range(ndim):
            d, pos = _read_uvarint(data, pos)
            shape.append(d)
        raw, pos = _read_raw(data, pos)
        # untrusted input: dtype strings and shape/byte-count mismatches
        # must surface as CodecError, not numpy ValueError/TypeError
        try:
            arr = np.frombuffer(raw, dtype=np.dtype(dt.decode())) \
                .reshape(shape)
        except (ValueError, TypeError) as e:
            raise CodecError(f"bad ndarray: {e}") from None
        return arr.copy(), pos
    if tag == _DATACLASS:
        raw, pos = _read_raw(data, pos)
        cls = _REGISTRY.get(raw.decode())
        if cls is None:
            raise CodecError(f"unknown dataclass: {raw.decode()!r}")
        n, pos = _read_uvarint(data, pos)
        fields = dataclasses.fields(cls)
        if n != len(fields):
            raise CodecError(f"field count mismatch for {raw.decode()}")
        values = []
        for _ in range(n):
            v, pos = _decode_at(data, pos, depth + 1)
            values.append(v)
        return cls(*values), pos
    if tag in (_TUPLE, _LIST, _SET):
        n, pos = _read_uvarint(data, pos)
        items = []
        for _ in range(n):
            v, pos = _decode_at(data, pos, depth + 1)
            items.append(v)
        if tag == _TUPLE:
            return tuple(items), pos
        if tag == _SET:
            return frozenset(items), pos
        return items, pos
    if tag == _DICT:
        n, pos = _read_uvarint(data, pos)
        d = {}
        for _ in range(n):
            k, pos = _decode_at(data, pos, depth + 1)
            v, pos = _decode_at(data, pos, depth + 1)
            d[k] = v
        return d, pos
    raise CodecError(f"unknown tag: {tag}")


def decode(data: bytes) -> Any:
    obj, pos = _decode_at(data, 0)
    if pos != len(data):
        raise CodecError(f"trailing bytes: {len(data) - pos}")
    return obj
