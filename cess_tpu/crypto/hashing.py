"""Hash helpers (host side).

Fragment/segment hashes are 64-byte hex-digest identities in the
reference (primitives/common/src/lib.rs:56 Hash([u8;64]) — an ASCII
hex sha256); here hashes are raw 32-byte sha256 digests.
"""
from __future__ import annotations

import hashlib


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def blake2b_256(data: bytes) -> bytes:
    return hashlib.blake2b(data, digest_size=32).digest()


def fragment_hash(data: bytes) -> bytes:
    """The on-chain identity of a fragment (goes into SegmentInfo)."""
    return sha256(data)
