"""RSA PKCS#1 v1.5 signature verification (pure Python).

Replaces the reference's `rsa` crate usage for TEE message checks
(/root/reference/primitives/enclave-verify/src/lib.rs:221-228) and the
signature check half of the IAS report validation (:135-219). Modular
exponentiation on multi-thousand-bit ints is fast enough host-side;
this is control-plane work, not the TPU data plane.
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import codec

# DigestInfo prefixes (DER) for EMSA-PKCS1-v1_5
_DIGEST_PREFIX = {
    "sha256": bytes.fromhex("3031300d060960864801650304020105000420"),
    "sha384": bytes.fromhex("3041300d060960864801650304020205000430"),
    "sha512": bytes.fromhex("3051300d060960864801650304020305000440"),
}


@codec.register
@dataclasses.dataclass(frozen=True)
class RsaPublicKey:
    n: int
    e: int = 65537

    @property
    def byte_len(self) -> int:
        return (self.n.bit_length() + 7) // 8

    def fingerprint(self) -> bytes:
        material = self.n.to_bytes(self.byte_len, "big") \
            + self.e.to_bytes(4, "big")
        return hashlib.sha256(material).digest()


def rsa_verify_pkcs1v15(key: RsaPublicKey, message: bytes, signature: bytes,
                        hash_name: str = "sha256") -> bool:
    """RSASSA-PKCS1-v1_5 verification; constant-structure EM compare."""
    k = key.byte_len
    if len(signature) != k:
        return False
    s = int.from_bytes(signature, "big")
    if s >= key.n:
        return False
    em = pow(s, key.e, key.n).to_bytes(k, "big")
    h = hashlib.new(hash_name, message).digest()
    t = _DIGEST_PREFIX[hash_name] + h
    if k < len(t) + 11:
        return False
    expected = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
    return em == expected


# -- test/dev signing helper (the chain only ever verifies) -----------------

@dataclasses.dataclass(frozen=True)
class RsaKeyPair:
    n: int
    e: int
    d: int

    @property
    def public(self) -> RsaPublicKey:
        return RsaPublicKey(self.n, self.e)

    def sign_pkcs1v15(self, message: bytes, hash_name: str = "sha256") -> bytes:
        k = (self.n.bit_length() + 7) // 8
        h = hashlib.new(hash_name, message).digest()
        t = _DIGEST_PREFIX[hash_name] + h
        em = b"\x00\x01" + b"\xff" * (k - len(t) - 3) + b"\x00" + t
        return pow(int.from_bytes(em, "big"), self.d, self.n).to_bytes(k, "big")


def generate_rsa_keypair(bits: int = 2048, seed: int = 0) -> RsaKeyPair:
    """Deterministic test keypair (Miller-Rabin primes from a seeded
    stream). Dev/test only — not for production key material."""
    import random

    rng = random.Random(seed)

    def is_probable_prime(n: int, rounds: int = 40) -> bool:
        if n < 2:
            return False
        for p in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29, 31, 37):
            if n % p == 0:
                return n == p
        d, r = n - 1, 0
        while d % 2 == 0:
            d //= 2
            r += 1
        for _ in range(rounds):
            a = rng.randrange(2, n - 1)
            x = pow(a, d, n)
            if x in (1, n - 1):
                continue
            for _ in range(r - 1):
                x = x * x % n
                if x == n - 1:
                    break
            else:
                return False
        return True

    def gen_prime(b: int) -> int:
        while True:
            cand = rng.getrandbits(b) | (1 << (b - 1)) | 1
            if is_probable_prime(cand):
                return cand

    e = 65537
    while True:
        p = gen_prime(bits // 2)
        q = gen_prime(bits // 2)
        if p == q:
            continue
        phi = (p - 1) * (q - 1)
        if phi % e:
            d = pow(e, -1, phi)
            return RsaKeyPair(n=p * q, e=e, d=d)
