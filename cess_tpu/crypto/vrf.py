"""Verifiable random function from deterministic Ed25519 signatures.

The reference's RRSC consensus claims slots with sr25519 VRFs
(schnorrkel, external crate; SURVEY.md §2.3 forked-Substrate row).
Here: Ed25519 signatures are deterministic, so
``output = sha256(sign(input))`` is a VRF — unpredictable without the
secret key, verifiable by anyone with the public key, and unique per
(key, input) because RFC 8032 signatures are deterministic and the
verifier checks the signature before trusting the output.
"""
from __future__ import annotations

import dataclasses
import hashlib

from . import ed25519
from .. import codec


@codec.register
@dataclasses.dataclass(frozen=True)
class VrfProof:
    output: bytes      # 32 bytes, uniform
    signature: bytes   # 64-byte proof


def vrf_sign(key: ed25519.SigningKey, data: bytes) -> VrfProof:
    sig = key.sign(b"cess-vrf:" + data)
    return VrfProof(output=hashlib.sha256(sig).digest(), signature=sig)


def vrf_verify(public: bytes, data: bytes, proof: VrfProof) -> bool:
    if not ed25519.verify(public, b"cess-vrf:" + data, proof.signature):
        return False
    return hashlib.sha256(proof.signature).digest() == proof.output


def output_below(output: bytes, threshold_num: int, threshold_den: int) -> bool:
    """Slot lottery check: uniform output < c fraction of 2^128."""
    v = int.from_bytes(output[:16], "little")
    return v * threshold_den < (1 << 128) * threshold_num
