"""ECVRF over edwards25519 (RFC 9381 construction, try-and-increment).

The reference's RRSC consensus claims slots with sr25519/schnorrkel
VRFs (SURVEY.md §2.3 forked-Substrate row). Round 1 used
``sha256(ed25519_sig)`` as the VRF — broken, because Ed25519
signatures are malleable BY THE KEY HOLDER (any nonce r yields a valid
signature), letting a malicious authority grind slot lotteries.

This is a real VRF with verifier-enforced uniqueness:

    Gamma  = a · H          H = hash_to_curve(pk, input)
    output = SHA-512(suite ‖ 0x03 ‖ 8·Gamma)[:32]
    proof  = (Gamma, c, s)  a DLEQ proof that log_B(A) == log_H(Gamma)

``Gamma`` is a pure function of (secret key, input) — the prover has
no nonce freedom over it, and the DLEQ proof (c, s) binds Gamma to the
registered public key: U = s·B − c·A, V = s·H − c·Gamma,
c' = H2(H, Gamma, U, V) must equal c. Different (c, s) pairs for the
same key+input can exist, but they all carry the SAME Gamma and hence
the same output — re-rolling the lottery is impossible by construction
(tested in tests/test_node.py::test_vrf_uniqueness_under_nonce_grinding).
"""
from __future__ import annotations

import dataclasses
import functools
import hashlib

from .. import codec
from . import ed25519
from .ed25519 import L, P, _add, _compress, _decompress, _mul

SUITE = b"cess-ecvrf-ed25519-tai"
_IDENTITY = _compress((0, 1, 1, 0))


def _neg(p):
    x, y, z, t = p
    return ((-x) % P, y, z, (-t) % P)


def _cofactor_mul(p):
    for _ in range(3):
        p = _add(p, p)
    return p


def _hash_to_curve(public: bytes, data: bytes):
    """Try-and-increment (ECVRF-ED25519-SHA512-TAI): hash to candidate
    y-encodings until one decompresses; clear the cofactor so H is in
    the prime-order subgroup."""
    ctr = 0
    while True:
        h = hashlib.sha512(SUITE + b"\x01" + public + data
                           + ctr.to_bytes(4, "little")).digest()[:32]
        try:
            pt = _cofactor_mul(_decompress(h))
        except ValueError:
            ctr += 1
            continue
        if _compress(pt) != _IDENTITY:
            return pt
        ctr += 1


def _challenge(*points: bytes) -> int:
    h = hashlib.sha512(SUITE + b"\x02" + b"".join(points)).digest()
    return int.from_bytes(h[:16], "little")  # 128-bit challenge


def _output_from_gamma(gamma) -> bytes:
    return hashlib.sha512(SUITE + b"\x03"
                          + _compress(_cofactor_mul(gamma))).digest()[:32]


@codec.register
@dataclasses.dataclass(frozen=True)
class VrfProof:
    output: bytes     # 32 bytes, uniform; unique per (key, input)
    gamma: bytes      # compressed point a·H
    c: bytes          # 16-byte DLEQ challenge
    s: bytes          # 32-byte DLEQ response


def _derive_nonce(prefix: bytes, h_bytes: bytes) -> int:
    """Deterministic DLEQ nonce (tests monkeypatch this to demonstrate
    that nonce freedom cannot change the output; reusing a nonce
    across inputs leaks the key, so it is not caller-selectable)."""
    return int.from_bytes(hashlib.sha512(prefix + h_bytes).digest(),
                          "little") % L


def vrf_sign(key: ed25519.SigningKey, data: bytes) -> VrfProof:
    a, prefix = key._expanded
    public = key.public
    h_pt = _hash_to_curve(public, data)
    h_bytes = _compress(h_pt)
    gamma = _mul(a, h_pt)
    gamma_bytes = _compress(gamma)
    k = _derive_nonce(prefix, h_bytes)
    u = _compress(_mul(k))          # k·B
    v = _compress(_mul(k, h_pt))    # k·H
    c = _challenge(h_bytes, gamma_bytes, u, v)
    s = (k + c * a) % L
    return VrfProof(output=_output_from_gamma(gamma), gamma=gamma_bytes,
                    c=c.to_bytes(16, "little"), s=s.to_bytes(32, "little"))


def vrf_verify(public: bytes, data: bytes, proof: VrfProof) -> bool:
    """Memoized like :func:`ed25519.verify`: a slot claim's proof is a
    pure function of its inputs and every node on the network verifies
    the identical claim — the bounded cache collapses those re-checks
    at simulation scale (cess_tpu/sim) without changing any verdict."""
    try:
        return _vrf_verify_cached(public, data, proof)
    except TypeError:           # unhashable input shapes: verify raw
        return _vrf_verify(public, data, proof)


@functools.lru_cache(maxsize=16384)
def _vrf_verify_cached(public: bytes, data: bytes,
                       proof: VrfProof) -> bool:
    return _vrf_verify(public, data, proof)


def _vrf_verify(public: bytes, data: bytes, proof: VrfProof) -> bool:
    if not (isinstance(proof, VrfProof) and isinstance(proof.gamma, bytes)
            and isinstance(proof.c, bytes) and len(proof.c) == 16
            and isinstance(proof.s, bytes) and len(proof.s) == 32
            and isinstance(proof.output, bytes)
            and isinstance(public, bytes) and len(public) == 32):
        return False
    try:
        a_pt = _decompress(public)
        gamma = _decompress(proof.gamma)
    except ValueError:
        return False
    # ECVRF_validate_key (RFC 9381 §5.4.5): a small-order public key
    # (a = 0 in the cofactor-cleared subgroup) makes Gamma degenerate
    # and the output an input-INDEPENDENT constant — an attacker
    # registering the identity point would win every slot. Reject any
    # key or Gamma that cofactor-clears to the identity.
    if _compress(_cofactor_mul(a_pt)) == _IDENTITY \
            or _compress(_cofactor_mul(gamma)) == _IDENTITY:
        return False
    c = int.from_bytes(proof.c, "little")
    s = int.from_bytes(proof.s, "little")
    if s >= L:
        return False
    h_pt = _hash_to_curve(public, data)
    # U = s·B − c·A ; V = s·H − c·Gamma
    u = _add(_mul(s), _neg(_mul(c, a_pt)))
    v = _add(_mul(s, h_pt), _neg(_mul(c, gamma)))
    if _challenge(_compress(h_pt), proof.gamma, _compress(u),
                  _compress(v)) != c:
        return False
    return proof.output == _output_from_gamma(gamma)


def output_below(output: bytes, threshold_num: int, threshold_den: int) -> bool:
    """Slot lottery check: uniform output < c fraction of 2^128."""
    v = int.from_bytes(output[:16], "little")
    return v * threshold_den < (1 << 128) * threshold_num
