"""Minimal secp256k1 ECDSA: sign + public-key recovery (ecrecover).

Backs the EVM precompile at address 0x1 (the reference runs full
pallet-evm with Frontier's precompile set,
/root/reference/runtime/src/lib.rs:1310-1380). Pure Python over the
standard short-Weierstrass curve; affine arithmetic with modular
inverses is plenty for precompile call rates (ecrecover is priced at
3000 gas — the chain's own hot loops never touch this module).

Recovered "Ethereum address" derivation here is
sha3_256(x32 || y32)[12:] — NOT keccak256 — consistent with the
interpreter's documented SHA3 deviation (evm_interp.py): hash-derived
identities use the same hash family everywhere in this framework.
"""
from __future__ import annotations

import hashlib
import hmac

# curve: y^2 = x^3 + 7 over F_p
P = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
Gx = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
Gy = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def _add(p1, p2):
    """Affine point addition; None is the identity."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = (3 * x1 * x1) * _inv(2 * y1, P) % P
    else:
        lam = (y2 - y1) * _inv(x2 - x1, P) % P
    x3 = (lam * lam - x1 - x2) % P
    return x3, (lam * (x1 - x3) - y1) % P


def _mul(k: int, point):
    acc = None
    while k:
        if k & 1:
            acc = _add(acc, point)
        point = _add(point, point)
        k >>= 1
    return acc


def pubkey(secret: int):
    return _mul(secret % N, (Gx, Gy))


def _rfc6979_k(secret: int, msg_hash: bytes) -> int:
    """Deterministic nonce (RFC 6979, SHA-256): signing never needs an
    RNG, so tests and replicas are reproducible."""
    x = secret.to_bytes(32, "big")
    k, v = b"\x00" * 32, b"\x01" * 32
    k = hmac.new(k, v + b"\x00" + x + msg_hash, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + x + msg_hash, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 1 <= cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(secret: int, msg_hash: bytes) -> tuple[int, int, int]:
    """Returns (v, r, s) with v in {27, 28} and low-s normalization
    (what eth tooling produces and ecrecover expects)."""
    z = int.from_bytes(msg_hash, "big")
    while True:
        k = _rfc6979_k(secret, msg_hash)
        R = _mul(k, (Gx, Gy))
        r = R[0] % N
        if r == 0:
            msg_hash = hashlib.sha256(msg_hash).digest()
            continue
        s = _inv(k, N) * (z + r * secret) % N
        if s == 0:
            msg_hash = hashlib.sha256(msg_hash).digest()
            continue
        recid = R[1] & 1
        if s > N // 2:
            s = N - s
            recid ^= 1
        return 27 + recid, r, s


def recover(msg_hash: bytes, v: int, r: int, s: int):
    """Recover the signing public key (x, y); None when the signature
    is invalid (the precompile then returns empty output)."""
    if v not in (27, 28) or not (1 <= r < N) or not (1 <= s < N):
        return None
    x = r          # high-r recovery (r + N) is vanishingly rare; skip
    try:
        y = pow((pow(x, 3, P) + 7) % P, (P + 1) // 4, P)
    except ValueError:
        return None
    if (y * y - (pow(x, 3, P) + 7)) % P != 0:
        return None
    if (y & 1) != (v - 27):
        y = P - y
    z = int.from_bytes(msg_hash, "big")
    rinv = _inv(r, N)
    # Q = r^-1 (s*R - z*G)
    q = _add(_mul(s * rinv % N, (x, y)),
             _mul((-z * rinv) % N, (Gx, Gy)))
    return q


def recover_address(msg_hash: bytes, v: int, r: int, s: int) -> bytes | None:
    """The 0x1 precompile's output: 20-byte address of the signer
    (sha3_256 of the uncompressed point — see module docstring)."""
    q = recover(msg_hash, v, r, s)
    if q is None:
        return None
    pub = q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")
    return hashlib.sha3_256(pub).digest()[12:]


def address_of(secret: int) -> bytes:
    q = pubkey(secret)
    pub = q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")
    return hashlib.sha3_256(pub).digest()[12:]
