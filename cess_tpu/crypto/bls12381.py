"""BLS12-381 min-sig signatures: the publicly verifiable seal on TEE
verdicts.

Role parity: the reference vendors an Internet-Computer-compatible BLS
verifier (/root/reference/utils/verify-bls-signatures/src/lib.rs:1-247)
and exposes it as ``enclave_verify::verify_bls``
(/root/reference/primitives/enclave-verify/src/lib.rs:230-235) so that
PoDR2 verdicts signed by a TEE master key can be re-verified by
*anyone* holding the 96-byte public key — not just the secret-holding
enclave. This module supplies that capability natively:

- min-sig variety (matching the reference's crate): signatures are
  G1 points (48-byte compressed), public keys are G2 points (96-byte
  compressed), ZCash serialization flags.
- verify:  e(sig, -G2gen) * e(H(msg), pk) == 1, one shared final
  exponentiation (the crate's multi_miller_loop shape, lib.rs:214-247).
- aggregation over distinct messages + proof-of-possession, so one
  pairing product covers a whole batch of TEE verdicts.

Redesign notes (capability-equivalent, not byte-compatible):
- hash-to-G1 uses expand_message_xmd(SHA-256) per RFC 9380 §5.3.1 but
  a try-and-increment curve map with explicit domain separation
  instead of the SSWU+11-isogeny ciphersuite — deterministic and
  uniform for signature security, chosen to avoid a page of opaque
  isogeny constants. Signing here happens in the in-repo TEE agent,
  so constant-time mapping is not load-bearing.
- Tower arithmetic is plain-Python bignum (Fp2 -> Fp6 -> Fp12); the
  pairing is the optimal ate loop over |u|, u = -0xd201_0000_0001_0000,
  with the final conjugation for u < 0. Cofactors and the cyclotomic
  exponent are DERIVED from u at import and asserted, never quoted.

This layer signs/verifies ~one verdict batch per block (6 s); the
per-fragment proof throughput path stays on the TPU F_p^2 MAC
(ops/podr2.py) — pairings seal the verdict, not the data plane.
"""
from __future__ import annotations

import hashlib
import hmac

# --- base field / curve parameters (standard BLS12-381) --------------
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001
U = -0xD201000000010000            # curve parameter (negative)

_G1X = 0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB
_G1Y = 0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1
_G2X = (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
        0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E)
_G2Y = (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
        0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE)

# Derived group orders/cofactors: #E(Fp) = p + 1 - t with t = u + 1
# for BLS12 curves, so #E(Fp) = p - u; the correct sextic twist order
# over Fp2 is whichever of p^2 + 1 -+ (t^2 - 2p) the subgroup order
# divides.  Both divisibility facts are asserted, so a misquoted
# constant above dies at import, not at verify time.
_N1 = P - U
assert _N1 % R == 0
H1 = _N1 // R                      # G1 cofactor
_T = U + 1
_T2 = _T * _T - 2 * P
if (P * P + 1 - _T2) % R == 0:
    _N2 = P * P + 1 - _T2
else:
    _N2 = P * P + 1 + _T2
assert _N2 % R == 0
H2 = _N2 // R                      # G2 cofactor
assert (P ** 4 - P ** 2 + 1) % R == 0   # r | Phi_12(p): final exp is sound

DST_G1 = b"CESS_TPU_BLS_SIG_BLS12381G1_TAI:SHA-256_RO_NUL_"
DST_POP = b"CESS_TPU_BLS_POP_BLS12381G1_TAI:SHA-256_RO_POP_"

SK_BYTES = 32
PK_BYTES = 96
SIG_BYTES = 48


# --- Fp ---------------------------------------------------------------
def _finv(a: int) -> int:
    return pow(a, P - 2, P)


def _fsqrt(a: int) -> int | None:
    """p == 3 (mod 4): candidate root a^((p+1)/4); None if non-residue."""
    s = pow(a, (P + 1) // 4, P)
    return s if s * s % P == a else None


# --- Fp2 = Fp[u]/(u^2 + 1) -------------------------------------------
def _f2add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def _f2sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def _f2neg(a):
    return (-a[0] % P, -a[1] % P)


def _f2mul(a, b):
    t0 = a[0] * b[0]
    t1 = a[1] * b[1]
    t2 = (a[0] + a[1]) * (b[0] + b[1])
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def _f2sqr(a):
    t = a[0] * a[1]
    return ((a[0] + a[1]) * (a[0] - a[1]) % P, (t + t) % P)


def _f2inv(a):
    d = _finv((a[0] * a[0] + a[1] * a[1]) % P)
    return (a[0] * d % P, -a[1] * d % P)


def _f2conj(a):
    return (a[0], -a[1] % P)


_F2ZERO = (0, 0)
_F2ONE = (1, 0)
_XI = (1, 1)                       # Fp6 nonresidue xi = 1 + u


def _f2muls(a, s: int):
    return (a[0] * s % P, a[1] * s % P)


def _f2pow(a, e: int):
    out = _F2ONE
    while e:
        if e & 1:
            out = _f2mul(out, a)
        a = _f2sqr(a)
        e >>= 1
    return out


def _f2sqrt(a):
    """sqrt in Fp2 via the complex method; None if non-residue."""
    if a == _F2ZERO:
        return _F2ZERO
    # norm = a0^2 + a1^2 must be a QR in Fp
    n = (a[0] * a[0] + a[1] * a[1]) % P
    d = _fsqrt(n)
    if d is None:
        return None
    inv2 = _finv(2)
    x0 = (a[0] + d) * inv2 % P
    r0 = _fsqrt(x0)
    if r0 is None:
        x0 = (a[0] - d) * inv2 % P
        r0 = _fsqrt(x0)
        if r0 is None:
            return None
    if r0 == 0:
        r1 = _fsqrt(a[1] * _finv(2) % P)  # pure-imaginary edge case
        if r1 is None:
            return None
        return (0, r1) if _f2sqr((0, r1)) == a else None
    r1 = a[1] * _finv(2 * r0 % P) % P
    cand = (r0, r1)
    return cand if _f2sqr(cand) == a else None


# --- Fp6 = Fp2[v]/(v^3 - xi) -----------------------------------------
def _f6add(a, b):
    return (_f2add(a[0], b[0]), _f2add(a[1], b[1]), _f2add(a[2], b[2]))


def _f6sub(a, b):
    return (_f2sub(a[0], b[0]), _f2sub(a[1], b[1]), _f2sub(a[2], b[2]))


def _f6neg(a):
    return (_f2neg(a[0]), _f2neg(a[1]), _f2neg(a[2]))


def _f6mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = _f2mul(a0, b0)
    t1 = _f2mul(a1, b1)
    t2 = _f2mul(a2, b2)
    c0 = _f2add(t0, _f2mul(_XI, _f2sub(_f2mul(_f2add(a1, a2), _f2add(b1, b2)), _f2add(t1, t2))))
    c1 = _f2add(_f2sub(_f2mul(_f2add(a0, a1), _f2add(b0, b1)), _f2add(t0, t1)), _f2mul(_XI, t2))
    c2 = _f2add(_f2sub(_f2mul(_f2add(a0, a2), _f2add(b0, b2)), _f2add(t0, t2)), t1)
    return (c0, c1, c2)


def _f6sqr(a):
    return _f6mul(a, a)


def _f6mulv(a):
    """multiply by v: (c0, c1, c2) -> (xi*c2, c0, c1)."""
    return (_f2mul(_XI, a[2]), a[0], a[1])


def _f6inv(a):
    a0, a1, a2 = a
    t0 = _f2sub(_f2sqr(a0), _f2mul(_XI, _f2mul(a1, a2)))
    t1 = _f2sub(_f2mul(_XI, _f2sqr(a2)), _f2mul(a0, a1))
    t2 = _f2sub(_f2sqr(a1), _f2mul(a0, a2))
    den = _f2add(_f2mul(a0, t0), _f2mul(_XI, _f2add(_f2mul(a2, t1), _f2mul(a1, t2))))
    di = _f2inv(den)
    return (_f2mul(t0, di), _f2mul(t1, di), _f2mul(t2, di))


_F6ZERO = (_F2ZERO, _F2ZERO, _F2ZERO)
_F6ONE = (_F2ONE, _F2ZERO, _F2ZERO)


# --- Fp12 = Fp6[w]/(w^2 - v) -----------------------------------------
def _f12add(a, b):
    return (_f6add(a[0], b[0]), _f6add(a[1], b[1]))


def _f12sub(a, b):
    return (_f6sub(a[0], b[0]), _f6sub(a[1], b[1]))


def _f12mul(a, b):
    t0 = _f6mul(a[0], b[0])
    t1 = _f6mul(a[1], b[1])
    c1 = _f6sub(_f6mul(_f6add(a[0], a[1]), _f6add(b[0], b[1])), _f6add(t0, t1))
    return (_f6add(t0, _f6mulv(t1)), c1)


def _f12sqr(a):
    return _f12mul(a, a)


def _f12inv(a):
    den = _f6sub(_f6sqr(a[0]), _f6mulv(_f6sqr(a[1])))
    di = _f6inv(den)
    return (_f6mul(a[0], di), _f6neg(_f6mul(a[1], di)))


def _f12conj(a):
    """Frobenius^6: w -> -w (Galois conjugation over Fp6)."""
    return (a[0], _f6neg(a[1]))


_F12ONE = (_F6ONE, _F6ZERO)


def _f12pow(a, e: int):
    out = _F12ONE
    while e:
        if e & 1:
            out = _f12mul(out, a)
        a = _f12sqr(a)
        e >>= 1
    return out


# Frobenius gammas: v^p = v * xi^((p-1)/3), v^2p = v^2 * xi^(2(p-1)/3),
# w^p = w * xi^((p-1)/6).  All exist because p == 1 (mod 6).
assert (P - 1) % 6 == 0
_GAMMA_V = _f2pow(_XI, (P - 1) // 3)
_GAMMA_V2 = _f2pow(_XI, 2 * (P - 1) // 3)
_GAMMA_W = _f2pow(_XI, (P - 1) // 6)


def _f6frob(a):
    return (_f2conj(a[0]), _f2mul(_f2conj(a[1]), _GAMMA_V),
            _f2mul(_f2conj(a[2]), _GAMMA_V2))


def _f12frob(a):
    c0 = _f6frob(a[0])
    c1 = _f6frob(a[1])
    return (c0, (_f2mul(c1[0], _GAMMA_W), _f2mul(c1[1], _GAMMA_W),
                 _f2mul(c1[2], _GAMMA_W)))


def _final_exp(f):
    """f^((p^12-1)/r): easy part by conj/frobenius, hard part by a
    generic square-and-multiply over the ~1.3kbit cyclotomic exponent
    (clarity over the x-addition-chain; this runs once per verify)."""
    g = _f12mul(_f12conj(f), _f12inv(f))          # f^(p^6 - 1)
    g = _f12mul(_f12frob(_f12frob(g)), g)          # ^(p^2 + 1)
    return _f12pow(g, (P ** 4 - P ** 2 + 1) // R)  # ^(Phi12(p)/r)


# --- curve points -----------------------------------------------------
# G1 points are (x, y) ints or None (infinity); G2 points are
# (x, y) Fp2 pairs or None.  Affine + per-op inversion is fine at
# verdict rate; scalar muls use Jacobian to skip inversions.
_B1 = 4
_B2 = _f2muls(_XI, 4)              # twist: y^2 = x^3 + 4(1+u)


def g1_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (x * x * x + _B1)) % P == 0


def g2_is_on_curve(pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return _f2sub(_f2sqr(y), _f2add(_f2mul(x, _f2sqr(x)), _B2)) == _F2ZERO


def _g1_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        lam = 3 * x1 * x1 * _finv(2 * y1 % P) % P
    else:
        lam = (y2 - y1) * _finv((x2 - x1) % P) % P
    x3 = (lam * lam - x1 - x2) % P
    return (x3, (lam * (x1 - x3) - y1) % P)


def _g1_mul(pt, k: int):
    """Jacobian double-and-add over Fp."""
    k %= _N1
    if pt is None or k == 0:
        return None
    X, Y, Z = pt[0], pt[1], 1
    out = None                     # (X, Y, Z) or None
    for bit in bin(k)[2:]:
        if out is not None:
            out = _jac_dbl(out)
        if bit == "1":
            out = _jac_add(out, (X, Y, Z))
    return _jac_to_affine(out)


def _jac_dbl(pt):
    X, Y, Z = pt
    A = X * X % P
    B = Y * Y % P
    C = B * B % P
    D = 2 * ((X + B) * (X + B) - A - C) % P
    E = 3 * A % P
    F = E * E % P
    X3 = (F - 2 * D) % P
    Y3 = (E * (D - X3) - 8 * C) % P
    Z3 = 2 * Y * Z % P
    return (X3, Y3, Z3)


def _jac_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    X1, Y1, Z1 = p1
    X2, Y2, Z2 = p2
    Z1Z1 = Z1 * Z1 % P
    Z2Z2 = Z2 * Z2 % P
    U1 = X1 * Z2Z2 % P
    U2 = X2 * Z1Z1 % P
    S1 = Y1 * Z2 * Z2Z2 % P
    S2 = Y2 * Z1 * Z1Z1 % P
    if U1 == U2:
        if S1 != S2:
            return None
        return _jac_dbl(p1)
    H = (U2 - U1) % P
    I = 4 * H * H % P
    J = H * I % P
    rr = 2 * (S2 - S1) % P
    V = U1 * I % P
    X3 = (rr * rr - J - 2 * V) % P
    Y3 = (rr * (V - X3) - 2 * S1 * J) % P
    Z3 = 2 * H * Z1 * Z2 % P
    return (X3, Y3, Z3)


def _jac_to_affine(pt):
    if pt is None or pt[2] == 0:
        return None
    X, Y, Z = pt
    zi = _finv(Z)
    zi2 = zi * zi % P
    return (X * zi2 % P, Y * zi2 * zi % P)


def _g2_add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if _f2add(y1, y2) == _F2ZERO:
            return None
        lam = _f2mul(_f2muls(_f2sqr(x1), 3), _f2inv(_f2muls(y1, 2)))
    else:
        lam = _f2mul(_f2sub(y2, y1), _f2inv(_f2sub(x2, x1)))
    x3 = _f2sub(_f2sub(_f2sqr(lam), x1), x2)
    return (x3, _f2sub(_f2mul(lam, _f2sub(x1, x3)), y1))


def _g2_neg(pt):
    if pt is None:
        return None
    return (pt[0], _f2neg(pt[1]))


def _g1_neg(pt):
    if pt is None:
        return None
    return (pt[0], -pt[1] % P)


def _g2_mul(pt, k: int):
    k %= _N2
    if pt is None or k == 0:
        return None
    out = None
    for bit in bin(k)[2:]:
        if out is not None:
            out = _g2_dblstep(out)
        if bit == "1":
            out = _g2_addj(out, pt)
    return out if out is None else _g2j_to_affine(out)


# G2 Jacobian over Fp2 (same shapes as Fp Jacobian).
def _g2_dblstep(pt):
    X, Y, Z = pt
    A = _f2sqr(X)
    B = _f2sqr(Y)
    C = _f2sqr(B)
    D = _f2muls(_f2sub(_f2sub(_f2sqr(_f2add(X, B)), A), C), 2)
    E = _f2muls(A, 3)
    F = _f2sqr(E)
    X3 = _f2sub(F, _f2muls(D, 2))
    Y3 = _f2sub(_f2mul(E, _f2sub(D, X3)), _f2muls(C, 8))
    Z3 = _f2muls(_f2mul(Y, Z), 2)
    return (X3, Y3, Z3)


def _g2_addj(p1, p2aff):
    if p1 is None:
        return (p2aff[0], p2aff[1], _F2ONE)
    X1, Y1, Z1 = p1
    x2, y2 = p2aff
    Z1Z1 = _f2sqr(Z1)
    U2 = _f2mul(x2, Z1Z1)
    S2 = _f2mul(_f2mul(y2, Z1), Z1Z1)
    if U2 == X1:
        if S2 != Y1:
            return None
        return _g2_dblstep(p1)
    H = _f2sub(U2, X1)
    HH = _f2sqr(H)
    I = _f2muls(HH, 4)
    J = _f2mul(H, I)
    rr = _f2muls(_f2sub(S2, Y1), 2)
    V = _f2mul(X1, I)
    X3 = _f2sub(_f2sub(_f2sqr(rr), J), _f2muls(V, 2))
    Y3 = _f2sub(_f2mul(rr, _f2sub(V, X3)), _f2muls(_f2mul(Y1, J), 2))
    Z3 = _f2mul(_f2muls(H, 2), Z1)
    return (X3, Y3, Z3)


def _g2j_to_affine(pt):
    if pt is None or pt[2] == _F2ZERO:
        return None
    X, Y, Z = pt
    zi = _f2inv(Z)
    zi2 = _f2sqr(zi)
    return (_f2mul(X, zi2), _f2mul(Y, _f2mul(zi2, zi)))


G1_GEN = (_G1X, _G1Y)
G2_GEN = (_G2X, _G2Y)


def g1_in_subgroup(pt) -> bool:
    return g1_is_on_curve(pt) and _g1_mul(pt, R) is None


def g2_in_subgroup(pt) -> bool:
    return g2_is_on_curve(pt) and _g2_mul(pt, R) is None


# --- pairing ----------------------------------------------------------
def _untwist(q):
    """E'(Fp2) -> E(Fp12) for the M-twist: (x, y) -> (x/w^2, y/w^3)
    with w^6 = xi, i.e. x * v^2/xi embedded in Fp6, y * (v/xi) * w."""
    x, y = q
    xi_inv = _f2inv(_XI)
    xf6 = (_F2ZERO, _F2ZERO, _f2mul(x, xi_inv))      # x * v^2 / xi
    yf6 = (_F2ZERO, _f2mul(y, xi_inv), _F2ZERO)      # y * v / xi
    return ((xf6, _F6ZERO), (_F6ZERO, yf6))


def _f12_from_fp(a: int):
    return (((a % P, 0), _F2ZERO, _F2ZERO), _F6ZERO)


def _miller_loop(p1, q2):
    """Optimal ate f_{|u|, Q'}(P) with the trailing conjugation for
    u < 0; returns an UNexponentiated Fp12 value (combine products,
    then _final_exp once)."""
    if p1 is None or q2 is None:
        return _F12ONE
    xq, yq = _untwist(q2)
    xp = _f12_from_fp(p1[0])
    yp = _f12_from_fp(p1[1])
    xt, yt = xq, yq
    f = _F12ONE
    n = -U
    for bit in bin(n)[3:]:                 # from second-highest bit
        lam = _f12mul(_f12mul(_f12sqr(xt), _f12_from_fp(3)),
                      _f12inv(_f12mul(yt, _f12_from_fp(2))))
        line = _f12sub(_f12sub(yp, yt), _f12mul(lam, _f12sub(xp, xt)))
        f = _f12mul(_f12sqr(f), line)
        x3 = _f12sub(_f12sub(_f12mul(lam, lam), xt), xt)
        yt = _f12sub(_f12mul(lam, _f12sub(xt, x3)), yt)
        xt = x3
        if bit == "1":
            lam = _f12mul(_f12sub(yq, yt), _f12inv(_f12sub(xq, xt)))
            line = _f12sub(_f12sub(yp, yt), _f12mul(lam, _f12sub(xp, xt)))
            f = _f12mul(f, line)
            x3 = _f12sub(_f12sub(_f12mul(lam, lam), xt), xq)
            yt = _f12sub(_f12mul(lam, _f12sub(xt, x3)), yt)
            xt = x3
    return _f12conj(f)                     # u < 0


def pairing(p1, q2):
    """e(P, Q) for P in G1, Q in G2 (affine or None)."""
    return _final_exp(_miller_loop(p1, q2))


def multi_pairing(pairs) -> bool:
    """True iff prod e(Pi, Qi) == 1: one final exponentiation over the
    product of Miller loops (verify-bls-signatures lib.rs:214-247)."""
    f = _F12ONE
    for p1, q2 in pairs:
        f = _f12mul(f, _miller_loop(p1, q2))
    return _final_exp(f) == _F12ONE


# --- hash to G1 -------------------------------------------------------
def _expand_message_xmd(msg: bytes, dst: bytes, length: int) -> bytes:
    """RFC 9380 §5.3.1 with SHA-256."""
    h = hashlib.sha256
    b_in_bytes, r_in_bytes = 32, 64
    ell = -(-length // b_in_bytes)
    if ell > 255 or len(dst) > 255:
        raise ValueError("expand_message_xmd bounds")
    dst_prime = dst + bytes([len(dst)])
    z_pad = b"\x00" * r_in_bytes
    l_i_b = length.to_bytes(2, "big")
    b0 = h(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    bi = h(b0 + b"\x01" + dst_prime).digest()
    out = bi
    for i in range(2, ell + 1):
        bi = h(bytes(x ^ y for x, y in zip(b0, bi)) + bytes([i]) + dst_prime).digest()
        out += bi
    return out[:length]


def hash_to_g1(msg: bytes, dst: bytes = DST_G1):
    """Deterministic try-and-increment map (see module docstring),
    cofactor-cleared into the r-order subgroup."""
    for ctr in range(256):
        seed = _expand_message_xmd(msg, dst + b"|ctr=" + bytes([ctr]), 64)
        x = int.from_bytes(seed[:48], "big") % P
        y = _fsqrt((x * x * x + _B1) % P)
        if y is None:
            continue
        if (y & 1) != (seed[63] & 1):
            y = P - y
        pt = _g1_mul((x, y), H1)
        if pt is not None:
            return pt
    raise ValueError("hash_to_g1 failed to find a point")   # pragma: no cover


# --- serialization (ZCash flags) -------------------------------------
_C_FLAG, _I_FLAG, _S_FLAG = 0x80, 0x40, 0x20


def g1_compress(pt) -> bytes:
    if pt is None:
        return bytes([_C_FLAG | _I_FLAG]) + b"\x00" * 47
    x, y = pt
    flags = _C_FLAG | (_S_FLAG if y > (P - 1) // 2 else 0)
    out = bytearray(x.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g1_decompress(data: bytes, subgroup_check: bool = True):
    if len(data) != 48:
        raise ValueError("G1 point must be 48 bytes")
    flags = data[0]
    if not flags & _C_FLAG:
        raise ValueError("uncompressed G1 encoding unsupported")
    if flags & _I_FLAG:
        if any(data[1:]) or data[0] & 0x3F:
            raise ValueError("malformed infinity encoding")
        return None
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("G1 x out of range")
    y = _fsqrt((x * x * x + _B1) % P)
    if y is None:
        raise ValueError("G1 x not on curve")
    if (y > (P - 1) // 2) != bool(flags & _S_FLAG):
        y = P - y
    pt = (x, y)
    if subgroup_check and not g1_in_subgroup(pt):
        raise ValueError("G1 point not in subgroup")
    return pt


def g2_compress(pt) -> bytes:
    if pt is None:
        return bytes([_C_FLAG | _I_FLAG]) + b"\x00" * 95
    (x0, x1), (y0, y1) = pt
    bigy = y1 > (P - 1) // 2 or (y1 == 0 and y0 > (P - 1) // 2)
    flags = _C_FLAG | (_S_FLAG if bigy else 0)
    out = bytearray(x1.to_bytes(48, "big") + x0.to_bytes(48, "big"))
    out[0] |= flags
    return bytes(out)


def g2_decompress(data: bytes, subgroup_check: bool = True):
    if len(data) != 96:
        raise ValueError("G2 point must be 96 bytes")
    flags = data[0]
    if not flags & _C_FLAG:
        raise ValueError("uncompressed G2 encoding unsupported")
    if flags & _I_FLAG:
        if any(data[1:]) or data[0] & 0x3F:
            raise ValueError("malformed infinity encoding")
        return None
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("G2 x out of range")
    x = (x0, x1)
    y = _f2sqrt(_f2add(_f2mul(x, _f2sqr(x)), _B2))
    if y is None:
        raise ValueError("G2 x not on curve")
    bigy = y[1] > (P - 1) // 2 or (y[1] == 0 and y[0] > (P - 1) // 2)
    if bigy != bool(flags & _S_FLAG):
        y = _f2neg(y)
    pt = (x, y)
    if subgroup_check and not g2_in_subgroup(pt):
        raise ValueError("G2 point not in subgroup")
    return pt


# --- native dispatch --------------------------------------------------
# The C++ backend (native/bls381.cpp via bls_native.py) mirrors this
# module construction-for-construction: byte-identical signatures,
# agreeing verifies (differentially tested). Absent toolchain or
# CESS_TPU_NO_NATIVE_BLS=1 falls back to the pure-Python path here.
try:
    from . import bls_native as _native
except ImportError:
    _native = None


# --- signatures (min-sig: sig in G1, pk in G2) -----------------------
def keygen(seed: bytes) -> tuple[int, bytes]:
    """Derive (sk, pk_bytes) from a seed; sk in [1, r)."""
    sk = 0
    salt = b"CESS_TPU_BLS_KEYGEN"
    while sk == 0:
        sk = int.from_bytes(hmac.new(salt, seed, hashlib.sha512).digest(), "big") % R
        salt = hashlib.sha256(salt).digest()
    if _native is not None:
        return sk, _native.pk_from_sk(sk.to_bytes(32, "big"))
    return sk, g2_compress(_g2_mul(G2_GEN, sk))


def sign(sk: int, msg: bytes, dst: bytes = DST_G1) -> bytes:
    if _native is not None:
        return _native.sign(sk.to_bytes(32, "big"), msg, dst)
    return g1_compress(_g1_mul(hash_to_g1(msg, dst), sk))


_NEG_G2_GEN = _g2_neg(G2_GEN)


def verify(pk_bytes: bytes, msg: bytes, sig_bytes: bytes,
           dst: bytes = DST_G1) -> bool:
    """e(sig, -G2) * e(H(msg), pk) == 1."""
    if not isinstance(pk_bytes, bytes) or not isinstance(sig_bytes, bytes):
        return False
    if _native is not None:
        return _native.verify(pk_bytes, msg, sig_bytes, dst)
    try:
        pk = g2_decompress(pk_bytes)
        sig = g1_decompress(sig_bytes)
    except ValueError:
        return False
    if pk is None or sig is None:
        return False
    return multi_pairing([(sig, _NEG_G2_GEN), (hash_to_g1(msg, dst), pk)])


def aggregate(sig_list: list[bytes]) -> bytes:
    """Sum of G1 signatures."""
    if _native is not None:
        return _native.aggregate(list(sig_list))
    acc = None
    for s in sig_list:
        acc = _g1_add(acc, g1_decompress(s))
    return g1_compress(acc)


def aggregate_verify(pk_msg_pairs: list[tuple[bytes, bytes]],
                     agg_sig: bytes, dst: bytes = DST_G1) -> bool:
    """prod e(H(mi), pki) == e(asig, G2); messages MUST be distinct
    (enforced) unless callers prove possession — the standard
    rogue-key discipline."""
    msgs = [m for _, m in pk_msg_pairs]
    if len(set(msgs)) != len(msgs):
        return False
    if _native is not None and isinstance(agg_sig, bytes) \
            and all(isinstance(pk, bytes) for pk, _ in pk_msg_pairs):
        return _native.aggregate_verify(list(pk_msg_pairs), agg_sig, dst)
    try:
        sig = g1_decompress(agg_sig)
        pairs = [(sig, _NEG_G2_GEN)]
        for pk_bytes, msg in pk_msg_pairs:
            pk = g2_decompress(pk_bytes)
            if pk is None:
                return False
            pairs.append((hash_to_g1(msg, dst), pk))
    except ValueError:
        return False
    if sig is None:
        return False
    return multi_pairing(pairs)


def prove_possession(sk: int, pk_bytes: bytes) -> bytes:
    """PoP: sign your own pk under the PoP domain."""
    return sign(sk, pk_bytes, dst=DST_POP)


def verify_possession(pk_bytes: bytes, pop: bytes) -> bool:
    return verify(pk_bytes, pk_bytes, pop, dst=DST_POP)
