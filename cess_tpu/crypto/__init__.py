"""Host-side crypto for the chain layer.

The reference vendors ring/webpki/verify-bls-signatures (Rust+C+asm,
SURVEY.md §2.3) for SGX attestation verification, RSA message checks
and BLS proof signatures. Here the host path is pure Python (RSA
PKCS#1 v1.5 verify, SHA-2, Ed25519+VRF) with the batched field math on
TPU; a C++ fast path can slot in behind the same functions.
"""
from .rsa import rsa_verify_pkcs1v15, RsaPublicKey  # noqa: F401
from .hashing import sha256, blake2b_256  # noqa: F401
from .bls12381 import verify as verify_bls  # noqa: F401
