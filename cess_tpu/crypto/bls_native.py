"""ctypes binding for the native (C++) BLS12-381 backend.

Loads ``cess_tpu/native/libcessbls.so`` (auto-building with the
in-tree Makefile on first use when a compiler is available). The
native code mirrors cess_tpu/crypto/bls12381.py construction-for-
construction, so signatures are byte-identical and every verify
agrees — asserted by the differential tests in tests/test_bls.py.
bls12381.py dispatches here automatically (~35 ms verify vs ~200 ms
pure Python, ~0.6 ms sign vs ~80 ms); set CESS_TPU_NO_NATIVE_BLS=1 to
force the pure-Python path.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO = os.path.join(_NATIVE_DIR, "libcessbls.so")


def _load() -> ctypes.CDLL:
    if os.environ.get("CESS_TPU_NO_NATIVE_BLS"):
        raise ImportError("native BLS disabled by CESS_TPU_NO_NATIVE_BLS")
    if not os.path.exists(_SO):
        try:
            subprocess.run(["make", "-C", _NATIVE_DIR, "-s",
                            "libcessbls.so"], check=True,
                           capture_output=True)
        except (OSError, subprocess.CalledProcessError) as e:
            raise ImportError(f"cannot build native BLS: {e}") from e
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        raise ImportError(f"cannot load native BLS: {e}") from e
    u8p, szp = ctypes.c_char_p, ctypes.POINTER(ctypes.c_size_t)
    sz = ctypes.c_size_t
    lib.cessbls_verify.argtypes = [u8p, u8p, sz, u8p, u8p, sz]
    lib.cessbls_verify.restype = ctypes.c_int
    lib.cessbls_sign.argtypes = [u8p, u8p, sz, u8p, sz, u8p]
    lib.cessbls_sign.restype = ctypes.c_int
    lib.cessbls_pk_from_sk.argtypes = [u8p, u8p]
    lib.cessbls_pk_from_sk.restype = ctypes.c_int
    lib.cessbls_aggregate_verify.argtypes = [sz, u8p, u8p, szp, u8p,
                                             u8p, sz]
    lib.cessbls_aggregate_verify.restype = ctypes.c_int
    lib.cessbls_aggregate.argtypes = [sz, u8p, u8p]
    lib.cessbls_aggregate.restype = ctypes.c_int
    lib.cessbls_selftest.argtypes = []
    lib.cessbls_selftest.restype = ctypes.c_int
    if lib.cessbls_selftest() != 1:
        raise ImportError("native BLS selftest failed")   # wrong build
    return lib


_lib = _load()


def verify(pk: bytes, msg: bytes, sig: bytes, dst: bytes) -> bool:
    if len(pk) != 96 or len(sig) != 48:
        return False
    return _lib.cessbls_verify(pk, msg, len(msg), sig, dst,
                               len(dst)) == 1


def sign(sk_be32: bytes, msg: bytes, dst: bytes) -> bytes:
    out = ctypes.create_string_buffer(48)
    if _lib.cessbls_sign(sk_be32, msg, len(msg), dst, len(dst),
                         out) != 0:
        raise ValueError("native sign failed")
    return out.raw


def pk_from_sk(sk_be32: bytes) -> bytes:
    out = ctypes.create_string_buffer(96)
    if _lib.cessbls_pk_from_sk(sk_be32, out) != 0:
        raise ValueError("native pk derivation failed")
    return out.raw


def aggregate(sigs: list[bytes]) -> bytes:
    if any(len(s) != 48 for s in sigs):
        raise ValueError("signatures must be 48 bytes")
    out = ctypes.create_string_buffer(48)
    if _lib.cessbls_aggregate(len(sigs), b"".join(sigs), out) != 0:
        raise ValueError("invalid signature in aggregate")
    return out.raw


def aggregate_verify(pk_msg_pairs: list[tuple[bytes, bytes]],
                     agg_sig: bytes, dst: bytes) -> bool:
    if len(agg_sig) != 48 \
            or any(len(pk) != 96 for pk, _ in pk_msg_pairs):
        return False
    pks = b"".join(pk for pk, _ in pk_msg_pairs)
    msgs = b"".join(m for _, m in pk_msg_pairs)
    lens = (ctypes.c_size_t * len(pk_msg_pairs))(
        *[len(m) for _, m in pk_msg_pairs])
    return _lib.cessbls_aggregate_verify(len(pk_msg_pairs), pks, msgs,
                                         lens, agg_sig, dst,
                                         len(dst)) == 1
