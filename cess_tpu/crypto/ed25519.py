"""Ed25519 signatures (RFC 8032, pure Python).

Replaces the reference's sr25519 session/VRF key machinery (Substrate
keystore + schnorrkel, external) for block authorship and the
hash-based VRF in cess_tpu/crypto/vrf.py. Pure-Python bigint math is
plenty for control-plane signing rates; the data plane never signs.
"""
from __future__ import annotations

import dataclasses
import functools
import hashlib

P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
BASE_Y = 4 * pow(5, P - 2, P) % P


def _recover_x(y: int, sign: int) -> int:
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P:
        x = x * pow(2, (P - 1) // 4, P) % P
    if (x * x - x2) % P:
        raise ValueError("invalid point")
    if x & 1 != sign:
        x = P - x
    return x


BASE = (_recover_x(BASE_Y, 0), BASE_Y, 1, _recover_x(BASE_Y, 0) * BASE_Y % P)


def _add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def _mul(s: int, p=None):
    q = (0, 1, 1, 0)
    if p is None:
        # base-point multiply: the doublings 2^i*B are shared by every
        # scalar, so they are precomputed once (_BASE_POW2) and only
        # the conditional adds remain
        return _mul_tab(s, _BASE_POW2)
    while s:
        if s & 1:
            q = _add(q, p)
        p = _add(p, p)
        s >>= 1
    return q


def _mul_tab(s: int, table):
    q = (0, 1, 1, 0)
    for i in range(s.bit_length()):
        if (s >> i) & 1:
            q = _add(q, table[i])
    return q


def _pow2_table(p, n):
    out = []
    for _ in range(n):
        out.append(p)
        p = _add(p, p)
    return out


# scalars are < 2^255 (the clamped secret sets bit 254; r/s/k are < L)
_BASE_POW2 = _pow2_table(BASE, 256)


@functools.lru_cache(maxsize=512)
def _pubkey_pow2(public: bytes):
    """Doubles table for a signer's point: gossip re-verifies the same
    few keys under ever-new messages, so the k*A multiply amortizes to
    adds-only after one verify per key. Bounded — eviction just
    rebuilds. Raises ValueError on an invalid encoding (caller
    handles)."""
    return tuple(_pow2_table(_decompress(public), 256))


def _compress(p) -> bytes:
    x, y, z, _ = p
    zi = pow(z, P - 2, P)
    x, y = x * zi % P, y * zi % P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _decompress(b: bytes):
    v = int.from_bytes(b, "little")
    y = v & ((1 << 255) - 1)
    if y >= P:
        raise ValueError("invalid point encoding")
    x = _recover_x(y, v >> 255)
    return (x, y, 1, x * y % P)


def _h(data: bytes) -> int:
    return int.from_bytes(hashlib.sha512(data).digest(), "little")


@dataclasses.dataclass(frozen=True)
class SigningKey:
    seed: bytes  # 32 bytes

    @staticmethod
    def generate(seed_material: bytes) -> "SigningKey":
        return SigningKey(hashlib.sha256(seed_material).digest())

    # cached_property stores via __dict__, which a frozen dataclass
    # allows — both are pure functions of the immutable seed, and a
    # long-lived node key signs every block/vote it authors
    @functools.cached_property
    def _expanded(self) -> tuple[int, bytes]:
        h = hashlib.sha512(self.seed).digest()
        a = int.from_bytes(h[:32], "little")
        a &= (1 << 254) - 8
        a |= 1 << 254
        return a, h[32:]

    @functools.cached_property
    def public(self) -> bytes:
        a, _ = self._expanded
        return _compress(_mul(a))

    def sign(self, message: bytes) -> bytes:
        a, prefix = self._expanded
        pub = self.public
        r = _h(prefix + message) % L
        rp = _compress(_mul(r))
        k = _h(rp + pub + message) % L
        s = (r + k * a) % L
        return rp + s.to_bytes(32, "little")


def verify(public: bytes, message: bytes, signature: bytes) -> bool:
    """Memoized: verification is a pure function of its byte inputs,
    and gossip delivers the identical (vote, signature) to every node
    on the network — at cluster-simulation scale (cess_tpu/sim) the
    same triple is re-checked hundreds of times. The bounded cache
    dedupes those without changing any verdict."""
    try:
        return _verify_cached(public, message, signature)
    except TypeError:           # unhashable input (e.g. bytearray)
        return _verify(public, message, signature)


@functools.lru_cache(maxsize=65536)
def _verify_cached(public: bytes, message: bytes,
                   signature: bytes) -> bool:
    return _verify(public, message, signature)


def _verify(public: bytes, message: bytes, signature: bytes) -> bool:
    if len(signature) != 64 or len(public) != 32:
        return False
    try:
        a_tab = _pubkey_pow2(public)
        r_pt = _decompress(signature[:32])
    except ValueError:
        return False
    s = int.from_bytes(signature[32:], "little")
    if s >= L:
        return False
    k = _h(signature[:32] + public + message) % L
    # s*B == R + k*A  (check via compression to avoid projective compare)
    lhs = _mul(s)
    rhs = _add(r_pt, _mul_tab(k, a_tab))
    return _compress(lhs) == _compress(rhs)
