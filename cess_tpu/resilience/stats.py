"""Resilience counters: retries, abandons, batch requeues, fallbacks,
breaker state — the ``cess_resilience_*`` gauge family.

An engine built with a :class:`~cess_tpu.resilience.health.ResilienceConfig`
hangs one of these off its :class:`~cess_tpu.serve.stats.EngineStats`;
``EngineStats.metrics`` merges :meth:`metrics` into the exposition, so
the gauges ride the same ``GET /metrics`` surface and the same
``cess_engineStats`` RPC as the ``cess_engine_*`` family.

Unlike EngineStats (mutated only under the engine lock), these
counters are hit from submitter threads (retry wrappers), the batcher
(salvage/fallback) and whoever scrapes metrics — so this class owns
its lock and every access goes through it.
"""
from __future__ import annotations

import threading


class ResilienceStats:
    def __init__(self):
        self._mu = threading.Lock()
        self._retries: dict[str, int] = {}      # per op class
        self._abandoned: dict[str, int] = {}    # per op class
        self._fallback: dict[str, int] = {}     # batches served on CPU
        self._degraded: dict[str, int] = {}     # breaker-open dispatches
        self._batch_requeues = 0                # members re-run solo
        self._monitors: dict[str, object] = {}  # backend -> HealthMonitor

    # -- recording ----------------------------------------------------------
    def note_retry(self, cls: str) -> None:
        with self._mu:
            self._retries[cls] = self._retries.get(cls, 0) + 1

    def note_abandoned(self, cls: str) -> None:
        with self._mu:
            self._abandoned[cls] = self._abandoned.get(cls, 0) + 1

    def note_fallback(self, cls: str) -> None:
        with self._mu:
            self._fallback[cls] = self._fallback.get(cls, 0) + 1

    def note_degraded(self, cls: str) -> None:
        with self._mu:
            self._degraded[cls] = self._degraded.get(cls, 0) + 1

    def note_batch_requeues(self, members: int) -> None:
        with self._mu:
            self._batch_requeues += members

    def register_monitor(self, backend: str, monitor) -> None:
        with self._mu:
            self._monitors[backend] = monitor

    # -- export -------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._mu:
            out = {
                "batch_requeues": self._batch_requeues,
                "retries": dict(self._retries),
                "abandoned": dict(self._abandoned),
                "fallback_batches": dict(self._fallback),
                "degraded_batches": dict(self._degraded),
                "breakers": {name: mon.snapshot()
                             for name, mon in self._monitors.items()},
            }
        return out

    def metrics(self) -> dict[str, float]:
        """Flat gauges, merged by EngineStats.metrics into the
        ``cess_engine_*`` exposition."""
        snap = self.snapshot()
        out = {"cess_resilience_batch_requeues":
               float(snap["batch_requeues"])}
        for family in ("retries", "abandoned", "fallback_batches",
                       "degraded_batches"):
            for cls in sorted(snap[family]):
                out[f"cess_resilience_{cls}_{family}"] = \
                    float(snap[family][cls])
        for name in sorted(snap["breakers"]):
            b = snap["breakers"][name]
            # "held" (the SLO controller's external latch) is open for
            # traffic purposes: every dispatch degrades either way
            out[f"cess_resilience_breaker_{name}_open"] = \
                1.0 if b["state"] != "closed" else 0.0
            out[f"cess_resilience_breaker_{name}_held"] = \
                1.0 if b["state"] == "held" else 0.0
            for k in ("trips", "probes", "recoveries"):
                out[f"cess_resilience_breaker_{name}_{k}"] = float(b[k])
        return out
