"""Retry / timeout / backoff policies with deadline-budget propagation.

The engine's backpressure contract (serve/policy.py) deliberately puts
the retry decision on the caller: ``EngineSaturated`` means "come back
with jitter or shed". This module is the one place that decision is
implemented, so every caller retries the same way:

- exponential backoff, capped, with DETERMINISTIC jitter — the jitter
  fraction is a SHA-256 function of (token, attempt), so two runs of
  the same workload back off identically (chaos tests replay
  bit-exactly) while different tokens decorrelate concurrent callers
  exactly like random jitter would;
- deadline-budget propagation — a :class:`Budget` is created once per
  logical request; every attempt's timeout is the budget's REMAINING
  time, never the original timeout again, and a backoff that would
  outlive the budget abandons instead of sleeping through it.

Per-class retry/abandon counters land in the engine's
:class:`~cess_tpu.resilience.stats.ResilienceStats` and export as
``cess_resilience_*`` gauges next to the ``cess_engine_*`` family.
"""
from __future__ import annotations

import dataclasses
import hashlib
import time

from ..obs import trace as _trace


class Budget:
    """A request's remaining wall-clock allowance, shared across retry
    attempts. ``None`` seconds = unbounded (remaining() is None)."""

    __slots__ = ("deadline",)

    def __init__(self, seconds: float | None):
        self.deadline = None if seconds is None \
            else time.monotonic() + seconds

    def remaining(self) -> float | None:
        if self.deadline is None:
            return None
        return max(self.deadline - time.monotonic(), 0.0)

    def expired(self) -> bool:
        return self.deadline is not None \
            and time.monotonic() >= self.deadline


def _jitter_frac(token, attempt: int) -> float:
    tok = token if isinstance(token, bytes) else str(token).encode()
    h = hashlib.sha256(b"cess-retry:" + tok + b"|"
                       + attempt.to_bytes(4, "little")).digest()
    return int.from_bytes(h[:8], "little") / 2 ** 64


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """max_attempts: total tries (1 = no retry). base_delay_s grows by
    ``multiplier`` per attempt, capped at max_delay_s, then stretched
    by up to ``jitter_frac`` of itself (deterministic, see module
    doc)."""

    max_attempts: int = 4
    base_delay_s: float = 0.005
    multiplier: float = 2.0
    max_delay_s: float = 0.25
    jitter_frac: float = 0.5

    def __post_init__(self):
        if self.max_attempts < 1 or self.base_delay_s < 0 \
                or self.multiplier < 1 or self.max_delay_s < 0 \
                or not 0 <= self.jitter_frac <= 1:
            raise ValueError("invalid retry policy bounds")

    def delay_for(self, attempt: int, token="") -> float:
        """Backoff before attempt ``attempt + 1`` (attempt counts from
        1). Pure in (self, attempt, token)."""
        base = min(self.base_delay_s * self.multiplier ** (attempt - 1),
                   self.max_delay_s)
        if not self.jitter_frac:
            return base
        return base * (1.0 + self.jitter_frac
                       * _jitter_frac(token, attempt))

    def call(self, fn, *, retry_on=(Exception,),
             budget: Budget | None = None, token="",
             stats=None, cls: str = "", sleep=time.sleep):
        """Run ``fn(budget)`` with bounded retries on ``retry_on``.

        fn receives the shared Budget so each attempt can size its own
        timeout from ``budget.remaining()``. Exhausted attempts or an
        expired/insufficient budget re-raise the last error (counted
        as an abandon); every successful back-off is counted as a
        retry. ``stats``/``cls`` route the counters (None = uncounted).
        """
        if budget is None:
            budget = Budget(None)
        attempt = 0
        while True:
            attempt += 1
            try:
                return fn(budget)
            except retry_on:
                if attempt >= self.max_attempts or budget.expired():
                    if stats is not None:
                        stats.note_abandoned(cls)
                    _trace.event("retry.abandon", cls=cls,
                                 attempt=attempt)
                    raise
                delay = self.delay_for(attempt, token)
                left = budget.remaining()
                if left is not None and left <= delay:
                    # sleeping through the rest of the budget would
                    # guarantee an EngineTimeout: abandon now instead
                    if stats is not None:
                        stats.note_abandoned(cls)
                    _trace.event("retry.abandon", cls=cls,
                                 attempt=attempt, budget=True)
                    raise
                if stats is not None:
                    stats.note_retry(cls)
                # retries annotate the active span (cess_tpu/obs), so
                # a traced request shows every backoff it paid
                _trace.event("retry", cls=cls, attempt=attempt,
                             delay_s=round(delay, 6))
                sleep(delay)
