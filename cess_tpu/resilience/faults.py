"""Deterministic fault injection for the serving data plane.

CESS's whole value proposition is surviving loss — miners drop
fragments and the PoDR2/RS machinery detects and repairs it — yet a
serving stack can only CLAIM the same tolerance for its own faults if
those faults can be produced on demand, byte-identically, inside
tier-1. This module is that seam: a seeded :class:`FaultPlan` maps
injection *sites* (string names at the hot-path seams — engine batch
dispatch, streaming H2D staging, the codec gates, fragment transfer,
peer messaging) to per-ordinal :class:`FaultSpec` actions, and three
tiny hooks consult the armed plan:

- :func:`inject` — control seams (device dispatch, codec calls): a
  scheduled fault raises :class:`FaultInjected` or delays;
- :func:`allow` — messaging/transfer seams: ``False`` when a ``drop``
  fires (the caller skips the send / treats the transfer as lost);
- :func:`corrupt` — data seams: returns the payload with one byte
  flipped when a ``corrupt`` fires (integrity checks must catch it).

Determinism contract: a plan's schedule is a pure function of its
seed (:meth:`FaultPlan.seeded` derives firing ordinals from a SHA-256
counter stream — no ``random``, no wall clock), and ordinals count
hook crossings per site since arming. Driving the same sequential
workload under the same plan therefore fires the same faults at the
same sites in the same order — recorded in :meth:`FaultPlan.fired_log`
so chaos tests can pin the replay exactly (tests/test_resilience.py).

Cost contract: with no plan armed every hook is a single module-global
load and ``None`` check — the seams stay in production code.

Thread note: ordinal counters are lock-protected (hooks are called
from batcher, submitter and sender threads), but cross-thread firing
ORDER is whatever the thread schedule makes it — replay-exact chaos
tests drive their workload sequentially (submit-and-wait).
"""
from __future__ import annotations

import contextlib
import dataclasses
import hashlib
import threading
import time

import numpy as np

from ..obs import trace as _trace

KINDS = ("raise", "delay", "drop", "corrupt")


class FaultInjected(RuntimeError):
    """The error a ``raise`` FaultSpec throws at its site — a stand-in
    for a real device/transport failure, distinguishable from genuine
    errors so tests can assert exactly which path failed."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One fault action. ``delay_s`` applies to every kind (a slow
    failure is the common production shape); ``xor`` is the byte mask
    a ``corrupt`` flips into the payload's first byte."""

    kind: str = "raise"
    message: str = ""
    delay_s: float = 0.0
    xor: int = 0xFF

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}")
        if self.kind == "delay" and self.delay_s <= 0:
            raise ValueError("delay fault needs delay_s > 0")
        if not 1 <= self.xor <= 0xFF:
            # xor=0 would be a corruption that fires, logs a witness,
            # and changes nothing — the silent-no-op shape the delay
            # check above also rejects
            raise ValueError(f"corrupt xor mask {self.xor!r} must be "
                             "a non-zero byte")


class FaultPlan:
    """site -> {ordinal -> FaultSpec}, plus the per-site crossing
    counters and the fired-fault log. Build explicitly from a schedule
    dict, or derive one from a seed with :meth:`seeded`."""

    def __init__(self, schedule: dict[str, dict[int, FaultSpec]],
                 seed: bytes = b"", clock=None):
        self.schedule = {site: dict(specs)
                         for site, specs in schedule.items()}
        self.seed = seed
        # ``clock`` is any object with a ``sleep(seconds)`` method;
        # ``None`` means the wall clock (time.sleep), the production
        # default. A sim world injects its SimClock so delay faults
        # advance virtual time instead of blocking the test runner.
        self.clock = clock
        self._mu = threading.Lock()
        self._counts: dict[str, int] = {}
        self._fired: list[tuple[str, int, str]] = []

    @classmethod
    def seeded(cls, seed, sites: dict[str, tuple[float, "FaultSpec | str"]],
               horizon: int = 64, clock=None) -> "FaultPlan":
        """Derive a schedule from a seed: for each site, each ordinal
        in ``[0, horizon)`` fires with the given rate, decided by a
        SHA-256 counter stream over (seed, site, ordinal). Same seed
        => byte-identical schedule, on every host, every run.

        sites: ``{site: (rate, spec_or_kind)}`` with rate in [0, 1].
        """
        seed_b = seed if isinstance(seed, bytes) else str(seed).encode()
        schedule: dict[str, dict[int, FaultSpec]] = {}
        for site in sorted(sites):
            rate, spec = sites[site]
            if isinstance(spec, str):
                spec = FaultSpec(kind=spec,
                                 delay_s=0.001 if spec == "delay" else 0.0)
            ordinals: dict[int, FaultSpec] = {}
            for i in range(horizon):
                h = hashlib.sha256(b"cess-fault:" + seed_b + b"|"
                                   + site.encode() + b"|"
                                   + i.to_bytes(4, "little")).digest()
                if int.from_bytes(h[:8], "little") < rate * 2 ** 64:
                    ordinals[i] = spec
            schedule[site] = ordinals
        return cls(schedule, seed=seed_b, clock=clock)

    # -- plan state ---------------------------------------------------------
    def _next(self, site: str) -> tuple[int, FaultSpec | None]:
        """Advance the site's ordinal; return (ordinal, due spec)."""
        with self._mu:
            n = self._counts.get(site, 0)
            self._counts[site] = n + 1
            spec = self.schedule.get(site, {}).get(n)
            if spec is not None:
                self._fired.append((site, n, spec.kind))
            return n, spec

    def fired_log(self) -> tuple[tuple[str, int, str], ...]:
        """(site, ordinal, kind) for every fault that fired, in firing
        order — the replay-determinism witness."""
        with self._mu:
            return tuple(self._fired)

    def counts(self) -> dict[str, int]:
        """Hook crossings per site (fired or not)."""
        with self._mu:
            return dict(self._counts)

    def reset(self) -> None:
        """Zero the ordinal counters and the fired log (fresh run of
        the same schedule)."""
        with self._mu:
            self._counts.clear()
            self._fired.clear()


# -- arming ------------------------------------------------------------------
_MU = threading.Lock()
_PLAN: FaultPlan | None = None


def arm(plan: FaultPlan) -> FaultPlan:
    """Install ``plan`` as the process-wide armed plan."""
    global _PLAN
    with _MU:
        _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    with _MU:
        _PLAN = None


def armed_plan() -> FaultPlan | None:
    return _PLAN


@contextlib.contextmanager
def armed(plan: FaultPlan):
    """``with faults.armed(plan): ...`` — arm for the block, always
    disarm after (chaos tests must never leak faults into their
    neighbors)."""
    arm(plan)
    try:
        yield plan
    finally:
        disarm()


# -- hooks (the only calls production code makes) ----------------------------
def _fire(site: str) -> FaultSpec | None:
    plan = _PLAN
    if plan is None:            # zero-cost no-op: one load, one check
        return None
    n, spec = plan._next(site)
    if spec is None:
        return None
    # fault firings annotate the active trace span (cess_tpu/obs):
    # chaos runs under an armed tracer show WHERE each injected fault
    # landed in the request's path; a no-op without a current span
    _trace.event("fault", site=site, ordinal=n, kind=spec.kind)
    if spec.delay_s:            # sleep OUTSIDE the plan lock; the
        # plan's injected clock (if any) absorbs the delay as virtual
        # time — the wall clock only moves for unclocked plans
        (plan.clock or time).sleep(spec.delay_s)
    if spec.kind == "raise":
        detail = f": {spec.message}" if spec.message else ""
        raise FaultInjected(f"injected fault at {site}#{n}{detail}")
    return spec


def inject(site: str) -> None:
    """Control seam: a due ``raise`` throws, a ``delay`` sleeps;
    ``drop``/``corrupt`` specs are meaningless here and act as no-ops."""
    _fire(site)


def allow(site: str) -> bool:
    """Messaging/transfer seam: False when a ``drop`` fires (after any
    scheduled delay); a due ``raise`` still throws."""
    spec = _fire(site)
    return spec is None or spec.kind != "drop"


def corrupt(site: str, data):
    """Data seam: returns ``data`` with its first byte xor-flipped when
    a ``corrupt`` fires (bytes or uint8 ndarray), untouched otherwise."""
    spec = _fire(site)
    if spec is None or spec.kind != "corrupt":
        return data
    if isinstance(data, (bytes, bytearray)):
        out = bytearray(data)
        if out:
            out[0] ^= spec.xor
        return bytes(out)
    arr = np.array(data, copy=True)
    if arr.size:
        flat = arr.reshape(-1)
        flat[0] ^= np.asarray(spec.xor, dtype=arr.dtype)
    return arr
