"""Health-gated degradation: sliding-window backend health + a circuit
breaker that falls the engine back device -> CPU reference codec.

The engine's device backends (TPUCodec, the accelerator AuditBackend)
and its CPU references compute IDENTICAL bytes — the trait-gate
determinism the whole repo is built on (tests/test_serve.py pins
engine == direct, tests/test_rs_tpu.py pins TPU == NumPy oracle). That
makes degradation free of protocol risk: when a backend's error rate
trips the breaker, serving the same batches on the CPU reference
changes latency, never results.

:class:`HealthMonitor` is deliberately COUNT-based, not wall-clock
based: the breaker trips after an observed error fraction over a
sliding outcome window, and while open it converts every
``probe_every``-th admission request into a recovery probe (one in
flight at a time). No timers means deterministic, schedulable tests —
the same sequence of outcomes always produces the same state
transitions (the same seam discipline as resilience/faults.py).

Touched from both the engine's submitter threads (admission) and the
batcher (outcome recording), so every attribute is guarded by the one
internal lock — tools/cesslint.py's lock-discipline family scans this
package (tests/test_lint.py).
"""
from __future__ import annotations

import collections
import dataclasses
import threading
import typing

from ..obs import flight as _flight
from .retry import RetryPolicy
from .stats import ResilienceStats


class HealthMonitor:
    """Per-backend sliding-window health + breaker.

    window:           outcomes retained for the error-rate estimate.
    error_threshold:  observed error fraction that trips the breaker.
    min_samples:      outcomes required before tripping is possible
                      (one unlucky first call must not open it).
    probe_every:      while open, every Nth allow() becomes a recovery
                      probe (at most one in flight); a probe success
                      closes the breaker, a failure re-arms the count.
    """

    def __init__(self, window: int = 32, error_threshold: float = 0.5,
                 min_samples: int = 4, probe_every: int = 8):
        if window < 1 or not 0 < error_threshold <= 1 \
                or min_samples < 1 or probe_every < 1:
            raise ValueError("invalid health monitor bounds")
        self.window = window
        self.error_threshold = error_threshold
        self.min_samples = min_samples
        self.probe_every = probe_every
        # journal identity: the engine names its monitors ("codec",
        # "audit") at registration — and the device pool names each
        # lane's per-(backend, device) monitors ("codec.d0",
        # "audit.d3", serve/pool.py) — so breaker journal entries and
        # incident bundles say WHICH breaker moved, and a single sick
        # chip's trips never alias its siblings' health
        self.name = ""
        self._mu = threading.Lock()
        self._outcomes: collections.deque = \
            collections.deque(maxlen=window)      # (ok, latency_s)
        self._state = "closed"
        self._denied = 0           # opens since the last probe
        self._probe_inflight = False
        self._trips = 0
        self._probes = 0
        self._recoveries = 0
        # external latch (the SLO admission controller's "SLO at
        # risk" degrade, serve/adaptive.py): while held, the breaker
        # is open with NO recovery probes — the device is not broken,
        # it is being vacated, and only the holder may reopen it
        self._held = False
        self._hold_reason = ""
        self._holds = 0

    # -- gating -------------------------------------------------------------
    def allow(self) -> bool:
        """May the next dispatch use the monitored backend? While the
        breaker is open, every ``probe_every``-th call is admitted as
        a recovery probe (its outcome decides the state) — unless the
        open is an external HOLD, which admits nothing until
        released (probing a healthy device the SLO controller is
        deliberately vacating would defeat the vacating)."""
        with self._mu:
            if self._held:
                return False
            if self._state == "closed":
                return True
            if self._probe_inflight:
                return False
            self._denied += 1
            if self._denied >= self.probe_every:
                self._denied = 0
                self._probes += 1
                self._probe_inflight = True
                return True
            return False

    # -- outcomes -----------------------------------------------------------
    def record_success(self, latency_s: float = 0.0) -> None:
        recovered = False
        with self._mu:
            self._outcomes.append((True, latency_s))
            # only an ADMITTED probe's success closes the breaker: an
            # incidental success on a non-representative shape (e.g. a
            # 1-row salvage re-run while big coalesced batches still
            # die) must not flap the engine back onto a bad device
            if self._state == "open" and self._probe_inflight:
                self._state = "closed"
                self._recoveries += 1
                self._outcomes.clear()     # fresh window post-recovery
                recovered = True
            self._probe_inflight = False
        if recovered:
            # journal notes ALWAYS run with self._mu released: the
            # incident listener snapshots this very monitor
            _flight.note("breaker", "recover", name=self.name)

    def record_error(self) -> None:
        tripped = False
        with self._mu:
            self._outcomes.append((False, 0.0))
            self._probe_inflight = False
            if self._state != "closed":
                return                     # failed probe: stay open
            n = len(self._outcomes)
            errs = sum(1 for ok, _ in self._outcomes if not ok)
            if n >= self.min_samples \
                    and errs >= self.error_threshold * n:
                self._trip_locked()
                tripped = True
        if tripped:
            _flight.note("breaker", "trip", name=self.name,
                         reason="error-window")

    def _trip_locked(self) -> None:
        self._state = "open"
        self._trips += 1
        self._denied = 0
        self._outcomes.clear()

    # -- external latch (SLO-gated degradation, serve/adaptive.py) ----------
    def hold_open(self, reason: str = "held") -> None:
        """Latch the breaker open under an external controller: every
        dispatch degrades (no probes, no window-driven recovery) until
        :meth:`release`. The PR-6 extension of "device broken" to "SLO
        at risk" — the device stays healthy, the monitored backend is
        being vacated for higher-priority traffic. Idempotent; a hold
        over an already-tripped breaker just layers the latch (the
        trip's own recovery resumes on release)."""
        latched = False
        with self._mu:
            if not self._held:
                self._held = True
                self._holds += 1
                latched = True
            self._hold_reason = reason
        if latched:
            _flight.note("breaker", "hold", name=self.name,
                         reason=reason)

    def release(self) -> None:
        """Drop the external latch. A breaker that was ALSO tripped by
        its error window stays open and probes its way back (the hold
        never masks a real failure); one opened purely by the hold
        returns to closed with a fresh window."""
        with self._mu:
            if not self._held:
                return
            self._held = False
            self._hold_reason = ""
            if self._state == "closed":
                self._outcomes.clear()
                self._denied = 0
                self._probe_inflight = False
        _flight.note("breaker", "release", name=self.name)

    # -- manual control (bench/tests/ops) -----------------------------------
    def force_open(self) -> None:
        """Trip the breaker unconditionally (the bench's degraded-mode
        assertion, operator kill switches)."""
        tripped = False
        with self._mu:
            if self._state == "closed":
                self._trip_locked()
                tripped = True
        if tripped:
            _flight.note("breaker", "trip", name=self.name,
                         reason="forced")

    def force_close(self) -> None:
        with self._mu:
            self._held = False
            self._hold_reason = ""
            if self._state == "open":
                self._state = "closed"
                self._denied = 0
                self._probe_inflight = False
                self._outcomes.clear()

    # -- introspection ------------------------------------------------------
    @property
    def state(self) -> str:
        """"closed" / "open" (window-tripped) / "held" (external
        latch, serve/adaptive.py) — a held breaker reports held even
        if its window also tripped, since only release() can admit
        traffic again."""
        with self._mu:
            return "held" if self._held else self._state

    def snapshot(self) -> dict:
        with self._mu:
            n = len(self._outcomes)
            errs = sum(1 for ok, _ in self._outcomes if not ok)
            lats = [t for ok, t in self._outcomes if ok]
            return {
                "state": "held" if self._held else self._state,
                "held_reason": self._hold_reason,
                "holds": self._holds,
                "trips": self._trips,
                "probes": self._probes,
                "recoveries": self._recoveries,
                "window_samples": n,
                "error_rate": round(errs / n, 4) if n else 0.0,
                "mean_latency_s":
                    round(sum(lats) / len(lats), 6) if lats else 0.0,
            }


@dataclasses.dataclass
class ResilienceConfig:
    """Everything the engine needs to serve through failure: the retry
    policy for saturation backoff, a monitor factory (one breaker per
    backend: "codec", "audit"), whether a tripped breaker may fall
    back to the CPU reference backend, and the shared counter sink.

    ``fallback=False`` keeps the isolation/retry machinery but lets
    device failures surface after it (for deployments where silently
    absorbing a device loss is worse than failing loudly)."""

    retry: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    monitor: typing.Callable[[], HealthMonitor] = HealthMonitor
    fallback: bool = True
    stats: ResilienceStats = \
        dataclasses.field(default_factory=ResilienceStats)
