"""cess_tpu.resilience — fault tolerance for the serving data plane.

Four parts, one theme: the stack that audits OTHER people's storage
faults must survive its own. See each module for the full design:

- faults.py   deterministic fault injector: a seeded FaultPlan fires
              raise/delay/drop/corrupt actions at named sites threaded
              through the hot-path seams (engine dispatch, stream
              staging, codec gates, fragment transfer, peer
              messaging); zero-cost no-ops when nothing is armed, and
              same seed => bit-identical schedule, so chaos tests run
              in tier-1.
- retry.py    RetryPolicy (exponential backoff + deterministic
              jitter) and Budget (deadline propagation: each attempt
              spends from the request's ONE remaining-time pool).
- health.py   HealthMonitor (sliding-window error rates, count-based
              recovery probes) + the breaker-gated device->CPU
              degradation config; CPU results are bit-identical by
              construction, so degradation changes latency only.
- stats.py    cess_resilience_* counters, merged into the engine's
              GET /metrics exposition next to cess_engine_*.

Wire-up: ``serve.make_engine(..., resilience=ResilienceConfig())`` or
``node.cli --resilience`` (mirrors ``--engine``); everything stays
opt-in — without a config the engine behaves exactly as before.
"""
from .faults import (FaultInjected, FaultPlan, FaultSpec, allow, arm,
                     armed, armed_plan, corrupt, disarm, inject)
from .health import HealthMonitor, ResilienceConfig
from .retry import Budget, RetryPolicy
from .stats import ResilienceStats

__all__ = [
    "Budget",
    "FaultInjected",
    "FaultPlan",
    "FaultSpec",
    "HealthMonitor",
    "ResilienceConfig",
    "ResilienceStats",
    "RetryPolicy",
    "allow",
    "arm",
    "armed",
    "armed_plan",
    "corrupt",
    "disarm",
    "inject",
]
