"""Mersenne prime field F_p, p = 2^31 - 1, in 32-bit lane arithmetic.

TPUs have no native 64-bit integer path, so the PoDR2 field math
(tags, proof aggregation, verification) runs entirely in uint32 with
16-bit limb splitting and the M31 rotation identity (2^31 == 1 mod p,
so multiplying by 2^k is a 31-bit rotation). Every op keeps all
intermediates < 2^32 — exact, overflow-free, and pure VPU work.

The same functions trace under JAX (device path) and execute eagerly
on NumPy arrays (host oracle); tests/test_pfield.py checks both against
Python bigint arithmetic.

Why M31 and not GF(2^8): PoDR2 needs a field big enough that the
Shacham-Waters MAC check sigma == sum(nu_i f_k(i)) + sum(alpha_j mu_j)
has negligible forgery probability per element (~2^-31 here); the
reference's own PoDR2 lives in its external TEE repos and only the
on-chain contract (opaque proof blob <= SIGMA_MAX=2048 B,
/root/reference/runtime/src/lib.rs:992) constrains the design.
"""
from __future__ import annotations

import numpy as np

P = (1 << 31) - 1  # 2147483647, Mersenne prime M31
MASK16 = 0xFFFF


def _xp(x):
    """numpy/jax dispatch: use the module of the input array."""
    import jax

    return jax.numpy if isinstance(x, jax.Array) else np


def _cond_sub_p(xp, r):
    """r - P where r >= P, else r — without evaluating an underflowing
    branch (numpy's where computes both sides eagerly)."""
    return r - (r >= P).astype(xp.uint32) * xp.uint32(P)


def to_field(x):
    """Reduce arbitrary uint32 values into [0, p)."""
    xp = _xp(x)
    x = x.astype(xp.uint32)
    r = (x & P) + (x >> 31)  # < 2^31 + 1
    return _cond_sub_p(xp, r)


def addmod(a, b):
    """(a + b) mod p for a, b in [0, p)."""
    xp = _xp(a)
    s = a.astype(xp.uint32) + b.astype(xp.uint32)  # < 2^32 - 2: no overflow
    return _cond_sub_p(xp, s)


def submod(a, b):
    xp = _xp(a)
    a = a.astype(xp.uint32)
    b = b.astype(xp.uint32)
    return xp.where(a >= b, a - b, a + P - b)


def negmod(a):
    xp = _xp(a)
    a = a.astype(xp.uint32)
    return xp.where(a == 0, a, P - a)


def rotk(x, k: int):
    """x * 2^k mod p for x in [0, p), 0 <= k < 31: 31-bit rotation."""
    if k == 0:
        return x
    return ((x << k) & P) | (x >> (31 - k))


def _rot16(x):
    return rotk(x, 16)


def mulmod(a, b):
    """(a * b) mod p for a, b in [0, p), all intermediates < 2^32.

    Limb split a = a1*2^16 + a0 (a1 < 2^15), same for b:
    a*b = 2*a1*b1 + (a1*b0 + a0*b1)*2^16 + a0*b0  (mod p, 2^32 == 2).
    """
    xp = _xp(a)
    a = a.astype(xp.uint32)
    b = b.astype(xp.uint32)
    a0, a1 = a & MASK16, a >> 16
    b0, b1 = b & MASK16, b >> 16
    t_hi = to_field(a1 * b1 * 2)          # a1*b1 < 2^30 -> *2 < 2^31
    lo = to_field(a0 * b0)                # < 2^32
    m1 = a1 * b0                          # < 2^31
    m2 = a0 * b1                          # < 2^31
    mid = addmod(_rot16(_cond_sub_p(xp, m1)), _rot16(_cond_sub_p(xp, m2)))
    return addmod(addmod(t_hi, mid), lo)


def dot_u16_deferred(m, b, axis):
    """sum_j m_j * b_j mod p with DEFERRED reduction, for m in
    [0, 2^16), b in [0, p), and the contracted axis <= 256.

    The hot-loop trick behind PoDR2 tag-gen: split m into 8-bit and b
    into 16-bit limbs; every partial product is < 2^24, so a PLAIN
    uint32 sum over <= 256 terms cannot overflow (256 * 255 * 65535 =
    4,278,124,800 < 2^32) — one modular fold per OUTPUT element
    instead of a full mulmod + limb-split sum per INPUT element
    (~2.5x fewer VPU ops than mulmod_u16 + summod; measured on chip).
    """
    xp = _xp(m)
    n = m.shape[axis]
    assert n <= 256, f"deferred dot bound: axis dim {n} > 256"
    m = m.astype(xp.uint32)
    b = b.astype(xp.uint32)
    mlo, mhi = m & 0xFF, m >> 8
    b0, b1 = b & MASK16, b >> 16
    s00 = xp.sum(mlo * b0, axis=axis, dtype=xp.uint32)
    s10 = xp.sum(mhi * b0, axis=axis, dtype=xp.uint32)
    s01 = xp.sum(mlo * b1, axis=axis, dtype=xp.uint32)
    s11 = xp.sum(mhi * b1, axis=axis, dtype=xp.uint32)
    return addmod(addmod(to_field(s00), rotk(to_field(s10), 8)),
                  addmod(rotk(to_field(s01), 16),
                         rotk(to_field(s11), 24)))


def mulmod_u16(a, b):
    """(a * b) mod p for a in [0, 2^16), b in [0, p).

    The data-side fast path: PoDR2 packs fragment bytes two-per-element
    (pack_bytes width 2), so the m operand of every MAC/proof multiply
    is < 2^16 and its high limb is structurally zero — half of the
    generic mulmod disappears. With a < 2^16:
      a*b0 < 2^32 (one to_field), a*b1 <= (2^16-1)(2^15-1) < p (rot16
      directly). When b is a constant (alpha), XLA hoists its limb
      split, leaving ~2 multiplies + 2 reductions per element.
    """
    xp = _xp(a)
    a = a.astype(xp.uint32)
    b = b.astype(xp.uint32)
    return addmod(to_field(a * (b & MASK16)), _rot16(a * (b >> 16)))


def summod(x, axis=-1):
    """Exact modular sum along an axis; requires dim size <= 65535.

    Values in [0, p) are limb-split so the plain uint32 sums cannot
    overflow, then recombined mod p.
    """
    xp = _xp(x)
    n = x.shape[axis]
    if n > 65535:
        raise ValueError(f"summod axis dim {n} > 65535; fold first")
    x = x.astype(xp.uint32)
    lo = xp.sum(x & MASK16, axis=axis, dtype=xp.uint32)   # <= n * (2^16-1) < 2^32
    hi = xp.sum(x >> 16, axis=axis, dtype=xp.uint32)      # <= n * 2^15 < 2^31
    return addmod(_rot16(to_field(hi)), to_field(lo))


def dotmod(a, b, axis=-1):
    """Modular dot product sum_i a_i * b_i along an axis."""
    return summod(mulmod(a, b), axis=axis)


def psum_mod(x, axis_name: str):
    """Exact modular psum across a mesh axis (JAX only).

    Values in [0, p) are limb-split so plain uint32 psums cannot
    overflow for any device count <= 65536 (lo/hi <= ndev * (2^16 - 1)
    < 2^32), then recombined exactly mod p: both psum results are first
    reduced into [0, p) before the final addmod, so no intermediate can
    exceed 2^32 at any device count the limb bound admits.
    """
    import jax

    lo = jax.lax.psum(x & MASK16, axis_name)
    hi = jax.lax.psum(x >> 16, axis_name)
    return addmod(to_field(lo), _rot16(to_field(hi)))


def powmod(a: int, e: int) -> int:
    """Host-side scalar pow (for matrix inversion / host checks)."""
    return pow(int(a), int(e), P)


def invmod(a: int) -> int:
    if int(a) % P == 0:
        raise ZeroDivisionError("inverse of 0 in F_p")
    return pow(int(a), P - 2, P)


# -- byte packing ----------------------------------------------------------
#
# Elements embed bytes injectively into [0, p). Width 2 (16-bit) divides
# every power-of-two fragment size into whole blocks (8 MiB / 512 B
# blocks exactly), which keeps the PoDR2 block grid aligned with the
# reference's power-of-two segment/fragment geometry; width 3 (24-bit)
# is denser but leaves remainder bytes on power-of-two sizes.

BYTES_PER_ELEM = 2


def pack_bytes(data, width: int = BYTES_PER_ELEM, xp=None):
    """uint8 [..., width*L] -> uint32 field elements [..., L] (little-endian)."""
    if xp is None:
        xp = _xp(data)
    *lead, n = data.shape
    assert n % width == 0, f"byte length {n} not divisible by {width}"
    assert 1 <= width <= 3  # width 4 would not embed into [0, p)
    if xp is not np and width == 2 and data.dtype == xp.uint8:
        # device fast path: a u8-pair -> u16 BITCAST is the same
        # little-endian combine as the shift-or below but lowers to a
        # relayout instead of two shifted adds — measured 1.75x on the
        # tag-gen pack stage (v5e, r05); the numpy branch stays the
        # canonical oracle and tests pin both paths byte-equal
        import jax

        h = jax.lax.bitcast_convert_type(
            data.reshape(*lead, n // 2, 2), xp.uint16)
        return h.astype(xp.uint32)
    d = data.reshape(*lead, n // width, width).astype(xp.uint32)
    out = d[..., 0]
    for i in range(1, width):
        out = out | (d[..., i] << (8 * i))
    return out


def unpack_bytes(elems, width: int = BYTES_PER_ELEM, xp=None):
    """Inverse of pack_bytes: uint32 [..., L] (< 2^(8*width)) -> uint8."""
    if xp is None:
        xp = _xp(elems)
    e = elems.astype(xp.uint32)
    parts = xp.stack([(e >> (8 * i)) & 0xFF for i in range(width)], axis=-1)
    return parts.reshape(*e.shape[:-1], e.shape[-1] * width).astype(xp.uint8)
