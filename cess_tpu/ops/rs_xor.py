"""Bit-sliced executor for compiled XOR schedules (cess_tpu/ops/xor_sched).

Instead of materialising 0/1 bit-planes (8x expansion) or riding the
MXU (rs_pallas.py), this path keeps the data packed: 4 consecutive
data bytes are viewed as one uint32 lane, and bit-plane b of byte row
j is ``(row_u32 >> b) & 0x01010101`` — the information bit of every
byte sits at bit position 0 of its byte lane, so every schedule op is
one full-lane uint32 XOR over the column tile, covering 4 data bytes
per lane. Unpack is a shift+mask per touched input plane, pack is a
shift+or per output plane; byte order round-trips exactly because no
op ever mixes bit positions across byte lanes.

Two executors run the SAME schedule, bit-identical to
rs.py::_apply_bitmatrix by construction (both compute the same GF(2)
linear map exactly — pinned in tests/test_xor_sched.py):

- a Pallas TPU kernel: grid over (batch row, column tile), input and
  output tiles plus the schedule's liveness-allocated scratch slots
  in VMEM, every op a full-lane VPU uint32 instruction;
- a pure-jnp fallback executing the same op list for CPU and
  interpret-free testing (the CPU test mesh default).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .xor_sched import OP_ACC, OP_COPY, OP_XOR, XorSchedule

DEFAULT_TILE_LANES = 8192          # uint32 lanes per column tile
_MASK = 0x01010101                 # bit 0 of each packed byte


def _run_ops(sched: XorSchedule, read_input, zeros):
    """Trace the schedule once in SSA form: ``read_input(plane)``
    yields an input bit-plane lane vector, ``zeros()`` a zero vector.
    Returns (scratch_writes, out_planes): the ordered scratch-slot
    write list the Pallas kernel replays into VMEM, and the r8 output
    plane values. The jnp fallback ignores scratch_writes — its slots
    live as SSA values keyed by the same addresses."""
    q8, ob = sched.q8, sched.out_base
    vals: dict[int, jax.Array] = {}
    scratch_writes: list[tuple[int, jax.Array]] = []

    def get(i):
        if i not in vals:
            if i >= q8:
                raise AssertionError(f"read before write at {i}")
            vals[i] = read_input(i)
        return vals[i]

    for op, d, a, b in sched.ops:
        if op == OP_XOR:
            v = get(a) ^ get(b)
        elif op == OP_ACC:
            v = get(d) ^ get(a)
        elif op == OP_COPY:
            v = get(a)
        else:
            v = zeros()
        vals[d] = v
        if q8 <= d < ob:
            scratch_writes.append((d - q8, v))
    return scratch_writes, [vals[ob + i] for i in range(sched.r8)]


def _pack_rows(sched: XorSchedule, out_planes):
    """Fold the r8 output bit-planes back into r packed byte rows."""
    rows = []
    for i in range(sched.r8 // 8):
        word = out_planes[8 * i]
        for a in range(1, 8):
            word = word | (out_planes[8 * i + a] << a)
        rows.append(word)
    return rows


@functools.partial(jax.jit, static_argnums=(0,))
def _apply_jnp(sched: XorSchedule, u32: jax.Array) -> jax.Array:
    """u32 [B, q, n4] -> [B, r, n4]; the pure-jnp schedule executor."""
    mask = jnp.uint32(_MASK)

    def read_input(plane):
        j, b = divmod(plane, 8)
        return (u32[:, j, :] >> b) & mask

    _, out_planes = _run_ops(sched, read_input,
                             lambda: jnp.zeros_like(u32[:, 0, :]))
    return jnp.stack(_pack_rows(sched, out_planes), axis=1)


def _make_kernel(sched: XorSchedule, tile_lanes: int):
    def kernel(in_ref, out_ref, scratch_ref):
        mask = jnp.uint32(_MASK)

        def read_input(plane):
            j, b = divmod(plane, 8)
            return (in_ref[0, j, :] >> b) & mask

        scratch_writes, out_planes = _run_ops(
            sched, read_input,
            lambda: jnp.zeros((tile_lanes,), jnp.uint32))
        # replay the liveness-allocated slot writes into VMEM: the
        # scratch high-water mark bounds live intermediates per tile
        for slot, v in scratch_writes:
            scratch_ref[slot, :] = v
        for i, word in enumerate(_pack_rows(sched, out_planes)):
            out_ref[0, i, :] = word

    return kernel


@functools.partial(jax.jit, static_argnums=(0, 1))
def _apply_pallas(sched: XorSchedule, tile_lanes: int,
                  u32: jax.Array) -> jax.Array:
    """u32 [B, q, n4] -> [B, r, n4] through the bit-sliced VPU kernel."""
    b, q, n4 = u32.shape
    r = sched.r8 // 8
    grid = (b, n4 // tile_lanes)
    # interpret mode lets the same kernel run on the CPU test mesh
    interpret = jax.default_backend() == "cpu"
    return pl.pallas_call(
        _make_kernel(sched, tile_lanes),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, q, tile_lanes), lambda i, t: (i, 0, t),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r, tile_lanes),
                               lambda i, t: (i, 0, t),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, r, n4), jnp.uint32),
        scratch_shapes=[
            pltpu.VMEM((sched.n_scratch, tile_lanes), jnp.uint32),
        ],
        interpret=interpret,
    )(u32)


def apply_schedule(sched: XorSchedule, data: jax.Array,
                   tile_lanes: int = DEFAULT_TILE_LANES,
                   force: str | None = None) -> jax.Array:
    """Apply a compiled schedule to [..., q, n] uint8 data.

    Returns [..., r, n] uint8. ``force`` pins the executor ("pallas" |
    "jnp"); default is the Pallas kernel on real devices and the jnp
    fallback on the CPU backend. n is padded to the lane/tile multiple
    (zero columns produce zero outputs — harmless, stripped)."""
    q, r = sched.q8 // 8, sched.r8 // 8
    data = jnp.asarray(data, dtype=jnp.uint8)
    *lead, q_in, n = data.shape
    if q_in != q:
        raise ValueError(f"data rows {q_in} != schedule inputs {q}")
    use_pallas = force == "pallas" or (
        force is None and jax.default_backend() != "cpu")
    step = 4 * tile_lanes if use_pallas else 4
    pad = (-n) % step
    if pad:
        data = jnp.pad(data, [(0, 0)] * len(lead) + [(0, 0), (0, pad)])
    n_pad = n + pad
    flat = data.reshape(-1, q, n_pad // 4, 4)
    u32 = jax.lax.bitcast_convert_type(flat, jnp.uint32)  # [B, q, n4]
    if use_pallas:
        out32 = _apply_pallas(sched, tile_lanes, u32)
    else:
        out32 = _apply_jnp(sched, u32)
    out = jax.lax.bitcast_convert_type(out32, jnp.uint8)  # [B, r, n4, 4]
    out = out.reshape(*lead, r, n_pad)
    if pad:
        out = out[..., :n]
    return out
