"""PoDR2 proof-of-storage ops: batched tag-gen / prove / verify on TPU.

The reference's PoDR2 flow (SURVEY.md §3.3): a TEE worker computes
per-fragment tags off-chain; each challenge round, snapshotted miners
compute an aggregated (sigma, mu) proof over ~47 randomly challenged
chunks (c-pallets/audit/src/lib.rs:956-974), and a TEE verifies it
against the network PoDR2 key. The tag/proof math itself lives in
CESS's external TEE repos; on-chain only the contract shows: proof blob
<= SIGMA_MAX = 2048 bytes (runtime/src/lib.rs:992), challenge = chunk
indices + 20-byte randoms.

Here the scheme is a Shacham-Waters private-verification PoR with the
MAC over F_p^2, p = 2^31 - 1 (data stays in F_p), redesigned for
batched TPU execution:

- A fragment (FRAGMENT_SIZE bytes) is split into ``blocks`` of
  ``sectors`` field elements (2 bytes each, so power-of-two fragment
  sizes divide into whole 512-byte blocks). For 8 MiB fragments and
  sectors=256: 16384 blocks.
- TagGen (TEE secret key (alpha[sectors, 2], prf_key)): alpha and the
  PRF live in F_p^2 = F_p[i]/(i^2+1) (p == 3 mod 4 so irreducible);
  data m and challenge coefficients nu stay in the base field, so
  every F_p^2 operation used below is COMPONENTWISE — two
  independently-keyed copies of the base-field MAC, one per limb:
      tag[b] = f_k(fragment_id, b) + sum_j alpha[j] * m[b, j]  in F_p^2
  (tags are [blocks, 2] uint32).
- Challenge: ``count`` block indices I and coefficients nu in F_p
  (both PRF-derived from the round randomness, mirroring audit's
  46/1000 coverage and 20-byte randoms).
- Prove (miner, needs only data + tags, no secrets):
      mu[j]  = sum_{i in I} nu[i] * m[I[i], j]   (mod p, base field)
      sigma  = sum_{i in I} nu[i] * tag[I[i]]    (componentwise, F_p^2)
  Proof size: see PROOF_BYTES below — the ONE authoritative statement
  of the raw payload size and its relation to the framed wire size.
- Verify (TEE), one equation per limb, BOTH must hold:
      sigma ?= sum_i nu[i] * f_k(id, I[i]) + sum_j alpha[j] * mu[j]

SOUNDNESS: a forged (mu', sigma') with mu' != mu must hit
sum_j alpha_j (mu'_j - mu_j) in F_p^2 with alpha unknown and uniform:
acceptance probability p^-2 ~= 2^-62 per verification (vs ~2^-31 for
the r03 single-equation scheme; the reference's BLS check is ~2^-128
but needs pairings, /root/reference/utils/verify-bls-signatures/
src/lib.rs:1-247 via primitives/enclave-verify/src/lib.rs:230-235).
Grinding headroom: at 8000 miners x 14400 rounds/day (caps from
runtime/src/lib.rs:988) a 2^-62 break still needs ~10^11 years.

Everything is batch-first over a fragment axis and jit/vmap/pjit-able;
the byte/block axis shards across the mesh with psum aggregation
(cess_tpu/parallel/mesh.py).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from .. import constants
from . import pfield as pf

SECTORS = 256                       # field elements per block
BLOCK_BYTES = SECTORS * pf.BYTES_PER_ELEM   # 512
# Default MAC limb count: F_p^LIMBS, soundness ~p^-LIMBS per verify.
# MEASURED on the real v5e chip (r05, 128 x 8 MiB resident batches,
# jnp path): LIMBS=2 (soundness ~2^-62) tags at ~1926 frags/s,
# LIMBS=3 (~2^-93) at ~1681 — the third limb costs ~13% of tag
# throughput, and per-limb cost scales the same through the fused
# kernel (ops/podr2_pallas.py, ~6.4k frags/s at limbs=2 — tag-gen is
# the dominant audit stage; verify evaluates the PRF only at the
# challenged blocks and is width-insensitive). 2 stays the default:
# at protocol caps (8000 miners x 14400 rounds/day) a 2^-62 forgery
# still needs ~10^11 years, and the audit path is throughput-critical
# (100k fragments per round). Deployments wanting ~2^-93 pass
# Podr2Params(limbs=3) end to end (tests run both widths).
LIMBS = 2
# THE authoritative aggregated-proof size statement (three separate
# prose copies drifted to 1032/1028/1058 before r06; everything else
# refers here). The RAW payload is mu [SECTORS] + sigma [LIMBS]
# uint32: (SECTORS + LIMBS) * 4 = 1032 bytes at the defaults. On the
# wire the payload travels codec-framed (node/offchain.py Proof: two
# fixed-width ndarrays, so dtype/shape/length headers add a CONSTANT
# overhead independent of F — 26 bytes at the defaults, 1058 B framed,
# pinned by tests/test_podr2.py test_aggregate_proof_wire_size_constant
# via node/offchain.py proof_wire_bytes(), which lives next to Proof
# because framing is node-layer knowledge the ops layer must not
# import). Both forms stay under SIGMA_MAX = 2048
# (runtime/src/lib.rs:992), limbs=3 included.
PROOF_BYTES = (SECTORS + LIMBS) * 4
assert (SECTORS + 3) * 4 <= constants.SIGMA_MAX   # limbs=3 fits too


@dataclasses.dataclass(frozen=True)
class Podr2Params:
    sectors: int = SECTORS
    limbs: int = LIMBS          # MAC limb count (see module doc)

    def blocks_for(self, fragment_bytes: int) -> int:
        block_bytes = self.sectors * pf.BYTES_PER_ELEM
        assert fragment_bytes % block_bytes == 0, (
            f"fragment {fragment_bytes} B not divisible by block {block_bytes} B")
        return fragment_bytes // block_bytes


@dataclasses.dataclass(frozen=True)
class Podr2Key:
    """TEE-held secret key (the reference's TeePodr2Pk analog is the
    public handle; private verification keeps the whole key in the TEE,
    SURVEY.md §2.1 tee-worker)."""

    alpha: jax.Array        # [sectors, limbs] uint32 in [0, p)
    prf_key: jax.Array      # jax PRNG key

    @property
    def limbs(self) -> int:
        return self.alpha.shape[1]

    @staticmethod
    def generate(seed: int, params: Podr2Params = Podr2Params()) -> "Podr2Key":
        root = jax.random.key(seed)
        k_alpha, k_prf = jax.random.split(root)
        alpha = pf.to_field(
            jax.random.bits(k_alpha, (params.sectors, params.limbs),
                            jnp.uint32))
        return Podr2Key(alpha=alpha, prf_key=k_prf)


def keys_equal(a: Podr2Key, b: Podr2Key) -> bool:
    """Value equality of two PoDR2 keys (alpha + PRF key material).

    Security-sensitive single source of truth: components that accept
    an externally-built device stack (e.g. a submission engine's
    AuditBackend) must refuse a key that differs from their own, or
    tags/verdicts silently diverge from the protocol."""
    if a is b:
        return True
    return (np.array_equal(np.asarray(a.alpha), np.asarray(b.alpha))
            and np.array_equal(jax.random.key_data(a.prf_key),
                               jax.random.key_data(b.prf_key)))


def fragment_id_from_hash(fragment_hash: bytes) -> np.ndarray:
    """Protocol fragment id = low 8 bytes of the on-chain fragment hash,
    as a (lo, hi) uint32 pair (x32 mode cannot carry 64-bit scalars).

    SECURITY CONTRACT: tag-gen ids must be unique per key — reusing an
    id for different data under one key lets an adversary difference
    two tag sets and solve for alpha. Hash-derived ids give uniqueness
    for free (distinct fragments have distinct hashes).
    """
    v = int.from_bytes(fragment_hash[:8], "little")
    return np.array([v & 0xFFFFFFFF, v >> 32], dtype=np.uint32)


def _fragment_key(prf_key, fragment_id):
    """Per-fragment PRF key: fragment_id (possibly 64-bit) folds in as
    two 32-bit words (x32 mode cannot carry 64-bit scalars)."""
    if isinstance(fragment_id, int):
        lo = np.uint32(fragment_id & 0xFFFFFFFF)
        hi = np.uint32((fragment_id >> 32) & 0xFFFFFFFF)
    else:
        fid = jnp.asarray(fragment_id)
        if fid.ndim == 1 and fid.shape[0] == 2:   # (lo, hi) pair
            lo, hi = fid[0].astype(jnp.uint32), fid[1].astype(jnp.uint32)
        else:                                      # plain 32-bit scalar id
            lo, hi = fid.astype(jnp.uint32), jnp.uint32(0)
    return jax.random.fold_in(jax.random.fold_in(prf_key, lo), hi)


def prf_elems_at(prf_key, fragment_id, block_idx, limbs: int = LIMBS):
    """f_k(fragment_id, b) for the GIVEN block indices only
    [len(block_idx), limbs].

    The PRF is defined PER BLOCK — f_k(id, b) = bits(fold_in(key_id, b))
    — precisely so callers can evaluate it sparsely: a challenge names
    ~4.6% of a fragment's blocks (audit's 46/1000 coverage), and the
    verifier regenerating all 16384 was the dominant verify cost
    (measured ~40x on the real chip, r05). threefry is counter-based
    and platform-deterministic, so CPU and TPU paths agree bit-exactly
    (a protocol invariant, like the codec).
    """
    key = _fragment_key(prf_key, fragment_id)

    def one(b):
        return pf.to_field(jax.random.bits(
            jax.random.fold_in(key, b), (limbs,), jnp.uint32))

    return jax.vmap(one)(jnp.asarray(block_idx).astype(jnp.uint32))


def prf_elems(prf_key, fragment_id, n: int, limbs: int = LIMBS):
    """f_k(fragment_id, 0..n-1): the full per-block PRF range
    [n, limbs] (tag-gen side). Identical by construction to
    prf_elems_at over arange(n) — sharded executions slice their local
    range so tags are identical regardless of mesh topology."""
    return prf_elems_at(prf_key, fragment_id,
                        jnp.arange(n, dtype=jnp.uint32), limbs)


def tag_from_elems(alpha, f, m):
    """tags [B, limbs] from PRF slice f [B, limbs] and packed data
    m [B, s].

    m is base-field, alpha [s, limbs] is F_p^limbs: the product is
    componentwise, so each limb is an independent base-field MAC.
    m < 2^16 by the pack_bytes width-2 embedding, and sectors <= 256,
    so the deferred-reduction dot applies (the MAC is the tag-gen hot
    loop: 4M elements x limbs per 8 MiB fragment; see
    pf.dot_u16_deferred)."""
    if m.shape[-1] <= 256:
        return pf.addmod(f, pf.dot_u16_deferred(
            m[..., None], alpha[None, :, :], axis=-2))
    return pf.addmod(f, pf.summod(
        pf.mulmod_u16(m[..., None], alpha[None, :, :]), axis=-2))


def fragment_to_elems(fragment, sectors: int = SECTORS):
    """uint8 [..., fragment_bytes] -> uint32 [..., blocks, sectors]."""
    *lead, nbytes = fragment.shape
    elems = pf.pack_bytes(fragment)
    return elems.reshape(*lead, nbytes // (sectors * pf.BYTES_PER_ELEM), sectors)


def tag_fragment(key: Podr2Key, fragment_id, fragment) -> jax.Array:
    """Tags for one fragment: uint8 [fragment_bytes] -> uint32 [blocks, 2]."""
    m = fragment_to_elems(fragment, key.alpha.shape[0])     # [B, s]
    return tag_from_elems(key.alpha, prf_elems(key.prf_key, fragment_id,
                                               m.shape[0], key.limbs), m)


def tag_fragments(key: Podr2Key, fragment_ids, fragments) -> jax.Array:
    """Batched tag-gen: ids [F], fragments [F, fragment_bytes] ->
    [F, blocks, limbs]. Routes through the fused Pallas kernel
    (ops/podr2_pallas.py) when the shape envelope allows — identical
    results, one VMEM pass instead of materialised pack/MAC stages."""
    from . import podr2_pallas

    fragments = jnp.asarray(fragments)
    sectors = key.alpha.shape[0]
    blocks = fragments.shape[-1] // (sectors * pf.BYTES_PER_ELEM)
    # a TRACED alpha (key passed as a jit argument) cannot feed the
    # kernel's host-side weight precompute; the jnp path traces fine
    alpha_concrete = not isinstance(key.alpha, jax.core.Tracer)
    if alpha_concrete and podr2_pallas.supported(sectors, blocks):
        prf = jax.vmap(
            lambda i: prf_elems(key.prf_key, i, blocks,
                                key.limbs))(fragment_ids)
        return podr2_pallas.tag_fragments_fused(key.alpha, prf,
                                                fragments)
    return jax.vmap(lambda i, d: tag_fragment(key, i, d))(fragment_ids,
                                                          fragments)


def gen_challenge(seed_bytes: bytes | int, num_blocks: int,
                  count: int | None = None):
    """Derive (indices [c], nu [c]) from round randomness.

    Coverage mirrors audit's 46/1000 of chunks (SURVEY.md §3.3); the
    reference draws 20-byte randoms per index, here nu in F_p.
    """
    if count is None:
        count = max(1, num_blocks * constants.CHALLENGE_RATE_NUM
                    // constants.CHALLENGE_RATE_DEN)
    if isinstance(seed_bytes, bytes):
        import hashlib

        # 64-bit fold of the round randomness. jax.random.key truncates
        # its seed to 32 bits under x32, so the second word goes in via
        # fold_in rather than the seed.
        digest = hashlib.sha256(seed_bytes).digest()
        w0 = int.from_bytes(digest[:4], "little")
        w1 = int.from_bytes(digest[4:8], "little")
    else:
        w0 = int(seed_bytes) & 0xFFFFFFFF
        w1 = (int(seed_bytes) >> 32) & 0xFFFFFFFF
    key = jax.random.fold_in(jax.random.key(np.uint32(w0)), np.uint32(w1))
    k_idx, k_nu = jax.random.split(key)
    idx = jax.random.randint(k_idx, (count,), 0, num_blocks, dtype=jnp.int32)
    nu = pf.to_field(jax.random.bits(k_nu, (count,), jnp.uint32))
    return idx, nu


def prove(fragment, tags, idx, nu, sectors: int = SECTORS):
    """Miner-side proof for one fragment -> (mu [sectors], sigma [2]).

    Needs only public data: the fragment bytes and its tags [blocks, 2].
    """
    m = fragment_to_elems(fragment, sectors)       # [B, s]
    m_i = jnp.take(m, idx, axis=0)                 # [c, s]
    # m < 2^16 (pack_bytes width 2): data-side fast multiply
    mu = pf.summod(pf.mulmod_u16(m_i, nu[:, None]), axis=0)  # [s]
    sigma = pf.dotmod(nu[:, None], jnp.take(tags, idx, axis=0), axis=0)
    return mu, sigma


def prove_batch(fragments, tags, idx, nu, sectors: int = SECTORS):
    """[F, bytes], [F, blocks, 2] -> (mu [F, sectors], sigma [F, 2])."""
    return jax.vmap(lambda d, t: prove(d, t, idx, nu, sectors))(fragments, tags)


def aggregate_coeffs(seed_bytes: bytes, fragment_ids) -> jax.Array:
    """Per-fragment random linear-combination coefficients r[F] for
    cross-fragment proof aggregation, PRF-derived from the round seed
    and each fragment id — the prover cannot choose them.

    Aggregation (the SIGMA_MAX fix, runtime/src/lib.rs:992): instead
    of shipping (mu, sigma) PER fragment (O(F KiB) on the wire), the
    miner folds all its fragments into ONE (mu, sigma):

        mu_total    = sum_f r_f * mu_f
        sigma_total = sum_f r_f * sigma_f

    The Shacham-Waters verification equation is linear in (mu, sigma),
    so the TEE checks the fold against the fragment set the CHAIN says
    the miner owes — a constant-size proof regardless of F
    (PROOF_BYTES raw payload + constant codec framing; see the
    authoritative statement at PROOF_BYTES, framed total computed by
    node/offchain.py proof_wire_bytes).
    """
    import hashlib

    digest = hashlib.sha256(b"cess-podr2-agg:" + seed_bytes).digest()
    w0 = int.from_bytes(digest[:4], "little")
    w1 = int.from_bytes(digest[4:8], "little")
    key = jax.random.fold_in(jax.random.key(np.uint32(w0)), np.uint32(w1))
    ids = jnp.asarray(fragment_ids).reshape(-1, 2)

    def one(fid):
        k = jax.random.fold_in(jax.random.fold_in(key, fid[0]), fid[1])
        return pf.to_field(jax.random.bits(k, (), jnp.uint32))

    return jax.vmap(one)(ids)


def prove_aggregate(fragments, tags, idx, nu, r, sectors: int = SECTORS):
    """[F, bytes], [F, blocks, 2], r [F] -> (mu [sectors], sigma [2]).

    The constant-size aggregated proof across all of a miner's
    challenged fragments (see aggregate_coeffs)."""
    mu_f, sigma_f = prove_batch(fragments, tags, idx, nu, sectors)
    mu = pf.summod(pf.mulmod(r[:, None], mu_f), axis=0)
    sigma = pf.dotmod(r[:, None], sigma_f, axis=0)
    return mu, sigma


def verify_aggregate(key: Podr2Key, fragment_ids, num_blocks: int,
                     idx, nu, r, mu, sigma):
    """TEE-side check of an aggregated proof against the owed fragment
    set (ids [F, 2]). Returns a scalar bool — true only when BOTH
    F_p^2 limb equations hold (soundness ~p^-2, see module doc)."""
    ids = jnp.asarray(fragment_ids).reshape(-1, 2)
    f_i = jax.vmap(
        lambda i: prf_elems_at(key.prf_key, i, idx,
                               key.limbs))(ids)       # [F, c, limbs]
    lhs_f = jax.vmap(
        lambda f: pf.dotmod(nu[:, None], f, axis=0))(f_i)       # [F, limbs]
    lhs = pf.addmod(pf.dotmod(r[:, None], lhs_f, axis=0),
                    pf.dotmod(key.alpha, mu[:, None], axis=0))
    return jnp.all(lhs == jnp.asarray(sigma))


def verify_from_f(alpha, f, idx, nu, mu, sigma):
    """The verification equation given precomputed PRF values
    f [blocks, 2] (shared by single-device verify and the sharded mesh
    step). Both limb equations must hold."""
    lhs = pf.dotmod(nu[:, None], jnp.take(f, idx, axis=0), axis=0)   # [2]
    rhs = pf.dotmod(alpha, mu[:, None], axis=0)                      # [2]
    return jnp.all(pf.addmod(lhs, rhs) == sigma)


def verify(key: Podr2Key, fragment_id, num_blocks: int, idx, nu, mu, sigma):
    """TEE-side check; returns bool[] (scalar) per call — vmap for
    batches. Evaluates the PRF only at the challenged blocks
    (prf_elems_at), the verifier fast path."""
    f_i = prf_elems_at(key.prf_key, fragment_id, idx, key.limbs)
    lhs = pf.dotmod(nu[:, None], f_i, axis=0)
    rhs = pf.dotmod(key.alpha, mu[:, None], axis=0)
    return jnp.all(pf.addmod(lhs, rhs) == jnp.asarray(sigma))


def verify_batch(key: Podr2Key, fragment_ids, num_blocks: int, idx, nu, mu, sigma):
    """ids [F, 2] hash word pairs (or [F] scalar ids), mu [F, sectors],
    sigma [F, limbs] -> bool [F]."""
    return jax.vmap(
        lambda i, u, s: verify(key, i, num_blocks, idx, nu, u, s)
    )(fragment_ids, mu, sigma)
