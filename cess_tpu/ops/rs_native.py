"""ctypes binding for the native (C++) ErasureCodec backend.

Loads ``cess_tpu/native/libcessrs.so`` (auto-building it with the
in-tree Makefile on first use if a compiler is available) and exposes
``NativeCodec`` with the same surface as rs_ref.ReferenceCodec /
rs.TPUCodec. This is the framework's fast host path — the role the
reference delegates to native reed-solomon crates in its off-chain
components (SURVEY.md §2.3/§2.4) — and the honest CPU baseline for the
TPU-speedup benchmark (BASELINE.md, ≥40×).
"""
from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from . import gf

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO = os.path.join(_NATIVE_DIR, "libcessrs.so")


def _build() -> None:
    # build ONLY the RS target: a compile failure in another native
    # backend (e.g. bls381.cpp on an exotic toolchain) must not take
    # down this one
    subprocess.run(["make", "-C", _NATIVE_DIR, "-s", "libcessrs.so"],
                   check=True, capture_output=True)


def _load() -> ctypes.CDLL:
    if not os.path.exists(_SO):
        try:
            _build()
        except (OSError, subprocess.CalledProcessError) as e:
            raise ImportError(f"cannot build native codec: {e}") from e
    try:
        lib = ctypes.CDLL(_SO)
    except OSError as e:
        # stale / wrong-arch .so: importers expect ImportError so the
        # ErasureCodec gate (and bench) can fall back cleanly
        raise ImportError(f"cannot load {_SO}: {e}") from e
    lib.cess_rs_apply.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int,
    ]
    lib.cess_rs_apply.restype = None
    lib.cess_rs_simd.restype = ctypes.c_int
    return lib


_LIB = _load()


def simd_level() -> int:
    """0 = scalar build, 2 = AVX2 build."""
    return int(_LIB.cess_rs_simd())


def _as_u8_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def apply_matrix(mat: np.ndarray, shards: np.ndarray,
                 threads: int = 1) -> np.ndarray:
    """GF matrix [r, q] applied to shards [..., q, n] -> [..., r, n]."""
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    shards = np.ascontiguousarray(shards, dtype=np.uint8)
    r, q = mat.shape
    lead = shards.shape[:-2]
    if shards.shape[-2] != q:
        raise ValueError(f"expected {q} shard rows, got {shards.shape[-2]}")
    n = shards.shape[-1]
    batch = int(np.prod(lead, dtype=np.int64)) if lead else 1
    out = np.empty((*lead, r, n), dtype=np.uint8)
    _LIB.cess_rs_apply(_as_u8_ptr(mat), r, q, _as_u8_ptr(shards),
                       batch, n, _as_u8_ptr(out), int(threads))
    return out


class NativeCodec:
    """Systematic RS(k, m) on the native C++ path (ErasureCodec
    surface: encode / encode_parity / reconstruct / decode_data)."""

    def __init__(self, k: int, m: int, threads: int = 1):
        if k < 1 or m < 0 or k + m > gf.FIELD:
            raise ValueError(f"invalid RS geometry k={k}, m={m}")
        self.k = k
        self.m = m
        self.threads = threads
        self.parity = gf.cauchy_parity_matrix(k, m)

    def encode_parity(self, data: np.ndarray) -> np.ndarray:
        return apply_matrix(self.parity, data, self.threads)

    def encode(self, data: np.ndarray) -> np.ndarray:
        data = np.asarray(data, dtype=np.uint8)
        if data.shape[-2] != self.k:
            raise ValueError(
                f"expected {self.k} data shards, got {data.shape[-2]}")
        return np.concatenate([data, self.encode_parity(data)], axis=-2)

    def reconstruct(self, survivors: np.ndarray, present: tuple[int, ...],
                    missing: tuple[int, ...] | None = None) -> np.ndarray:
        present = tuple(present)
        if missing is None:
            missing = tuple(i for i in range(self.k + self.m)
                            if i not in present)
        mat = gf.repair_matrix(self.k, self.m, present, tuple(missing))
        return apply_matrix(mat, survivors, self.threads)

    def decode_data(self, survivors: np.ndarray,
                    present: tuple[int, ...]) -> np.ndarray:
        mat = gf.decode_matrix(self.k, self.m, tuple(present))
        return apply_matrix(mat, survivors, self.threads)
