"""Device-layer ops: GF(2^8) RS codec and PoDR2 audit kernels."""
