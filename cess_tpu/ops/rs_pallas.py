"""Pallas-fused GF(2^8) matrix apply for the RS codec (TPU).

The pure-XLA bitmatrix path (cess_tpu/ops/rs.py:_apply_bitmatrix)
materialises the 8x bit-plane expansion and the f32 matmul output in
HBM — ~5.8 GiB/s on v5e. This kernel fuses the whole chain
(unpack bits -> MXU matmul -> parity (&1) -> pack bytes) inside VMEM,
tiled along the byte axis, so HBM traffic is just the uint8 input and
output rows.

Round-4 probe findings (tools/probe2.py, v5e, 2 GiB resident batches —
smaller batches are dispatch-bound through the axon tunnel and mask
kernel differences):
- throughput was FLAT across group {1,2,4,8} x tile {8k..128k} x
  subtile interleave {1,2,4}: the kernel is NOT MXU-slot-bound, so
  kron-segment-grouping buys nothing (levers kept as tuning knobs);
- the VPU byte-PACK (bit-parity -> weighted sublane reduction) cost
  ~45% of runtime: skipping it measured 42.2 GiB/s vs 23.4 full;
- hence ``mxupack``: the pack is a SECOND small int8 matmul — packed
  byte = sum_b w_b * parity_b with w = [1,2,4,8,16,32,64,-128] (the
  -128 exploits two's-complement wraparound of the uint8 cast), so
  the sublane reduction rides the idle MXU instead of the VPU;
- iota-broadcast bit ops are the fast VPU lowering: jnp.stack of 8
  strided slices forces sublane relayouts, measured ~3x slower.

Layout contract: data [..., q, n] uint8 is viewed as [B, q, n] (segment
rows are contiguous); the grid walks (segment-group, column-tile) and
each step applies the (8rg x 8qg) GF(2) block-diagonal bit-matrix
``kron(I_group, expand_bitmatrix(mat))`` to one (g x q x TILE_N) tile.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_TILE_N = 32768
DEFAULT_GROUP = 2      # v5e probe: mxupack g=2/32k 51.9 GiB/s, the peak
DEFAULT_SUBTILES = 1
PACK_W = (1, 2, 4, 8, 16, 32, 64, -128)   # int8-safe byte weights


def _make_kernel(q: int, r: int, g: int, tile_n: int, subtiles: int,
                 acc_dtype, mxu_pack: bool):
    op_dtype = jnp.bfloat16 if acc_dtype == jnp.float32 else jnp.int8
    ts = tile_n // subtiles

    def kernel(bmat_ref, pack_ref, data_ref, out_ref):
        for s in range(subtiles):
            sl = slice(s * ts, (s + 1) * ts)
            data = data_ref[:, :, sl].astype(jnp.int32)      # [g, q, ts]
            # unpack bit-planes: contraction row g_i*8q + 8j + b
            shifts = jax.lax.broadcasted_iota(jnp.int32, (1, 1, 8, 1), 2)
            bits = (data[:, :, None, :] >> shifts) & 1       # [g, q, 8, ts]
            bits = bits.reshape(8 * q * g, ts).astype(op_dtype)
            prod = jnp.dot(bmat_ref[:], bits,
                           preferred_element_type=acc_dtype)
            if mxu_pack:
                y = (prod.astype(jnp.int32) & 1).astype(jnp.int8)
                packed = jnp.dot(pack_ref[:], y,
                                 preferred_element_type=jnp.int32)
                out_ref[:, :, sl] = packed.reshape(
                    g, r, ts).astype(jnp.uint8)
            else:
                obits = prod.astype(jnp.int32) & 1           # parity == XOR
                obits = obits.reshape(g, r, 8, ts)
                weights = jax.lax.broadcasted_iota(
                    jnp.int32, (1, 1, 8, 1), 2)
                packed = jnp.sum(obits << weights, axis=2)   # [g, r, ts]
                out_ref[:, :, sl] = packed.astype(jnp.uint8)

    return kernel


@functools.partial(jax.jit, static_argnums=(2, 3, 4, 5, 6, 7, 9))
def _apply_3d(bmat: jax.Array, packmat: jax.Array, q: int, r: int, g: int,
              tile_n: int, subtiles: int, use_int8: bool,
              data3d: jax.Array, mxu_pack: bool) -> jax.Array:
    """bmat [8rg, 8qg] block-diag; data3d [B, q, n] -> [B, r, n]."""
    b, _, n = data3d.shape
    acc_dtype = jnp.int32 if use_int8 else jnp.float32
    kernel = _make_kernel(q, r, g, tile_n, subtiles, acc_dtype, mxu_pack)
    grid = (b // g, n // tile_n)
    # interpret mode lets the same kernel run on the CPU test mesh
    interpret = jax.default_backend() == "cpu"
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * r * g, 8 * q * g), lambda i, t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((r * g, 8 * r * g), lambda i, t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((g, q, tile_n), lambda i, t: (i, 0, t),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((g, r, tile_n), lambda i, t: (i, 0, t),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, r, n), jnp.uint8),
        interpret=interpret,
    )(bmat, packmat, data3d)


@functools.lru_cache(maxsize=64)
def _matrices_np(bmat_key, g: int, r: int,
                 use_int8: bool) -> tuple[np.ndarray, np.ndarray]:
    # cache NUMPY only: a jnp array created inside a jit trace would be
    # a tracer, and caching a tracer leaks it across traces
    bmat_np = np.frombuffer(bmat_key[2], dtype=np.uint8).reshape(bmat_key[:2])
    big = np.kron(np.eye(g, dtype=np.uint8), bmat_np)
    big = big.astype(np.int8) if use_int8 else big.astype(np.float32)
    # pack matrix [rg, 8rg]: row i selects its 8 bit-rows with weights
    pack = np.kron(np.eye(r * g, dtype=np.int8),
                   np.asarray(PACK_W, dtype=np.int8)[None, :])
    return big, pack


def apply_bitmatrix(bmat_np: np.ndarray, data: jax.Array,
                    tile_n: int = DEFAULT_TILE_N, use_int8: bool = True,
                    group: int = DEFAULT_GROUP,
                    subtiles: int = DEFAULT_SUBTILES,
                    mxu_pack: bool = True) -> jax.Array:
    """Apply an expanded (8r x 8q) GF(2) bit-matrix to [..., q, n] uint8 data.

    Returns [..., r, n] uint8. n is padded to a multiple of tile_n if
    needed (zero columns encode to zero parity — harmless, stripped);
    ``group`` degrades to the largest divisor of the flattened batch.
    """
    r8, q8 = bmat_np.shape
    q, r = q8 // 8, r8 // 8
    data = jnp.asarray(data, dtype=jnp.uint8)
    *lead, q_in, n = data.shape
    assert q_in == q, f"data rows {q_in} != matrix cols {q}"
    pad = (-n) % tile_n
    if pad:
        data = jnp.pad(data, [(0, 0)] * len(lead) + [(0, 0), (0, pad)])
    flat = data.reshape(-1, q, data.shape[-1])  # [B, q, n_pad]
    g = group
    while flat.shape[0] % g:
        g //= 2
    sub = subtiles
    while tile_n % sub:
        sub //= 2
    bmat_u8 = np.ascontiguousarray(bmat_np.astype(np.uint8))
    big_np, pack_np = _matrices_np(
        (bmat_u8.shape[0], bmat_u8.shape[1], bmat_u8.tobytes()), g, r,
        use_int8)
    bmat = jnp.asarray(big_np,
                       dtype=jnp.int8 if use_int8 else jnp.bfloat16)
    packmat = jnp.asarray(pack_np)
    out = _apply_3d(bmat, packmat, q, r, g, tile_n, sub, use_int8, flat,
                    mxu_pack)
    out = out.reshape(*lead, r, data.shape[-1])
    if pad:
        out = out[..., :n]
    return out
