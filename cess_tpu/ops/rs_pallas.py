"""Pallas-fused GF(2^8) matrix apply for the RS codec (TPU).

The pure-XLA bitmatrix path (cess_tpu/ops/rs.py:_apply_bitmatrix)
materialises the 8x bit-plane expansion and the f32 matmul output in
HBM — ~5.8 GiB/s on v5e. This kernel fuses the whole chain
(unpack bits -> MXU matmul -> parity (&1) -> pack bytes) inside VMEM,
tiled along the byte axis, so HBM traffic is just the uint8 input and
output rows.

Layout contract: data [..., q, n] uint8 is viewed as [B*q, n] (segment
rows are contiguous); the grid walks (segment, column-tile) and each
step applies the (8r x 8q) GF(2) bit-matrix to one (q x TILE_N) tile.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_TILE_N = 32768  # v5e sweep: 8k..64k within ~4%, 32k the sweet spot


def _make_kernel(q: int, r: int, tile_n: int, acc_dtype):
    op_dtype = jnp.bfloat16 if acc_dtype == jnp.float32 else jnp.int8

    def kernel(bmat_ref, data_ref, out_ref):
        data = data_ref[0].astype(jnp.int32)  # [q, T]
        # unpack bit-planes: row 8j+b = bit b of byte row j
        shifts = jax.lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1)
        bits = (data[:, None, :] >> shifts) & 1          # [q, 8, T]
        bits = bits.reshape(8 * q, tile_n).astype(op_dtype)
        prod = jnp.dot(bmat_ref[:], bits, preferred_element_type=acc_dtype)
        obits = prod.astype(jnp.int32) & 1               # parity == XOR-accumulate
        obits = obits.reshape(r, 8, tile_n)
        weights = jax.lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1)
        packed = jnp.sum(obits << weights, axis=1)       # [r, T]
        out_ref[0] = packed.astype(jnp.uint8)

    return kernel


@functools.partial(jax.jit, static_argnums=(1, 2, 3, 4))
def _apply_3d(bmat: jax.Array, q: int, r: int, tile_n: int, use_int8: bool,
              data3d: jax.Array) -> jax.Array:
    """bmat [8r, 8q]; data3d [B, q, n] -> [B, r, n]."""
    b, _, n = data3d.shape
    acc_dtype = jnp.int32 if use_int8 else jnp.float32
    kernel = _make_kernel(q, r, tile_n, acc_dtype)
    grid = (b, n // tile_n)
    # interpret mode lets the same kernel run on the CPU test mesh
    interpret = jax.default_backend() == "cpu"
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * r, 8 * q), lambda i, t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, q, tile_n), lambda i, t: (i, 0, t),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r, tile_n), lambda i, t: (i, 0, t),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((b, r, n), jnp.uint8),
        interpret=interpret,
    )(bmat, data3d)


def apply_bitmatrix(bmat_np: np.ndarray, data: jax.Array,
                    tile_n: int = DEFAULT_TILE_N, use_int8: bool = True) -> jax.Array:
    """Apply an expanded (8r x 8q) GF(2) bit-matrix to [..., q, n] uint8 data.

    Returns [..., r, n] uint8. n is padded to a multiple of tile_n if
    needed (zero columns encode to zero parity — harmless, stripped).
    """
    r8, q8 = bmat_np.shape
    q, r = q8 // 8, r8 // 8
    data = jnp.asarray(data, dtype=jnp.uint8)
    *lead, q_in, n = data.shape
    assert q_in == q, f"data rows {q_in} != matrix cols {q}"
    pad = (-n) % tile_n
    if pad:
        data = jnp.pad(data, [(0, 0)] * len(lead) + [(0, 0), (0, pad)])
    flat = data.reshape(-1, q, data.shape[-1])  # [B, q, n_pad]
    op_dtype = np.int8 if use_int8 else jnp.bfloat16
    bmat = jnp.asarray(bmat_np.astype(np.int8) if use_int8 else bmat_np,
                       dtype=op_dtype)
    out = _apply_3d(bmat, q, r, tile_n, use_int8, flat)
    out = out.reshape(*lead, r, data.shape[-1])
    if pad:
        out = out[..., :n]
    return out
