"""TPU-native GF(2^8) Reed-Solomon erasure codec (JAX/XLA).

The reference framework's segment->fragment erasure coding runs as a
sequential CPU loop in off-chain components (SURVEY.md §2.3, §6); here
it becomes a batched GF(2^8) matrix apply on TPU. Two lowerings, both
byte-exact against the NumPy oracle (cess_tpu/ops/rs_ref.py):

- ``gather``: the classic SIMD "split table" scheme (two 16-entry
  nibble tables per generator coefficient) vectorised over the byte
  axis — VPU-bound, no bit expansion, minimal HBM traffic.
- ``bitmatrix``: every GF(2^8) constant multiply is an 8x8 GF(2)
  matrix, so the whole (r x q) GF apply becomes one (8r x 8q) 0/1
  matrix applied to bit-planes with XOR accumulation = bf16 matmul on
  the MXU followed by ``& 1``. 8x bit expansion, but all FLOPs land on
  the systolic array. (A Pallas-fused variant that keeps the expansion
  in VMEM lives in cess_tpu/ops/rs_pallas.py.)
- ``xor``: the bitmatrix compiled ONCE into a CSE'd XOR schedule
  (cess_tpu/ops/xor_sched.py) executed bit-sliced on the VPU
  (cess_tpu/ops/rs_xor.py) — sparse work instead of the dense 8x
  expansion.
- ``auto``: a compile-time cost model picks dense vs scheduled-XOR per
  (matrix, dispatch shape); the choice is recorded in cache_meta so
  program-cache keys attribute it. Explicit ``strategy=`` always
  forces.

Geometry (k, m) is first-class (reference pins FRAGMENT_COUNT=3 i.e.
RS(2,1), /root/reference/runtime/src/lib.rs:1026-1027; BASELINE.json
targets RS(4,8)). Decode/repair matrices for a given erasure pattern
are built host-side (tiny Gauss-Jordan) and applied with the same
batched device kernels.
"""
from __future__ import annotations

import contextlib
import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from ..resilience import faults
from . import gf

Strategy = str  # "gather" | "bitmatrix" | "pallas" | "xor" | "auto"

# ---------------------------------------------------------------------------
# Table construction (host side, tiny)
# ---------------------------------------------------------------------------


def nibble_tables(mat: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Split tables for an (r x q) GF matrix.

    Returns (lo, hi), each [r, q, 16] uint8 with
    ``lo[i, j, x] = mat[i,j] * x`` and ``hi[i, j, x] = mat[i,j] * (x << 4)``
    so ``mat[i,j] * b == lo[i,j,b & 15] ^ hi[i,j,b >> 4]``.
    """
    mat = np.asarray(mat, dtype=np.uint8)
    r, q = mat.shape
    mt = gf.mul_table()
    lo = np.zeros((r, q, 16), dtype=np.uint8)
    hi = np.zeros((r, q, 16), dtype=np.uint8)
    nib = np.arange(16, dtype=np.uint8)
    for i in range(r):
        for j in range(q):
            lo[i, j] = mt[mat[i, j]][nib]
            hi[i, j] = mt[mat[i, j]][nib << 4]
    return lo, hi


# ---------------------------------------------------------------------------
# Device kernels (generic GF matrix apply, jitted per shape signature)
# ---------------------------------------------------------------------------


@jax.jit
def _apply_gather(lo: jax.Array, hi: jax.Array, data: jax.Array) -> jax.Array:
    """GF apply via nibble-table gathers.

    lo/hi: [r, q, 16] uint8 split tables; data: [..., q, n] uint8.
    Returns [..., r, n] uint8.
    """
    r, q, _ = lo.shape
    d_lo = (data & 0x0F).astype(jnp.int32)
    d_hi = (data >> 4).astype(jnp.int32)
    acc = None
    for j in range(q):
        # tables for input row j: [r, 16]; gather over the byte axis
        t_lo = jnp.take(lo[:, j], d_lo[..., j, :], axis=1)  # [r, ..., n]
        t_hi = jnp.take(hi[:, j], d_hi[..., j, :], axis=1)
        term = t_lo ^ t_hi
        acc = term if acc is None else acc ^ term
    return jnp.moveaxis(acc, 0, -2)  # [..., r, n]


@jax.jit
def _apply_bitmatrix(bmat: jax.Array, data: jax.Array) -> jax.Array:
    """GF apply via the GF(2) bit-matrix lowering on the MXU.

    bmat: [8r, 8q] bf16 0/1 matrix (gf.expand_bitmatrix of the GF matrix);
    data: [..., q, n] uint8. Returns [..., r, n] uint8.
    """
    q = data.shape[-2]
    n = data.shape[-1]
    r8 = bmat.shape[0]
    # unpack bytes to bit-planes: [..., q, n] -> [..., 8q, n]
    shifts = jnp.arange(8, dtype=jnp.uint8)
    bits = (data[..., :, None, :] >> shifts[None, :, None]) & 1  # [..., q, 8, n]
    bits = bits.reshape(*data.shape[:-2], 8 * q, n)
    # bit-matrix apply with f32 accumulation; entries <= 8q so exact
    prod = jnp.einsum(
        "ab,...bn->...an",
        bmat,
        bits.astype(jnp.bfloat16),
        preferred_element_type=jnp.float32,
    )
    obits = prod.astype(jnp.int32) & 1  # XOR accumulate == parity of the sum
    # pack bit-planes back to bytes: [..., 8r, n] -> [..., r, n]
    obits = obits.reshape(*data.shape[:-2], r8 // 8, 8, n)
    weights = (1 << jnp.arange(8, dtype=jnp.int32))[None, :, None]
    out = jnp.sum(obits * weights, axis=-2, dtype=jnp.int32)
    return out.astype(jnp.uint8)


def _pallas_apply(bmat_np: np.ndarray, data: jax.Array) -> jax.Array:
    from . import rs_pallas  # local import: pallas only needed on this path

    return rs_pallas.apply_bitmatrix(bmat_np, data)


# ---------------------------------------------------------------------------
# Codec front-end
# ---------------------------------------------------------------------------


class _MatrixApply:
    """A GF matrix baked into device tables, applied with a chosen strategy."""

    def __init__(self, mat: np.ndarray, strategy: Strategy):
        self.mat = np.asarray(mat, dtype=np.uint8)
        self.strategy = strategy
        if strategy == "gather":
            lo, hi = nibble_tables(self.mat)
            self._lo = jnp.asarray(lo)
            self._hi = jnp.asarray(hi)
        elif strategy == "bitmatrix":
            self._bmat_np = gf.expand_bitmatrix(self.mat)
            self._bmat = jnp.asarray(self._bmat_np, dtype=jnp.bfloat16)
        elif strategy == "pallas":
            self._bmat_np = gf.expand_bitmatrix(self.mat)
        elif strategy == "xor":
            from . import xor_sched  # local: default strategies never pay it

            self._sched = xor_sched.compile_schedule(
                gf.expand_bitmatrix(self.mat))
        elif strategy == "auto":
            # compile-time cost model: bake BOTH lowerings, pick per
            # dispatch shape (the decision is pure arithmetic over
            # static shapes — results never change, only which program
            # serves them; cache_meta records the choice)
            from . import xor_sched

            self._sched = xor_sched.compile_schedule(
                gf.expand_bitmatrix(self.mat))
            self._auto_base = _MatrixApply(self.mat, default_strategy())
        else:
            raise ValueError(f"unknown strategy {strategy!r}")

    def _decide(self, shape) -> dict:
        """Cost-model verdict for one data shape (strategy="auto")."""
        from . import xor_sched

        rows = 1
        for d in shape[:-2]:
            rows *= int(d)
        return xor_sched.estimate(self._sched.r8, self._sched.q8,
                                  self._sched.n_xors,
                                  xor_sched.rows_bucket(rows))

    def cache_meta(self, shape) -> tuple:
        """Program-cache key components attributing this apply: the
        strategy that serves ``shape`` plus the cost-model estimate
        (nested str/int tuples, so they ride ProgramCache keys into
        OpProfiler/CompileLedger verbatim). Empty — zero cache-key
        growth — for the dense default strategies."""
        if self.strategy == "auto":
            est = self._decide(tuple(shape))
            return (("strategy", "auto:" + est["chosen"]),
                    ("dense_cost", est["dense_cost"]),
                    ("xor_cost", est["xor_cost"]),
                    ("n_xors", est["n_xors"]))
        if self.strategy == "xor":
            return (("strategy", "xor"),
                    ("n_xors", self._sched.n_xors),
                    ("dense_xors", self._sched.dense_xors))
        return ()

    def _apply_xor(self, data: jax.Array) -> jax.Array:
        from . import rs_xor

        return rs_xor.apply_schedule(self._sched, data)

    def __call__(self, data: jax.Array) -> jax.Array:
        if data.shape[-2] != self.mat.shape[1]:
            raise ValueError(
                f"expected {self.mat.shape[1]} shard rows, got {data.shape[-2]}"
            )
        if self.strategy == "gather":
            return _apply_gather(self._lo, self._hi, data)
        if self.strategy == "pallas":
            return _pallas_apply(self._bmat_np, data)
        if self.strategy == "xor":
            return self._apply_xor(data)
        if self.strategy == "auto":
            if self._decide(data.shape)["chosen"] == "xor":
                return self._apply_xor(data)
            return self._auto_base(data)
        return _apply_bitmatrix(self._bmat, data)

    def aot(self, shape, dtype=jnp.uint8, device=None):
        """AOT-compile this apply for one exact input shape: the
        tables/matrices are baked into the executable as constants
        (pre-staged) and calls skip the jit dispatch/tracing machinery
        entirely — the repair warm path (TPUCodec.warm_reconstruct).
        ``device`` pins which device the executable is compiled and
        staged for (None = the current default device); the compiled
        program is bound to that one device. Returns the compiled
        callable (data) -> result."""
        fn = jax.jit(self.__call__)
        with contextlib.nullcontext() if device is None \
                else jax.default_device(device):
            return fn.lower(
                jax.ShapeDtypeStruct(tuple(shape), dtype)).compile()


def _placement_device():
    """The device a dispatch issued RIGHT NOW would land on: the
    active ``jax.default_device`` scope's device (the pool's per-lane
    placement, serve/engine.py ``_lane_placement``), or None when no
    scope is active — JAX's backend default. This is the device
    component of the AOT warm-program cache key: an executable is
    bound to the device it was compiled for, so a warm hit compiled
    under device 0's scope must never be dispatched inside device 3's
    (the one-device-assumption bug this key component fixes)."""
    try:
        return jax.config.jax_default_device
    except AttributeError:   # very old jax: no such config state
        return None


def default_strategy() -> Strategy:
    """Pick the lowering for the current default backend.

    The MXU bit-matrix path wins on TPU (measured in bench.py); the
    gather path is the portable fallback (CPU test mesh, older chips).
    """
    return "gather" if jax.default_backend() == "cpu" else "pallas"


class TPUCodec:
    """Systematic RS(k, m) over GF(2^8) on the JAX device path.

    Same surface as rs_ref.ReferenceCodec (encode / encode_parity /
    reconstruct / decode_data); shards are uint8 [..., rows, n] with
    arbitrary leading batch dims — vmap is implicit via batched shapes.
    Decode matrices per erasure pattern are cached.
    """

    def __init__(self, k: int, m: int, strategy: Strategy | None = None):
        if k < 1 or m < 0 or k + m > gf.FIELD:
            raise ValueError(f"invalid RS geometry k={k}, m={m}")
        self.k = k
        self.m = m
        self.strategy = strategy or default_strategy()
        self._parity_apply = _MatrixApply(gf.cauchy_parity_matrix(k, m), self.strategy)
        self._cache: dict[tuple, _MatrixApply] = {}
        self._warm: dict[tuple, Callable] = {}   # AOT repair programs
        # observable warm-path dispatches: lets callers (bench.py's
        # fragment_repair_warm_p99_ms, tests) PROVE the warm program
        # ran rather than a silent fallback to the cold jit path
        self.warm_hits = 0

    # -- encode -------------------------------------------------------------
    def encode_parity(self, data: jax.Array) -> jax.Array:
        """[..., k, n] uint8 -> [..., m, n] parity shards."""
        return self._parity_apply(jnp.asarray(data, dtype=jnp.uint8))

    def encode(self, data: jax.Array) -> jax.Array:
        """[..., k, n] -> [..., k+m, n] coded shards (systematic).

        Fault seam ``rs.encode`` (cess_tpu/resilience): hooks sit on
        the DEVICE codec only — the CPU ReferenceCodec stays
        injection-free, so a chaos plan failing the device path leaves
        the breaker's fallback clean."""
        faults.inject("rs.encode")
        data = jnp.asarray(data, dtype=jnp.uint8)
        if data.shape[-2] != self.k:
            raise ValueError(f"expected {self.k} data shards, got {data.shape[-2]}")
        return jnp.concatenate([data, self.encode_parity(data)], axis=-2)

    # -- decode -------------------------------------------------------------
    def _matrix_for(self, kind: str, present: tuple[int, ...],
                    missing: tuple[int, ...] = ()) -> _MatrixApply:
        key = (kind, present, missing)
        if key not in self._cache:
            if kind == "decode":
                mat = gf.decode_matrix(self.k, self.m, present)
            else:
                mat = gf.repair_matrix(self.k, self.m, present, missing)
            self._cache[key] = _MatrixApply(mat, self.strategy)
        return self._cache[key]

    def warm_reconstruct(self, present, missing=None, shape=None,
                         device=None):
        """Pre-compile + pre-stage the reconstruct program for ONE
        erasure pattern and exact survivor shape (the restoral-market
        warm path): the decode matrix is built AND baked into an AOT
        executable now, so a later ``reconstruct`` with this pattern
        and shape dispatches the compiled program directly — no jit
        cache lookup, no tracing, no first-call compile in the latency
        budget (bench.py fragment_repair_warm_p99_ms measures the
        difference).

        ``device`` pins the device the executable is compiled for
        (the device-pool path warms once per lane); None warms for
        the CURRENT placement — the active jax.default_device scope,
        else the backend default. The warm cache is keyed by that
        placement too: a ``reconstruct`` only hits a warm program
        compiled for the placement it is dispatching under, never an
        executable bound to a different chip (tests/test_pool.py pins
        the two-device case). Returns the compiled callable."""
        present = tuple(present)
        if missing is None:
            missing = tuple(i for i in range(self.k + self.m)
                            if i not in present)
        missing = tuple(missing)
        if shape is None:
            raise ValueError("warm_reconstruct needs the exact "
                             "survivor shape, e.g. (k, fragment_size)")
        key = (present, missing, tuple(shape),
               _placement_device() if device is None else device)
        if key not in self._warm:
            self._warm[key] = self._matrix_for(
                "repair", present, missing).aot(shape, device=device)
        return self._warm[key]

    def reconstruct(self, survivors: jax.Array, present: tuple[int, ...],
                    missing: tuple[int, ...] | None = None) -> jax.Array:
        """Recover missing shards from any k survivors.

        survivors: [..., k, n] rows ordered as ``present``; returns
        [..., len(missing), n] (missing defaults to all absent rows).
        Dispatches a pre-compiled executable when the exact
        (pattern, shape) has been warmed (see warm_reconstruct).
        """
        faults.inject("rs.reconstruct")
        present = tuple(present)
        if missing is None:
            missing = tuple(i for i in range(self.k + self.m) if i not in present)
        missing = tuple(missing)
        survivors = jnp.asarray(survivors, dtype=jnp.uint8)
        # the warm key carries the CURRENT placement (see
        # warm_reconstruct): under a pool lane's default_device scope
        # only that lane's executable can hit
        warm = self._warm.get((present, missing,
                               tuple(survivors.shape),
                               _placement_device()))
        if warm is not None:
            self.warm_hits += 1
            return warm(survivors)
        apply_ = self._matrix_for("repair", present, missing)
        return apply_(survivors)

    def decode_data(self, survivors: jax.Array, present: tuple[int, ...]) -> jax.Array:
        """Recover the k data shards from any k survivors."""
        faults.inject("rs.decode")
        apply_ = self._matrix_for("decode", tuple(present))
        return apply_(jnp.asarray(survivors, dtype=jnp.uint8))

    def program_meta(self, kind: str, present=(), missing=(),
                     shape=()) -> tuple:
        """Program-cache key metadata for one engine op: which strategy
        serves (kind, pattern, shape) and the cost-model estimate that
        picked it (serve/engine.py appends this to ProgramCache keys so
        OpProfiler/CompileLedger attribute the choice). Returns () — no
        key growth at all — unless this codec runs strategy "xor" or
        "auto"; the default strategies stay invisible here."""
        if self.strategy not in ("xor", "auto"):
            return ()
        if kind == "encode":
            apply_ = self._parity_apply
        else:
            apply_ = self._matrix_for(kind, tuple(present), tuple(missing))
        return apply_.cache_meta(tuple(shape))


# ---------------------------------------------------------------------------
# ErasureCodec factory — the trait boundary of the north star
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def make_codec(k: int, m: int, backend: str = "cpu", strategy: Strategy | None = None):
    """The ``ErasureCodec`` gate: CPU path is the default, TPU opt-in.

    Mirrors the north-star design (BASELINE.json): erasure coding is
    gated behind a codec trait with the CPU reference implementation as
    default and the JAX/TPU path selectable. backend: "cpu" | "native"
    (C++ via ctypes) | "tpu"/"jax" | "regen" (regenerating-code repair
    plane, ops/regen.py) | "auto" (tpu if a TPU is present).
    """
    if backend == "auto":
        backend = "tpu" if jax.default_backend() != "cpu" else "cpu"
    if backend == "cpu":
        from .rs_ref import ReferenceCodec

        return ReferenceCodec(k, m)
    if backend == "native":
        try:
            from .rs_native import NativeCodec
        except ImportError as e:
            raise NotImplementedError(
                "native (C++) ErasureCodec backend not built; run "
                "`make -C cess_tpu/native` or use backend='cpu'"
            ) from e
        return NativeCodec(k, m)
    if backend in ("tpu", "jax"):
        return TPUCodec(k, m, strategy=strategy)
    if backend == "regen":
        # regenerating-code repair plane (ops/regen.py); imported lazily
        # because regen builds on this module
        from .regen import RegenCodec

        return RegenCodec(k, m, strategy=strategy)
    raise ValueError(f"unknown ErasureCodec backend {backend!r}")
