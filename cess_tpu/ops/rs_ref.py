"""Reference (NumPy, CPU) systematic Reed-Solomon erasure codec.

This is the byte-exact oracle for the TPU codec (cess_tpu/ops/rs.py) and
the default CPU path behind the ``ErasureCodec`` interface — mirroring
the reference framework, where erasure coding runs on CPU in off-chain
components and the chain only sees hashes (SURVEY.md §1; reference
c-pallets/file-bank/src/lib.rs:423-428 trusts precomputed fragment
hashes). Geometry (k, m) is first-class: the reference snapshot uses
(2, 1) (runtime/src/lib.rs:1026-1027); BASELINE.json uses (4, 8).
"""
from __future__ import annotations

import numpy as np

from . import gf


class ReferenceCodec:
    """Systematic RS(k, m) over GF(2^8) with a Cauchy parity matrix.

    ``encode`` maps k data shards to k+m shards (data rows first);
    ``reconstruct`` recovers any missing shards from any k survivors.
    Shards are uint8 arrays of equal length; a leading batch dimension
    is supported ([..., k, n] -> [..., k+m, n]).
    """

    def __init__(self, k: int, m: int):
        if k < 1 or m < 0 or k + m > gf.FIELD:
            raise ValueError(f"invalid RS geometry k={k}, m={m}")
        self.k = k
        self.m = m
        self.parity = gf.cauchy_parity_matrix(k, m)

    # -- core --------------------------------------------------------------
    def _apply(self, mat: np.ndarray, shards: np.ndarray) -> np.ndarray:
        """GF matmul of mat [r, q] with shards [..., q, n] -> [..., r, n]."""
        shards = np.asarray(shards, dtype=np.uint8)
        lead = shards.shape[:-2]
        q, n = shards.shape[-2:]
        flat = shards.reshape(-1, q, n)
        out = np.empty((flat.shape[0], mat.shape[0], n), dtype=np.uint8)
        for b in range(flat.shape[0]):
            out[b] = gf.gf_matmul(mat, flat[b])
        return out.reshape(*lead, mat.shape[0], n)

    def encode(self, data: np.ndarray) -> np.ndarray:
        """[..., k, n] data shards -> [..., k+m, n] coded shards."""
        data = np.asarray(data, dtype=np.uint8)
        if data.shape[-2] != self.k:
            raise ValueError(f"expected {self.k} data shards, got {data.shape[-2]}")
        parity = self._apply(self.parity, data)
        return np.concatenate([data, parity], axis=-2)

    def encode_parity(self, data: np.ndarray) -> np.ndarray:
        """[..., k, n] -> just the [..., m, n] parity shards."""
        return self._apply(self.parity, np.asarray(data, dtype=np.uint8))

    def reconstruct(self, survivors: np.ndarray, present: tuple[int, ...],
                    missing: tuple[int, ...] | None = None) -> np.ndarray:
        """Recover shards from any k survivors.

        survivors: [..., k, n] rows ordered as ``present`` (indices into
        the k+m shard rows). Returns the recovered [..., len(missing), n]
        shards; ``missing`` defaults to all absent indices in order.
        """
        present = tuple(present)
        if missing is None:
            missing = tuple(i for i in range(self.k + self.m) if i not in present)
        mat = gf.repair_matrix(self.k, self.m, present, tuple(missing))
        return self._apply(mat, survivors)

    def decode_data(self, survivors: np.ndarray, present: tuple[int, ...]) -> np.ndarray:
        """Recover the original k data shards from any k survivors."""
        mat = gf.decode_matrix(self.k, self.m, tuple(present))
        return self._apply(mat, survivors)
