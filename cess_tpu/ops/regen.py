"""Regenerating-code repair plane: computed repair symbols + fast
Cauchy-MDS decode.

Two papers, one plane:

- Fast Product-Matrix Regenerating Codes (arxiv 1412.3022): repair
  traffic should carry COMPUTED symbols, not raw fragments. Here each
  helper scales its survivor fragment by one product-matrix repair
  coefficient (``repair_coeffs``) and XOR-folds the result into a
  partial-sum accumulator passed down the helper chain
  (``fold_symbol_host``); only the final fragment-sized aggregate ever
  reaches the rebuilder. By GF(2^8) linearity the aggregate IS the
  reference reconstruction — ``XOR_j coeff_j * fragment_j`` equals the
  repair-matrix row applied to the survivors — so the rebuilder's
  ingress drops from k fragments to one, bit-identically.
- Cauchy MDS Array Codes With Efficient Decoding Method (arxiv
  1611.09968): the decode matrix for an erasure pattern is the inverse
  of a k x k submatrix of the systematic Cauchy generator. Instead of
  Gauss-Jordan elimination (gf.gf_mat_inv, O(t^3) with table lookups),
  the surviving-parity-by-missing-data subsystem is itself Cauchy, so
  its inverse has the closed product form (``cauchy_inverse``,
  O(t^2)); the full decode matrix assembles from it by one Schur
  complement step (``decode_matrix``). A field inverse is unique, so
  the fast construction is byte-identical to the reference path —
  pinned by tests, never assumed.

Device surfaces live behind the existing ``ErasureCodec`` gate
(ops/rs.py ``make_codec(..., backend="regen")``): ``RegenCodec``
subclasses TPUCodec, swaps every decode/repair matrix construction for
the closed form, and adds the batched symbol fold
(``fold_symbol`` — a [1, 2] GF matmul over (accumulator, fragment) row
pairs via the same gather/bitmatrix/pallas lowerings) with per-pattern
warm/AOT programs that ride ``engine.warm_repair``'s per-lane cache.
``RegenReference`` is the NumPy twin serving as the byte-exact oracle
and the engine's CPU-degraded fallback.

Determinism and sharing contracts (cesslint: this module is in the
sim-determinism and lock-discipline families): coefficient and matrix
construction feed the deterministic sim's repair storm and the
engine's warm caches, so nothing here may read a clock or draw
entropy; the warm/apply caches inherited from TPUCodec are shared by
the engine's batcher and pool-lane worker threads, so any state this
module adds must stay within the same single-writer warm-then-dispatch
discipline.
"""
from __future__ import annotations

import numpy as np

from . import gf
from .rs import TPUCodec, _MatrixApply, _placement_device
from .rs_ref import ReferenceCodec

__all__ = [
    "cauchy_inverse", "decode_matrix", "repair_matrix", "repair_coeffs",
    "fold_symbol_host", "fold_symbol_pairs", "RegenCodec",
    "RegenReference",
]


# ---------------------------------------------------------------------------
# Pattern validation (shared by every construction below)
# ---------------------------------------------------------------------------


def _check_pattern(k: int, m: int, present: tuple[int, ...],
                   what: str = "present") -> tuple[int, ...]:
    """Refuse malformed erasure patterns loudly: duplicates,
    out-of-range rows and (for ``present``) wrong survivor counts all
    produce garbage matrices downstream if let through."""
    present = tuple(int(r) for r in present)
    rows = k + m
    if len(set(present)) != len(present):
        raise ValueError(f"duplicate {what} shard indices: {present}")
    for r in present:
        if not 0 <= r < rows:
            raise ValueError(f"{what} shard index {r} out of range for "
                             f"RS({k},{m}) with {rows} rows")
    return present


# ---------------------------------------------------------------------------
# The efficient decoding method (arxiv 1611.09968)
# ---------------------------------------------------------------------------


def cauchy_inverse(xs, ys) -> np.ndarray:
    """Closed-form inverse of the Cauchy matrix A[i, j] = 1/(xs[i] ^ ys[j]).

    The classic product formula (subtraction is XOR in GF(2^8)):

        inv[j, i] = prod_l (xs[l]^ys[j]) * prod_l (xs[i]^ys[l])
                    / ((xs[i]^ys[j]) * prod_{l!=j} (ys[j]^ys[l])
                                     * prod_{l!=i} (xs[i]^xs[l]))

    O(t^2) multiplies after the O(t) prefix products, vs O(t^3) for
    Gauss-Jordan — and exactly equal to it, because a matrix inverse
    over a field is unique.
    """
    xs = tuple(int(x) for x in xs)
    ys = tuple(int(y) for y in ys)
    t = len(xs)
    if len(ys) != t:
        raise ValueError(f"need square Cauchy geometry, got {len(xs)} "
                         f"x-nodes and {len(ys)} y-nodes")
    if len(set(xs)) != t or len(set(ys)) != t or set(xs) & set(ys):
        raise ValueError("Cauchy nodes must be distinct and disjoint")
    # row/column products: full_x[i] = prod_l (xs[i] ^ ys[l]),
    # full_y[j] = prod_l (xs[l] ^ ys[j]); the diagonal-free node
    # products feed the denominator
    full_x = [1] * t
    full_y = [1] * t
    for i in range(t):
        for l in range(t):
            full_x[i] = gf.gf_mul(full_x[i], xs[i] ^ ys[l])
            full_y[i] = gf.gf_mul(full_y[i], xs[l] ^ ys[i])
    node_x = [1] * t
    node_y = [1] * t
    for i in range(t):
        for l in range(t):
            if l == i:
                continue
            node_x[i] = gf.gf_mul(node_x[i], xs[i] ^ xs[l])
            node_y[i] = gf.gf_mul(node_y[i], ys[i] ^ ys[l])
    inv = np.zeros((t, t), dtype=np.uint8)
    for j in range(t):
        for i in range(t):
            num = gf.gf_mul(full_y[j], full_x[i])
            den = gf.gf_mul(xs[i] ^ ys[j],
                            gf.gf_mul(node_y[j], node_x[i]))
            inv[j, i] = gf.gf_mul(num, gf.gf_inv(den))
    return inv


def decode_matrix(k: int, m: int, present: tuple[int, ...]) -> np.ndarray:
    """Decode matrix for ``present`` via one Schur-complement step over
    the closed-form Cauchy inverse — byte-identical to
    ``gf.decode_matrix`` (same unique inverse), without the
    Gauss-Jordan elimination.

    The survivor system splits: present data rows pin their own bytes
    directly, and each surviving parity row q reduces to an equation
    over just the MISSING data columns M —

        sum_{j in M} c[q, j] * data_j
            = shard_q  ^  sum_{d in D} c[q, d] * shard_d.

    The t x t submatrix c[q, j] = 1/((k+q) ^ j) is itself Cauchy
    (x-nodes k+q, y-nodes j), so its inverse is ``cauchy_inverse``.
    """
    present = _check_pattern(k, m, present)
    if len(present) != k:
        raise ValueError(f"need exactly k={k} present shard indices, "
                         f"got {len(present)}")
    pos = {r: p for p, r in enumerate(present)}
    data_rows = [r for r in present if r < k]
    parity_rows = [r - k for r in present if r >= k]
    missing_cols = [j for j in range(k) if j not in pos]
    inv = np.zeros((k, k), dtype=np.uint8)
    for d in data_rows:
        inv[d, pos[d]] = 1
    if not missing_cols:
        return inv
    w = cauchy_inverse([k + q for q in parity_rows], missing_cols)
    mt = gf.mul_table()
    for b, col in enumerate(missing_cols):
        for a, q in enumerate(parity_rows):
            coeff = int(w[b, a])
            inv[col, pos[k + q]] ^= coeff
            for d in data_rows:
                inv[col, pos[d]] ^= int(
                    mt[coeff, gf.gf_inv((k + q) ^ d)])
    return inv


def repair_matrix(k: int, m: int, present: tuple[int, ...],
                  missing: tuple[int, ...]) -> np.ndarray:
    """Repair matrix (generator rows of ``missing`` times the decode
    matrix) built on the fast path — byte-identical to
    ``gf.repair_matrix``."""
    missing = _check_pattern(k, m, missing, what="missing")
    g = gf.systematic_generator(k, m)
    return gf.gf_matmul(g[list(missing)], decode_matrix(k, m, present))


def repair_coeffs(k: int, m: int, present: tuple[int, ...],
                  missing: tuple[int, ...]) -> tuple[int, ...]:
    """The per-helper product-matrix coefficients for one lost row:
    helper at survivor position p contributes coeff[p] * fragment_p,
    and the XOR of all k contributions IS the lost fragment."""
    missing = tuple(int(r) for r in missing)
    if len(missing) != 1:
        raise ValueError("repair symbols regenerate ONE row per chain; "
                         f"got missing={missing}")
    row = repair_matrix(k, m, present, missing)
    return tuple(int(c) for c in row[0])


# ---------------------------------------------------------------------------
# The symbol fold: CPU reference twins
# ---------------------------------------------------------------------------


def fold_symbol_host(acc: np.ndarray, fragment: np.ndarray,
                     coeff: int) -> np.ndarray:
    """One helper's partial-sum hop on the host: acc ^ coeff*fragment.
    The byte-exact oracle for the device fold."""
    mt = gf.mul_table()
    acc = np.asarray(acc, dtype=np.uint8)
    fragment = np.asarray(fragment, dtype=np.uint8)
    return (acc ^ mt[int(coeff)][fragment]).astype(np.uint8)


def fold_symbol_pairs(pairs: np.ndarray, coeff: int) -> np.ndarray:
    """Batched host twin of ``RegenCodec.fold_symbol``: pairs
    [..., 2, n] of (accumulator, fragment) rows -> [..., 1, n]."""
    pairs = np.asarray(pairs, dtype=np.uint8)
    if pairs.shape[-2] != 2:
        raise ValueError(f"expected (accumulator, fragment) row pairs, "
                         f"got {pairs.shape[-2]} rows")
    mt = gf.mul_table()
    return (pairs[..., 0:1, :]
            ^ mt[int(coeff)][pairs[..., 1:2, :]]).astype(np.uint8)


def _symbol_matrix(coeff: int) -> np.ndarray:
    """The fold as a GF matrix: [1, coeff] applied to (acc, fragment)
    row pairs — one batched GF(2^8) matmul, same lowerings as every
    other codec apply."""
    coeff = int(coeff)
    if not 0 <= coeff < gf.FIELD:
        raise ValueError(f"repair coefficient {coeff} outside GF(2^8)")
    return np.array([[1, coeff]], dtype=np.uint8)


# ---------------------------------------------------------------------------
# Device codec behind the ErasureCodec gate
# ---------------------------------------------------------------------------


class RegenCodec(TPUCodec):
    """TPUCodec with the regenerating-repair surfaces: every decode /
    repair matrix comes from the closed-form Cauchy construction, and
    ``fold_symbol`` runs the helper partial-sum hop as a batched device
    matmul. Warm/AOT machinery (``warm_reconstruct``, ``warm_hits``,
    the per-device program keys) is inherited unchanged, so
    ``engine.warm_repair``'s per-lane cache serves regen patterns the
    same way it serves plain reconstructs."""

    def _matrix_for(self, kind: str, present: tuple[int, ...],
                    missing: tuple[int, ...] = ()) -> _MatrixApply:
        key = (kind, present, missing)
        if key not in self._cache:
            if kind == "decode":
                mat = decode_matrix(self.k, self.m, present)
            elif kind == "symbol":
                mat = _symbol_matrix(present[0])
            else:
                mat = repair_matrix(self.k, self.m, present, missing)
            self._cache[key] = _MatrixApply(mat, self.strategy)
        return self._cache[key]

    # -- the symbol fold ---------------------------------------------------
    def _symbol_key(self, coeff: int):
        # a warm-dict key that can never collide with reconstruct keys
        # (their first element is a tuple of int rows)
        return ("symbol", int(coeff))

    def warm_fold(self, coeff: int, shape, device=None):
        """Pre-compile + pre-stage the symbol fold for one coefficient
        and exact pair shape, per device — the regen leg of
        ``engine.warm_repair``. Same placement-keyed contract as
        ``warm_reconstruct``."""
        key = (self._symbol_key(coeff), (), tuple(shape),
               _placement_device() if device is None else device)
        if key not in self._warm:
            self._warm[key] = self._matrix_for(
                "symbol", (int(coeff),)).aot(shape, device=device)
        return self._warm[key]

    def fold_symbol(self, pairs, coeff: int):
        """pairs [..., 2, n] uint8 (accumulator, fragment) rows ->
        [..., 1, n]: acc ^ coeff*fragment, batched on device.
        Dispatches the pre-staged AOT executable when warmed for this
        placement (``warm_hits`` proves it, as for reconstruct)."""
        import jax.numpy as jnp

        pairs = jnp.asarray(pairs, dtype=jnp.uint8)
        warm = self._warm.get((self._symbol_key(coeff), (),
                               tuple(pairs.shape), _placement_device()))
        if warm is not None:
            self.warm_hits += 1
            return warm(pairs)
        return self._matrix_for("symbol", (int(coeff),))(pairs)

    def repair_coeffs(self, present: tuple[int, ...],
                      missing: tuple[int, ...]) -> tuple[int, ...]:
        """Geometry-bound convenience over module-level
        ``repair_coeffs``."""
        return repair_coeffs(self.k, self.m, tuple(present),
                             tuple(missing))


class RegenReference(ReferenceCodec):
    """NumPy twin of RegenCodec: the same closed-form matrix
    constructions applied with the host GF matmul loop. The byte-exact
    oracle the device path is pinned against, and the symbol fold the
    engine's CPU-degraded path serves."""

    def reconstruct(self, survivors: np.ndarray, present: tuple[int, ...],
                    missing: tuple[int, ...] | None = None) -> np.ndarray:
        present = tuple(present)
        if missing is None:
            missing = tuple(i for i in range(self.k + self.m)
                            if i not in present)
        mat = repair_matrix(self.k, self.m, present, tuple(missing))
        return self._apply(mat, survivors)

    def decode_data(self, survivors: np.ndarray,
                    present: tuple[int, ...]) -> np.ndarray:
        mat = decode_matrix(self.k, self.m, tuple(present))
        return self._apply(mat, survivors)

    def fold_symbol(self, pairs: np.ndarray, coeff: int) -> np.ndarray:
        return fold_symbol_pairs(pairs, coeff)

    def repair_coeffs(self, present: tuple[int, ...],
                      missing: tuple[int, ...]) -> tuple[int, ...]:
        return repair_coeffs(self.k, self.m, tuple(present),
                             tuple(missing))
