"""Pallas-fused PoDR2 tag generation (TPU).

The pure-XLA tag path (podr2.tag_from_elems) materialises the packed
field elements [F, blocks, sectors] u32 (2x the fragment bytes) plus
the partial-product reduction traffic in HBM. This kernel reads the
RAW fragment bytes once and produces tags — nothing else touches HBM.

The trick that removes byte-unpacking entirely: the MAC is linear, so
    sum_j m_j * alpha_j
      = sum_j (b_{2j} + 256 b_{2j+1}) * alpha_j
      = sum_i b_i * W_i          with  W_{2j}   = alpha_j
                                       W_{2j+1} = 256 * alpha_j mod p
— an INTERLEAVED field-weight vector over the natural byte lanes. W is
split into 16-bit limbs (w0, w1) host-side; every in-kernel partial
product b_i * w ( < 2^8 * 2^16 = 2^24 ) accumulates exactly in 32-bit
lanes over <= 256-term chunks (256 * 255 * 65535 < 2^32), with one
modular fold per chunk per output element. Measured on v5e (r05):
~7.3k frags/s for 8 MiB fragments at limbs=2 (block tile 128) — vs
~3.1k for a u16 bitcast variant and ~1.9k for the jnp path — because
the kernel's HBM traffic is exactly one pass over the u8 input.

Mosaic constraints shaping this design: no unsigned reductions (sums
run in int32 and bitcast back — bit-exact below 2^32), no in-kernel
bitwidth-changing bitcasts, and strided u8 gathers ICE the compiler —
the interleaved weights avoid all three.

Layout contract:
- data [F, blocks, 2*sectors] uint8 (a reshape of the fragment bytes);
- w0/w1 [limbs, 2*sectors] int32: the 16-bit limbs of W per MAC limb;
- prf   [F, limbs, blocks] uint32 (limb-major: block axis on lanes);
- out   [F, limbs, blocks] uint32, transposed by the caller to the
  protocol's [F, blocks, limbs].

Interpret mode runs the identical kernel on the CPU test mesh; tests
pin it byte-equal to the jnp path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import pfield as pf

# v5e interleaved A/B sweep (r05): tile 128 runs ~7.3k frags/s vs
# ~6.2k at 256 and ~6.4k at 512-1024 (8 MiB fragments, 128-resident)
DEFAULT_BLOCK_TILE = 128
_CHUNK = 256        # max exactly-accumulable terms per 32-bit sum


def _target_platform() -> str:
    """The platform the jitted call will actually run on: honors a
    jax.default_device pin (AuditBackend('cpu') on a TPU host pins the
    CPU device while jax.default_backend() still says 'tpu' —
    Mosaic-lowering the kernel there would fail; review-caught).
    Interpret mode runs everywhere else."""
    dev = jax.config.jax_default_device
    if dev is not None:
        return dev.platform
    return jax.default_backend()


def _kernel(limbs: int, lanes: int):
    chunk = min(_CHUNK, lanes)

    def kernel(w0_ref, w1_ref, f_ref, d_ref, out_ref):
        d = d_ref[0].astype(jnp.int32)             # [bt, lanes]

        def fold(t):
            """Exact 32-bit chunk sums -> one field element [bt]."""
            acc = None
            for lo in range(0, lanes, chunk):
                s = jax.lax.bitcast_convert_type(
                    jnp.sum(t[:, lo:lo + chunk], axis=1,
                            dtype=jnp.int32), jnp.uint32)
                s = pf.to_field(s)
                acc = s if acc is None else pf.addmod(acc, s)
            return acc

        for limb in range(limbs):
            acc0 = fold(d * w0_ref[limb][None, :])
            acc1 = fold(d * w1_ref[limb][None, :])
            out_ref[0, limb] = pf.addmod(
                f_ref[0, limb], pf.addmod(acc0, pf.rotk(acc1, 16)))

    return kernel


@functools.partial(jax.jit, static_argnums=(4, 5, 6),
                   donate_argnums=(2,))
def _tags_3d(w0: jax.Array, w1: jax.Array, prf: jax.Array,
             data: jax.Array, limbs: int, lanes: int,
             block_tile: int) -> jax.Array:
    """data [F, blocks, lanes] u8 + prf [F, limbs, blocks] ->
    [F, limbs, blocks] tags.

    prf is DONATED: the caller's limb-major transpose is fresh per
    call (tag_fragments_fused builds it with moveaxis) and exactly
    matches the output shape/dtype, so XLA can write the tags into
    the PRF buffer instead of allocating a second [F, limbs, blocks]
    u32 array — on an 8 MiB x 128-fragment batch that is ~16 MiB of
    HBM per limb that never has to coexist. data is NOT donated: it
    is a reshape VIEW of the caller's fragment buffer, which the
    fused pipeline forward returns to its caller."""
    fcount, blocks, _ = data.shape
    interpret = _target_platform() != "tpu"
    return pl.pallas_call(
        _kernel(limbs, lanes),
        grid=(fcount, blocks // block_tile),
        in_specs=[
            pl.BlockSpec((limbs, lanes), lambda i, t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((limbs, lanes), lambda i, t: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, limbs, block_tile), lambda i, t: (i, 0, t),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_tile, lanes), lambda i, t: (i, t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, limbs, block_tile),
                               lambda i, t: (i, 0, t),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fcount, limbs, blocks),
                                       jnp.uint32),
        interpret=interpret,
    )(w0, w1, prf, data)


def supported(sectors: int, blocks: int) -> bool:
    """The fused path's shape envelope; callers fall back to the jnp
    path outside it (protocol results are identical either way).
    Deliberately narrow: sectors == 256 (the protocol geometry,
    512 byte lanes) is the only shape validated through the real
    Mosaic toolchain — this remote compiler ICEs on patterns that
    interpret mode happily runs, so an interpret-green shape is NOT
    evidence the TPU path works (review-caught when a vacuous bound
    replaced the alignment gate).

    The block gate tracks DEFAULT_BLOCK_TILE: blocks must either fit
    in one tile or divide it evenly. Retuning the tile (256 -> 128,
    r05) therefore SHIFTS the envelope — e.g. blocks=192 now takes the
    jnp fallback, blocks=384 now fuses — which is intended: every
    admitted shape is the same kernel with a different grid count, and
    tests/test_podr2.py pins the membership."""
    return (sectors == 256
            and blocks % min(blocks, DEFAULT_BLOCK_TILE) == 0)


@functools.lru_cache(maxsize=16)
def _weight_limbs(alpha_key) -> tuple[np.ndarray, np.ndarray]:
    """(w0, w1) int32 [limbs, 2*sectors] from alpha bytes (cached on
    the raw key material — numpy only, never tracers)."""
    sectors, limbs, raw = alpha_key
    alpha = np.frombuffer(raw, dtype=np.uint32).reshape(
        sectors, limbs).astype(np.uint64)
    w = np.empty((limbs, 2 * sectors), dtype=np.uint64)
    w[:, 0::2] = alpha.T
    w[:, 1::2] = (alpha.T * 256) % pf.P
    return ((w & 0xFFFF).astype(np.int32), (w >> 16).astype(np.int32))


def tag_fragments_fused(alpha: jax.Array, prf: jax.Array,
                        fragments: jax.Array) -> jax.Array:
    """fragments [F, bytes] uint8, prf [F, blocks, limbs] ->
    tags [F, blocks, limbs] (the tag_from_elems contract, fused)."""
    fcount, nbytes = fragments.shape
    sectors, limbs = alpha.shape
    lanes = 2 * sectors
    blocks = nbytes // lanes
    alpha_np = np.asarray(jax.device_get(alpha), dtype=np.uint32)
    w0, w1 = _weight_limbs((sectors, limbs, alpha_np.tobytes()))
    tile = min(blocks, DEFAULT_BLOCK_TILE)
    out = _tags_3d(jnp.asarray(w0), jnp.asarray(w1),
                   jnp.moveaxis(prf, -1, 1),
                   fragments.reshape(fcount, blocks, lanes),
                   limbs, lanes, tile)
    return jnp.moveaxis(out, 1, -1)                 # [F, blocks, limbs]
