"""Pallas-fused PoDR2 tag generation (TPU).

The pure-XLA tag path (podr2.tag_from_elems) materialises the packed
field elements [F, blocks, sectors] u32 (2x the fragment bytes) plus
the partial-product reduction traffic in HBM. This kernel fuses the
whole per-tile chain — u16 view -> 8-bit data limbs x 16-bit alpha
limbs -> four deferred-reduction partial sums -> modular fold -> PRF
add — inside VMEM, so HBM traffic is one pass over the u16 fragment
view plus the (tiny) PRF values and tag outputs.

Layout contract:
- m16  [F, blocks, sectors] uint16: the little-endian u16 view of the
  fragment bytes (a bitcast, same embedding as pf.pack_bytes width 2);
- alpha limb planes [limbs, 2, sectors] uint32: (a & 0xFFFF, a >> 16)
  per MAC limb;
- prf  [F, limbs, blocks] uint32 (limb-major so the block axis is the
  128-lane axis);
- out  [F, limbs, blocks] uint32 tags, transposed by the caller to the
  protocol's [F, blocks, limbs].

The grid walks (fragment, block-tile); each step MACs a
[BT, sectors] tile with all partial products < 2^24, so plain uint32
accumulation over sectors <= 256 is exact (see pf.dot_u16_deferred,
whose math this kernel inlines). Interpret mode runs the identical
kernel on the CPU test mesh; tests pin it byte-equal to the jnp path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import pfield as pf

DEFAULT_BLOCK_TILE = 256


def _target_platform() -> str:
    """The platform the jitted call will actually run on: honors a
    jax.default_device pin (AuditBackend('cpu') on a TPU host pins the
    CPU device while jax.default_backend() still says 'tpu' —
    Mosaic-lowering the kernel there would fail; review-caught).
    Interpret mode runs everywhere else."""
    dev = jax.config.jax_default_device
    if dev is not None:
        return dev.platform
    return jax.default_backend()


def _kernel(limbs: int):
    def kernel(a_ref, f_ref, m_ref, out_ref):
        # Mosaic has no unsigned reductions: accumulate in int32 —
        # every partial product is < 2^24 and the 256-term sum < 2^32,
        # so int32 wraparound is the BIT-EXACT uint32 sum; a bitcast
        # recovers it before the modular fold
        m = m_ref[0].astype(jnp.int32)             # [bt, s]
        mlo = m & 0xFF
        mhi = m >> 8

        def usum(x):
            return jax.lax.bitcast_convert_type(
                jnp.sum(x, axis=1, dtype=jnp.int32), jnp.uint32)

        for limb in range(limbs):
            a0 = a_ref[limb, 0][None, :]           # [1, s] int32
            a1 = a_ref[limb, 1][None, :]
            s00 = usum(mlo * a0)
            s10 = usum(mhi * a0)
            s01 = usum(mlo * a1)
            s11 = usum(mhi * a1)
            acc = pf.addmod(
                pf.addmod(pf.to_field(s00),
                          pf.rotk(pf.to_field(s10), 8)),
                pf.addmod(pf.rotk(pf.to_field(s01), 16),
                          pf.rotk(pf.to_field(s11), 24)))
            out_ref[0, limb] = pf.addmod(f_ref[0, limb], acc)
    return kernel


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _tags_3d(alpha_planes: jax.Array, prf: jax.Array, m16: jax.Array,
             limbs: int, sectors: int, block_tile: int) -> jax.Array:
    """[F, blocks, s] u16 + [F, limbs, blocks] PRF -> [F, limbs, blocks]."""
    fcount, blocks, _ = m16.shape
    interpret = _target_platform() != "tpu"
    return pl.pallas_call(
        _kernel(limbs),
        grid=(fcount, blocks // block_tile),
        in_specs=[
            pl.BlockSpec((limbs, 2, sectors), lambda i, t: (0, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, limbs, block_tile), lambda i, t: (i, 0, t),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_tile, sectors), lambda i, t: (i, t, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, limbs, block_tile),
                               lambda i, t: (i, 0, t),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((fcount, limbs, blocks),
                                       jnp.uint32),
        interpret=interpret,
    )(alpha_planes, prf, m16)


def supported(sectors: int, blocks: int) -> bool:
    """The fused path's shape envelope; callers fall back to the jnp
    path outside it (protocol results are identical either way)."""
    return (sectors <= 256 and sectors % 128 == 0
            and blocks % min(blocks, DEFAULT_BLOCK_TILE) == 0)


def tag_fragments_fused(alpha: jax.Array, prf: jax.Array,
                        fragments: jax.Array) -> jax.Array:
    """fragments [F, bytes] uint8, prf [F, blocks, limbs] ->
    tags [F, blocks, limbs] (the tag_from_elems contract, fused)."""
    fcount, nbytes = fragments.shape
    sectors, limbs = alpha.shape
    blocks = nbytes // (sectors * pf.BYTES_PER_ELEM)
    m16 = jax.lax.bitcast_convert_type(
        fragments.reshape(fcount, blocks * sectors, 2),
        jnp.uint16).reshape(fcount, blocks, sectors)
    planes = jnp.stack([alpha.T & 0xFFFF, alpha.T >> 16],
                       axis=1).astype(jnp.int32)    # [limbs, 2, s]
    tile = min(blocks, DEFAULT_BLOCK_TILE)
    out = _tags_3d(planes, jnp.moveaxis(prf, -1, 1), m16,
                   limbs, sectors, tile)
    return jnp.moveaxis(out, 1, -1)                 # [F, blocks, limbs]
