"""The ``AuditBackend`` gate — the PoDR2 half of the north-star trait
pair (BASELINE.json: "gated behind a new ErasureCodec + AuditBackend
trait pair ... so the existing CPU path stays the default").

``make_audit_backend(backend)`` mirrors rs.make_codec: "cpu" (default)
pins every op to the host CPU device, "tpu"/"jax" runs on the default
accelerator, "auto" picks TPU when present. The math is identical —
cess_tpu/ops/podr2.py is platform-deterministic (threefry PRF + M31
lane arithmetic), a protocol invariant tested in tests/test_podr2.py —
so the gate chooses WHERE the batch runs, never WHAT it computes.
"""
from __future__ import annotations

import functools

import jax

from ..resilience import faults
from . import podr2


class AuditBackend:
    """Batched PoDR2 surface bound to one device: tag generation
    (TEE role), challenge derivation, proving (miner role, aggregated
    constant-size proofs), verification (TEE role).

    Fault seams (cess_tpu/resilience): ``podr2.<op>.<platform>`` —
    the platform suffix lets a chaos plan fail the accelerator-bound
    gate while the CPU instance (the resilience layer's degradation
    target) stays clean."""

    def __init__(self, key: podr2.Podr2Key, device):
        self.key = key
        self.device = device
        self._site = f"podr2.{{}}.{device.platform}"

    def _on(self, op: str, fn, *args):
        faults.inject(self._site.format(op))
        with jax.default_device(self.device):
            return fn(*args)

    # -- TEE: tag generation ------------------------------------------------
    def tag_fragments(self, fragment_ids, fragments):
        return self._on("tag", podr2.tag_fragments, self.key,
                        fragment_ids, fragments)

    # -- round: challenge derivation ----------------------------------------
    def gen_challenge(self, seed: bytes, num_blocks: int,
                      count: int | None = None):
        with jax.default_device(self.device):
            return podr2.gen_challenge(seed, num_blocks, count)

    # -- miner: proving ------------------------------------------------------
    def prove_batch(self, fragments, tags, idx, nu):
        return self._on("prove", podr2.prove_batch, fragments, tags,
                        idx, nu)

    def prove_aggregate(self, fragments, tags, idx, nu, r):
        return self._on("prove", podr2.prove_aggregate, fragments, tags,
                        idx, nu, r)

    def aggregate_coeffs(self, seed: bytes, fragment_ids):
        return self._on("prove", podr2.aggregate_coeffs, seed,
                        fragment_ids)

    # -- TEE: verification ---------------------------------------------------
    def verify_batch(self, fragment_ids, num_blocks, idx, nu, mu, sigma):
        return self._on("verify", podr2.verify_batch, self.key,
                        fragment_ids, num_blocks, idx, nu, mu, sigma)

    def verify_aggregate(self, fragment_ids, num_blocks, idx, nu, r, mu,
                         sigma):
        return self._on("verify", podr2.verify_aggregate, self.key,
                        fragment_ids, num_blocks, idx, nu, r, mu, sigma)


@functools.lru_cache(maxsize=None)
def _device_for(backend: str):
    if backend == "auto":
        backend = "tpu" if jax.default_backend() != "cpu" else "cpu"
    if backend == "cpu":
        return jax.devices("cpu")[0]
    if backend in ("tpu", "jax"):
        if jax.default_backend() == "cpu":
            # an EXPLICIT accelerator request must fail loudly, not
            # silently run the audit batch on CPU
            raise RuntimeError(
                "AuditBackend 'tpu' requested but no accelerator is "
                "present; use 'cpu' or 'auto'")
        return jax.devices()[0]
    raise ValueError(f"unknown AuditBackend {backend!r}")


def make_audit_backend(key: podr2.Podr2Key,
                       backend: str = "cpu") -> AuditBackend:
    """backend: "cpu" (default) | "tpu"/"jax" | "auto"."""
    return AuditBackend(key, _device_for(backend))
