"""GF(2^8) arithmetic core (NumPy, host-side).

This module owns the finite-field math the erasure codec is built on:

- exp/log tables for GF(2^8) with the AES-adjacent polynomial 0x11D
  (x^8 + x^4 + x^3 + x^2 + 1), the same field used by standard
  reed-solomon-erasure implementations the reference's off-chain
  components rely on (see SURVEY.md §2.3).
- Cauchy parity-matrix construction for a systematic RS(k, m) code.
- GF matrix inversion (Gauss-Jordan) for decode.
- Bit-matrix ("bitslice") expansion: every GF(2^8) constant multiply
  is an 8x8 matrix over GF(2), so an (r x k) GF byte-matrix apply
  becomes an (8r x 8k) 0/1 matrix applied to the bit-planes of the
  data with XOR accumulation — i.e. an integer matmul followed by
  ``& 1``. That is the lowering that puts RS encode/decode onto the
  TPU MXU (see cess_tpu/ops/rs.py).

All functions here are NumPy/host-side; they produce small constant
matrices consumed by the JAX/Pallas device paths.
"""
from __future__ import annotations

import functools

import numpy as np

POLY = 0x11D
FIELD = 256
ORDER = 255  # multiplicative group order


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    exp = np.zeros(2 * ORDER, dtype=np.uint8)
    log = np.zeros(FIELD, dtype=np.int32)
    x = 1
    for i in range(ORDER):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= POLY
    exp[ORDER:] = exp[:ORDER]
    return exp, log


EXP, LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    if a == 0 or b == 0:
        return 0
    return int(EXP[LOG[a] + LOG[b]])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of 0")
    return int(EXP[ORDER - LOG[a]])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(EXP[(LOG[a] * n) % ORDER])


@functools.cache
def mul_table() -> np.ndarray:
    """Full 256x256 multiplication table; MUL[a, b] = a*b in GF(2^8)."""
    la = LOG.reshape(FIELD, 1)
    lb = LOG.reshape(1, FIELD)
    t = EXP[(la + lb) % ORDER].astype(np.uint8)
    t[0, :] = 0
    t[:, 0] = 0
    t.flags.writeable = False  # shared cached table; mutation would corrupt all math
    return t


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product of uint8 matrices a @ b (XOR-accumulate).

    a: [r, k], b: [k, n] (n may be large — b rows are data). Vectorised
    with the 256-entry row tables of ``mul_table``; this is the CPU
    oracle the TPU path is golden-tested against.
    """
    a = np.asarray(a, dtype=np.uint8)
    b = np.asarray(b, dtype=np.uint8)
    assert a.ndim == 2 and b.ndim == 2 and a.shape[1] == b.shape[0]
    mt = mul_table()
    out = np.zeros((a.shape[0], b.shape[1]), dtype=np.uint8)
    for i in range(a.shape[0]):
        acc = out[i]
        for j in range(a.shape[1]):
            c = a[i, j]
            if c:
                acc ^= mt[c][b[j]]
        out[i] = acc
    return out


def gf_mat_inv(m: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination."""
    m = np.array(m, dtype=np.uint8)
    n = m.shape[0]
    assert m.shape == (n, n)
    aug = np.concatenate([m, np.eye(n, dtype=np.uint8)], axis=1)
    mt = mul_table()
    for col in range(n):
        pivot = None
        for row in range(col, n):
            if aug[row, col]:
                pivot = row
                break
        if pivot is None:
            raise np.linalg.LinAlgError("singular GF(2^8) matrix")
        if pivot != col:
            aug[[col, pivot]] = aug[[pivot, col]]
        inv_p = gf_inv(int(aug[col, col]))
        aug[col] = mt[inv_p][aug[col]]
        for row in range(n):
            if row != col and aug[row, col]:
                aug[row] ^= mt[int(aug[row, col])][aug[col]]
    return aug[:, n:].copy()


def cauchy_parity_matrix(k: int, m: int) -> np.ndarray:
    """The m x k Cauchy parity matrix C[i, j] = 1 / (x_i ^ y_j).

    Points: y_j = j for data columns, x_i = k + i for parity rows; all
    distinct for k + m <= 256, so every square submatrix of the
    systematic generator [[I_k], [C]] is invertible (MDS property).
    """
    if k + m > FIELD:
        raise ValueError(f"k + m = {k + m} exceeds field size {FIELD}")
    c = np.zeros((m, k), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            c[i, j] = gf_inv((k + i) ^ j)
    return c


def systematic_generator(k: int, m: int) -> np.ndarray:
    """(k+m) x k generator: rows 0..k-1 identity, rows k..k+m-1 Cauchy."""
    return np.concatenate(
        [np.eye(k, dtype=np.uint8), cauchy_parity_matrix(k, m)], axis=0
    )


def _check_rows(k: int, m: int, rows: tuple[int, ...], what: str) -> None:
    if len(set(rows)) != len(rows):
        raise ValueError(f"duplicate {what} shard indices: {rows}")
    for r in rows:
        if not 0 <= int(r) < k + m:
            raise ValueError(f"{what} shard index {r} out of range for "
                             f"RS({k},{m}) with {k + m} rows")


def decode_matrix(k: int, m: int, present: tuple[int, ...]) -> np.ndarray:
    """Matrix R s.t. data = R @ shards[present] for any k present shard rows."""
    if len(present) != k:
        raise ValueError(f"need exactly k={k} present shard indices, got {len(present)}")
    _check_rows(k, m, present, "present")
    g = systematic_generator(k, m)
    sub = g[list(present)]
    return gf_mat_inv(sub)


def repair_matrix(k: int, m: int, present: tuple[int, ...],
                  missing: tuple[int, ...]) -> np.ndarray:
    """Matrix M s.t. shards[missing] = M @ shards[present]."""
    _check_rows(k, m, missing, "missing")
    g = systematic_generator(k, m)
    inv = decode_matrix(k, m, present)
    return gf_matmul(g[list(missing)], inv)


@functools.cache
def _single_bitmatrix(c: int) -> np.ndarray:
    """8x8 GF(2) matrix of multiply-by-c: M[a, b] = bit a of (c * 2^b)."""
    m = np.zeros((8, 8), dtype=np.uint8)
    for b in range(8):
        prod = gf_mul(c, 1 << b)
        for a in range(8):
            m[a, b] = (prod >> a) & 1
    m.flags.writeable = False  # shared cached matrix
    return m


def expand_bitmatrix(gf_mat: np.ndarray) -> np.ndarray:
    """Expand an (r x k) GF(2^8) byte matrix to its (8r x 8k) GF(2) form.

    Row index 8*i + a is output bit a of output byte i; column index
    8*j + b is input bit b of input byte j. Applying this matrix to the
    bit-planes of the data with XOR accumulation (integer matmul, then
    ``& 1``) computes the GF(2^8) matrix product — the MXU-friendly
    lowering used by the TPU codec.
    """
    gf_mat = np.asarray(gf_mat, dtype=np.uint8)
    r, k = gf_mat.shape
    out = np.zeros((8 * r, 8 * k), dtype=np.uint8)
    for i in range(r):
        for j in range(k):
            out[8 * i:8 * i + 8, 8 * j:8 * j + 8] = _single_bitmatrix(int(gf_mat[i, j]))
    return out
