"""XOR-schedule compiler for GF(2^8) codec matrices (host-side).

The dense lowerings apply the full (8r x 8q) GF(2) bitmatrix
(gf.expand_bitmatrix) to the data's bit-planes — every 1-bit costs an
op whether or not another output row already computed the same
subexpression. This module compiles that bitmatrix ONCE into a sparse
XOR program (arxiv 2108.02692: erasure-code matrix apply as a
program-optimization problem):

- greedy pairwise common-subexpression elimination (Paar's algorithm):
  repeatedly extract the pair of live terms shared by the most output
  rows, materialise it as one intermediate XOR, and substitute it into
  every row that contains both halves — until no pair of output rows
  shares >= 2 live terms;
- a topologically-ordered op list over a flat address space
  [inputs | scratch | outputs], with scratch slots assigned by
  liveness analysis (each intermediate is freed after its last read,
  slots are min-index-reused), so the executor's scratch high-water
  mark is bounded far below the intermediate count;
- a canonical serialized form (``XorSchedule.witness()``): compilation
  is a pure function of the matrix bytes — same matrix, byte-identical
  schedule, on every host, every time. No clock reads, no entropy, no
  dict-order dependence anywhere in this module (it sits under the
  sim-determinism lint family for exactly this reason).

The executors live in cess_tpu/ops/rs_xor.py (bit-sliced Pallas kernel
+ pure-jnp fallback); the compile-time cost model (``estimate``) picks
dense-MXU vs scheduled-XOR per (matrix, shape) for strategy="auto" in
cess_tpu/ops/rs.py.
"""
from __future__ import annotations

import dataclasses
import functools
import hashlib
import heapq
import json

import numpy as np

# opcodes (serialized as the first element of each 4-tuple op)
OP_XOR = 0   # buf[dst] = buf[a] ^ buf[b]
OP_ACC = 1   # buf[dst] ^= buf[a]
OP_COPY = 2  # buf[dst] = buf[a]
OP_ZERO = 3  # buf[dst] = 0


@dataclasses.dataclass(frozen=True)
class XorSchedule:
    """A compiled XOR program for one (8r x 8q) GF(2) bitmatrix.

    ``ops`` is the topologically-ordered instruction list; operands
    are flat indices into [inputs 0..q8) | scratch q8..q8+n_scratch) |
    outputs q8+n_scratch..q8+n_scratch+r8). ``n_xors`` counts real
    XOR work (OP_XOR + OP_ACC); ``dense_xors`` is what the dense
    bitmatrix expansion pays (sum over rows of popcount-1);
    ``saving_frac`` = 1 - n_xors/dense_xors.
    """

    r8: int
    q8: int
    n_scratch: int
    ops: tuple[tuple[int, int, int, int], ...]
    n_xors: int
    dense_xors: int
    saving_frac: float
    matrix_sha256: str

    @property
    def out_base(self) -> int:
        return self.q8 + self.n_scratch

    def witness(self) -> bytes:
        """Canonical bytes: the same matrix always compiles to the
        byte-identical witness (pinned by tests/test_xor_sched.py)."""
        return json.dumps(
            {"v": 1, "r8": self.r8, "q8": self.q8,
             "scratch": self.n_scratch, "n_xors": self.n_xors,
             "dense_xors": self.dense_xors,
             "saving_frac": round(self.saving_frac, 6),
             "matrix_sha256": self.matrix_sha256,
             "ops": [list(op) for op in self.ops]},
            sort_keys=True, separators=(",", ":")).encode()

    def dump(self) -> dict:
        """Viewer-facing summary (tools/xor_view.py)."""
        counts = {"xor": 0, "acc": 0, "copy": 0, "zero": 0}
        names = {OP_XOR: "xor", OP_ACC: "acc",
                 OP_COPY: "copy", OP_ZERO: "zero"}
        for op in self.ops:
            counts[names[op[0]]] += 1
        return {"kind": "xor_schedule", "r8": self.r8, "q8": self.q8,
                "n_xors": self.n_xors, "dense_xors": self.dense_xors,
                "saving_frac": round(self.saving_frac, 6),
                "scratch_high_water": self.n_scratch,
                "op_counts": counts, "total_ops": len(self.ops),
                "matrix_sha256": self.matrix_sha256}


def _cse(rows: list[set[int]], q8: int):
    """Greedy pairwise CSE: returns (rows, parents) where ``parents``
    maps each new intermediate id (>= q8, creation order = topological
    order) to its (lo, hi) parent pair. Deterministic: the most-shared
    pair wins, ties to the lexicographically smallest pair."""
    parents: dict[int, tuple[int, int]] = {}
    next_id = q8
    while True:
        counts: dict[tuple[int, int], int] = {}
        for row in rows:
            terms = sorted(row)
            for x in range(len(terms)):
                for y in range(x + 1, len(terms)):
                    pair = (terms[x], terms[y])
                    counts[pair] = counts.get(pair, 0) + 1
        best, best_n = None, 1
        for pair in sorted(counts):
            n = counts[pair]
            if n > best_n:
                best, best_n = pair, n
        if best is None:
            return rows, parents
        a, b = best
        t = next_id
        next_id += 1
        parents[t] = (a, b)
        for row in rows:
            if a in row and b in row:
                row.discard(a)
                row.discard(b)
                row.add(t)


def _schedule(rows: list[set[int]], parents: dict[int, tuple[int, int]],
              q8: int, r8: int):
    """Linearize the DAG output-row by output-row with liveness-based
    scratch allocation. Returns (sym_ops, n_scratch) where operands
    are ("i", j) / ("s", slot) / ("o", i) symbols."""
    uses: dict[int, int] = {t: 0 for t in parents}
    for a, b in parents.values():
        for p in (a, b):
            if p in uses:
                uses[p] += 1
    for row in rows:
        for t in row:
            if t in uses:
                uses[t] += 1
    slot_of: dict[int, int] = {}
    free: list[int] = []
    high = 0
    computed: set[int] = set()
    ops: list[tuple[int, tuple, tuple, tuple]] = []

    def operand(t):
        return ("i", t) if t < q8 else ("s", slot_of[t])

    def consume(t):
        if t < q8:
            return
        uses[t] -= 1
        if uses[t] == 0:
            heapq.heappush(free, slot_of[t])

    def emit_term(t):
        nonlocal high
        stack = [t]
        while stack:
            cur = stack[-1]
            if cur < q8 or cur in computed:
                stack.pop()
                continue
            a, b = parents[cur]
            pending = [p for p in (a, b)
                       if p >= q8 and p not in computed]
            if pending:
                stack.extend(pending)
                continue
            stack.pop()
            srcs = (operand(a), operand(b))
            consume(a)
            consume(b)
            if free:
                slot = heapq.heappop(free)
            else:
                slot = high
                high += 1
            slot_of[cur] = slot
            ops.append((OP_XOR, ("s", slot), srcs[0], srcs[1]))
            computed.add(cur)

    nil = ("i", 0)
    for i, row in enumerate(rows):
        terms = sorted(row)
        for t in terms:
            emit_term(t)
        dst = ("o", i)
        if not terms:
            ops.append((OP_ZERO, dst, nil, nil))
        elif len(terms) == 1:
            ops.append((OP_COPY, dst, operand(terms[0]), nil))
            consume(terms[0])
        else:
            ops.append((OP_XOR, dst, operand(terms[0]),
                        operand(terms[1])))
            consume(terms[0])
            consume(terms[1])
            for t in terms[2:]:
                ops.append((OP_ACC, dst, operand(t), nil))
                consume(t)
    return ops, high


def _compile(shape: tuple[int, int], raw: bytes) -> XorSchedule:
    r8, q8 = shape
    bmat = np.frombuffer(raw, dtype=np.uint8).reshape(shape)
    rows = [set(np.flatnonzero(bmat[i]).tolist()) for i in range(r8)]
    dense_xors = sum(max(0, len(row) - 1) for row in rows)
    rows, parents = _cse(rows, q8)
    sym_ops, high = _schedule(rows, parents, q8, r8)
    n_scratch = max(high, 1)   # executors always carry >= 1 slot

    def flat(sym):
        space, idx = sym
        if space == "i":
            return idx
        if space == "s":
            return q8 + idx
        return q8 + n_scratch + idx

    ops = tuple((op, flat(dst), flat(a), flat(b))
                for op, dst, a, b in sym_ops)
    n_xors = sum(1 for op in ops if op[0] in (OP_XOR, OP_ACC))
    saving = 0.0 if dense_xors == 0 \
        else 1.0 - n_xors / dense_xors
    return XorSchedule(
        r8=r8, q8=q8, n_scratch=n_scratch, ops=ops,
        n_xors=n_xors, dense_xors=dense_xors, saving_frac=saving,
        matrix_sha256=hashlib.sha256(raw).hexdigest())


@functools.lru_cache(maxsize=128)
def _compile_cached(shape: tuple[int, int], raw: bytes) -> XorSchedule:
    return _compile(shape, raw)


def compile_schedule(bmat: np.ndarray) -> XorSchedule:
    """Compile an (8r x 8q) 0/1 bitmatrix (gf.expand_bitmatrix) into
    its canonical XOR schedule. Cached on the matrix bytes — the
    compiled program is immutable and shared."""
    bmat = np.ascontiguousarray(np.asarray(bmat, dtype=np.uint8))
    if bmat.ndim != 2 or bmat.shape[0] % 8 or bmat.shape[1] % 8:
        raise ValueError(f"expected an (8r x 8q) bitmatrix, "
                         f"got shape {bmat.shape}")
    return _compile_cached(bmat.shape, bmat.tobytes())


# ---------------------------------------------------------------------------
# Compile-time cost model (the strategy="auto" selector, rs.py)
# ---------------------------------------------------------------------------

#: MXU issue width: 128x128 MACs per step
_MXU_MACS = 16384.0
#: VPU issue width in uint32 lanes (8x128); the bit-sliced executor
#: packs 4 data bytes per lane
_VPU_LANES = 1024.0
#: per-instruction issue overhead relative to one lane-op, amortized
#: over the row bucket: the scheduled kernel streams n_xors distinct
#: vector instructions per tile where the dense path issues a handful
#: of fused ops
_ISSUE = 64.0


def rows_bucket(rows: int) -> int:
    """Next power-of-two row bucket (the engine's coalescing shape)."""
    b = 1
    while b < max(rows, 1):
        b *= 2
    return b


def estimate(r8: int, q8: int, n_xors: int, bucket: int) -> dict:
    """Dense-MXU vs scheduled-XOR cost per output byte-column, in
    arbitrary issue-slot units x 1e6 (ints, so the estimate can ride
    program-cache keys into the CompileLedger). Deterministic pure
    arithmetic — never a measurement."""
    r, q = r8 // 8, q8 // 8
    bucket = max(int(bucket), 1)
    # dense: the full bitmatrix rides the MXU (r8*q8 MACs per bit
    # column) plus VPU unpack/pack of every bit-plane
    dense = (r8 * q8) / _MXU_MACS + (16.0 * q + 15.0 * r) / _VPU_LANES
    dense += _ISSUE * (r + q) / bucket / _VPU_LANES
    # scheduled: n_xors full-lane u32 ops cover 4 bytes each, plus
    # shift/mask unpack and shift/or pack of the touched planes
    xor = (n_xors + 16.0 * q + 16.0 * r) / (4.0 * _VPU_LANES)
    xor += _ISSUE * n_xors / bucket / (4.0 * _VPU_LANES)
    chosen = "xor" if xor < dense else "dense"
    return {"chosen": chosen, "dense_cost": int(dense * 1e6),
            "xor_cost": int(xor * 1e6), "rows_bucket": bucket,
            "n_xors": n_xors}
