"""End-to-end data-plane pipelines (the framework's "model" layer).

The flagship workload is the storage pipeline: a batch of 16 MiB
segments is erasure-coded into fragments and PoDR2-tagged, mirroring
the reference's OSS-gateway + TEE-worker off-chain compute
(SURVEY.md §3.2) as one batched TPU program.
"""
from .pipeline import PipelineConfig, StoragePipeline  # noqa: F401
