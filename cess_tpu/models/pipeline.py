"""The storage pipeline: segment -> RS fragments (-> PoDR2 tags).

This is the flagship end-to-end workload ("model") of the framework:
the batched device program that replaces the reference's off-chain
OSS-gateway chunk/encode step and TEE tag computation
(SURVEY.md §3.2: user -> OSS chunks file into 16 MiB segments,
RS-encodes each into fragments; §3.3: TEE computes PoDR2 tags).

Everything here is jit-able and batch-first: segments [B, segment_size]
uint8 -> fragments [B, k+m, fragment_size] uint8 (+ per-fragment tags
once the audit backend is wired in).
"""
from __future__ import annotations

import dataclasses

import jax.numpy as jnp

from .. import constants
from ..ops import gf
from ..ops.rs import default_strategy, _MatrixApply


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    k: int = constants.REF_K
    m: int = constants.REF_M
    segment_size: int = constants.SEGMENT_SIZE
    strategy: str | None = None  # None -> rs.default_strategy()

    @property
    def fragment_size(self) -> int:
        assert self.segment_size % self.k == 0
        return self.segment_size // self.k


class StoragePipeline:
    """Batched segment->fragment encode (and tag) program.

    Unlike TPUCodec (a generic codec front with per-pattern caches),
    this is a single fused forward step meant to be jitted/pjitted as
    one program over a segment batch.
    """

    def __init__(self, config: PipelineConfig):
        self.config = config
        strategy = config.strategy or default_strategy()
        self._parity = _MatrixApply(
            gf.cauchy_parity_matrix(config.k, config.m), strategy
        )

    def encode_step(self, segments: jnp.ndarray) -> jnp.ndarray:
        """[B, segment_size] uint8 -> [B, k+m, fragment_size] uint8.

        Data fragments are the k row-slices of the segment (systematic
        code: fragment bytes == segment bytes, hash-stable), parity
        fragments follow.
        """
        cfg = self.config
        b = segments.shape[0]
        data = segments.reshape(b, cfg.k, cfg.fragment_size)
        parity = self._parity(data)
        return jnp.concatenate([data, parity], axis=-2)

    def forward(self, segments: jnp.ndarray) -> dict[str, jnp.ndarray]:
        """The full pipeline step (grows as subsystems land)."""
        shards = self.encode_step(segments)
        return {"fragments": shards}
