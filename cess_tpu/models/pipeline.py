"""The storage pipeline: segment -> RS fragments (-> PoDR2 tags).

This is the flagship end-to-end workload ("model") of the framework:
the batched device program that replaces the reference's off-chain
OSS-gateway chunk/encode step and TEE tag computation
(SURVEY.md §3.2: user -> OSS chunks file into 16 MiB segments,
RS-encodes each into fragments; §3.3: TEE computes PoDR2 tags).

Everything here is jit-able and batch-first: segments [B, segment_size]
uint8 -> fragments [B, k+m, fragment_size] uint8 (+ per-fragment tags
once the audit backend is wired in).

The direct (engine-less) ``forward`` is ONE jitted device program —
encode and tag fused, with the segment buffer DONATED on accelerator
backends so XLA can reclaim it for the program's intermediates
instead of holding staged input alongside the packed-element temps
(the CPU backend skips donation — it cannot use an unaliased donated
buffer and would warn per dispatch). Donation contract: on
accelerators, callers must not reuse a device-resident ``segments``
array after ``forward`` (host numpy inputs are unaffected — jit
stages a fresh device copy and donates that). The double-buffered
streaming driver (cess_tpu/serve/stream.py) is built on exactly this
program.
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from .. import constants
from ..obs import trace
from ..ops import gf, podr2
from ..ops.rs import default_strategy, _MatrixApply


@dataclasses.dataclass(frozen=True)
class PipelineConfig:
    k: int = constants.REF_K
    m: int = constants.REF_M
    segment_size: int = constants.SEGMENT_SIZE
    strategy: str | None = None  # None -> rs.default_strategy()
    sectors: int = podr2.SECTORS  # PoDR2 block geometry

    @property
    def fragment_size(self) -> int:
        assert self.segment_size % self.k == 0
        return self.segment_size // self.k

    @property
    def blocks_per_fragment(self) -> int:
        return podr2.Podr2Params(self.sectors).blocks_for(self.fragment_size)


class StoragePipeline:
    """Batched segment->fragment encode + PoDR2 tag program.

    Unlike TPUCodec (a generic codec front with per-pattern caches),
    this is a single fused forward step meant to be jitted/pjitted as
    one program over a segment batch. The tag step plays the
    reference's TEE role (SURVEY.md §3.2 step "TEE worker computes
    PoDR2 tags for fragments").
    """

    def __init__(self, config: PipelineConfig,
                 podr2_key: podr2.Podr2Key | None = None, engine=None):
        self.config = config
        self.podr2_key = podr2_key or podr2.Podr2Key.generate(0, podr2.Podr2Params(config.sectors))
        strategy = config.strategy or default_strategy()
        self._parity = _MatrixApply(
            gf.cauchy_parity_matrix(config.k, config.m), strategy
        )
        self._fused = None   # lazily-built fused encode+tag program
        # optional submission engine (cess_tpu/serve): when configured,
        # encode/tag submit through its batched queues so concurrent
        # callers coalesce into shared device batches. The direct
        # synchronous path below stays the default (trait-gate
        # philosophy), and engine results are bit-identical to it.
        self.engine = engine
        if engine is not None and engine.codec is not None \
                and (engine.codec.k, engine.codec.m) != (config.k, config.m):
            raise ValueError(
                f"engine codec RS({engine.codec.k},{engine.codec.m}) != "
                f"pipeline RS({config.k},{config.m})")
        if engine is not None and engine.audit is not None \
                and not podr2.keys_equal(engine.audit.key,
                                         self.podr2_key):
            # a mismatched key would tag with DIFFERENT secrets than
            # the direct path — silent protocol divergence
            raise ValueError("engine AuditBackend key differs from "
                             "the pipeline's PoDR2 key")

    def encode_step(self, segments: jnp.ndarray,
                    tenant: str | None = None) -> jnp.ndarray:
        """[B, segment_size] uint8 -> [B, k+m, fragment_size] uint8.

        Data fragments are the k row-slices of the segment (systematic
        code: fragment bytes == segment bytes, hash-stable), parity
        fragments follow. ``tenant`` rides into the engine submit for
        per-tenant accounting (obs/slo.py) — ignored on the direct
        path and free when the engine has no SLO board.
        """
        cfg = self.config
        segments = jnp.asarray(segments)
        b = segments.shape[0]
        data = segments.reshape(b, cfg.k, cfg.fragment_size)
        with trace.span("pipeline.encode", sys="pipeline", segments=b):
            if self.engine is not None and self.engine.codec is not None:
                # zero-copy handoff: the engine accepts and returns
                # jax.Array, so an already-device-resident batch never
                # round-trips through the host on its way to the codec
                return jnp.asarray(self.engine.encode(data,
                                                      tenant=tenant))
            parity = self._parity(data)
            return jnp.concatenate([data, parity], axis=-2)

    def tag_step(self, fragments: jnp.ndarray,
                 fragment_ids: jnp.ndarray | None = None,
                 tenant: str | None = None) -> jnp.ndarray:
        """[B, k+m, fragment_size] -> PoDR2 tags [B, k+m, blocks, limbs].

        fragment_ids: unique-per-key ids ([B, k+m] or [B, k+m, 2] hash
        word pairs, see podr2.fragment_id_from_hash). The arange default
        is for benches/demos ONLY — production must pass hash-derived
        ids, since id reuse across different data breaks unforgeability.
        """
        fragments = jnp.asarray(fragments)
        b, rows, n = fragments.shape
        flat = fragments.reshape(b * rows, n)
        if fragment_ids is None:
            fragment_ids = jnp.arange(b * rows, dtype=jnp.int32)
        else:
            fragment_ids = jnp.asarray(fragment_ids)
            fragment_ids = fragment_ids.reshape(
                (b * rows, 2) if fragment_ids.ndim == 3 else (b * rows,))
        with trace.span("pipeline.tag", sys="pipeline", fragments=b * rows):
            if self.engine is not None and self.engine.audit is not None \
                    and fragment_ids.ndim == 2:
                # engine tag class takes (lo, hi) id pairs; the arange
                # bench default stays on the direct path. Device arrays
                # hand off zero-copy (engine returns jax.Array back).
                tags = jnp.asarray(self.engine.tag_fragments(
                    fragment_ids, flat, tenant=tenant))
            else:
                tags = podr2.tag_fragments(self.podr2_key, fragment_ids,
                                           flat)
        return tags.reshape(b, rows, *tags.shape[1:])

    def fused_program(self):
        """The fused encode+tag device program: ONE jitted call,
        segments DONATED (see module doc), results bit-identical to
        encode_step -> tag_step. jit caches per batch/id shape, so the
        streaming driver reuses one compiled program per bucket.

        Signature: (segments [B, segment_size] u8,
                    fragment_ids [B*(k+m)] | [B, k+m] | [B, k+m, 2])
                 -> {"fragments": [B, k+m, frag], "tags": [B, k+m, blocks, limbs]}
        """
        if self._fused is None:
            cfg = self.config

            def run(segments, fragment_ids):
                b = segments.shape[0]
                data = segments.reshape(b, cfg.k, cfg.fragment_size)
                parity = self._parity(data)
                shards = jnp.concatenate([data, parity], axis=-2)
                rows = shards.shape[-2]
                flat = shards.reshape(b * rows, cfg.fragment_size)
                ids = fragment_ids.reshape(
                    (b * rows, 2) if fragment_ids.ndim == 3
                    else (b * rows,))
                tags = podr2.tag_fragments(self.podr2_key, ids, flat)
                return {"fragments": shards,
                        "tags": tags.reshape(b, rows, *tags.shape[1:])}

            # donate the staged segment batch: the buffer is dead the
            # moment the program consumes it (the streaming driver
            # stages a fresh one per batch), so XLA may reclaim it for
            # the program's own intermediates instead of carrying
            # 2 GiB of input alongside ~4x that of packed-element
            # temps. The CPU backend cannot use an unaliased donation
            # (no output matches the [B, seg] shape) and would warn on
            # every dispatch, so the gate: accelerator-only.
            donate = (0,) if jax.default_backend() != "cpu" else ()
            self._fused = jax.jit(run, donate_argnums=donate)
        return self._fused

    def forward(self, segments: jnp.ndarray,
                fragment_ids: jnp.ndarray | None = None,
                tenant: str | None = None) -> dict[str, jnp.ndarray]:
        """The full pipeline step: encode + tag (the reference's
        OSS-encode + TEE-tag off-chain compute as one device program).

        Without an engine this is the FUSED path: one jitted call, no
        intermediate materialization between encode and tag, segment
        buffer donated. With an engine the two steps submit through its
        queues (still zero-copy for device-resident inputs), carrying
        the optional per-tenant accounting tag."""
        segments = jnp.asarray(segments)
        with trace.span("pipeline.forward", sys="pipeline",
                        segments=int(segments.shape[0])):
            if self.engine is not None:
                shards = self.encode_step(segments, tenant=tenant)
                tags = self.tag_step(shards, fragment_ids,
                                     tenant=tenant)
                return {"fragments": shards, "tags": tags}
            b = segments.shape[0]
            if fragment_ids is None:
                rows = self.config.k + self.config.m
                fragment_ids = jnp.arange(b * rows, dtype=jnp.int32)
            else:
                fragment_ids = jnp.asarray(fragment_ids)
            return self.fused_program()(segments, fragment_ids)
