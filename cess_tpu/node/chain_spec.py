"""Chain specifications: genesis config for dev/local/test networks.

Reference: node/src/chain_spec.rs (dev/local/testnet/mainnet builders
plus baked raw specs, :84,210,318-434). A spec fully determines
genesis state, so every node starting from the same spec reaches the
same state root — the reproducible-genesis property the reference gets
from baked JSON specs.
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import constants
from ..chain.runtime import Runtime, RuntimeConfig
from ..crypto import ed25519

D = constants.DOLLARS


def eth_chain_id(chain_id: str) -> int:
    """One derivation for eth_chainId, net_version, AND the CHAINID
    opcode (stamped into genesis state below) — Eth tooling
    cross-checks all three."""
    return int.from_bytes(
        hashlib.sha256(chain_id.encode()).digest()[:4], "big")


@dataclasses.dataclass(frozen=True)
class ValidatorGenesis:
    account: str
    bond: int


@dataclasses.dataclass(frozen=True)
class ChainSpec:
    name: str
    chain_id: str
    endowed: tuple[tuple[str, int], ...]
    validators: tuple[ValidatorGenesis, ...]
    era_blocks: int = constants.EPOCH_DURATION_BLOCKS * constants.SESSIONS_PER_ERA
    epoch_blocks: int = constants.EPOCH_DURATION_BLOCKS
    fragment_count: int = constants.FRAGMENT_COUNT
    max_validators: int = 100
    audit_challenge_life: int | None = None   # None -> audit defaults
    audit_verify_life: int | None = None
    sudo: str | None = None                    # dev root origin account
    # the spec version the chain was BORN at (part of the genesis
    # hash): any code version reproduces the genesis byte-exactly;
    # upgrades activate via system.apply_runtime_upgrade in a block.
    # 0 = resolved to the current code's version AT CONSTRUCTION, so
    # the stored field is always concrete and exports/imports
    # round-trip exactly.
    genesis_spec_version: int = 0

    def __post_init__(self):
        if self.genesis_spec_version == 0:
            from ..chain import migrations

            object.__setattr__(self, "genesis_spec_version",
                               migrations.SPEC_VERSION)

    def session_key(self, account: str) -> ed25519.SigningKey:
        """Deterministic dev session keys derived from the spec id —
        the analog of //Alice-style dev seeds. Production nodes load
        keys from their keystore instead."""
        return ed25519.SigningKey.generate(
            f"{self.chain_id}:{account}".encode())

    def account_key(self, account: str) -> ed25519.SigningKey:
        """Deterministic dev ACCOUNT keys (domain-separated from
        session keys): what extrinsics are signed with. Production
        accounts bring their own keys; dev chains derive them like
        //Alice seeds."""
        return ed25519.SigningKey.generate(
            f"{self.chain_id}/account:{account}".encode())

    def genesis_hash(self) -> bytes:
        """Chain identity bound into every signature (replay domain).
        Covers the FULL genesis configuration — two chains differing
        in any endowment, validator, or parameter have different
        signing domains."""
        from .. import codec

        return hashlib.sha256(b"cess-tpu-genesis:" + codec.encode((
            self.name, self.chain_id, self.endowed,
            tuple((v.account, v.bond) for v in self.validators),
            self.era_blocks, self.epoch_blocks, self.fragment_count,
            self.max_validators, self.audit_challenge_life,
            self.audit_verify_life, self.sudo,
            self.genesis_spec_version))).digest()

    def build_runtime(self) -> Runtime:
        rt = Runtime(RuntimeConfig(
            fragment_count=self.fragment_count, era_blocks=self.era_blocks,
            max_validators=self.max_validators,
            audit_challenge_life=self.audit_challenge_life,
            audit_verify_life=self.audit_verify_life,
            genesis_spec_version=self.genesis_spec_version))
        rt.set_genesis_hash(self.genesis_hash())
        rt.state.put("system", "chain_id", eth_chain_id(self.chain_id))
        if self.sudo:
            rt.system.set_sudo(self.sudo)
        for who, amount in self.endowed:
            rt.fund(who, amount)
            rt.system.bind_account_key(who, self.account_key(who).public)
        for v in self.validators:
            rt.fund(v.account, v.bond + 100 * D)
            rt.system.bind_account_key(v.account,
                                       self.account_key(v.account).public)
            rt.system.set_session_key(v.account,
                                      self.session_key(v.account).public)
            rt.apply_extrinsic(v.account, "staking.bond", v.bond)
            rt.apply_extrinsic(v.account, "staking.validate")
        rt.audit.set_keys(tuple(v.account for v in self.validators))
        rt.state.archive_events()
        return rt


def spec_to_json(spec: ChainSpec) -> dict:
    """Reproducible-genesis export (the reference's raw chain specs,
    node/src/chain_spec.rs:318-434): every field that determines
    genesis state, plus the derived genesis hash for integrity."""
    return {
        "name": spec.name, "chain_id": spec.chain_id,
        "endowed": [[w, a] for w, a in spec.endowed],
        "validators": [[v.account, v.bond] for v in spec.validators],
        "era_blocks": spec.era_blocks, "epoch_blocks": spec.epoch_blocks,
        "fragment_count": spec.fragment_count,
        "max_validators": spec.max_validators,
        "audit_challenge_life": spec.audit_challenge_life,
        "audit_verify_life": spec.audit_verify_life,
        "sudo": spec.sudo,
        "genesis_spec_version": spec.genesis_spec_version,
        "genesis_hash": "0x" + spec.genesis_hash().hex(),
    }


def spec_from_json(data: dict) -> ChainSpec:
    spec = ChainSpec(
        name=data["name"], chain_id=data["chain_id"],
        endowed=tuple((w, a) for w, a in data["endowed"]),
        validators=tuple(ValidatorGenesis(a, b)
                         for a, b in data["validators"]),
        era_blocks=data["era_blocks"], epoch_blocks=data["epoch_blocks"],
        fragment_count=data["fragment_count"],
        max_validators=data["max_validators"],
        audit_challenge_life=data["audit_challenge_life"],
        audit_verify_life=data["audit_verify_life"],
        sudo=data.get("sudo"),
        genesis_spec_version=data.get("genesis_spec_version", 0))
    want = data.get("genesis_hash")
    if want and "0x" + spec.genesis_hash().hex() != want:
        raise ValueError("chain spec genesis hash mismatch")
    return spec


def dev_spec(era_blocks: int = 60, epoch_blocks: int = 20) -> ChainSpec:
    """Single-authority dev chain (the reference's --dev)."""
    return ChainSpec(
        name="cess-tpu dev", chain_id="dev",
        endowed=(("alice", 1_000_000_000 * D), ("bob", 1_000_000_000 * D)),
        validators=(ValidatorGenesis("alice", 4_000_000 * D),),
        era_blocks=era_blocks, epoch_blocks=epoch_blocks, sudo="alice")


def local_spec(n_validators: int = 4, era_blocks: int = 120,
               epoch_blocks: int = 30) -> ChainSpec:
    """Multi-authority local testnet (the reference's local_testnet)."""
    vals = tuple(ValidatorGenesis(f"val{i}", 4_000_000 * D)
                 for i in range(n_validators))
    endowed = tuple((f"user{i}", 100_000_000 * D) for i in range(4)) \
        + (("faucet", 10_000_000_000 * D),)
    return ChainSpec(name="cess-tpu local", chain_id="local",
                     endowed=endowed, validators=vals,
                     era_blocks=era_blocks, epoch_blocks=epoch_blocks,
                     sudo="val0")
