"""Kademlia-style authority discovery (the reference's
authority-discovery worker over the libp2p Kademlia DHT,
/root/reference/node/src/service.rs:508-537).

The reference publishes each validator's signed address record into a
DHT keyed by authority id, so validators find each other without any
of them being globally known. This module is the framework-native
equivalent, transport-agnostic (cess_tpu/node/net.py wires it to
short-lived TCP request/response sockets):

- node ids and record keys live in a 256-bit XOR metric space
  (sha256), contacts sort into per-prefix buckets capped at K with
  oldest-out eviction, lookups walk toward the target iteratively.
- an ``AuthorityRecord`` is signed by the authority's SESSION key (the
  same registry finality votes verify against, system.set_session_key)
  and carries a monotonic serial — newest-serial-wins on store, so a
  re-published address supersedes stale ones and a replayed old record
  cannot roll a fresh one back.
- storage is verified-on-arrival and bounded (STORE_CAP), so an
  unauthenticated peer cannot grow memory or plant records for
  non-authorities.

The gossip ring (net.py) keeps block/tx/vote propagation connected;
this layer answers the *directory* question — "where does authority X
listen?" — with O(log n) routed hops instead of flooding.
"""
from __future__ import annotations

import dataclasses
import hashlib
import threading
import time

from .. import codec
from ..crypto import ed25519

K = 8          # bucket size == store/lookup replication
ALPHA = 3      # lookup concurrency (serialized per round here)
ID_BITS = 256
STORE_CAP = 512
# stored records expire after TTL unless republished (libp2p Kademlia's
# provider-record expiry role: a retired authority's address does not
# linger forever); publishers re-publish every ~10 slots, far inside it
RECORD_TTL = 600.0
# a bucket untouched this long gets a synthetic-target lookup (Kademlia
# bucket refresh: keeps far buckets populated through churn)
BUCKET_REFRESH_INTERVAL = 60.0
RECORD_SIGNING_CONTEXT = b"cess-tpu/authority-record-v1:"


def node_id(port: int) -> bytes:
    """A node's DHT identity; derived from its canonical gossip port
    (the in-repo analog of deriving it from the libp2p peer id)."""
    return hashlib.sha256(b"cess-dht-node:%d" % port).digest()


def record_key(authority: str) -> bytes:
    return hashlib.sha256(b"cess-dht-authority:"
                          + authority.encode()).digest()


def distance(a: bytes, b: bytes) -> int:
    return int.from_bytes(a, "big") ^ int.from_bytes(b, "big")


@codec.register
@dataclasses.dataclass(frozen=True)
class Contact:
    port: int         # gossip listen port == node identity
    dht_port: int     # where this node answers DHT RPCs

    def node_id(self) -> bytes:
        return node_id(self.port)


@codec.register
@dataclasses.dataclass(frozen=True)
class AuthorityRecord:
    authority: str
    port: int
    dht_port: int
    serial: int       # publisher-monotonic; newest wins
    signature: bytes  # session-key signature

    def signing_payload(self) -> bytes:
        return RECORD_SIGNING_CONTEXT + codec.encode(
            (self.authority, self.port, self.dht_port, self.serial))

    def contact(self) -> Contact:
        return Contact(port=self.port, dht_port=self.dht_port)


def sign_record(key: ed25519.SigningKey, authority: str, port: int,
                dht_port: int, serial: int) -> AuthorityRecord:
    rec = AuthorityRecord(authority=authority, port=port,
                          dht_port=dht_port, serial=serial, signature=b"")
    return dataclasses.replace(rec,
                               signature=key.sign(rec.signing_payload()))


class Kademlia:
    """Routing table + verified record store + request handler. Thread
    safe; ``verify_record(rec) -> bool`` is supplied by the node layer
    (checks the session-key signature AND that the authority is in the
    current set)."""

    def __init__(self, self_contact: Contact, verify_record,
                 k: int = K, record_ttl: float = RECORD_TTL,
                 refresh_interval: float = BUCKET_REFRESH_INTERVAL):
        self.self_contact = self_contact
        self.self_id = self_contact.node_id()
        self.verify_record = verify_record
        self.k = k
        self.record_ttl = record_ttl
        self.refresh_interval = refresh_interval
        self._buckets: list[list[Contact]] = [[] for _ in range(ID_BITS)]
        self._touched: list[float] = [time.time()] * ID_BITS
        self._store: dict[bytes, AuthorityRecord] = {}
        self._stored_at: dict[bytes, float] = {}
        self._lock = threading.Lock()

    # -- routing table ------------------------------------------------------
    def _bucket_of(self, nid: bytes) -> list[Contact] | None:
        d = distance(self.self_id, nid)
        if d == 0:
            return None
        return self._buckets[d.bit_length() - 1]

    def note(self, c: Contact) -> None:
        """Learn/refresh a contact: move-to-tail on re-sight, oldest
        evicted past k (plain LRU; no liveness probe at test scale)."""
        if not (isinstance(c, Contact) and 0 < c.port < 65536
                and 0 < c.dht_port < 65536):
            return
        with self._lock:
            b = self._bucket_of(c.node_id())
            if b is None:
                return
            d = distance(self.self_id, c.node_id())
            self._touched[d.bit_length() - 1] = time.time()
            for i, have in enumerate(b):
                if have.port == c.port:
                    del b[i]
                    break
            b.append(c)
            if len(b) > self.k:
                del b[0]

    def contacts(self) -> list[Contact]:
        with self._lock:
            return [c for b in self._buckets for c in b]

    def closest(self, key: bytes, n: int | None = None) -> list[Contact]:
        """The n known contacts closest to key (XOR metric)."""
        return sorted(self.contacts(),
                      key=lambda c: distance(c.node_id(), key))[:n or self.k]

    # -- record store -------------------------------------------------------
    def store_record(self, rec, now: float | None = None) -> bool:
        """Verify + keep (newest serial wins); False if rejected. A
        re-store of the SAME record refreshes its TTL clock (that is
        what periodic republication is for)."""
        if not isinstance(rec, AuthorityRecord) \
                or not self.verify_record(rec):
            return False
        now = time.time() if now is None else now
        key = record_key(rec.authority)
        with self._lock:
            self._expire_locked(now)
            have = self._store.get(key)
            if have is not None and have.serial >= rec.serial:
                if have.serial == rec.serial and have == rec:
                    self._stored_at[key] = now    # republish: new TTL
                    return True
                return False
            if have is None and len(self._store) >= STORE_CAP:
                return False
            self._store[key] = rec
            self._stored_at[key] = now
        return True

    def record(self, key: bytes,
               now: float | None = None) -> AuthorityRecord | None:
        now = time.time() if now is None else now
        with self._lock:
            at = self._stored_at.get(key)
            if at is not None and now - at > self.record_ttl:
                del self._store[key]
                del self._stored_at[key]
                return None
            return self._store.get(key)

    def expire(self, now: float | None = None) -> int:
        """Drop every record past its TTL; returns how many went."""
        now = time.time() if now is None else now
        with self._lock:
            return self._expire_locked(now)

    def _expire_locked(self, now: float) -> int:
        stale = [k for k, at in self._stored_at.items()
                 if now - at > self.record_ttl]
        for k in stale:
            del self._store[k]
            del self._stored_at[k]
        return len(stale)

    def refresh_targets(self, now: float | None = None) -> list[bytes]:
        """One synthetic lookup target per STALE non-empty bucket (id
        with exactly that bucket's bit differing from ours — any
        lookup toward it exercises the bucket). Marks returned buckets
        touched; the caller runs the lookups."""
        now = time.time() if now is None else now
        out = []
        self_int = int.from_bytes(self.self_id, "big")
        with self._lock:
            for i, b in enumerate(self._buckets):
                if b and now - self._touched[i] > self.refresh_interval:
                    out.append((self_int ^ (1 << i)).to_bytes(
                        ID_BITS // 8, "big"))
                    self._touched[i] = now
        return out

    # -- request handling ---------------------------------------------------
    def handle(self, req):
        """One DHT RPC: (op, sender_contact, arg) -> response tuple.
        Every request teaches us the sender (Kademlia's implicit
        table maintenance)."""
        if not (isinstance(req, tuple) and len(req) == 3):
            return ("err", "bad request")
        op, sender, arg = req
        if isinstance(sender, Contact):
            self.note(sender)
        if op == "find_node" and isinstance(arg, bytes) \
                and len(arg) == ID_BITS // 8:
            return ("nodes", tuple(self.closest(arg)))
        if op == "find_value" and isinstance(arg, bytes) \
                and len(arg) == ID_BITS // 8:
            rec = self.record(arg)
            if rec is not None:
                return ("value", rec)
            return ("nodes", tuple(self.closest(arg)))
        if op == "store":
            return ("ok", self.store_record(arg))
        if op == "ping":
            return ("pong", self.self_contact)
        return ("err", "unknown op")
