"""On-disk persistence: append-only block store + state snapshots.

The reference persists chain state in RocksDB via the Substrate
backend and resumes/warp-syncs on restart
(/root/reference/node/src/service.rs:259-274). Here the same
capability with the framework's own canonical codec:

- ``BlockStore``: an append-only log of length-prefixed codec-encoded
  blocks (bodies included — the node serves sync from it). Torn tail
  writes from a crash are detected and truncated on open.
- ``Snapshot``: periodic full-state checkpoint (headers, KV state,
  consensus randomness, authorities, finality mark) so restart cost is
  O(blocks since snapshot), not O(chain length). The restored KV is
  integrity-checked against the stored head's state root before use.

A restarted node replays its own stored blocks through the normal
import path (claims re-verified, state re-executed) and then catches
up missed blocks from peers (Node.sync_from).
"""
from __future__ import annotations

import os
import struct
from typing import Iterator

from .. import codec

_LEN = struct.Struct("<I")
_MAGIC = b"CTPU"


class BlockStore:
    """Append-only block log: [4-byte magic] then per record
    [4-byte LE length][codec bytes]."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        valid = self._scan_valid_length()
        if valid is None:
            with open(path, "wb") as f:
                f.write(_MAGIC)
        elif valid < os.path.getsize(path):
            # torn tail from a crash: truncate to the last whole record
            with open(path, "r+b") as f:
                f.truncate(valid)
        self._f = open(path, "ab")

    def _scan_valid_length(self) -> int | None:
        if not os.path.exists(self.path):
            return None
        size = os.path.getsize(self.path)
        if size < len(_MAGIC):
            return None
        with open(self.path, "rb") as f:
            if f.read(len(_MAGIC)) != _MAGIC:
                return None
            pos = len(_MAGIC)
            while pos + _LEN.size <= size:
                (n,) = _LEN.unpack(f.read(_LEN.size))
                if pos + _LEN.size + n > size:
                    break
                f.seek(n, 1)
                pos += _LEN.size + n
            return pos

    def append(self, block) -> None:
        raw = codec.encode(block)
        self._f.write(_LEN.pack(len(raw)) + raw)
        self._f.flush()
        os.fsync(self._f.fileno())

    def __iter__(self) -> Iterator:
        with open(self.path, "rb") as f:
            f.read(len(_MAGIC))
            while True:
                head = f.read(_LEN.size)
                if len(head) < _LEN.size:
                    return
                (n,) = _LEN.unpack(head)
                raw = f.read(n)
                if len(raw) < n:
                    return
                try:
                    yield codec.decode(raw)
                except codec.CodecError:
                    return

    def close(self) -> None:
        self._f.close()


SNAPSHOT_FILE = "snapshot.bin"
BLOCKS_FILE = "blocks.bin"


def snapshot_payload(node) -> bytes:
    """The checkpoint wire/disk payload (shared by on-disk snapshots
    and warp sync)."""
    return codec.encode((
        tuple(node.chain),
        node.runtime.state.kv,
        node.runtime.state.block,
        node.rrsc.randomness,
        node.rrsc._epoch_vrf,
        tuple(node.authorities),
        node.finalized,
        dict(node.finality.justifications),
        node.rrsc.genesis_slot,
    ))


def write_snapshot(base_path: str, node) -> None:
    """Atomic full-node checkpoint (tmp + rename)."""
    payload = snapshot_payload(node)
    tmp = os.path.join(base_path, SNAPSHOT_FILE + ".tmp")
    with open(tmp, "wb") as f:
        f.write(_MAGIC + payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(base_path, SNAPSHOT_FILE))


def load_snapshot(base_path: str, node) -> bool:
    """Restore a checkpoint into ``node``; returns True on success.
    The restored KV must re-derive the stored head's state root —
    a corrupt/tampered snapshot is rejected."""
    path = os.path.join(base_path, SNAPSHOT_FILE)
    if not os.path.exists(path):
        return False
    with open(path, "rb") as f:
        raw = f.read()
    if not raw.startswith(_MAGIC):
        return False
    return restore_snapshot_payload(node, raw[len(_MAGIC):])


def verify_and_adopt_warp(node, snap_bytes: bytes, just,
                          make_probe) -> bool:
    """The ONE warp-sync trust path, shared by the in-process
    Node.warp_sync_from and the TCP NodeService._try_warp.

    Verified before adoption, in this order:
    1. the justification carries >= 2/3 valid signatures from the
       authority set + session keys of the node's OWN (genesis) state —
       the trusted base derived from the chain spec, NEVER material
       carried by the snapshot being judged (else any attacker snapshot
       naming its own authorities would self-verify). If the set has
       legitimately rotated since genesis this fails closed and the
       caller falls back to full replay sync (the reference instead
       follows authority-set handoff proofs from genesis);
    2. the snapshot's header chain starts at the node's locally
       computed genesis and is parent-linked with consecutive numbers;
    3. the snapshot KV re-derives the head's state root
       (restore_snapshot_payload enforces this) and the justification
       targets a block ON that chain.
    Skipped (the warp trade-off, same as the reference's): per-block
    claim verification and execution. Only meaningful on a fresh node.

    ``make_probe()`` builds a throwaway same-spec node used to decode
    the snapshot without touching ``node`` until every check passes.
    """
    if node.head().number != 0:
        return False
    if not (0 < just.target_number
            and node.finality.verify_justification(just)):
        return False
    probe = make_probe()
    if not restore_snapshot_payload(probe, snap_bytes):
        return False
    chain = probe.chain
    if chain[0].hash() != node.chain[0].hash():
        return False
    for parent, child in zip(chain, chain[1:]):
        if child.parent != parent.hash() \
                or child.number != parent.number + 1:
            return False
    if not (just.target_number < len(chain)
            and chain[just.target_number].hash() == just.target_hash):
        return False
    if not restore_snapshot_payload(node, snap_bytes):
        return False
    node.finality.justifications[just.round] = just
    node.finalized = max(node.finalized, just.target_number)
    if node.store is not None:
        write_snapshot(node.base_path, node)
    return True


def restore_snapshot_payload(node, payload: bytes) -> bool:
    """Decode + integrity-check a checkpoint payload into ``node``."""
    try:
        (chain, kv, block, randomness, epoch_vrf, authorities,
         finalized, justifications,
         genesis_slot) = codec.decode(payload)
    except (codec.CodecError, ValueError):
        return False
    if not chain or chain[0].hash() != node.chain[0].hash():
        # empty chain (head() would explode later) or a different
        # genesis than our spec-derived one: refuse before touching
        # any node state
        return False
    state = node.runtime.state
    prev_kv, prev_block = state.kv, state.block
    state.kv = dict(kv)
    state.block = block
    state.rebuild_root_cache()
    if chain and state.state_root() != chain[-1].state_root:
        # Corrupt-but-decodable snapshot: restore the pristine state
        # and report failure so the caller falls back to replaying
        # blocks.bin — bricking startup here would make a recoverable
        # corruption fatal.
        state.kv, state.block = prev_kv, prev_block
        state.rebuild_root_cache()
        return False
    node.chain = list(chain)
    # rebuild the block-tree index for the canonical chain (bodies are
    # re-registered when the block-log replay re-imports them); no undo
    # logs survive a restart, so snapshot blocks cannot be rewound.
    # Pre-restore tree state (headers/bodies/authsets from any chain
    # built before this restore) must not survive — stale entries
    # would mix two histories.
    node.headers = {}
    node.bodies = {}
    node.block_bodies = {}
    node._primaries = {}
    node._undo = {}
    node._authset = {}
    prev_primaries = 0
    for hd in node.chain:
        h = hd.hash()
        node.headers[h] = hd
        prev_primaries += 1 if (hd.claim and hd.claim.vrf) else 0
        node._primaries[h] = prev_primaries
    # Historical per-block authority sets are not in the snapshot.
    # Stamp genesis with the spec-derived set and the head (+ its
    # parent, which is what a head-targeting justification verifies
    # against) with the restored set; justification verification for
    # intermediate heights falls back to the genesis set — i.e. only
    # checkpoint-head justifications are verified against the exact
    # set; deeper history is conservative (fails closed on rotation).
    node._authset[node.chain[0].hash()] = tuple(
        v.account for v in node.spec.validators)
    node._authset[node.chain[-1].hash()] = tuple(authorities)
    if len(node.chain) > 1:
        node._authset[node.chain[-2].hash()] = tuple(authorities)
    node.rrsc.randomness = {int(k): v for k, v in randomness.items()}
    node.rrsc._epoch_vrf = {int(k): list(v) for k, v in epoch_vrf.items()}
    node.authorities = tuple(authorities)
    node.finalized = finalized
    node.finality.justifications = {int(k): v
                                    for k, v in justifications.items()}
    node.rrsc.genesis_slot = genesis_slot
    return True
