"""Node CLI (reference: node/src/cli.rs + command.rs: run, key tools
(key/sign/verify), build-spec, check/export/import/revert blocks).

  python -m cess_tpu.node.cli --dev --blocks 20 --rpc-port 9944
  python -m cess_tpu.node.cli --chain local --validator val0 \
      --port 30333 --peers 30334,30335 --genesis-time 1700000000
  python -m cess_tpu.node.cli --chain local --validators 4 --blocks 50
  python -m cess_tpu.node.cli build-spec --chain dev
  python -m cess_tpu.node.cli key --suri my-seed
  python -m cess_tpu.node.cli sign --suri my-seed --message 0xdead
  python -m cess_tpu.node.cli verify --public 0x.. --message 0x.. --signature 0x..
  python -m cess_tpu.node.cli export-blocks --dev --base-path data --to chain.blocks
  python -m cess_tpu.node.cli import-blocks --dev --base-path data2 --from chain.blocks
  python -m cess_tpu.node.cli revert --dev --base-path data --blocks 3
  python -m cess_tpu.node.cli check-block --dev --base-path data --number 5
"""
from __future__ import annotations

import argparse
import json
import sys
import time

from ..crypto import ed25519
from .chain_spec import dev_spec, local_spec, spec_from_json, spec_to_json
from .network import Network, Node
from .rpc import RpcServer


def _load_spec(chain: str, validators: int):
    """dev | local | path-to-exported-spec.json (reproducible
    genesis, chain_spec.rs:318-434 analog)."""
    if chain == "dev":
        return dev_spec()
    if chain == "local":
        return local_spec(validators)
    with open(chain) as f:
        return spec_from_json(json.load(f))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cess-tpu-node")
    ap.add_argument("subcommand", nargs="?", default="run",
                    choices=["run", "build-spec", "key", "sign",
                             "verify", "export-blocks", "import-blocks",
                             "revert", "check-block", "vanity",
                             "benchmark", "try-runtime"])
    ap.add_argument("--dev", action="store_true",
                    help="single-authority dev chain")
    ap.add_argument("--chain", default="dev",
                    help="dev | local | path to an exported spec JSON")
    ap.add_argument("--validators", type=int, default=4)
    ap.add_argument("--blocks", type=int, default=0,
                    help="produce N blocks then exit (0 = run forever)")
    ap.add_argument("--block-time", type=float, default=0.0,
                    help="seconds between slots (0 = as fast as possible)")
    ap.add_argument("--rpc-port", type=int, default=0,
                    help="serve JSON-RPC on this port (0 = off)")
    ap.add_argument("--base-path", default=None,
                    help="persist chain data here and resume on restart")
    ap.add_argument("--suri", default="dev-seed", help="key seed material")
    ap.add_argument("--message", default="0x", help="hex payload (sign/verify)")
    ap.add_argument("--public", default="", help="hex public key (verify)")
    ap.add_argument("--signature", default="", help="hex signature (verify)")
    ap.add_argument("--to", default="chain.blocks", help="export target file")
    ap.add_argument("--from", dest="from_file", default="chain.blocks",
                    help="import source file")
    ap.add_argument("--number", type=int, default=None,
                    help="block (check-block; default: head)")
    ap.add_argument("--port", type=int, default=0,
                    help="run ONE node over TCP gossip on this port "
                         "(production shape: one process per node)")
    ap.add_argument("--peers", default="",
                    help="comma-separated peer ports (TCP mode)")
    ap.add_argument("--validator", default="",
                    help="which genesis validator key this node holds "
                         "(TCP mode; empty = full node, no authoring)")
    ap.add_argument("--genesis-time", type=float, default=0.0,
                    help="shared slot-numbering wall-clock origin (TCP "
                         "mode). Epoch numbering anchors at the first "
                         "block's slot, so 0 (absolute unix slots) "
                         "works; matching values across nodes keeps "
                         "slot numbers aligned")
    ap.add_argument("--slot-time", type=float, default=6.0,
                    help="seconds per slot (TCP mode; ref block time 6s)")
    ap.add_argument("--pattern", default="",
                    help="hex prefix the public key must start with "
                         "(vanity)")
    ap.add_argument("--reps", type=int, default=20,
                    help="dispatches per benchmark sample")
    ap.add_argument("--telemetry", default="",
                    help="stream per-block telemetry JSON lines to "
                         "this host:port endpoint")
    ap.add_argument("--engine", default="off",
                    choices=["off", "cpu", "auto", "tpu"],
                    help="attach a device submission engine "
                         "(cess_tpu/serve) as node.engine: dynamic "
                         "micro-batching for the RS encode/repair hot "
                         "paths with the chosen ErasureCodec backend, "
                         "used by storage drivers embedding this node. "
                         "The PoDR2 classes (tag/prove/verify) need "
                         "the holder's secret key, so they activate "
                         "only on engines the TEE/miner drivers build "
                         "themselves (serve.make_engine(podr2_key=...))"
                         ". Engine queue/batch/latency counters appear "
                         "under cess_engine_* on GET /metrics and via "
                         "the cess_engineStats RPC. 'off' (default) "
                         "keeps every caller on the direct synchronous "
                         "path")
    ap.add_argument("--trace", nargs="?", const="", default=None,
                    metavar="PATH",
                    help="arm the request-scoped tracer (cess_tpu/obs) "
                         "for this run: spans from the pipeline / "
                         "engine / stream / resilience / net seams "
                         "are collected in a bounded ring, served "
                         "live via the cess_traceDump RPC, and — "
                         "with --trace=PATH — written on exit as "
                         "Chrome trace-event JSON (open it in "
                         "Perfetto or chrome://tracing). Without the "
                         "flag every trace hook is a no-op")
    ap.add_argument("--flight", nargs="?", const="", default=None,
                    metavar="DIR",
                    help="arm the flight recorder (cess_tpu/obs/"
                         "flight.py) over the --trace tracer: "
                         "tail-sampled trace retention (anomalous "
                         "traces pinned past ring eviction plus a "
                         "seeded baseline), black-box journals, and "
                         "an IncidentReporter whose bundles are "
                         "served live via the cess_incidentDump RPC "
                         "and — with --flight=DIR — written on exit "
                         "as one JSON file per incident (render with "
                         "tools/incident_view.py). Requires --trace; "
                         "absent = zero-cost off")
    ap.add_argument("--fleet", action="store_true",
                    help="arm the fleet observability plane "
                         "(cess_tpu/obs/fleet.py) on this node: the "
                         "gossip layer exchanges scrape contributions "
                         "with peers every few slots and the node "
                         "federates them — instance-labeled metric "
                         "federation with counter-reset clamping, a "
                         "global per-class SLO view (worst-of + "
                         "quorum), cross-node trace stitching and MAD "
                         "straggler detection — served via the "
                         "cess_fleetStatus RPC (render with "
                         "tools/fleet_view.py). With --flight, "
                         "incident bundles gain the stitched "
                         "cross-node trace view. Absent = zero-cost "
                         "off (the --trace contract)")
    ap.add_argument("--chainwatch", action="store_true",
                    help="arm the chain-plane observability watch "
                         "(cess_tpu/obs/chainwatch.py) on this node: "
                         "per-node consensus health (finality lag, "
                         "reorg depth, fork counts, vote-lock ages, "
                         "a block/vote equivocation detector with "
                         "offences-shaped evidence records), the "
                         "storage-market ledger (audit pass/fail "
                         "spikes, declared-vs-audited capacity "
                         "drift, restoral-auction accounting) and "
                         "edge-triggered chain anomalies (finality-"
                         "stall / deep-reorg / equivocation / audit-"
                         "failure-spike incident triggers) — served "
                         "via the cess_chainStatus RPC and as "
                         "cess_chain_* gauges on GET /metrics "
                         "(render with tools/chain_view.py). With "
                         "--fleet, chain health rides the fleet "
                         "gossip and peers fold per-node finality "
                         "lag into their quorum views. Absent = "
                         "zero-cost off (the --trace contract)")
    ap.add_argument("--remediate", nargs="?", const="act",
                    default=None, choices=["act", "dry"],
                    help="arm the remediation plane "
                         "(cess_tpu/serve/remediate.py) on this "
                         "node: a count-sequenced policy engine that "
                         "subscribes to the --flight recorder's "
                         "detector edges (perf regressions, breaker "
                         "trips, fleet stragglers, chain anomalies) "
                         "and maps each through a declarative policy "
                         "table to a journaled recovery action — pin "
                         "a class to the reference backend, "
                         "quarantine a pool lane, file an "
                         "equivocation offence, flip a miner's "
                         "repair mode — with count-based cooldowns, "
                         "rate limits and release conditions. "
                         "'--remediate=dry' journals every decision "
                         "without acting. Served via the "
                         "cess_remediationStatus RPC and "
                         "cess_remediation_* gauges on GET /metrics "
                         "(render with tools/remediation_view.py). "
                         "Requires --flight; absent = zero-cost off "
                         "(the --trace contract)")
    ap.add_argument("--custody", action="store_true",
                    help="arm the durability plane "
                         "(cess_tpu/obs/custody.py) on this node: a "
                         "bounded per-segment custody ledger fed by "
                         "the --flight recorder's lineage notes "
                         "(gateway dispatch, fragment transfer, TEE "
                         "audit verdict, repair completion), folded "
                         "into live erasure margins every few slots "
                         "with edge-triggered custody.at_risk / "
                         "custody.lost announcements. With "
                         "--remediate the at-risk edge drives the "
                         "proactive-repair policy. Served via the "
                         "cess_custodyStatus RPC and cess_custody_* "
                         "gauges on GET /metrics (render with "
                         "tools/custody_view.py). Requires --flight; "
                         "absent = zero-cost off (the --trace "
                         "contract)")
    ap.add_argument("--slo", nargs="?", const="", default=None,
                    metavar="TARGETS",
                    help="attach an SLO board (cess_tpu/obs/slo.py) to "
                         "the --engine: burn-rate monitors over the "
                         "live per-class latency/error signal, "
                         "per-tenant accounting, and weighted-fair "
                         "dequeue. TARGETS is ';'-separated "
                         "<class>:p99=<dur>[,err=<rate>] (e.g. "
                         "'verify:p99=50ms,err=1%;encode:p99=2s'); "
                         "omitted = the default targets. Gauges "
                         "appear as cess_slo_*/cess_tenant_* on GET "
                         "/metrics and via the cess_sloStatus RPC. "
                         "Requires --engine; absent = zero-cost off "
                         "(the --trace contract)")
    ap.add_argument("--adaptive", action="store_true",
                    help="trace-driven adaptive control "
                         "(cess_tpu/serve/adaptive.py) over the "
                         "--engine: per-class batching knobs tuned "
                         "from the live latency histograms "
                         "(occupancy-targeting replaces the static "
                         "BatchPolicy constants), and — with --slo — "
                         "deadline-aware admission that sheds or "
                         "CPU-degrades encode-class load while a "
                         "verify-class SLO is burning (extends the "
                         "--resilience breaker from 'device broken' "
                         "to 'SLO at risk'). Requires --engine and "
                         "--slo (the board's targets steer the "
                         "tuner)")
    ap.add_argument("--pool", nargs="?", const=0, type=int,
                    default=None, metavar="N",
                    help="shard the --engine across the local device "
                         "mesh (cess_tpu/serve/pool.py): a DevicePool "
                         "routes op-class batches over per-device "
                         "worker lanes — deterministic least-loaded "
                         "placement, per-(backend, device) breakers "
                         "(with --resilience: one sick chip drains to "
                         "its siblings before degrading to CPU), "
                         "per-lane program caches. N limits the lanes "
                         "(bare --pool = all local devices). Per-lane "
                         "gauges appear as cess_engine_device_* on "
                         "GET /metrics and in cess_engineStats. "
                         "Results stay bit-identical to the "
                         "single-device engine. Requires --engine")
    ap.add_argument("--profile", nargs="?", const="", default=None,
                    metavar="BASELINE",
                    help="arm the continuous-profiling plane "
                         "(cess_tpu/obs/profile.py) on the --engine: "
                         "per-(class, bucket, device) stage "
                         "breakdowns (queue-wait/h2d/dispatch), the "
                         "unified pad ledger (engine bucket padding + "
                         "stream ragged tails in ONE account), "
                         "program-cache compile events, and a "
                         "bench-anchored PerfWatchdog that "
                         "edge-triggers a perf-regression incident "
                         "when live windowed throughput drops below a "
                         "guard fraction of the checked-in bench "
                         "record. BASELINE is a bench_diff "
                         "--baseline-out artifact (bare --profile "
                         "scans ./BENCH_r*.json for the newest "
                         "round; no record found = profiling without "
                         "judging). Served via the cess_profileDump "
                         "RPC and cess_profile_* gauges on GET "
                         "/metrics (render with tools/"
                         "profile_view.py). Requires --engine; "
                         "absent = zero-cost off (the --trace "
                         "contract)")
    ap.add_argument("--resilience", default="off",
                    choices=["off", "on"],
                    help="attach the resilience layer "
                         "(cess_tpu/resilience) to the --engine: "
                         "saturated submits retry with deterministic "
                         "backoff inside the request's deadline "
                         "budget, a failed coalesced batch re-runs "
                         "its members individually (one poisoned "
                         "request cannot fail its batch-mates), and a "
                         "per-backend health breaker transparently "
                         "degrades device->CPU reference codec "
                         "(bit-identical results) with recovery "
                         "probes. Counters appear under "
                         "cess_resilience_* beside the cess_engine_* "
                         "family. Requires --engine; 'off' (default) "
                         "keeps the engine fail-fast")
    args = ap.parse_args(argv)

    def unhex(s: str) -> bytes:
        return bytes.fromhex(s[2:] if s.startswith("0x") else s)

    if args.subcommand == "key":
        key = ed25519.SigningKey.generate(args.suri.encode())
        print(json.dumps({"public": "0x" + key.public.hex(),
                          "seed": "0x" + key.seed.hex()}))
        return 0

    if args.subcommand == "sign":
        key = ed25519.SigningKey.generate(args.suri.encode())
        sig = key.sign(unhex(args.message))
        print(json.dumps({"public": "0x" + key.public.hex(),
                          "signature": "0x" + sig.hex()}))
        return 0

    if args.subcommand == "verify":
        ok = ed25519.verify(unhex(args.public), unhex(args.message),
                            unhex(args.signature))
        print(json.dumps({"valid": bool(ok)}))
        return 0 if ok else 1

    if args.subcommand == "vanity":
        # the reference's `key vanity` (node/src/cli.rs:23-70 via
        # sc-cli): grind seeds until the public key starts with the
        # requested hex prefix
        want = args.pattern.lower().removeprefix("0x")
        if not want or any(c not in "0123456789abcdef" for c in want):
            print("--pattern must be non-empty hex", file=sys.stderr)
            return 1
        if len(want) > 6:
            print("--pattern longer than 6 hex digits would grind for "
                  "hours; refusing", file=sys.stderr)
            return 1
        base = args.suri
        if base == "dev-seed":
            # the shared dev default would hand every operator the SAME
            # deterministic "vanity" key; mix fresh entropy unless the
            # caller pinned a suri deliberately (review-caught)
            import secrets

            base = "vanity-" + secrets.token_hex(16)
        i = 0
        while True:
            seed = f"{base}/{i}".encode()
            key = ed25519.SigningKey.generate(seed)
            if key.public.hex().startswith(want):
                print(json.dumps({"public": "0x" + key.public.hex(),
                                  "seed": seed.decode(),
                                  "tries": i + 1}))
                return 0
            i += 1

    if args.subcommand == "benchmark":
        # the `benchmark` subcommand role (node/src/cli.rs:23-70):
        # measure this host's dispatch + block-execution rates against
        # the weight unit so operators can judge whether their machine
        # keeps up with the 6 s slot budget
        import statistics
        import time as _time

        from ..chain.runtime import Runtime, RuntimeConfig

        rt = Runtime(RuntimeConfig(era_blocks=100_000))
        rt.fund("bench-a", 10 ** 24)
        times = []
        for i in range(max(args.reps, 5)):
            t0 = _time.perf_counter()
            rt.apply_extrinsic("bench-a", "balances.transfer",
                               f"bench-b{i}", 10 ** 12)
            times.append(_time.perf_counter() - t0)
        unit_us = statistics.median(times) * 1e6
        t0 = _time.perf_counter()
        rt.advance_blocks(50)
        empty_block_us = (_time.perf_counter() - t0) / 50 * 1e6
        print(json.dumps({
            "weight_unit_us": round(unit_us, 2),
            "empty_block_us": round(empty_block_us, 2),
            "transfers_per_6s_block": int(6e6 / unit_us),
        }))
        return 0

    spec = dev_spec() if args.dev else _load_spec(args.chain,
                                                  args.validators)
    if args.subcommand == "build-spec":
        print(json.dumps(spec_to_json(spec), indent=2))
        return 0

    import os

    if args.subcommand in ("export-blocks", "import-blocks", "revert",
                           "check-block"):
        if not args.base_path:
            print("--base-path required", file=sys.stderr)
            return 1
        return _block_tool(args, spec)

    if args.subcommand == "try-runtime":
        # the try-runtime role (ref node/src/cli.rs:23-70): dry-run the
        # RUNNING code's pending migrations against a real persisted
        # chain's state — report what would change, commit nothing
        if not args.base_path:
            print("--base-path required", file=sys.stderr)
            return 1
        return _try_runtime(args, spec)

    if args.port:
        return _run_tcp_node(args, spec)

    nodes = [Node(spec, f"node-{v.account}",
                  {v.account: spec.session_key(v.account)},
                  base_path=(os.path.join(args.base_path,
                                          f"node-{v.account}")
                             if args.base_path else None))
             for v in spec.validators]
    net = Network(nodes)
    if args.telemetry:
        from .metrics import TelemetryStream

        nodes[0].offchain_agents.append(TelemetryStream(args.telemetry))
    tracer = _arm_cli_tracer(args)
    if tracer is not None:
        nodes[0].tracer = tracer      # cess_traceDump RPC surface
    engine = _make_cli_engine(args, spec)
    if engine is not None:
        nodes[0].engine = engine
        if engine.profile is not None:
            nodes[0].profile = engine.profile  # cess_profileDump RPC
    recorder, reporter = _arm_cli_flight(args, tracer, engine)
    if reporter is not None:
        nodes[0].flight = recorder
        nodes[0].incidents = reporter  # cess_incidentDump RPC surface
    plane = _arm_cli_fleet(args, nodes[0], reporter)
    watch = _arm_cli_chainwatch(args, nodes[0], reporter, plane)
    custody = _arm_cli_custody(args, nodes[0], recorder, reporter)
    remediation = _arm_cli_remediate(args, nodes[0], recorder,
                                     reporter, engine)
    if remediation is not None and custody is not None:
        remediation.bind_custody(custody)  # proactive-repair targets
    rpc = None
    import threading

    # block production and RPC reads share one lock (RPC iterates
    # live runtime state; unsynchronized scrapes race block execution)
    chain_lock = threading.Lock()
    if args.rpc_port:
        rpc = RpcServer(nodes[0], port=args.rpc_port,
                        lock=chain_lock).start()
        print(f"JSON-RPC on 127.0.0.1:{rpc.port}", file=sys.stderr)
    produced = 0
    slot = max(len(nodes[0].chain), 1)
    try:
        while args.blocks == 0 or produced < args.blocks:
            with chain_lock:
                made = net.run_slot(slot)
            if made is not None:
                produced += 1
                head = nodes[0].chain[-1]
                print(f"#{head.number} author={head.author} "
                      f"state={head.state_root.hex()[:16]} "
                      f"finalized=#{nodes[0].finalized}", file=sys.stderr)
            slot += 1
            # single-process deployment: no gossip to scrape peers
            # over, so the watch/plane tick themselves (self-only
            # rounds; the watch scans first so its lag fold lands in
            # the plane's same-slot seal)
            if watch is not None and slot % 4 == 0:
                with chain_lock:
                    watch.scan_node(nodes[0])
                watch.seal_round()
            if plane is not None and slot % 4 == 0:
                with chain_lock:
                    plane.tick()
            # the custody margin fold seals after the scans above so
            # the MarketWatch cross-check reads this slot's market
            # view; its at-risk/lost edges land in the remediation
            # plane's SAME decision round below
            if custody is not None and slot % 4 == 0:
                with chain_lock:
                    _cli_custody_scrape(nodes[0], watch, custody)
            # the remediation plane decides AFTER the detectors'
            # scan/tick above: edges they announced this slot land as
            # actions in the same decision round. Actions may submit
            # extrinsics, so the tick runs under the chain lock
            if remediation is not None and slot % 4 == 0:
                with chain_lock:
                    remediation.tick()
            if args.block_time:
                time.sleep(args.block_time)
    except KeyboardInterrupt:
        pass
    finally:
        if rpc:
            rpc.stop()
        if engine is not None:
            engine.close()
        _finish_cli_profile(engine)
        _finish_cli_remediate(remediation)
        _finish_cli_custody(custody)
        _finish_cli_chainwatch(watch)
        _finish_cli_fleet(plane, tracer)
        _finish_cli_flight(args, recorder, reporter)
        _finish_cli_tracer(args, tracer)
    return 0


def _arm_cli_tracer(args):
    """--trace: arm a process-wide Tracer (cess_tpu/obs) for the run;
    every instrumented seam (pipeline, engine, stream, resilience,
    net, offchain agents) then records request-scoped spans. Returns
    the tracer (also attached as ``node.tracer`` by the callers so
    cess_traceDump serves it) or None."""
    if args.trace is None:
        return None
    from ..obs import trace as obs_trace

    return obs_trace.arm(obs_trace.Tracer(capacity=65536))


def _finish_cli_tracer(args, tracer) -> None:
    """Disarm and, when --trace carried a PATH, write the Chrome
    trace-event JSON artifact (open it in Perfetto)."""
    if tracer is None:
        return
    from ..obs import trace as obs_trace

    obs_trace.disarm()
    if args.trace:
        with open(args.trace, "w") as f:
            json.dump(tracer.export_chrome(), f)
        print(f"trace written to {args.trace} "
              f"({len(tracer.finished())} spans)", file=sys.stderr)


def _arm_cli_flight(args, tracer, engine):
    """--flight: build a FlightRecorder over the --trace tracer
    (tail-sampled retention + black-box journals) and an
    IncidentReporter bundling its triggers; returns ``(recorder,
    reporter)`` (attached by the callers as ``node.flight`` /
    ``node.incidents`` so cess_incidentDump serves them) or
    ``(None, None)``. SLO targets on the engine's board become the
    over-objective pin thresholds."""
    if getattr(args, "flight", None) is None:
        return None, None
    if tracer is None:
        print("--flight requires --trace (retention decisions run on "
              "finished spans)", file=sys.stderr)
        raise SystemExit(2)
    from ..obs import flight as obs_flight
    from ..obs.incident import IncidentReporter

    objectives = {}
    board = None if engine is None else engine.slo
    if board is not None:
        objectives = {t.cls: t.p99_s for t in board.targets}
    recorder = obs_flight.arm(obs_flight.FlightRecorder(
        b"cess-cli", baseline_rate=1 / 64, objectives=objectives))
    tracer.attach_flight(recorder)
    reporter = IncidentReporter(recorder, engine=engine)
    return recorder, reporter


def _finish_cli_flight(args, recorder, reporter) -> None:
    """Disarm and, when --flight carried a DIR, write each incident
    bundle as its own JSON artifact (render a timeline with
    tools/incident_view.py)."""
    if recorder is None:
        return
    import os

    from ..obs import flight as obs_flight

    obs_flight.disarm()
    bundles = reporter.bundles()
    if args.flight:
        os.makedirs(args.flight, exist_ok=True)
        for b in bundles:
            path = os.path.join(
                args.flight, f"incident_{b['seq']:03d}_{b['trigger']}.json")
            with open(path, "w") as f:
                json.dump(b, f, indent=2)
    snap = recorder.snapshot()
    where = f", written to {args.flight}" if args.flight and bundles else ""
    print(f"flight recorder: {snap['pins']} pinned trace(s) "
          f"({snap['pinned_spans']} spans), {len(bundles)} incident "
          f"bundle(s){where}", file=sys.stderr)


def _arm_cli_fleet(args, node, reporter):
    """--fleet: arm a FleetPlane (obs/fleet.py) as ``node.fleet``.
    In TCP mode the net author loop gossips this node's scrape to
    peers every FLEET_EVERY slots and seals rounds over whatever
    peers gossiped in; in-process mode ticks self-only rounds. The
    self scrape source is the node's own /metrics exposition plus the
    engine SLO board snapshot when one exists. With --flight, the
    incident reporter's bundles gain the plane's stitched cross-node
    trace view. Returns the plane or None."""
    if not getattr(args, "fleet", False):
        return None
    from ..obs.fleet import FleetPlane
    from .metrics import render_metrics

    plane = FleetPlane(node.name)

    def _source():
        board = getattr(getattr(node, "engine", None), "slo", None)
        slo = None if board is None else board.snapshot()
        # with --chainwatch, chain health rides the fleet frame: the
        # node's consensus state under "chain" plus a finality_lag
        # SLO class every receiver's FleetBoard folds into its
        # worst/quorum views. Late-bound getattr: the watch arms
        # after the plane.
        watch = getattr(node, "chainwatch", None)
        if watch is not None:
            chain_slo = watch.self_slo(node)
            slo = dict(slo or {})
            targets = dict(slo.get("targets") or {})
            targets.update(chain_slo["targets"])
            slo["targets"] = targets
            slo["chain"] = chain_slo["chain"]
        return (render_metrics(node), slo)

    plane.attach_source(_source)
    if reporter is not None:
        reporter.stitcher = plane.stitcher
    node.fleet = plane
    return plane


def _finish_cli_fleet(plane, tracer) -> None:
    """Feed the run's own trace dump into the stitcher (so the final
    fleet snapshot stitches this node's side of every cross-node hop)
    and print the plane summary."""
    if plane is None:
        return
    if tracer is not None:
        plane.stitcher.add_dump(plane.instance, tracer.finished())
    snap = plane.snapshot()
    print(f"fleet plane: {snap['rounds']} scrape round(s), "
          f"{len(snap['federation']['instances'])} instance(s), "
          f"{snap['stitch']['spans']} stitched span(s)",
          file=sys.stderr)


def _arm_cli_chainwatch(args, node, reporter, plane):
    """--chainwatch: arm a ChainWatch (obs/chainwatch.py) as
    ``node.chainwatch``. The net author loop (TCP mode) or the main
    loop (in-process mode) scans this node's own chain + market state
    every few slots and seals a detector round; with --fleet the
    node's consensus state rides the fleet gossip frames (the plane's
    scrape source folds it into the slo dict) and per-node finality
    lag feeds the plane's straggler windows at every seal. With
    --flight, incident bundles embed the chain-health snapshot.
    Returns the watch or None."""
    if not getattr(args, "chainwatch", False):
        return None
    from ..obs.chainwatch import ChainWatch

    watch = ChainWatch(node.name)
    if plane is not None:
        watch.attach_fleet(plane)
    if reporter is not None:
        reporter.chainwatch = watch
    node.chainwatch = watch
    return watch


def _finish_cli_chainwatch(watch) -> None:
    """Print the chain-watch summary: rounds, anomaly totals and the
    currently-bad anomaly keys (render the full cess_chainStatus
    payload with tools/chain_view.py)."""
    if watch is None:
        return
    snap = watch.snapshot()
    active = {cls: keys
              for cls, keys in snap["anomalies"]["active"].items()
              if keys}
    verdict = "; ".join(f"{cls}: {','.join(keys)}"
                        for cls, keys in sorted(active.items())) \
        or "no active anomalies"
    print(f"chain watch: {snap['rounds']} round(s), "
          f"{len(snap['consensus']['nodes'])} node(s) watched, "
          f"{len(snap['consensus']['equivocations'])} equivocation "
          f"evidence record(s), "
          f"{snap['anomalies']['anomalies']} anomaly edge(s); "
          f"{verdict}", file=sys.stderr)


def _arm_cli_remediate(args, node, recorder, reporter, engine):
    """--remediate: arm a RemediationPlane (serve/remediate.py) as
    ``node.remediation``: it subscribes to the --flight recorder's
    detector edges and acts through the node (extrinsics) and the
    --engine (monitor pins, lane quarantine) when one exists. The
    author/main loop ticks it every few slots — AFTER the detector
    scans, so their edges are decided in the same round. With
    ``--remediate=dry`` every decision is journaled but no seam is
    touched. Returns the plane or None."""
    if getattr(args, "remediate", None) is None:
        return None
    if recorder is None:
        print("--remediate requires --flight (the policy engine "
              "subscribes to the flight recorder's detector edges)",
              file=sys.stderr)
        raise SystemExit(2)
    from ..serve.remediate import RemediationPlane

    plane = RemediationPlane(b"cess-cli",
                             dry_run=args.remediate == "dry")
    if engine is not None:
        plane.bind_engine(engine)
    plane.bind_node(node)
    recorder.add_listener(plane.on_note)
    if reporter is not None:
        reporter.remediation = plane  # bundles embed the journal tail
    node.remediation = plane
    return plane


def _finish_cli_remediate(plane) -> None:
    """Print the remediation summary: decision counts and what is
    still engaged (render the full cess_remediationStatus payload
    with tools/remediation_view.py)."""
    if plane is None:
        return
    snap = plane.snapshot()
    c = snap["counters"]
    engaged = ", ".join(sorted(snap["engaged"])) or "nothing engaged"
    mode = " [dry-run]" if snap["dry_run"] else ""
    print(f"remediation plane{mode}: {snap['edges_total']} edge(s), "
          f"{sum(snap['fires'].values())} fire(s), "
          f"{c['suppressed']} suppressed, {c['releases']} release(s), "
          f"{c['flaps']} flap(s); {engaged}", file=sys.stderr)


def _arm_cli_custody(args, node, recorder, reporter):
    """--custody: arm a CustodyPlane (obs/custody.py) as
    ``node.custody``: its ledger subscribes to the --flight
    recorder's ("custody", ...) lineage notes, and the author/main
    loop seals one margin-fold round every few slots (scraping the
    open restoral-order set from the node's own runtime state, and
    cross-checking the --chainwatch MarketWatch when one rides).
    Returns the plane or None."""
    if not getattr(args, "custody", False):
        return None
    if recorder is None:
        print("--custody requires --flight (the custody ledger "
              "subscribes to the flight recorder's lineage notes)",
              file=sys.stderr)
        raise SystemExit(2)
    from ..obs.custody import CustodyPlane

    plane = CustodyPlane(node.name)
    recorder.add_listener(plane.on_note)
    if reporter is not None:
        reporter.custody = plane  # bundles embed custody timelines
    node.custody = plane
    return plane


def _cli_custody_scrape(node, watch, custody) -> None:
    """One self-only custody round on a live node: the open
    restoral-order set from the (replicated) runtime state, the
    MarketWatch cross-check when a --chainwatch rides, then the seal
    folds margins and runs the at-risk/lost detectors. Holder
    liveness stays at the plane's default (alive) — a single node
    has no fleet view to grade peers by."""
    custody.observe_restorals(tuple(
        frag for (frag,), _o in sorted(
            node.runtime.state.iter_prefix("file_bank", "restoral"))))
    if watch is not None:
        custody.cross_check_market(watch.market.snapshot())
    custody.seal_round()


def _finish_cli_custody(custody) -> None:
    """Print the custody summary: ledger sizes, the margin histogram
    and what is at risk (render the full cess_custodyStatus payload
    with tools/custody_view.py)."""
    if custody is None:
        return
    snap = custody.snapshot()
    sizes = snap["ledger"]
    at_risk = ", ".join(snap["at_risk"]) or "nothing at risk"
    print(f"custody plane: {snap['rounds']} round(s), "
          f"{sizes['segments']} segment(s), "
          f"{sizes['fragments']} fragment(s), "
          f"{sizes['events_total']} ledger event(s), "
          f"margins {snap['histogram']}; {at_risk}", file=sys.stderr)


def _finish_cli_profile(engine) -> None:
    """Print the profile-plane summary: observation/pad/compile
    totals and the watchdog verdict (render the full cess_profileDump
    payload with tools/profile_view.py)."""
    plane = getattr(engine, "profile", None)
    if plane is None:
        return
    pads = plane.pads.total()
    compiles = plane.compiles.snapshot()
    wd = plane.watchdog
    verdict = "watchdog off (no baseline)"
    if wd is not None:
        snap = wd.snapshot()
        regressed = sorted(m for m, s in snap["states"].items()
                           if s == "regressed")
        verdict = (f"REGRESSED: {','.join(regressed)}" if regressed
                   else f"ok ({len(snap['states'])} metric(s) "
                        f"watched)")
    print(f"profile plane: {plane.ops.observations()} observation(s), "
          f"{pads['padded']} padded row(s) vs {pads['served']} served, "
          f"{compiles['builds']} compile(s); {verdict}",
          file=sys.stderr)


def _make_cli_engine(args, spec):
    """--engine: build a submission engine over the chain's RS
    geometry with the requested ErasureCodec backend and attach it as
    ``node.engine`` — the handle embedding code (gateway/miner/TEE
    drivers constructed around this node, tests, notebooks) submits
    through. RS-only: the PoDR2 secret never lives in the node, so the
    audit classes stay inert here (drivers holding a key build their
    own engine via serve.make_engine(podr2_key=...)). The CLI itself
    spawns no storage agents, so with a bare node the flag's visible
    effect is the stats surface: counters on GET /metrics
    (cess_engine_*) and the cess_engineStats RPC.

    --resilience mirrors the shape: opt-in, wraps THIS engine with
    the retry/isolation/degradation layer (cess_tpu/resilience) and
    adds the cess_resilience_* counters to the same surfaces.
    --slo / --adaptive mirror it again (ISSUE 6): an SLO board with
    burn-rate monitors + per-tenant accounting, and the adaptive
    batching/admission layer consuming it — cess_slo_*/cess_tenant_*/
    cess_adaptive_* counters on the same surfaces plus the
    cess_sloStatus RPC. --profile mirrors it once more (ISSUE 13):
    the continuous-profiling plane (obs/profile.py) — cess_profile_*
    gauges plus the cess_profileDump RPC."""
    # getattr defaults: embedders hand-build minimal Namespaces
    slo_spec = getattr(args, "slo", None)
    adaptive = getattr(args, "adaptive", False)
    pool_spec = getattr(args, "pool", None)
    profile_spec = getattr(args, "profile", None)
    if args.engine == "off":
        if args.resilience != "off":
            raise SystemExit("--resilience requires --engine "
                             "(it wraps the submission engine)")
        if slo_spec is not None:
            raise SystemExit("--slo requires --engine (it watches the "
                             "submission engine's latency signal)")
        if adaptive:
            raise SystemExit("--adaptive requires --engine (it tunes "
                             "the submission engine's batching)")
        if pool_spec is not None:
            raise SystemExit("--pool requires --engine (it shards the "
                             "submission engine's dispatch)")
        if profile_spec is not None:
            raise SystemExit("--profile requires --engine (it "
                             "accounts the submission engine's "
                             "dispatches)")
        return None
    if pool_spec is not None and pool_spec < 0:
        raise SystemExit("--pool takes a non-negative lane count")
    if adaptive and slo_spec is None:
        raise SystemExit("--adaptive requires --slo (without a board's "
                         "targets the knob tuner has nothing to steer "
                         "toward and would silently never adjust)")
    from ..serve import make_engine

    resilience = None
    if args.resilience == "on":
        from ..resilience import ResilienceConfig

        resilience = ResilienceConfig()
    slo = None
    if slo_spec is not None:
        from ..obs.slo import SloBoard, parse_targets

        slo = SloBoard(parse_targets(slo_spec))
    profile = None
    if profile_spec is not None:
        from ..obs import profile as obs_profile

        # --profile=PATH: a bench_diff --baseline-out artifact; bare
        # --profile: the newest checked-in BENCH_r*.json round. No
        # record found = an unanchored plane (profiling without
        # judging) — the ledgers still fill, the watchdog stays inert.
        baseline = (obs_profile.load_baseline(profile_spec)
                    if profile_spec
                    else obs_profile.latest_bench_baseline())
        profile = obs_profile.ProfilePlane(baseline=baseline)
    k = max(spec.fragment_count - 1, 1)      # reference RS(k, 1) shape
    # --pool = all local devices; --pool=N = the first N lanes
    pool = None if pool_spec is None else (pool_spec or True)
    return make_engine(k, spec.fragment_count - k,
                       rs_backend=args.engine, resilience=resilience,
                       slo=slo, adaptive=True if adaptive else None,
                       pool=pool, profile=profile)


def _data_dir(args, spec) -> "str | None":
    """Locate the persisted node data dir under --base-path: an
    existing node-* dir WITH a block log, or the base path itself if
    it is one — never a directory that would make Node() silently
    fabricate a fresh chain (shared by _block_tool and _try_runtime;
    review-caught: try-runtime's own weaker scan could pick an
    unrelated subdir and report against a fabricated genesis)."""
    import os

    from . import store as _store

    candidates = sorted(
        d for d in (os.listdir(args.base_path)
                    if os.path.isdir(args.base_path) else [])
        if d.startswith("node-")
        and os.path.exists(os.path.join(args.base_path, d,
                                        _store.BLOCKS_FILE)))
    if candidates:
        preferred = f"node-{spec.validators[0].account}"
        base = os.path.join(args.base_path,
                            preferred if preferred in candidates
                            else candidates[0])
        if len(candidates) > 1:
            print(f"note: multiple node dirs {candidates}, using "
                  f"{os.path.basename(base)}", file=sys.stderr)
        return base
    if os.path.exists(os.path.join(args.base_path, _store.BLOCKS_FILE)):
        return args.base_path
    return None


def _try_runtime(args, spec) -> int:
    from ..chain import migrations

    base = _data_dir(args, spec)
    if base is None:
        print(f"no node data under {args.base_path}", file=sys.stderr)
        return 1
    node = Node(spec, "try-runtime", {}, base_path=base)
    state = node.runtime.state
    root_before = state.state_root()
    before = migrations.spec_version(state)
    versions_before = {pallet: migrations.storage_version(state, pallet)
                       for pallet in migrations.current_versions()}
    state.begin_tx()
    try:
        applied = migrations.run_pending(state)
        after = migrations.spec_version(state)
    finally:
        state.rollback_tx()          # dry run: NOTHING commits
    ok = state.state_root() == root_before
    print(json.dumps({
        "base_path": base,
        "head": node.head().number,
        "spec_version": {"on_chain": before, "code": after},
        "storage_versions": versions_before,
        "pending_migrations": applied,
        "would_change_state": bool(applied),
        "rollback_clean": ok,
    }, indent=2))
    return 0 if ok else 1


def _run_tcp_node(args, spec) -> int:
    """Production-shaped deployment: ONE node per OS process, gossiping
    over TCP (the reference's model; node/src/service.rs). Peers are
    seeded via --peers and extended by the peer exchange."""
    import os

    from .net import NodeService

    keystore = {}
    if args.validator:
        if args.validator not in {v.account for v in spec.validators}:
            print(f"unknown validator {args.validator!r}", file=sys.stderr)
            return 1
        keystore[args.validator] = spec.session_key(args.validator)
    name = args.validator or f"full-{args.port}"
    base = os.path.join(args.base_path, f"node-{name}")         if args.base_path else None
    node = Node(spec, name, keystore, base_path=base)
    if args.telemetry:
        from .metrics import TelemetryStream

        node.offchain_agents.append(TelemetryStream(args.telemetry))
    peers = [int(p) for p in args.peers.split(",") if p.strip()]
    tracer = _arm_cli_tracer(args)
    if tracer is not None:
        node.tracer = tracer          # cess_traceDump RPC surface
    engine = _make_cli_engine(args, spec)
    if engine is not None:
        node.engine = engine
        if engine.profile is not None:
            node.profile = engine.profile  # cess_profileDump RPC
    recorder, reporter = _arm_cli_flight(args, tracer, engine)
    if reporter is not None:
        node.flight = recorder
        node.incidents = reporter     # cess_incidentDump RPC surface
    plane = _arm_cli_fleet(args, node, reporter)
    watch = _arm_cli_chainwatch(args, node, reporter, plane)
    custody = _arm_cli_custody(args, node, recorder, reporter)
    remediation = _arm_cli_remediate(args, node, recorder, reporter,
                                     engine)
    if remediation is not None and custody is not None:
        remediation.bind_custody(custody)  # proactive-repair targets
    svc = NodeService(node, args.port, peers, slot_time=args.slot_time,
                      genesis_time=args.genesis_time)
    rpc = None
    if args.rpc_port:
        rpc = RpcServer(node, port=args.rpc_port, lock=svc.lock,
                        service=svc).start()
        print(f"JSON-RPC on 127.0.0.1:{rpc.port}", file=sys.stderr)
    svc.start()
    print(f"node {name} on :{args.port}, peers {peers}", file=sys.stderr)
    try:
        last = -1
        while True:
            time.sleep(max(args.slot_time, 0.2))
            with svc.lock:
                head = node.head()
                fin = node.finalized
            if head.number != last:
                last = head.number
                print(f"#{head.number} author={head.author} "
                      f"finalized=#{fin} peers={len(svc._known_peers)}",
                      file=sys.stderr)
            # the custody margin fold seals once per monitor
            # iteration, BEFORE the remediation decision below, so
            # an at-risk edge is acted on in the same pass
            if custody is not None:
                with svc.lock:
                    _cli_custody_scrape(node, watch, custody)
            # one remediation decision round per monitor iteration:
            # edges the service's detector scans announced since the
            # last pass become actions here. Extrinsic-filing actions
            # share the service lock with block import
            if remediation is not None:
                with svc.lock:
                    remediation.tick()
            if args.blocks and head.number >= args.blocks:
                break
    except KeyboardInterrupt:
        pass
    finally:
        svc.stop()
        if rpc:
            rpc.stop()
        if engine is not None:
            engine.close()
        _finish_cli_profile(engine)
        _finish_cli_remediate(remediation)
        _finish_cli_custody(custody)
        _finish_cli_chainwatch(watch)
        _finish_cli_fleet(plane, tracer)
        _finish_cli_flight(args, recorder, reporter)
        _finish_cli_tracer(args, tracer)
    return 0


def _block_tool(args, spec) -> int:
    """check/export/import/revert blocks (command.rs analogs). Each
    loads the node from --base-path (which replays + verifies the
    whole log through normal import) and operates on the canonical
    chain."""
    import os

    from . import store as _store

    # locate the node data dir (shared helper; import-blocks alone may
    # create the canonical layout — it writes data by design)
    base = _data_dir(args, spec)
    if base is None and args.subcommand == "import-blocks":
        base = os.path.join(args.base_path,
                            f"node-{spec.validators[0].account}")
        os.makedirs(base, exist_ok=True)
    elif base is None:
        print(f"no node data under {args.base_path}", file=sys.stderr)
        return 1
    node = Node(spec, "tool", {}, base_path=base)
    head = node.head().number

    if args.subcommand == "check-block":
        n = head if args.number is None else args.number
        if not 0 <= n <= head:
            print(f"block {n} out of range (head #{head})",
                  file=sys.stderr)
            return 1
        h = node.chain[n]
        # the load above already re-executed and root-checked the chain
        print(json.dumps({"number": n, "hash": "0x" + h.hash().hex(),
                          "state_root": "0x" + h.state_root.hex(),
                          "author": h.author, "verified": True}))
        return 0

    if args.subcommand == "export-blocks":
        if os.path.exists(args.to):
            os.remove(args.to)   # truncate: re-exports must not append
        exp = _store.BlockStore(args.to)
        for n in range(1, head + 1):
            exp.append(node.block_bodies[n])
        exp.close()
        print(f"exported #{1}..#{head} to {args.to}", file=sys.stderr)
        return 0

    if args.subcommand == "import-blocks":
        src_store = _store.BlockStore(args.from_file)
        imported = 0
        for block in src_store:
            try:
                node.import_block(block)
                imported += 1
            except ValueError:
                continue   # duplicates / stale forks
        print(f"imported {imported} blocks, head #{node.head().number}",
              file=sys.stderr)
        return 0

    if args.subcommand == "revert":
        target = max(0, head - args.blocks)
        if target < node.finalized:
            print(f"refusing to revert below finalized "
                  f"#{node.finalized}", file=sys.stderr)
            return 1
        # rewrite the block log up to the target and drop the snapshot
        # (the next start replays the truncated log)
        blocks_file = os.path.join(base, _store.BLOCKS_FILE)
        tmp = blocks_file + ".tmp"
        out = _store.BlockStore(tmp)
        for n in range(1, target + 1):
            out.append(node.block_bodies[n])
        out.close()
        node.store.close()
        os.replace(tmp, blocks_file)
        snap = os.path.join(base, _store.SNAPSHOT_FILE)
        if os.path.exists(snap):
            os.remove(snap)
        print(f"reverted to #{target}", file=sys.stderr)
        return 0
    return 1


if __name__ == "__main__":
    sys.exit(main())
