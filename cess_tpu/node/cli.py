"""Node CLI (reference: node/src/cli.rs + command.rs).

  python -m cess_tpu.node.cli --dev --blocks 20 --rpc-port 9944
  python -m cess_tpu.node.cli --chain local --validators 4 --blocks 50
  python -m cess_tpu.node.cli build-spec --chain dev
  python -m cess_tpu.node.cli key --suri my-seed
"""
from __future__ import annotations

import argparse
import json
import sys
import time

from ..crypto import ed25519
from .chain_spec import dev_spec, local_spec, spec_from_json, spec_to_json
from .network import Network, Node
from .rpc import RpcServer


def _load_spec(chain: str, validators: int):
    """dev | local | path-to-exported-spec.json (reproducible
    genesis, chain_spec.rs:318-434 analog)."""
    if chain == "dev":
        return dev_spec()
    if chain == "local":
        return local_spec(validators)
    with open(chain) as f:
        return spec_from_json(json.load(f))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cess-tpu-node")
    ap.add_argument("subcommand", nargs="?", default="run",
                    choices=["run", "build-spec", "key"])
    ap.add_argument("--dev", action="store_true",
                    help="single-authority dev chain")
    ap.add_argument("--chain", default="dev",
                    help="dev | local | path to an exported spec JSON")
    ap.add_argument("--validators", type=int, default=4)
    ap.add_argument("--blocks", type=int, default=0,
                    help="produce N blocks then exit (0 = run forever)")
    ap.add_argument("--block-time", type=float, default=0.0,
                    help="seconds between slots (0 = as fast as possible)")
    ap.add_argument("--rpc-port", type=int, default=0,
                    help="serve JSON-RPC on this port (0 = off)")
    ap.add_argument("--base-path", default=None,
                    help="persist chain data here and resume on restart")
    ap.add_argument("--suri", default="dev-seed", help="key seed material")
    args = ap.parse_args(argv)

    if args.subcommand == "key":
        key = ed25519.SigningKey.generate(args.suri.encode())
        print(json.dumps({"public": "0x" + key.public.hex(),
                          "seed": "0x" + key.seed.hex()}))
        return 0

    spec = dev_spec() if args.dev else _load_spec(args.chain,
                                                  args.validators)
    if args.subcommand == "build-spec":
        print(json.dumps(spec_to_json(spec), indent=2))
        return 0

    import os

    nodes = [Node(spec, f"node-{v.account}",
                  {v.account: spec.session_key(v.account)},
                  base_path=(os.path.join(args.base_path,
                                          f"node-{v.account}")
                             if args.base_path else None))
             for v in spec.validators]
    net = Network(nodes)
    rpc = None
    import contextlib
    import threading

    # block production and RPC reads share one lock (RPC iterates
    # live runtime state; unsynchronized scrapes race block execution)
    chain_lock = threading.Lock()
    if args.rpc_port:
        rpc = RpcServer(nodes[0], port=args.rpc_port,
                        lock=chain_lock).start()
        print(f"JSON-RPC on 127.0.0.1:{rpc.port}", file=sys.stderr)
    produced = 0
    slot = max(len(nodes[0].chain), 1)
    try:
        while args.blocks == 0 or produced < args.blocks:
            with chain_lock:
                made = net.run_slot(slot)
            if made is not None:
                produced += 1
                head = nodes[0].chain[-1]
                print(f"#{head.number} author={head.author} "
                      f"state={head.state_root.hex()[:16]} "
                      f"finalized=#{nodes[0].finalized}", file=sys.stderr)
            slot += 1
            if args.block_time:
                time.sleep(args.block_time)
    except KeyboardInterrupt:
        pass
    finally:
        if rpc:
            rpc.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
