"""Block production, import, fork choice and finality (in-process net).

The reference's node assembles libp2p gossip + RRSC authoring + GRANDPA
voting (SURVEY.md §3.1, §3.4); multi-node behavior is only exercised on
live testnets. Here the same roles run as an in-process network
harness: every Node owns a full Runtime replica, authors blocks when
its keys win the slot lottery, imports and RE-EXECUTES peers' blocks
verifying the VRF claim and state root (state-machine replication), and
finalizes with 2/3 vote counting (GRANDPA's role, round-simplified).

This doubles as the determinism test rig the reference lacks in-repo:
any divergence between replicas surfaces as a state-root mismatch at
import.
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import codec
from ..chain.extrinsic import SignedExtrinsic, sign_extrinsic
from ..chain.state import DispatchError
from .chain_spec import ChainSpec
from .consensus import Rrsc, SlotClaim, elect_validators


@codec.register
@dataclasses.dataclass(frozen=True)
class Header:
    number: int
    parent: bytes
    state_root: bytes
    author: str
    claim: SlotClaim | None    # None only for genesis

    def hash(self) -> bytes:
        # codec-canonical (NOT repr): identical bytes on every process
        # and across the disk/gossip wire
        return hashlib.sha256(codec.encode(self)).digest()


@codec.register
@dataclasses.dataclass(frozen=True)
class Block:
    header: Header
    extrinsics: tuple  # ((origin, call, args, kwargs), ...)


class Node:
    def __init__(self, spec: ChainSpec, name: str,
                 keystore: dict[str, object] | None = None,
                 base_path: str | None = None,
                 snapshot_interval: int = 50):
        self.spec = spec
        self.name = name
        # dev keystore: session keys for the accounts this node runs
        self.keystore = keystore if keystore is not None else {}
        self.runtime = spec.build_runtime()
        self.rrsc = Rrsc(spec.epoch_blocks)
        self.authorities = tuple(v.account for v in spec.validators)
        genesis = Header(number=0, parent=b"\0" * 32,
                         state_root=self.runtime.state.state_root(),
                         author="", claim=None)
        self.chain: list[Header] = [genesis]
        self.tx_pool: list[SignedExtrinsic] = []
        self.offchain_agents: list = []
        self.finalized: int = 0
        self._proposal: tuple | None = None
        # bodies kept for serving peer sync (a real deployment serves
        # from the BlockStore; the in-process harness keeps them hot)
        self.block_bodies: dict[int, Block] = {}
        self.base_path = base_path
        self.snapshot_interval = snapshot_interval
        self.store = None
        if base_path:
            import os

            from . import store as _store

            os.makedirs(base_path, exist_ok=True)
            # fast path: state checkpoint, then replay the block tail
            _store.load_snapshot(base_path, self)
            self.store = _store.BlockStore(
                os.path.join(base_path, _store.BLOCKS_FILE))
            for block in self.store:
                self.block_bodies[block.header.number] = block
                if block.header.number >= len(self.chain):
                    self.import_block(block, _persist=False)

    def _persist_block(self, block: Block) -> None:
        self.block_bodies[block.header.number] = block
        if self.store is not None:
            self.store.append(block)
            if self.snapshot_interval \
                    and block.header.number % self.snapshot_interval == 0:
                from . import store as _store

                _store.write_snapshot(self.base_path, self)

    def sync_from(self, peer: "Node") -> int:
        """Catch up missed blocks from a peer's served bodies (the
        restart/warp-sync path, ref service.rs:259-274). Returns the
        number of blocks imported."""
        imported = 0
        while len(self.chain) <= peer.chain[-1].number:
            body = peer.block_bodies.get(len(self.chain))
            if body is None:
                break
            self.import_block(body)
            imported += 1
        self.finalized = max(self.finalized,
                             min(peer.finalized, self.chain[-1].number))
        return imported

    # -- tx pool ---------------------------------------------------------------
    def submit_extrinsic(self, origin: str, call: str, *args, **kwargs) -> None:
        """Dev-mode convenience: sign with the spec-derived account key
        (the //Alice pattern) and submit. ``origin="root"`` signs as
        the chain's sudo account. Production clients build a
        SignedExtrinsic themselves and use :meth:`submit_signed`."""
        if origin == "root":
            sudo = self.runtime.system.sudo()
            if sudo is None:
                raise DispatchError("system.BadOrigin", call)
            origin = sudo
        key = self.spec.account_key(origin)
        nonce = self.runtime.system.nonce(origin) \
            + sum(1 for xt in self.tx_pool if xt.signer == origin)
        self.submit_signed(sign_extrinsic(
            key, self.runtime.genesis_hash(), origin, nonce, call, args,
            kwargs))

    def submit_signed(self, xt: SignedExtrinsic) -> None:
        """Pool admission: full SignedExtra validation (signature,
        binding, sequential nonce, fee affordability) before the tx is
        gossiped. Raises DispatchError when invalid."""
        pending = sum(1 for t in self.tx_pool if t.signer == xt.signer)
        self.runtime.validate_signed(xt, pending_from_signer=pending)
        self.tx_pool.append(xt)

    # -- authoring ---------------------------------------------------------------
    def try_author(self, slot: int,
                   extrinsics: tuple | None = None) -> Block | None:
        """Claim the slot with any local authority key and build a block
        as an OPEN PROPOSAL — the caller must commit_proposal() or
        abort_proposal() (fork choice may prefer a peer's block).

        ``extrinsics``: the tx set to include (the Network hands every
        proposer the same gossip snapshot); standalone nodes default to
        draining their own pool."""
        assert self._proposal is None, "previous proposal not resolved"
        for account, key in self.keystore.items():
            if account not in self.authorities:
                continue
            claim = self.rrsc.claim_slot(slot, account, key, self.authorities)
            if claim is None:
                continue
            if extrinsics is None:
                extrinsics = tuple(self.tx_pool)
                self.tx_pool.clear()
            snapshot = (self.runtime.state.block,
                        list(self.runtime.state.events))
            self.runtime.state.begin_tx()
            self._execute(claim, extrinsics)
            header = Header(number=len(self.chain),
                            parent=self.chain[-1].hash(),
                            state_root=self.runtime.state.state_root(),
                            author=account, claim=claim)
            self._proposal = (header, extrinsics, snapshot)
            return Block(header=header, extrinsics=extrinsics)
        return None

    def commit_proposal(self) -> None:
        header, extrinsics, _ = self._proposal
        self.runtime.state.commit_tx()
        self._proposal = None
        self.chain.append(header)
        self._persist_block(Block(header=header, extrinsics=extrinsics))
        self._post_block(header.claim)

    def abort_proposal(self, requeue: bool = True) -> None:
        """Fork choice lost: roll the whole block back; re-queue txs
        unless the caller owns tx distribution (Network does)."""
        _, extrinsics, (block0, events0) = self._proposal
        self.runtime.state.rollback_tx()
        self.runtime.state.block = block0
        # the aborted block's archive stamped everything with block0
        self.runtime.state.truncate_history(block0)
        self.runtime.state.events[:] = events0
        self._proposal = None
        if requeue:
            self.tx_pool[:0] = list(extrinsics)

    def _execute(self, claim: SlotClaim, extrinsics: tuple) -> None:
        self.runtime.init_block(self.rrsc.block_randomness(claim),
                                author=claim.authority)
        for xt in extrinsics:
            try:
                self.runtime.apply_signed(xt)
            except DispatchError as e:
                # deterministic across replicas: every node skips the
                # same invalid tx with the same event
                call = getattr(xt, "call", "<malformed>")
                self.runtime.state.deposit_event(
                    "system", "ExtrinsicFailed", call=call, error=e.name)

    def _post_block(self, claim: SlotClaim) -> None:
        if claim.vrf is not None:
            self.rrsc.note_vrf(claim.slot, claim.vrf.output)
        self._maybe_rotate_session()
        for agent in self.offchain_agents:
            agent.on_block(self)

    def _maybe_rotate_session(self) -> None:
        """Era boundary: credit-weighted election refreshes the
        authority set (reference §3.5)."""
        if self.runtime.state.block % self.spec.era_blocks:
            return
        stakes = {v: self.runtime.staking.bonded(v)
                  for v in self.runtime.staking.validators()}
        credits = self.runtime.credit.credits()
        elected = elect_validators(stakes, credits, self.spec.max_validators)
        if elected:
            self.authorities = elected

    # -- import -------------------------------------------------------------------
    def import_block(self, block: Block, _persist: bool = True) -> None:
        """Verify the claim, re-execute, check the state root."""
        header = block.header
        if header.number != len(self.chain):
            raise ValueError(f"{self.name}: non-sequential import "
                             f"{header.number} != {len(self.chain)}")
        if header.parent != self.chain[-1].hash():
            raise ValueError(f"{self.name}: parent hash mismatch")
        public = self.spec.session_key(header.author).public
        if not self.rrsc.verify_claim(header.claim, public, self.authorities):
            raise ValueError(f"{self.name}: bad slot claim")
        self._execute(header.claim, block.extrinsics)
        got = self.runtime.state.state_root()
        if got != header.state_root:
            raise ValueError(
                f"{self.name}: state root mismatch at #{header.number} — "
                "replicas diverged")
        self.chain.append(header)
        if _persist:
            self._persist_block(block)
        else:
            self.block_bodies[header.number] = block
        self._post_block(header.claim)


class Network:
    """Drives slots across nodes: fork choice (primary beats secondary,
    lowest VRF output wins ties), broadcast, 2/3 finality votes."""

    def __init__(self, nodes: list[Node]):
        self.nodes = nodes
        # tx gossip: one shared mempool (instant propagation); dedupe
        # by identity — nodes re-networked after a peer restart may
        # already share one pool object
        shared: list[SignedExtrinsic] = []
        seen: set[int] = set()
        for node in nodes:
            for tx in node.tx_pool:
                if id(tx) not in seen:
                    seen.add(id(tx))
                    shared.append(tx)
        for node in nodes:
            node.tx_pool = shared

    def run_slot(self, slot: int) -> Block | None:
        """Authors race; fork choice = primary beats secondary, then
        lowest VRF output; losers roll back and re-import the winner."""
        txs = tuple(self.nodes[0].tx_pool)   # one gossip snapshot for all
        candidates: list[tuple[int, bytes, Node, Block]] = []
        for node in self.nodes:
            blk = node.try_author(slot, extrinsics=txs)
            if blk is not None:
                claim = blk.header.claim
                prio = 0 if claim.vrf is not None else 1
                tiebreak = claim.vrf.output if claim.vrf else b"\xff" * 32
                candidates.append((prio, tiebreak, node, blk))
        if not candidates:
            return None
        candidates.sort(key=lambda c: (c[0], c[1]))
        _, _, author_node, best = candidates[0]
        for _, _, loser, _ in candidates[1:]:
            loser.abort_proposal(requeue=False)
        # drop included txs from the shared pool BEFORE _post_block
        # fires the offchain agents: their new submissions compute
        # nonces as on-chain + pending, and the included txs' nonces
        # are already consumed on chain — counting them again would
        # assign too-high nonces that fail at execution (BadNonce)
        pool = self.nodes[0].tx_pool
        included = {id(tx) for tx in best.extrinsics}
        pool[:] = [tx for tx in pool if id(tx) not in included]
        author_node.commit_proposal()
        for node in self.nodes:
            if node is not author_node:
                node.import_block(best)
        self._finalize(best.header)
        return best

    def _finalize(self, header: Header) -> None:
        """GRANDPA-lite: every authority on every node votes for the
        imported head; 2/3 finalizes."""
        votes = set()
        for node in self.nodes:
            for account in node.keystore:
                if account in node.authorities:
                    votes.add(account)
        n_auth = len(self.nodes[0].authorities)
        if 3 * len(votes) >= 2 * n_auth:
            for node in self.nodes:
                node.finalized = header.number

    def run_slots(self, count: int) -> None:
        start = max(len(n.chain) for n in self.nodes)
        produced = 0
        slot = start
        while produced < count:
            if self.run_slot(slot) is not None:
                produced += 1
            slot += 1
