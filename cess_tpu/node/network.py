"""Block production, tree-based import, fork choice, reorg, finality.

The reference's node assembles libp2p gossip + RRSC authoring + a
GRANDPA voter loop (SURVEY.md §3.1, §3.4;
/root/reference/node/src/service.rs:448-506,556-580); multi-node
behavior is only exercised on live testnets. Here the same roles with
a real block TREE:

- every Node owns a full Runtime replica and imports blocks onto any
  known parent (side branches included), re-executing and verifying
  VRF claim + state root only when a branch becomes canonical;
- fork choice: heaviest chain by (height, cumulative primary-slot
  count); reorgs rewind per-block state undo logs (O(changes), the
  role of Substrate's tree-backed storage) and replay the winning
  branch;
- finality is a vote exchange (cess_tpu/node/finality.py): signed
  votes, 2/3 justifications, equivocation evidence reportable on
  chain. Finalized blocks bound fork choice; a justification on a
  side branch forces the node onto it.

The in-process Network driver at the bottom synchronizes slots across
nodes — the socket transport (cess_tpu/node/net.py) runs the same Node
between OS processes.
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import codec
from ..chain.extrinsic import SignedExtrinsic, sign_extrinsic
from ..chain.state import DispatchError
from ..obs import trace
from .chain_spec import ChainSpec
from .consensus import Rrsc, SlotClaim
from .finality import FinalityGadget, Justification


@codec.register
@dataclasses.dataclass(frozen=True)
class Header:
    number: int
    parent: bytes
    state_root: bytes
    author: str
    claim: SlotClaim | None    # None only for genesis

    def hash(self) -> bytes:
        # codec-canonical (NOT repr): identical bytes on every process
        # and across the disk/gossip wire. Memoized — hashing is on the
        # fork-choice/finality hot path (not a codec field, so encoding
        # and equality are unaffected).
        h = self.__dict__.get("_hash")
        if h is None:
            h = hashlib.sha256(codec.encode(self)).digest()
            object.__setattr__(self, "_hash", h)
        return h


@codec.register
@dataclasses.dataclass(frozen=True)
class Block:
    header: Header
    extrinsics: tuple  # (SignedExtrinsic, ...)


@dataclasses.dataclass
class _UndoRec:
    """Everything needed to rewind one canonical block in a reorg."""

    state_undo: list
    block_before: int
    events_before: list
    authorities_before: tuple[str, ...]
    vrf_note: tuple[int, bytes] | None   # (epoch, output) if primary


class Node:
    def __init__(self, spec: ChainSpec, name: str,
                 keystore: dict[str, object] | None = None,
                 base_path: str | None = None,
                 snapshot_interval: int = 50):
        self.spec = spec
        self.name = name
        # dev keystore: session keys for the accounts this node runs
        self.keystore = keystore if keystore is not None else {}
        self.runtime = spec.build_runtime()
        self.rrsc = Rrsc(spec.epoch_blocks)
        self.authorities = tuple(v.account for v in spec.validators)
        genesis = Header(number=0, parent=b"\0" * 32,
                         state_root=self.runtime.state.state_root(),
                         author="", claim=None)
        self.chain: list[Header] = [genesis]
        # block tree: all known headers/bodies by hash; side branches
        # are stored unexecuted until fork choice adopts them
        gh = genesis.hash()
        self.headers: dict[bytes, Header] = {gh: genesis}
        self.bodies: dict[bytes, Block] = {}
        self._primaries: dict[bytes, int] = {gh: 0}
        self._undo: dict[bytes, _UndoRec] = {}
        # authority set AFTER applying each executed block (era
        # rotation makes the set branch-dependent)
        self._authset: dict[bytes, tuple[str, ...]] = {gh: self.authorities}
        self.tx_pool: list[SignedExtrinsic] = []
        self.offchain_agents: list = []
        self.finalized: int = 0
        self.finality = FinalityGadget(self)
        self._proposal: tuple | None = None
        # canonical bodies by number, kept for serving peer sync
        self.block_bodies: dict[int, Block] = {}
        self.base_path = base_path
        self.snapshot_interval = snapshot_interval
        self.store = None
        if base_path:
            import os

            from . import store as _store

            os.makedirs(base_path, exist_ok=True)
            # fast path: state checkpoint, then replay the block tail
            _store.load_snapshot(base_path, self)
            self.store = _store.BlockStore(
                os.path.join(base_path, _store.BLOCKS_FILE))
            for block in self.store:
                try:
                    self.import_block(block, _persist=False)
                except ValueError:
                    continue   # dead fork below finality, duplicates

    # -- tree bookkeeping -----------------------------------------------------
    def head(self) -> Header:
        return self.chain[-1]

    def _index_header(self, header: Header) -> None:
        h = header.hash()
        self.headers[h] = header
        self._primaries[h] = self._primaries[header.parent] \
            + (1 if header.claim and header.claim.vrf is not None else 0)

    def _weight(self, tip_hash: bytes) -> tuple[int, int]:
        """Fork-choice weight: (height, cumulative primary slots).
        Strictly-greater wins; ties keep the incumbent (deterministic
        per node; the vote exchange settles cross-node ties)."""
        return (self.headers[tip_hash].number, self._primaries[tip_hash])

    def _is_canonical(self, h: bytes) -> bool:
        header = self.headers.get(h)
        return (header is not None and header.number < len(self.chain)
                and self.chain[header.number].hash() == h)

    def authorities_at(self, block_hash: bytes) -> tuple[str, ...]:
        """The authority set in force for a child of ``block_hash``:
        the set after applying that block, or (for stored-unexecuted
        side-branch ancestors) the deepest executed ancestor's set.
        Era rotation makes this branch-dependent — verifying a fork
        block against the head's set would reject valid forks."""
        cur = block_hash
        while cur in self.headers:
            got = self._authset.get(cur)
            if got is not None:
                return got
            cur = self.headers[cur].parent
        return self.authorities

    def _persist_block(self, block: Block) -> None:
        self.block_bodies[block.header.number] = block
        if self.store is not None:
            self.store.append(block)
            if self.snapshot_interval \
                    and block.header.number % self.snapshot_interval == 0:
                from . import store as _store

                _store.write_snapshot(self.base_path, self)

    # -- sync -----------------------------------------------------------------
    def sync_from(self, peer: "Node") -> int:
        """Catch up from a peer's canonical chain (the restart/warp
        sync path, ref service.rs:259-274). Finds the highest common
        block, imports the peer's tail (fork choice decides whether to
        adopt), then verifies + adopts the peer's justifications.
        Returns the number of blocks imported."""
        if self.chain[0].hash() != peer.chain[0].hash():
            return 0   # different genesis: not our chain, refuse cleanly
        common = min(self.head().number, peer.head().number)
        while self.chain[common].hash() != peer.chain[common].hash():
            common -= 1
        imported = 0
        for n in range(common + 1, peer.head().number + 1):
            body = peer.block_bodies.get(n)
            if body is None:
                break
            try:
                self.import_block(body)
            except ValueError:
                break
            imported += 1
        if peer.finality.justifications:
            # adopt the peer's newest justification (older rounds are
            # implied: finalizing a block finalizes its ancestors)
            rnd = max(peer.finality.justifications)
            just = peer.finality.justifications[rnd]
            if rnd > self.finalized \
                    and self.finality.verify_justification(just):
                self.finality.justifications[rnd] = just
                self.on_justification(just)
        return imported

    def warp_sync_from(self, peer: "Node") -> bool:
        """Checkpoint (warp) sync: adopt the peer's state snapshot
        WITHOUT replaying the chain — the reference's warp-sync role
        (service.rs:259-263), shaped like production checkpoint sync.

        Trust model: store.verify_and_adopt_warp — the ONE shared
        verification path (genesis-derived authority set, never the
        snapshot's own; genesis-anchored parent-linked header chain;
        state-root-proven KV; justification targeting that chain),
        also used by the TCP transport (net.NodeService._try_warp)."""
        from . import store as _store

        if not peer.finality.justifications:
            return False
        rnd = max(peer.finality.justifications)
        just = peer.finality.justifications[rnd]
        return _store.verify_and_adopt_warp(
            self, _store.snapshot_payload(peer), just,
            lambda: Node(self.spec, f"{self.name}-warp", {}))

    # -- tx pool ---------------------------------------------------------------
    def queue_heartbeats(self) -> list[SignedExtrinsic]:
        """im-online OCW analog shared by both network drivers: queue
        one heartbeat per era for each local authority key not yet
        beaten/pending. Returns the newly queued txs (the TCP service
        gossips them — authoring a block is not guaranteed per era)."""
        era = self.runtime.staking.current_era()
        new = []
        staking = self.runtime.staking
        for account in self.keystore:
            # gate matches im_online admission: EXPOSED validators must
            # beat even when not in the elected author set (the
            # max_validators cap), else they are liveness-slashed while
            # fully online
            is_authority = account in self.authorities \
                or account in staking.validators() \
                or account in staking.era_validators(era)
            if not is_authority \
                    or self.runtime.im_online.has_beat(era, account) \
                    or any(t.call == "im_online.heartbeat"
                           and t.signer == account for t in self.tx_pool):
                continue
            try:
                self.submit_extrinsic(account, "im_online.heartbeat")
                new.append(self.tx_pool[-1])
            except DispatchError:
                pass
        return new

    def submit_extrinsic(self, origin: str, call: str, *args, **kwargs) -> None:
        """Dev-mode convenience: sign with the spec-derived account key
        (the //Alice pattern) and submit. ``origin="root"`` signs as
        the chain's sudo account. Production clients build a
        SignedExtrinsic themselves and use :meth:`submit_signed`."""
        if origin == "root":
            sudo = self.runtime.system.sudo()
            if sudo is None:
                raise DispatchError("system.BadOrigin", call)
            origin = sudo
        key = self.spec.account_key(origin)
        nonce = self.runtime.system.nonce(origin) \
            + sum(1 for xt in self.tx_pool if xt.signer == origin)
        self.submit_signed(sign_extrinsic(
            key, self.runtime.genesis_hash(), origin, nonce, call, args,
            kwargs))

    def submit_signed(self, xt: SignedExtrinsic) -> None:
        """Pool admission: full SignedExtra validation (signature,
        binding, sequential nonce, fee affordability) before the tx is
        gossiped. Raises DispatchError when invalid."""
        pending = sum(1 for t in self.tx_pool if t.signer == xt.signer)
        self.runtime.validate_signed(xt, pending_from_signer=pending)
        self.tx_pool.append(xt)

    # -- authoring ---------------------------------------------------------------
    def try_author(self, slot: int,
                   extrinsics: tuple | None = None) -> Block | None:
        """Claim the slot with any local authority key and build a block
        on the current best head as an OPEN PROPOSAL — the caller must
        commit_proposal() or abort_proposal() (fork choice may prefer a
        peer's block).

        ``extrinsics``: the tx set to include (the Network hands every
        proposer the same gossip snapshot); standalone nodes default to
        draining their own pool."""
        assert self._proposal is None, "previous proposal not resolved"
        for account, key in self.keystore.items():
            if account not in self.authorities:
                continue
            if self.head().number == 0:
                # pre-genesis: the epoch anchor floats with the trial
                # slot; it pins permanently at block #1 (import/adopt)
                self.rrsc.genesis_slot = slot
            claim = self.rrsc.claim_slot(slot, account, key, self.authorities)
            if claim is None:
                continue
            if extrinsics is None:
                extrinsics = tuple(self.tx_pool)
                self.tx_pool.clear()
            snapshot = (self.runtime.state.block,
                        list(self.runtime.state.events))
            self.runtime.state.begin_tx()
            self._execute(claim, extrinsics)
            header = Header(number=len(self.chain),
                            parent=self.head().hash(),
                            state_root=self.runtime.state.state_root(),
                            author=account, claim=claim)
            self._proposal = (header, extrinsics, snapshot)
            return Block(header=header, extrinsics=extrinsics)
        return None

    def commit_proposal(self) -> None:
        header, extrinsics, (block0, events0) = self._proposal
        if header.number == 1:
            self.rrsc.genesis_slot = header.claim.slot
        undo = self.runtime.state.commit_tx_undo()
        self._proposal = None
        self._adopt_block(Block(header=header, extrinsics=extrinsics),
                          undo, block0, events0, persist=True)

    def abort_proposal(self, requeue: bool = True) -> None:
        """Fork choice lost: roll the whole block back; re-queue txs
        unless the caller owns tx distribution (Network does)."""
        _, extrinsics, (block0, events0) = self._proposal
        self.runtime.state.rollback_tx()
        self.runtime.state.block = block0
        # the aborted block's archive stamped everything with block0
        self.runtime.state.truncate_history(block0)
        self.runtime.state.events[:] = events0
        self._proposal = None
        if requeue:
            self.tx_pool[:0] = list(extrinsics)

    def _execute(self, claim: SlotClaim, extrinsics: tuple) -> None:
        self.runtime.init_block(self.rrsc.block_randomness(claim),
                                author=claim.authority)
        for xt in extrinsics:
            # deterministic across replicas: every node skips the same
            # invalid tx with the same event, and records the same
            # eth-visible receipt (runtime.apply_in_block)
            self.runtime.apply_in_block(xt)

    def _adopt_block(self, block: Block, undo: list, block0: int,
                     events0: list, persist: bool,
                     fire_agents: bool = True) -> None:
        """Append an EXECUTED block to the canonical chain, recording
        its undo + consensus side effects for possible rewind."""
        header = block.header
        claim = header.claim
        vrf_note = None
        if claim.vrf is not None:
            epoch = self.rrsc.epoch_of(claim.slot)
            self.rrsc.note_vrf(claim.slot, claim.vrf.output)
            vrf_note = (epoch, claim.vrf.output)
        auth_before = self.authorities
        self.chain.append(header)
        self._index_header(header)
        self.bodies[header.hash()] = block
        self._undo[header.hash()] = _UndoRec(
            state_undo=undo, block_before=block0, events_before=events0,
            authorities_before=auth_before, vrf_note=vrf_note)
        self._maybe_rotate_session()
        self._authset[header.hash()] = self.authorities
        if persist:
            self._persist_block(block)
        else:
            self.block_bodies[header.number] = block
        if fire_agents:
            for agent in self.offchain_agents:
                agent.on_block(self)

    def _maybe_rotate_session(self) -> None:
        """Era boundary: READ the multi-phase election result that the
        runtime's era hook resolved inside block execution (verified
        signed solution if one beat the solver, else the on-chain
        credit-weighted fallback) and refresh the authority set
        (reference §3.5; runtime/src/lib.rs:613,834-863). Resolution
        itself lives in the runtime so deposits/queue sweeps are
        covered by the block undo log."""
        if self.runtime.state.block % self.spec.era_blocks:
            return
        elected = self.runtime.election.result()
        if elected:
            self.authorities = elected

    # -- import -------------------------------------------------------------------
    def import_block(self, block: Block, _persist: bool = True) -> None:
        """Tree import: verify the claim; execute (re-deriving the
        state root) when the block extends the best chain, store
        side-branch blocks and reorg when their branch outweighs."""
        header = block.header
        h = header.hash()
        if h in self.headers:
            # duplicate (idempotent: gossip redelivers); re-register the
            # body if we only held the header (snapshot-restored chain)
            if h not in self.bodies:
                self.bodies[h] = block
                if self._is_canonical(h):
                    self.block_bodies.setdefault(header.number, block)
            return
        parent = self.headers.get(header.parent)
        if parent is None:
            raise ValueError(f"{self.name}: unknown parent for "
                             f"#{header.number}")
        if header.number != parent.number + 1:
            raise ValueError(f"{self.name}: number {header.number} does "
                             f"not follow parent {parent.number}")
        if header.number <= self.finalized:
            raise ValueError(f"{self.name}: #{header.number} conflicts "
                             f"with finality at #{self.finalized}")
        public = self.spec.session_key(header.author).public
        authorities = self.authorities_at(header.parent)
        if header.number == 1 and self.rrsc.genesis_slot is None:
            # epoch numbering anchors at the chain's first slot; pin it
            # BEFORE verification so author and importers agree. Only
            # an UNPINNED node pins here — a competing block #1 on a
            # progressed node must not re-anchor epochs (that would
            # poison every later claim); restore on verify failure so
            # a junk claim cannot pin garbage
            self.rrsc.genesis_slot = header.claim.slot
            if not self.rrsc.verify_claim(header.claim, public,
                                          authorities):
                self.rrsc.genesis_slot = None
                raise ValueError(f"{self.name}: bad slot claim")
        elif not self.rrsc.verify_claim(header.claim, public, authorities):
            raise ValueError(f"{self.name}: bad slot claim")
        if header.parent == self.head().hash():
            self._apply_to_head(block, persist=_persist)
            return
        # side branch: store, reorg if the branch now outweighs
        self._index_header(header)
        self.bodies[h] = block
        if self._weight(h) > self._weight(self.head().hash()):
            self._reorg_to(h, persist=_persist)

    def _apply_to_head(self, block: Block, persist: bool,
                       fire_agents: bool = True) -> None:
        """Execute a block extending the current head; raises (with
        full rollback) on state-root mismatch."""
        state = self.runtime.state
        snapshot = (state.block, list(state.events))
        state.begin_tx()
        try:
            self._execute(block.header.claim, block.extrinsics)
            got = state.state_root()
            if got != block.header.state_root:
                raise ValueError(
                    f"{self.name}: state root mismatch at "
                    f"#{block.header.number} — replicas diverged")
        except Exception:
            state.rollback_tx()
            state.block = snapshot[0]
            state.truncate_history(snapshot[0])
            state.events[:] = snapshot[1]
            raise
        undo = state.commit_tx_undo()
        self._adopt_block(block, undo, snapshot[0], snapshot[1],
                          persist=persist, fire_agents=fire_agents)

    # -- reorg --------------------------------------------------------------------
    def _can_rewind_to(self, fork_number: int) -> bool:
        """Every canonical block above the fork point must carry an
        undo log (snapshot-restored blocks do not) — checked BEFORE
        any rewind so a refused reorg leaves the node untouched."""
        return all(self.chain[n].hash() in self._undo
                   for n in range(fork_number + 1, len(self.chain)))

    def _rewind_one(self) -> None:
        head = self.chain[-1]
        rec = self._undo.pop(head.hash(), None)
        if rec is None:
            # blocks restored from a snapshot carry no undo log —
            # they are effectively final for this node
            raise ValueError(f"{self.name}: cannot rewind #{head.number} "
                             "(no undo log; snapshot-restored)")
        self.chain.pop()
        self._authset.pop(head.hash(), None)
        state = self.runtime.state
        state.apply_undo(rec.state_undo)
        state.block = rec.block_before
        state.truncate_history(rec.block_before)
        state.events[:] = rec.events_before
        self.authorities = rec.authorities_before
        if head.number == 1:
            self.rrsc.genesis_slot = None   # re-pins with the next block 1
        if rec.vrf_note is not None:
            epoch, output = rec.vrf_note
            outs = self.rrsc._epoch_vrf.get(epoch, [])
            if output in outs:
                outs.remove(output)
            # later epoch randomness derived from these outputs is stale
            for e in [e for e in self.rrsc.randomness if e > epoch]:
                del self.rrsc.randomness[e]
        self.block_bodies.pop(head.number, None)

    def _branch_path(self, tip_hash: bytes) -> tuple[int, list[bytes]]:
        """(fork_number, path tip->..->child-of-fork) back to the
        canonical chain."""
        path = []
        cur = tip_hash
        while not self._is_canonical(cur):
            path.append(cur)
            cur = self.headers[cur].parent
        return self.headers[cur].number, path

    def _reorg_to(self, tip_hash: bytes, persist: bool = True) -> None:
        fork_number, path = self._branch_path(tip_hash)
        if fork_number < self.finalized:
            raise ValueError(f"{self.name}: reorg below finalized "
                             f"#{self.finalized}")
        if not self._can_rewind_to(fork_number):
            raise ValueError(f"{self.name}: reorg to fork at "
                             f"#{fork_number} crosses a snapshot "
                             "boundary (no undo logs)")
        old_tail = [self.block_bodies[n]
                    for n in range(fork_number + 1, len(self.chain))]
        while self.head().number > fork_number:
            self._rewind_one()
        try:
            for i, h in enumerate(reversed(path)):
                if self.bodies[h].header.number == 1:
                    # adopting a different block #1: re-anchor epochs
                    self.rrsc.genesis_slot = \
                        self.bodies[h].header.claim.slot
                # agents fire once, on the new head, not per replayed block
                self._apply_to_head(self.bodies[h], persist=persist,
                                    fire_agents=(i == len(path) - 1))
        except ValueError:
            # losing branch was invalid after all: restore the old chain
            while self.head().number > fork_number:
                self._rewind_one()
            for i, body in enumerate(old_tail):
                self._apply_to_head(body, persist=False,
                                    fire_agents=(i == len(old_tail) - 1))
            raise
        if old_tail:
            self.tx_pool[:0] = [
                xt for b in old_tail for xt in b.extrinsics
                if not any(xt == kept
                           for h2 in path
                           for kept in self.bodies[h2].extrinsics)]

    # -- finality -----------------------------------------------------------------
    def on_justification(self, just: Justification) -> None:
        """2/3 votes assembled (locally or from a peer): finalize —
        forcing a reorg if the justified block is on a side branch."""
        num = just.target_number
        if num <= self.finalized:
            return
        if not self._is_canonical(just.target_hash):
            if just.target_hash not in self.headers:
                return   # unknown block; sync will fetch + re-apply
            try:
                self._reorg_to(just.target_hash)
            except ValueError:
                # pinned (snapshot boundary) or invalid branch: stay
                # put; catch-up sync re-delivers once resolvable
                return
        prev = self.finalized
        self.finalized = num
        # undo logs at/below finality can never rewind: drop them
        # (O(newly finalized), not O(chain))
        for n in range(prev + 1, min(num + 1, len(self.chain))):
            self._undo.pop(self.chain[n].hash(), None)


def author_race(candidates: "list[tuple[Node, Block]]"):
    """Rank an authoring race: primary claims beat secondary, lowest
    VRF output breaks ties. Returns ``(winner_node, winner_block,
    losers)`` with losers as ``(node, block)`` pairs in rank order, or
    ``(None, None, ())`` for an empty race. Shared by the in-process
    :class:`Network` driver and the discrete-event simulation
    (cess_tpu/sim) so both worlds apply the identical fork-choice at
    the authoring seam."""
    ranked = []
    for node, blk in candidates:
        claim = blk.header.claim
        prio = 0 if claim.vrf is not None else 1
        tiebreak = claim.vrf.output if claim.vrf else b"\xff" * 32
        ranked.append((prio, tiebreak, node, blk))
    if not ranked:
        return None, None, ()
    ranked.sort(key=lambda c: (c[0], c[1]))
    _, _, winner, best = ranked[0]
    return winner, best, tuple((n, b) for _, _, n, b in ranked[1:])


class Network:
    """Drives slots across nodes: fork choice (primary beats secondary,
    lowest VRF output wins ties), broadcast, vote-based finality."""

    def __init__(self, nodes: list[Node]):
        self.nodes = nodes
        # tx gossip: one shared mempool (instant propagation); dedupe
        # by identity — nodes re-networked after a peer restart may
        # already share one pool object. The socket transport
        # (node/net.py) replaces this with real per-process pools.
        shared: list[SignedExtrinsic] = []
        seen: set[int] = set()
        for node in nodes:
            for tx in node.tx_pool:
                if id(tx) not in seen:
                    seen.add(id(tx))
                    shared.append(tx)
        for node in nodes:
            node.tx_pool = shared

    def _queue_heartbeats(self) -> None:
        """Each node queues heartbeats (a node that is down queues
        nothing and is reported at era end)."""
        for node in self.nodes:
            node.queue_heartbeats()

    def run_slot(self, slot: int) -> Block | None:
        """Authors race; fork choice = primary beats secondary, then
        lowest VRF output; losers roll back and re-import the winner."""
        self._queue_heartbeats()
        txs = tuple(self.nodes[0].tx_pool)   # one gossip snapshot for all
        candidates: list[tuple[Node, Block]] = []
        for node in self.nodes:
            blk = node.try_author(slot, extrinsics=txs)
            if blk is not None:
                candidates.append((node, blk))
        author_node, best, losers = author_race(candidates)
        if author_node is None:
            return None
        for loser, _ in losers:
            loser.abort_proposal(requeue=False)
        # drop included txs from the shared pool BEFORE _post_block
        # fires the offchain agents: their new submissions compute
        # nonces as on-chain + pending, and the included txs' nonces
        # are already consumed on chain — counting them again would
        # assign too-high nonces that fail at execution (BadNonce)
        pool = self.nodes[0].tx_pool
        included = {id(tx) for tx in best.extrinsics}
        pool[:] = [tx for tx in pool if id(tx) not in included]
        author_node.commit_proposal()
        for node in self.nodes:
            if node is not author_node:
                # the in-process gossip hop: one delivery span per peer
                # import (the socket transport's envelope analog, so an
                # armed tracer sees the same net-hop stage here as the
                # TCP service's net.send/net.recv spans record)
                with trace.span("net.deliver", sys="net",
                                block=best.header.number, to=node.name):
                    node.import_block(best)
        self.exchange_votes()
        return best

    def exchange_votes(self) -> None:
        """The GRANDPA-gossip analog: every node casts signed votes
        for its best chain and every vote reaches every node; each
        node tallies + finalizes independently.

        Each node also RE-SHARES its own unfinalized votes (receivers
        dedup first-seen): nodes re-joined after a partition would
        otherwise never learn the other side's round votes, leaving
        own-vote locks (finality._locked) un-releasable and finality
        needlessly stalled until the lock horizon."""
        votes = []
        for node in self.nodes:
            votes.extend(node.finality.cast_votes())
            votes.extend(node.finality.own_unfinalized_votes())
        for node in self.nodes:
            for v in votes:
                node.finality.on_vote(v)

    def run_slots(self, count: int) -> None:
        start = max(len(n.chain) for n in self.nodes)
        produced = 0
        slot = start
        while produced < count:
            if self.run_slot(slot) is not None:
                produced += 1
            slot += 1
