"""Vote-based finality: signed vote exchange, 2/3 counting,
equivocation detection, persisted justifications.

The reference runs a GRANDPA voter loop gossiping signed votes per
round and importing justifications
(/root/reference/node/src/service.rs:556-580). This gadget is the
framework-native equivalent, round-simplified: round r finalizes at
most one block; every authority votes for its best chain's block at
height r (GRANDPA's "ghost of the best chain" collapsed to the head
ancestor at that height); 2/3 distinct signed votes for the same hash
form a justification that finalizes the block and all ancestors.

Safety properties kept from GRANDPA:
- a vote is a SIGNED, self-contained statement (chain/offences.py
  Vote) — replicas verify against the on-chain session-key registry;
- two votes by one voter in one round for different hashes are
  cryptographic proof of equivocation, reportable on chain
  (offences.report_equivocation) where staking slashes + chills;
- finality never reverts: justified blocks bound fork choice (a node
  never reorgs below its finalized height), and a justification on a
  side branch FORCES the node onto that branch.
"""
from __future__ import annotations

import dataclasses

from .. import codec
from ..chain.offences import Vote, sign_vote
from ..obs import flight as _flight


@codec.register
@dataclasses.dataclass(frozen=True)
class Justification:
    """Proof of finality for (target_hash, round): >= 2/3 of the
    authority set's signed votes. Persisted with the chain so a
    restarted/syncing node can verify finality without replaying the
    vote exchange (the reference persists GRANDPA justifications in
    the block store)."""

    round: int
    target_hash: bytes
    target_number: int
    votes: tuple[Vote, ...]


class FinalityGadget:
    """Per-node vote tracker. The node feeds it local keys + incoming
    votes; it emits outgoing votes, detects equivocations, and
    surfaces justifications when a target reaches 2/3."""

    def __init__(self, node):
        self.node = node
        # round -> target_hash -> {voter: Vote}
        self._tally: dict[int, dict[bytes, dict[str, Vote]]] = {}
        # round -> voter -> first-seen Vote (for equivocation detection)
        self._first: dict[int, dict[str, Vote]] = {}
        # (round, voter) pairs handed out by vote_jobs but not yet
        # ingested — prevents a concurrent collector from double-
        # signing the same round (self-equivocation)
        self._signing: set[tuple[int, str]] = set()
        # accounts observed locked by vote_jobs' last pass — the edge
        # detector behind the flight journal's lock-acquire/release
        # entries (an own-vote lock engaging is exactly the "finality
        # stall" moment a postmortem needs on its timeline)
        self._lock_active: set[str] = set()
        self.equivocations: list[tuple[Vote, Vote]] = []
        self.justifications: dict[int, Justification] = {}

    # -- outgoing ----------------------------------------------------------
    # sync batches can advance the head many blocks at once; voting
    # only the head round would skip the intermediate rounds entirely
    # and starve them of this voter forever (each voter votes a round
    # at most once). Vote a bounded tail of rounds instead.
    VOTE_TAIL = 32
    # own-vote lock liveness backstop: a voter locked to a reorged-away
    # branch (see _locked) abstains at most this many rounds before
    # resuming — healing re-gossip normally releases it much sooner by
    # proving the old target can no longer reach 2/3
    LOCK_HORIZON = 32

    def _quorum_impossible(self, rnd: int, target_hash: bytes) -> bool:
        """True when ``target_hash`` can provably never reach 2/3 in
        round ``rnd``: enough OTHER signed round-``rnd`` votes have
        been observed that the remaining voters cannot complete a
        quorum. Votes are one-per-voter-per-round (equivocations are
        slashable evidence, not counted), so observed contrary votes
        bound the target's possible support globally, not just in our
        view — the GRANDPA release argument."""
        node = self.node
        others = sum(len(votes) for h, votes
                     in self._tally.get(rnd, {}).items()
                     if h != target_hash)
        n_auth = len(node.authorities)
        return 3 * (n_auth - others) < 2 * n_auth

    def _locked(self, account: str, head_number: int) -> bool:
        """The GRANDPA-style own-vote lock: having voted round r for a
        block we since reorged AWAY from, we must not vote later
        rounds on the new branch while that old vote could still
        complete a 2/3 quorum elsewhere — two conflicting
        justifications assembled from partial vote views are exactly
        how replicas diverge irrecoverably (the one-phase gadget's
        unsafe window; root cause of the chain-topology discovery
        flake). The lock releases when the old vote finalizes, its
        branch regains canonicity, a quorum on it becomes provably
        impossible (healing re-gossip supplies the contrary votes), or
        the LOCK_HORIZON liveness backstop passes."""
        return bool(self.locked_rounds(account, head_number))

    def locked_rounds(self, account: str,
                      head_number: int) -> tuple[int, ...]:
        """The rounds whose own-votes currently lock ``account`` (see
        :meth:`_locked` for the lock rationale). Exposed so external
        auditors — the sim invariant checkers (cess_tpu/sim) — can
        assert no lock ever names a round older than LOCK_HORIZON,
        instead of re-deriving the lock rule from ``_first``."""
        node = self.node
        rounds = []
        for rnd, votes in self._first.items():
            v = votes.get(account)
            if v is None or rnd <= node.finalized:
                continue
            if node._is_canonical(v.target_hash):
                continue
            if head_number - rnd > self.LOCK_HORIZON:
                continue
            if not self._quorum_impossible(rnd, v.target_hash):
                rounds.append(rnd)
        return tuple(sorted(rounds))

    def vote_jobs(self) -> list[tuple]:
        """Collect the (account, key, round, target_hash) tuples this
        node should sign: every unvoted round up to the current HEAD
        (round = block height; the round target is the canonical block
        at that height). Voting the whole unfinalized tail keeps
        liveness for straggler nodes whose head jumps in sync batches,
        and across reorgs: a voter that committed to a dead branch at
        height h can never re-vote round h (that would be
        equivocation), but the chain outgrows h and a fresh round
        finalizes past it.

        Caller holds the node lock. Collected rounds are marked
        in-flight so a concurrent collector cannot double-sign them
        (self-equivocation); ingest_own clears the marks."""
        node = self.node
        jobs = []
        head = node.chain[-1]
        if head.number <= node.finalized:
            return jobs
        lo = max(node.finalized + 1, head.number - self.VOTE_TAIL + 1)
        voters = []
        locked_now = set()
        for a, k in node.keystore.items():
            if a not in node.authorities:
                continue
            if self._locked(a, head.number):
                locked_now.add(a)
            else:
                voters.append((a, k))
        # journal lock EDGES (under the node lock the caller holds —
        # safe: finality entries never trigger bundle builds)
        if locked_now != self._lock_active:
            for a in sorted(locked_now - self._lock_active):
                _flight.note("finality", "lock-acquire", account=a,
                             head=head.number)
            for a in sorted(self._lock_active - locked_now):
                _flight.note("finality", "lock-release", account=a,
                             head=head.number)
            self._lock_active = locked_now
        for rnd in range(lo, head.number + 1):
            target = node.chain[rnd]
            for account, key in voters:
                if account in self._first.get(rnd, {}) \
                        or (rnd, account) in self._signing:
                    continue   # never double-vote (that's equivocation)
                self._signing.add((rnd, account))
                jobs.append((account, key, rnd, target.hash()))
        return jobs

    def sign_jobs(self, jobs: list[tuple]) -> list[Vote]:
        """ed25519-sign collected jobs — ~6 ms each in pure python, so
        callers run this OUTSIDE the node lock (the TCP service would
        otherwise stall recv/RPC/authoring for a whole sync batch)."""
        gh = self.node.runtime.genesis_hash()
        return [sign_vote(key, gh, account, rnd, th, rnd)
                for (account, key, rnd, th) in jobs]

    def ingest_own(self, votes: list[Vote]) -> None:
        """Tally self-signed votes (caller holds the lock). Signature
        verification is skipped — we just produced them."""
        node = self.node
        for v in votes:
            self._signing.discard((v.round, v.voter))
            if v.round <= node.finalized:
                continue
            first = self._first.setdefault(v.round, {})
            if v.voter in first:
                continue
            first[v.voter] = v
            self._tally.setdefault(v.round, {}).setdefault(
                v.target_hash, {})[v.voter] = v
            self._try_finalize(v.round, v.target_hash)

    def cast_votes(self) -> list[Vote]:
        """Single-threaded convenience (the in-process Network driver):
        collect + sign + tally in one call."""
        votes = self.sign_jobs(self.vote_jobs())
        self.ingest_own(votes)
        return votes

    # -- healing -----------------------------------------------------------
    # Gossip is fire-and-forget and sync re-fetches BLOCKS, never
    # votes: a vote relayed into a partially-formed mesh is lost
    # forever, which both stalls finality and (combined with reorgs)
    # opens the conflicting-quorum window _locked guards against. The
    # transports therefore re-offer this state every round; receivers
    # dedup, so repetition costs bytes, not correctness.
    def own_unfinalized_votes(self, limit: int = 8) -> list[Vote]:
        """This node's own signed votes for the newest ``limit``
        unfinalized rounds — the re-gossip set. Caller holds the node
        lock."""
        node = self.node
        out: list[Vote] = []
        for rnd in sorted(self._first, reverse=True):
            if rnd <= node.finalized:
                continue
            for account in node.keystore:
                v = self._first[rnd].get(account)
                if v is not None:
                    out.append(v)
            if len(out) >= limit:
                break
        return out

    def newest_justification(self) -> Justification | None:
        """The highest-round justification held (older rounds are
        pruned — finality is ancestor-transitive)."""
        if not self.justifications:
            return None
        return self.justifications[max(self.justifications)]

    def apply_pending(self) -> None:
        """Re-apply stored justifications whose target block has since
        been imported. A justification can arrive BEFORE its block:
        on_justification skips unknown headers, and _try_finalize's
        round-dedup then never re-fires for that round — without this
        sweep the node holds a valid proof of finality it never acts
        on. Caller holds the node lock.

        Also prunes superseded rounds afterwards: peer-sync nodes
        accumulate justifications through the "just" handler without
        ever reaching _try_finalize's prune (they assemble no local
        quorum), and finality is ancestor-transitive, so only the
        newest round needs retaining — the same O(1) retention the
        vote path keeps."""
        for rnd in sorted(self.justifications):
            j = self.justifications[rnd]
            if j.target_number > self.node.finalized \
                    and j.target_hash in self.node.headers:
                self.node.on_justification(j)
        if self.justifications:
            newest = max(self.justifications)
            for r in [r for r in self.justifications if r < newest]:
                del self.justifications[r]

    # -- incoming ----------------------------------------------------------
    def on_vote(self, vote: Vote) -> None:
        """Tally a (possibly remote) vote. Invalid signatures are
        dropped; equivocations are recorded as evidence and the vote
        is NOT counted (first vote stands, GRANDPA-style)."""
        from ..crypto import ed25519

        node = self.node
        if vote.voter not in node.authorities:
            return
        if vote.round <= node.finalized:
            return   # stale round
        pub = node.runtime.state.get("system", "session_key", vote.voter)
        if pub is None or not ed25519.verify(
                pub, vote.signing_payload(node.runtime.genesis_hash()),
                vote.signature):
            return
        first = self._first.setdefault(vote.round, {})
        prev = first.get(vote.voter)
        if prev is not None:
            if prev.target_hash != vote.target_hash:
                self.equivocations.append((prev, vote))
            return
        first[vote.voter] = vote
        self._tally.setdefault(vote.round, {}).setdefault(
            vote.target_hash, {})[vote.voter] = vote
        self._try_finalize(vote.round, vote.target_hash)

    def _try_finalize(self, rnd: int, target_hash: bytes) -> None:
        node = self.node
        votes = self._tally.get(rnd, {}).get(target_hash, {})
        n_auth = len(node.authorities)
        if 3 * len(votes) < 2 * n_auth or rnd in self.justifications:
            return
        just = Justification(round=rnd, target_hash=target_hash,
                             target_number=rnd,
                             votes=tuple(votes[v]
                                         for v in sorted(votes)))
        self.justifications[rnd] = just
        node.on_justification(just)
        # rounds below the justified height are settled; older
        # justifications are implied by the newest (finality is
        # ancestor-transitive), so retention stays O(1)
        for r in [r for r in self._tally if r < rnd]:
            self._tally.pop(r, None)
            self._first.pop(r, None)
        for r in [r for r in self.justifications if r < rnd]:
            del self.justifications[r]

    # -- evidence ----------------------------------------------------------
    def take_equivocations(self) -> list[tuple[Vote, Vote]]:
        evs, self.equivocations = self.equivocations, []
        return evs

    def verify_justification(self, just: Justification) -> bool:
        """Check a peer-supplied justification: 2/3 distinct authority
        votes, all validly signed over the claimed target (used when
        syncing finality without having seen the votes live)."""
        from ..crypto import ed25519

        node = self.node
        # judge against the authority set in force AT the target (era
        # rotation makes the set height-dependent); falls back to the
        # current set for targets we have not yet imported
        target = node.headers.get(just.target_hash)
        authorities = node.authorities_at(target.parent) \
            if target is not None else node.authorities
        seen = set()
        for v in just.votes:
            if not isinstance(v, Vote) or v.voter in seen:
                return False
            if v.round != just.round or v.target_hash != just.target_hash \
                    or v.target_number != just.target_number:
                return False
            if v.voter not in authorities:
                return False
            pub = node.runtime.state.get("system", "session_key", v.voter)
            if pub is None or not ed25519.verify(
                    pub, v.signing_payload(node.runtime.genesis_hash()),
                    v.signature):
                return False
            seen.add(v.voter)
        return 3 * len(seen) >= 2 * len(authorities)
