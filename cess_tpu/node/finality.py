"""Vote-based finality: signed vote exchange, 2/3 counting,
equivocation detection, persisted justifications.

The reference runs a GRANDPA voter loop gossiping signed votes per
round and importing justifications
(/root/reference/node/src/service.rs:556-580). This gadget is the
framework-native equivalent, round-simplified: round r finalizes at
most one block; every authority votes for its best chain's block at
height r (GRANDPA's "ghost of the best chain" collapsed to the head
ancestor at that height); 2/3 distinct signed votes for the same hash
form a justification that finalizes the block and all ancestors.

Safety properties kept from GRANDPA:
- a vote is a SIGNED, self-contained statement (chain/offences.py
  Vote) — replicas verify against the on-chain session-key registry;
- two votes by one voter in one round for different hashes are
  cryptographic proof of equivocation, reportable on chain
  (offences.report_equivocation) where staking slashes + chills;
- finality never reverts: justified blocks bound fork choice (a node
  never reorgs below its finalized height), and a justification on a
  side branch FORCES the node onto that branch.
"""
from __future__ import annotations

import dataclasses

from .. import codec
from ..chain.offences import Vote, sign_vote


@codec.register
@dataclasses.dataclass(frozen=True)
class Justification:
    """Proof of finality for (target_hash, round): >= 2/3 of the
    authority set's signed votes. Persisted with the chain so a
    restarted/syncing node can verify finality without replaying the
    vote exchange (the reference persists GRANDPA justifications in
    the block store)."""

    round: int
    target_hash: bytes
    target_number: int
    votes: tuple[Vote, ...]


class FinalityGadget:
    """Per-node vote tracker. The node feeds it local keys + incoming
    votes; it emits outgoing votes, detects equivocations, and
    surfaces justifications when a target reaches 2/3."""

    def __init__(self, node):
        self.node = node
        # round -> target_hash -> {voter: Vote}
        self._tally: dict[int, dict[bytes, dict[str, Vote]]] = {}
        # round -> voter -> first-seen Vote (for equivocation detection)
        self._first: dict[int, dict[str, Vote]] = {}
        self.equivocations: list[tuple[Vote, Vote]] = []
        self.justifications: dict[int, Justification] = {}

    # -- outgoing ----------------------------------------------------------
    def cast_votes(self) -> list[Vote]:
        """Votes from every local authority key for the current HEAD
        (round = head height; a justification finalizes the target and
        every ancestor). Voting only the head keeps liveness across
        reorgs: a voter that committed to a dead branch at height h
        can never re-vote round h (that would be equivocation), but
        the chain outgrows h and a fresh round finalizes past it."""
        node = self.node
        out = []
        head = node.chain[-1]
        rnd = head.number
        if rnd <= node.finalized:
            return out
        for account, key in node.keystore.items():
            if account not in node.authorities:
                continue
            if account in self._first.get(rnd, {}):
                continue   # never double-vote (that's equivocation)
            v = sign_vote(key, node.runtime.genesis_hash(), account,
                          rnd, head.hash(), rnd)
            self.on_vote(v)   # count own vote
            out.append(v)
        return out

    # -- incoming ----------------------------------------------------------
    def on_vote(self, vote: Vote) -> None:
        """Tally a (possibly remote) vote. Invalid signatures are
        dropped; equivocations are recorded as evidence and the vote
        is NOT counted (first vote stands, GRANDPA-style)."""
        from ..crypto import ed25519

        node = self.node
        if vote.voter not in node.authorities:
            return
        if vote.round <= node.finalized:
            return   # stale round
        pub = node.runtime.state.get("system", "session_key", vote.voter)
        if pub is None or not ed25519.verify(
                pub, vote.signing_payload(node.runtime.genesis_hash()),
                vote.signature):
            return
        first = self._first.setdefault(vote.round, {})
        prev = first.get(vote.voter)
        if prev is not None:
            if prev.target_hash != vote.target_hash:
                self.equivocations.append((prev, vote))
            return
        first[vote.voter] = vote
        self._tally.setdefault(vote.round, {}).setdefault(
            vote.target_hash, {})[vote.voter] = vote
        self._try_finalize(vote.round, vote.target_hash)

    def _try_finalize(self, rnd: int, target_hash: bytes) -> None:
        node = self.node
        votes = self._tally.get(rnd, {}).get(target_hash, {})
        n_auth = len(node.authorities)
        if 3 * len(votes) < 2 * n_auth or rnd in self.justifications:
            return
        just = Justification(round=rnd, target_hash=target_hash,
                             target_number=rnd,
                             votes=tuple(votes[v]
                                         for v in sorted(votes)))
        self.justifications[rnd] = just
        node.on_justification(just)
        # rounds below the justified height are settled; older
        # justifications are implied by the newest (finality is
        # ancestor-transitive), so retention stays O(1)
        for r in [r for r in self._tally if r < rnd]:
            self._tally.pop(r, None)
            self._first.pop(r, None)
        for r in [r for r in self.justifications if r < rnd]:
            del self.justifications[r]

    # -- evidence ----------------------------------------------------------
    def take_equivocations(self) -> list[tuple[Vote, Vote]]:
        evs, self.equivocations = self.equivocations, []
        return evs

    def verify_justification(self, just: Justification) -> bool:
        """Check a peer-supplied justification: 2/3 distinct authority
        votes, all validly signed over the claimed target (used when
        syncing finality without having seen the votes live)."""
        from ..crypto import ed25519

        node = self.node
        # judge against the authority set in force AT the target (era
        # rotation makes the set height-dependent); falls back to the
        # current set for targets we have not yet imported
        target = node.headers.get(just.target_hash)
        authorities = node.authorities_at(target.parent) \
            if target is not None else node.authorities
        seen = set()
        for v in just.votes:
            if not isinstance(v, Vote) or v.voter in seen:
                return False
            if v.round != just.round or v.target_hash != just.target_hash \
                    or v.target_number != just.target_number:
                return False
            if v.voter not in authorities:
                return False
            pub = node.runtime.state.get("system", "session_key", v.voter)
            if pub is None or not ed25519.verify(
                    pub, v.signing_payload(node.runtime.genesis_hash()),
                    v.signature):
                return False
            seen.add(v.voter)
        return 3 * len(seen) >= 2 * len(authorities)
