"""Merkle Mountain Range over block headers (the pallet-mmr role,
/root/reference/runtime/src/lib.rs:1270-1274,1492 with LeafData =
ParentNumberAndHash, served over the node's Mmr RPC,
/root/reference/node/src/rpc.rs namespace list).

An MMR commits to every header ever produced with one root that only
ever APPENDS: a light client holding the current root can check an
inclusion proof for any historical header without replaying the chain
— the complement to warp sync (which discards old bodies). Leaves are
(number, header_hash); interior nodes / roots are domain-tagged
SHA-256, and the root binds the leaf count so a truncated forest
cannot masquerade as a smaller valid one.

Design notes (redesigned native, not a port): positions use the
standard 0-based MMR numbering (parent immediately follows its right
child); proofs carry the climb path as (sibling_hash, sibling_is_right)
plus the other peaks split around the leaf's peak, so verification is
a single fold with no position arithmetic on the verifier side.
The node keeps an incrementally-extended instance per canonical chain
(rebuilt from headers after a reorg — headers are always retained,
even by warp sync)."""
from __future__ import annotations

import dataclasses
import hashlib

from .. import codec

_LEAF = b"cess-mmr-leaf:"
_NODE = b"cess-mmr-node:"
_ROOT = b"cess-mmr-root:"


def leaf_hash(number: int, header_hash: bytes) -> bytes:
    return hashlib.sha256(_LEAF + number.to_bytes(8, "little")
                          + header_hash).digest()


def _node_hash(left: bytes, right: bytes) -> bytes:
    return hashlib.sha256(_NODE + left + right).digest()


def _root_hash(leaf_count: int, peaks: list[bytes]) -> bytes:
    return hashlib.sha256(_ROOT + leaf_count.to_bytes(8, "little")
                          + b"".join(peaks)).digest()


def _pos_height(pos: int) -> int:
    """Height of the node at 0-based position ``pos``: jump left
    across perfect subtrees until the 1-based index is all-ones."""
    p = pos + 1
    while p & (p + 1):
        p -= (1 << (p.bit_length() - 1)) - 1
    return p.bit_length() - 1


def _leaf_pos(i: int) -> int:
    """Position of leaf i: 2*i minus the perfect-tree parents skipped."""
    return 2 * i - bin(i).count("1")


def _peak_positions(size: int) -> list[int]:
    """Peak positions of an MMR with ``size`` nodes (greedy largest
    perfect subtrees, left to right)."""
    out, pos, left = [], 0, size
    while left > 0:
        h = (left + 1).bit_length() - 1
        tree = (1 << h) - 1
        out.append(pos + tree - 1)
        pos += tree
        left -= tree
    return out


@codec.register
@dataclasses.dataclass(frozen=True)
class MmrProof:
    leaf_index: int
    leaf_count: int
    # climb path bottom-up: (sibling hash, sibling-is-right-child)
    path: tuple
    peaks_left: tuple      # peak hashes left of the leaf's peak
    peaks_right: tuple     # ...and right of it


class Mmr:
    """Append-only forest; nodes held in a flat positional list."""

    def __init__(self):
        self.nodes: list[bytes] = []
        self.leaf_count = 0

    def append(self, number: int, header_hash: bytes) -> None:
        self.nodes.append(leaf_hash(number, header_hash))
        self.leaf_count += 1
        # merge equal-height subtrees while the new position closes one
        h = 0
        while _pos_height(len(self.nodes)) > h:
            right = self.nodes[-1]
            left = self.nodes[len(self.nodes) - (2 << h)]
            self.nodes.append(_node_hash(left, right))
            h += 1

    def root(self) -> bytes:
        peaks = [self.nodes[p] for p in _peak_positions(len(self.nodes))]
        return _root_hash(self.leaf_count, peaks)

    def proof(self, leaf_index: int) -> MmrProof:
        if not 0 <= leaf_index < self.leaf_count:
            raise IndexError(f"leaf {leaf_index} of {self.leaf_count}")
        peaks = _peak_positions(len(self.nodes))
        pos, h, path = _leaf_pos(leaf_index), 0, []
        while pos not in peaks:
            if _pos_height(pos + 1) == h + 1:
                # pos is a right child; left sibling precedes the tree
                sib = pos - ((2 << h) - 1)
                path.append((self.nodes[sib], False))
                pos += 1
            else:
                sib = pos + ((2 << h) - 1)
                path.append((self.nodes[sib], True))
                pos = sib + 1
            h += 1
        k = peaks.index(pos)
        return MmrProof(
            leaf_index=leaf_index, leaf_count=self.leaf_count,
            path=tuple(path),
            peaks_left=tuple(self.nodes[p] for p in peaks[:k]),
            peaks_right=tuple(self.nodes[p] for p in peaks[k + 1:]))


def verify_proof(root: bytes, number: int, header_hash: bytes,
                 proof: MmrProof) -> bool:
    """Check a header's inclusion against an MMR root — pure function,
    no chain access (the light-client half)."""
    try:   # EVERY check inside: crafted inputs fail closed, never raise
        if not isinstance(proof, MmrProof) \
                or not isinstance(proof.leaf_count, int) \
                or isinstance(proof.leaf_count, bool) \
                or not 0 < proof.leaf_count < 1 << 63 \
                or not isinstance(number, int) \
                or isinstance(number, bool) \
                or not 0 <= number < 1 << 63 \
                or not isinstance(header_hash, bytes) \
                or not all(isinstance(pk, bytes) for pk in
                           tuple(proof.peaks_left)
                           + tuple(proof.peaks_right)):
            return False
        acc = leaf_hash(number, header_hash)
        for item in proof.path:
            if not (isinstance(item, tuple) and len(item) == 2
                    and isinstance(item[0], bytes)):
                return False
            sib, right = item
            acc = _node_hash(acc, sib) if right else _node_hash(sib, acc)
        peaks = list(proof.peaks_left) + [acc] + list(proof.peaks_right)
        return _root_hash(proof.leaf_count, peaks) == root
    except (TypeError, ValueError, OverflowError):
        return False   # belt-and-braces: the contract is bool, not raise


class HeaderMmr:
    """Node-side cache: tracks the canonical chain, extending
    incrementally and rebuilding after a reorg (header lists are
    always retained — warp sync prunes bodies, not headers)."""

    def __init__(self):
        self._mmr = Mmr()
        self._hashes: list[bytes] = []   # header hash per appended leaf

    def sync(self, chain) -> Mmr:
        """Bring the MMR in line with ``chain`` (list of headers)."""
        n = len(self._hashes)
        if n > len(chain) or any(
                self._hashes[i] != chain[i].hash()
                for i in (n - 1, n // 2, 0) if 0 <= i < n):
            # reorg (spot-checked at three depths): rebuild
            self._mmr = Mmr()
            self._hashes = []
            n = 0
        for i in range(n, len(chain)):
            h = chain[i].hash()
            self._mmr.append(chain[i].number, h)
            self._hashes.append(h)
        return self._mmr
