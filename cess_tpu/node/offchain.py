"""Off-chain ecosystem agents: OSS gateway, storage miner, TEE, OCW.

The reference's L6 (SURVEY.md §1): OSS gateways chunk+encode files,
storage miners hold fragments and prove storage, TEE workers tag and
verify, validator offchain workers generate challenges — all external
repos interacting via extrinsics and events. Here they are in-process
agents around a Node, driving the TPU data plane
(cess_tpu.models.pipeline / cess_tpu.ops.podr2) for the heavy math:

- OssGateway.upload(): segments the file, RS-encodes + PoDR2-tags the
  whole batch on device, declares on chain, serves fragments.
- MinerAgent: fetches assigned fragments, reports transfer, computes
  aggregated (mu, sigma) proofs over its REAL stored bytes each
  challenge round (drop its ``store`` entries to simulate data loss),
  claims restoral orders and repairs via RS reconstruction.
- TeeAgent: holds the PoDR2 secret key, verifies queued proofs
  batch-wise on device, reports results.
- ValidatorOcw: the audit offchain worker (lib.rs:347-369): builds the
  deterministic challenge snapshot and submits the proposal.

Every agent's ``on_block`` runs after each imported block (Substrate
OCW semantics) and communicates ONLY via extrinsics + events + the
fragment transfer channel, like the reference's network boundary.

Device submission: each agent accepts an optional ``engine``
(cess_tpu/serve) — OssGateway encodes/tags through its pipeline's
engine, MinerAgent proves and RS-repairs through the prove/repair
queues, TeeAgent verifies through the (highest-priority) verify
queue. Results are bit-identical to the direct calls; None (the
default) keeps every path direct and synchronous. ValidatorOcw has no
device op on its path (challenge snapshots are chain-side host math),
so it takes no engine.
"""
from __future__ import annotations

import dataclasses
import hashlib
import threading
import time

import jax.numpy as jnp
import numpy as np

from .. import codec, constants
from ..obs import flight as _flight
from ..obs import trace
from ..resilience import faults
from ..chain.file_bank import UserBrief
from ..chain.state import DispatchError
from ..crypto import bls12381
from ..crypto.hashing import fragment_hash
from ..models.pipeline import PipelineConfig, StoragePipeline
from ..ops import pfield as pf
from ..ops import podr2
from .network import Node


class OssGateway:
    """The user-facing gateway: chunk -> encode -> tag -> declare.

    The gateway is where the per-tenant accounting contract
    (obs/slo.py) STARTS: every engine submit an upload generates is
    tagged with the uploading OWNER's account, so the exposition's
    ``cess_tenant_*`` series and the batcher's weighted-fair dequeue
    see the user behind the bytes — not just the one shared gateway
    account. Free when the engine has no SLO board."""

    def __init__(self, node: Node, account: str,
                 pipeline: StoragePipeline):
        self.node = node
        self.account = account
        self.pipeline = pipeline
        self.fragment_store: dict[bytes, bytes] = {}   # hash -> bytes
        self.tag_store: dict[bytes, np.ndarray] = {}   # hash -> [blocks] u32

    def upload(self, owner: str, bucket: str, file_name: str,
               data: bytes) -> bytes:
        """Segment + encode + tag on device; declare on chain; keep
        fragments ready for miners to fetch. Returns the file hash."""
        cfg = self.pipeline.config
        seg_size = cfg.segment_size
        padded = data + b"\0" * ((-len(data)) % seg_size)
        n_segs = len(padded) // seg_size
        segments = np.frombuffer(padded, dtype=np.uint8).reshape(n_segs, seg_size)
        frag_hashes = [
            [fragment_hash(b"pending")] * (cfg.k + cfg.m)
            for _ in range(n_segs)]
        with trace.span("offchain.upload", sys="offchain",
                        file=file_name, segments=n_segs,
                        size=len(data)):
            # hash fragments first (ids feed the tag PRF), then tag on
            # device. The device-resident fragments feed tag_step
            # DIRECTLY (zero-copy engine handoff): the hashing fetch is
            # the only D2H, and the fragment bytes are never
            # re-uploaded for tagging
            frags_dev = self.pipeline.encode_step(jnp.asarray(segments),
                                                  tenant=owner)
            out_frags = np.asarray(frags_dev)
            ids = np.zeros((n_segs, cfg.k + cfg.m, 2), dtype=np.uint32)
            for i in range(n_segs):
                for j in range(cfg.k + cfg.m):
                    h = fragment_hash(out_frags[i, j].tobytes())
                    frag_hashes[i][j] = h
                    ids[i, j] = podr2.fragment_id_from_hash(h)
            tags = np.asarray(self.pipeline.tag_step(frags_dev,
                                                     jnp.asarray(ids),
                                                     tenant=owner))
            for i in range(n_segs):
                for j in range(cfg.k + cfg.m):
                    h = frag_hashes[i][j]
                    self.fragment_store[h] = out_frags[i, j].tobytes()
                    self.tag_store[h] = tags[i, j]
            seg_list = [(fragment_hash(segments[i].tobytes()),
                         tuple(frag_hashes[i])) for i in range(n_segs)]
            file_hash = fragment_hash(b"".join(h for _, fs in seg_list
                                               for h in fs))
            self.node.submit_extrinsic(
                self.account, "file_bank.upload_declaration", file_hash,
                seg_list, UserBrief(owner, file_name, bucket), len(data))
            # custody lineage: one encode+dispatch event per upload —
            # the declared seg_list is exactly what the ledger needs
            # (the guarded note is free when no recorder is armed)
            _flight.note("custody", "dispatch", owner=owner,
                         file=file_hash, k=cfg.k, m=cfg.m,
                         segments=seg_list)
            return file_hash


def filler_bytes(miner: str, index: int, size: int) -> bytes:
    """Deterministic filler (idle file) content: a SHA-256 counter-mode
    stream over (miner, index). Anyone — miner, TEE, auditor — can
    regenerate a filler byte-exactly, which is how the TEE certifies
    filler hashes before the chain credits idle space (the reference's
    generated idle files, file-bank/src/lib.rs:798-859).

    Known limitation (documented at file_bank.upload_filler): publicly
    derivable content proves TAG possession, not disk. The
    PoIS-direction upgrade is :func:`slow_filler_bytes`."""
    out = bytearray()
    seed = b"cess-filler:" + miner.encode() + index.to_bytes(8, "little")
    ctr = 0
    while len(out) < size:
        out += hashlib.sha256(seed + ctr.to_bytes(8, "little")).digest()
        ctr += 1
    return bytes(out[:size])


SLOW_FILLER_WORK = 2048   # sequential hashes per 512-B block (cost knob)


def filler_seed_commitment(secret: bytes) -> bytes:
    """The on-chain commitment to a miner's filler seed."""
    return hashlib.sha256(b"cess-filler-seed:" + secret).digest()


def slow_filler_bytes(secret: bytes, index: int, size: int,
                      work: int = SLOW_FILLER_WORK) -> bytes:
    """PoIS-direction filler content (the upgrade CESS itself made —
    SURVEY.md notes idle files were later replaced by PoIS):

    - seeded by a MINER SECRET (committed on chain via
      sminer.commit_filler_seed), so the network at large cannot
      derive the content; the TEE learns the secret once, inside the
      enclave, at certification time;
    - each 512-byte block is the output of a ``work``-step SEQUENTIAL
      hash chain, so even the secret-holding miner cannot cheaply
      regenerate challenged blocks inside an audit window: answering
      a ~47-block challenge without the data costs ~47*work sequential
      hashes per filler, versus one disk read each — dedicated storage
      becomes the rational strategy, which is what the idle-space
      ledger is supposed to measure.

    Audit verification is UNAFFECTED: the TEE tags the content once at
    certification; challenges verify against tags (Shacham-Waters),
    never by regeneration.
    """
    block_bytes = 512
    out = bytearray()
    for j in range(-(-size // block_bytes)):
        state = hashlib.sha256(
            b"cess-pois-filler:" + secret + index.to_bytes(8, "little")
            + j.to_bytes(8, "little")).digest()
        for _ in range(work):          # the sequential cost
            state = hashlib.sha256(state).digest()
        for c in range(block_bytes // 32):   # cheap expansion
            out += hashlib.sha256(state + c.to_bytes(4, "little")).digest()
    return bytes(out[:size])


class MinerAgent:
    def __init__(self, node: Node, account: str, gateways: list[OssGateway],
                 pipeline: StoragePipeline, engine=None, retry=None,
                 clock=None):
        self.node = node
        self.account = account
        self.gateways = gateways
        self.pipeline = pipeline
        # optional cess_tpu.resilience.RetryPolicy for fragment
        # transfers: dropped/corrupted fetches (the "offchain.fetch"
        # fault seam) re-attempt with deterministic backoff instead of
        # waiting a whole deal-servicing round. None = one attempt.
        self.retry = retry
        # retry backoff clock: any object with sleep(seconds). None =
        # wall clock; a sim world injects its SimClock so transfer
        # backoff advances virtual time (cess_tpu/sim).
        self.clock = clock
        # optional submission engine (cess_tpu/serve): proving and RS
        # repair go through its prove/repair queues — concurrent miners
        # answering the same round coalesce into shared device batches.
        # None (default) keeps the direct synchronous path.
        self.engine = None
        if engine is not None:
            self.attach_engine(engine)
        # repair dispatch mode (ops/regen.py): "fragments" fetches k
        # whole survivor rows per repair; "symbols" walks the
        # product-matrix repair-symbol chain through the helpers so
        # only the final fragment-sized aggregate is ingressed. The
        # mode can be flipped mid-run (set_repair_mode) by tests or
        # the remediation plane; the lock keeps the flip + flight
        # note atomic against concurrent flippers.
        self.repair_mode = "fragments"
        self._mode_mu = threading.Lock()
        # ingress accounting: every repair is charged by the bytes
        # that crossed the wire INTO this miner vs the bytes it
        # recovered — the regenerating claim is ingress/recovered ~ 1
        # against the whole-fragment baseline of k (sim invariant
        # "repair-ingress-bound", bench ingress_bytes_per_recovered_byte)
        self.repair_ingress_bytes = 0
        self.repair_recovered_bytes = 0
        self.repair_symbol_repairs = 0
        self.repair_whole_repairs = 0
        self.repair_fallbacks = 0
        self.store: dict[bytes, bytes] = {}        # fragment hash -> bytes
        self.tags: dict[bytes, np.ndarray] = {}
        self.filler_store: dict[bytes, bytes] = {}  # filler hash -> bytes
        self.filler_tags: dict[bytes, np.ndarray] = {}
        self._reported: set[bytes] = set()
        self._proved_round: int = -1

    def attach_engine(self, engine) -> None:
        """Bind a submission engine, geometry-checked: a mismatched
        codec would feed repair wrong shard geometry, so this is loud —
        like StoragePipeline/TeeAgent — whether it happens at
        construction or late (the sim's repair storm attaches the pool
        engine to rescuers that were built without one)."""
        if engine is not None and engine.codec is not None \
                and (engine.codec.k, engine.codec.m) \
                != (self.pipeline.config.k, self.pipeline.config.m):
            raise ValueError(
                f"engine codec RS({engine.codec.k},{engine.codec.m}) != "
                f"miner pipeline RS({self.pipeline.config.k},"
                f"{self.pipeline.config.m})")
        self.engine = engine

    def set_repair_mode(self, mode: str) -> None:
        """Flip the repair dispatch mode mid-run. Thread-safe and
        flight-noted (("repair", "mode")) so mode changes show up in
        incident bundles; a no-op flip stays silent."""
        if mode not in ("symbols", "fragments"):
            raise ValueError(
                f"repair_mode must be 'symbols' or 'fragments', "
                f"got {mode!r}")
        with self._mode_mu:
            frm = self.repair_mode
            if frm == mode:
                return
            self.repair_mode = mode
        _flight.note("repair", "mode", miner=self.account, frm=frm,
                     to=mode)

    # -- fillers -----------------------------------------------------------------
    def setup_fillers(self, tee: "TeeAgent", count: int) -> None:
        """Generate ``count`` fillers, have the TEE certify + tag them,
        and register them on chain (idle space enters the ledger)."""
        size = self.pipeline.config.fragment_size
        blobs = [filler_bytes(self.account, i, size) for i in range(count)]
        hashes, tags, sig = tee.certify_fillers(self.account,
                                                list(range(count)), blobs)
        for h, blob, tag in zip(hashes, blobs, tags):
            self.filler_store[h] = blob
            self.filler_tags[h] = tag
        self.node.submit_extrinsic(self.account, "file_bank.upload_filler",
                                   tuple(hashes), tee.controller, sig)

    def commit_filler_seed(self, secret: bytes) -> None:
        """Submit the one-time on-chain seed commitment; it must be in
        a block before the TEE will certify (run a slot in between)."""
        self.node.submit_extrinsic(self.account,
                                   "sminer.commit_filler_seed",
                                   filler_seed_commitment(secret))

    def setup_fillers_pois(self, tee: "TeeAgent", count: int,
                           secret: bytes,
                           work: int = SLOW_FILLER_WORK) -> None:
        """Secret-seeded filler setup: the seed commitment must
        already be on chain (commit_filler_seed); the TEE derives +
        certifies against it, then the batch is registered."""
        hashes, tags, sig, blobs = tee.certify_pois_fillers(
            self.account, secret, list(range(count)), work)
        for h, blob, tag in zip(hashes, blobs, tags):
            self.filler_store[h] = blob
            self.filler_tags[h] = tag
        self.node.submit_extrinsic(self.account, "file_bank.upload_filler",
                                   tuple(hashes), tee.controller, sig)

    # -- deal servicing ---------------------------------------------------------
    def _fetch(self, frag_hash: bytes) -> bool:
        for gw in self.gateways:
            blob = self._transfer(gw, frag_hash)
            if blob is not None:
                self.store[frag_hash] = blob
                self.tags[frag_hash] = gw.tag_store[frag_hash]
                return True
        # repair path: reconstruct from peers (restoral flow fetches
        # survivor rows from other miners via the network harness)
        return False

    def _transfer(self, gw: OssGateway, frag_hash: bytes) -> bytes | None:
        """One gateway fragment transfer: faultable (seam
        "offchain.fetch" drops the transfer, "offchain.fetch_bytes"
        corrupts the payload), INTEGRITY-CHECKED against the on-chain
        fragment hash (a corrupted transfer is a failed transfer,
        never poisoned storage — the same contract try_repair applies
        to reconstructed bytes), and retried under the configured
        policy. Returns the verified bytes or None."""
        attempts = 1 if self.retry is None else self.retry.max_attempts
        for attempt in range(1, attempts + 1):
            if attempt > 1:
                # deterministic jitter keyed by the fragment identity:
                # replayable in chaos tests, decorrelated across frags
                (self.clock or time).sleep(
                    self.retry.delay_for(attempt - 1, token=frag_hash))
            if not faults.allow("offchain.fetch"):
                continue             # transfer dropped: transient
            blob = gw.fragment_store.get(frag_hash)
            if blob is None:
                return None          # gateway lacks it: not transient
            blob = faults.corrupt("offchain.fetch_bytes", blob)
            if fragment_hash(blob) == frag_hash:
                return blob
            # corrupted in flight: counts as a failed attempt
        return None

    def on_block(self, node: Node) -> None:
        rt = node.runtime
        # service assigned deals
        for (fh,), deal in list(rt.state.iter_prefix("file_bank", "deal")):
            if self.account not in deal.assigned or fh in self._reported \
                    or self.account in deal.complete:
                continue
            row = deal.assigned.index(self.account)
            with trace.span("offchain.transfer", sys="offchain",
                            miner=self.account, file=fh):
                done = all(self._fetch(seg.fragment_hashes[row])
                           for seg in deal.segments)
            if done:
                node.submit_extrinsic(self.account,
                                      "file_bank.transfer_report", fh)
                self._reported.add(fh)
                # custody transfer: this miner now holds its row of
                # every segment (the ledger flips gateway -> miner)
                _flight.note("custody", "transfer", miner=self.account,
                             file=fh, row=row,
                             frags=tuple(seg.fragment_hashes[row]
                                         for seg in deal.segments))
        # answer challenges over REAL stored bytes
        ch = rt.audit.challenge()
        if ch is not None and not ch.cleared \
                and rt.state.block <= ch.challenge_deadline \
                and ch.start != self._proved_round \
                and any(s.miner == self.account for s in ch.miners):
            self._submit_proof(node, ch)
            self._proved_round = ch.start

    def _submit_proof(self, node: Node, ch) -> None:
        """Distinct idle + service proofs, each a constant-size
        aggregated (mu, sigma) over the owed sets FROZEN in the
        challenge snapshot — the reference's two-proof submit_proof
        (audit/src/lib.rs:430-479) with honest wire sizing."""
        seed = b"".join(ch.net.randoms)
        snap = next(s for s in ch.miners if s.miner == self.account)
        limbs = self.pipeline.podr2_key.limbs
        with trace.span("offchain.prove", sys="offchain",
                        miner=self.account, round=ch.start,
                        service=len(snap.service_frags),
                        idle=len(snap.fillers)):
            service = build_proof(seed, list(snap.service_frags),
                                  self.store, self.tags, limbs=limbs,
                                  engine=self.engine,
                                  tenant=self.account)
            idle = build_proof(seed, list(snap.fillers),
                               self.filler_store, self.filler_tags,
                               limbs=limbs, engine=self.engine,
                               tenant=self.account)
            node.submit_extrinsic(self.account, "audit.submit_proof",
                                  idle, service)

    # -- restoral servicing -------------------------------------------------------
    def warm_restoral(self) -> None:
        """Pre-compile + pre-stage the restoral market's reconstruct
        programs — one per lost row, with the k lowest surviving rows
        (exactly the survivor set try_repair assembles when every peer
        holds its fragment) — so a claimed order pays kernel time, not
        first-call compile + table staging. With an engine, the
        engine's repair program cache is warmed under the keys its
        batcher will hit; without one, the codec's AOT warm path is
        used directly (no-op on the NumPy reference codec)."""
        cfg = self.pipeline.config
        rows = cfg.k + cfg.m
        patterns = []
        for row in range(rows):
            present = tuple(j for j in range(rows) if j != row)[:cfg.k]
            patterns.append((present, (row,)))
        if self.engine is not None and self.engine.codec is not None:
            # restoral repairs are single-order blocking submits, so
            # only the 1-row bucket's programs are ever dispatched —
            # warming bucket 2 as well would double the AOT compile
            # sweep (per pattern x per lane) for programs a repair
            # never hits
            self.engine.warm_repair(patterns, cfg.fragment_size,
                                    buckets=(1,))
            return
        from ..ops.rs import make_codec

        # make_codec is lru_cached: this is the SAME instance
        # try_repair resolves later, so the warm programs persist
        codec_ = make_codec(cfg.k, cfg.m, backend="auto")
        warm = getattr(codec_, "warm_reconstruct", None)
        if warm is not None:
            for present, missing in patterns:
                warm(present, missing, (cfg.k, cfg.fragment_size))

    def repair_symbol(self, frag_hash: bytes, coeff: int,
                      acc: np.ndarray | None = None) -> np.ndarray | None:
        """Helper side of a regenerating repair (ops/regen.py): fold
        THIS miner's survivor fragment into the partial-sum chain,
        acc ^ coeff*fragment, and return the fragment-sized aggregate
        for the next helper (or the rebuilder, on the last hop).
        Returns None when this helper can't serve — fragment not held,
        or the transfer dropped (seam "offchain.symbol"). The outgoing
        aggregate rides the "offchain.symbol_bytes" corruption seam;
        integrity is the REBUILDER's hash check, exactly as for
        whole-fragment transfers."""
        blob = self.store.get(frag_hash)
        if blob is None:
            return None
        if not faults.allow("offchain.symbol"):
            return None
        frag = np.frombuffer(blob, dtype=np.uint8)
        acc = np.zeros_like(frag) if acc is None \
            else np.asarray(acc, dtype=np.uint8)
        if self.engine is not None and self.engine.codec is not None \
                and hasattr(self.engine.codec, "fold_symbol"):
            out = self.engine.repair_symbol(np.stack([acc, frag]),
                                            int(coeff),
                                            tenant=self.account)
            sym = np.asarray(out)[0]
        else:
            from ..ops import regen

            sym = regen.fold_symbol_host(acc, frag, int(coeff))
        return np.asarray(faults.corrupt("offchain.symbol_bytes", sym),
                          dtype=np.uint8)

    def _repair_via_symbols(self, seg, row: int,
                            present: tuple[int, ...],
                            holders: dict[int, "MinerAgent"],
                            cfg: PipelineConfig) -> bytes | None:
        """Walk the product-matrix repair-symbol chain: each holder
        folds coeff_j * fragment_j into the travelling partial sum, and
        only the FINAL fragment-sized aggregate reaches this miner —
        ingress n bytes for n recovered, vs k*n on the whole-fragment
        path. Returns the (unverified) aggregate bytes, or None when
        any hop refuses (the caller then falls back)."""
        from ..ops import regen

        try:
            coeffs = regen.repair_coeffs(cfg.k, cfg.m, present, (row,))
        except ValueError:
            return None
        acc = None
        for j, coeff in zip(present, coeffs):
            acc = holders[j].repair_symbol(seg.fragment_hashes[j],
                                           int(coeff), acc)
            if acc is None:
                return None
        # the aggregate crossed the wire whether or not it hashes
        # clean — honest accounting charges it either way
        self.repair_ingress_bytes += acc.nbytes
        return acc.tobytes()

    def _repair_via_fragments(self, seg, row: int,
                              present: tuple[int, ...],
                              holders: dict[int, "MinerAgent"],
                              cfg: PipelineConfig) -> bytes:
        """Whole-fragment dispatch: ingress k survivor rows and
        reconstruct (engine repair queue when attached, direct codec
        otherwise)."""
        survivors = [np.frombuffer(
            holders[j].store[seg.fragment_hashes[j]], dtype=np.uint8)
            for j in present]
        self.repair_ingress_bytes += sum(s.nbytes for s in survivors)
        if self.engine is not None and self.engine.codec is not None:
            rec = self.engine.reconstruct(np.stack(survivors),
                                          present, (row,),
                                          tenant=self.account)
        else:
            from ..ops.rs import make_codec

            codec_ = make_codec(cfg.k, cfg.m, backend="auto")
            rec = codec_.reconstruct(np.stack(survivors), present,
                                     (row,))
        return np.asarray(rec)[0].tobytes()

    def try_repair(self, frag_hash: bytes, peers: list["MinerAgent"],
                   gateways: list[OssGateway] | None = None) -> bool:
        """Claim + repair a broken fragment from peer-held rows, then
        report completion. ``repair_mode`` picks the dispatch:
        "fragments" ingresses k whole survivor rows; "symbols" walks
        the regenerating repair-symbol chain (ops/regen.py) and
        ingresses one fragment-sized aggregate, falling back to the
        whole-fragment path when a helper refuses or the aggregate
        fails its hash (counted in ``repair_fallbacks`` and noted to
        the flight recorder). EITHER WAY the repaired bytes must
        re-hash to the on-chain identity before they are stored — a
        bad decode is a failed repair, never poisoned storage."""
        rt = self.node.runtime
        order = rt.file_bank.restoral_order(frag_hash)
        if order is None:
            return False
        f = rt.file_bank.file(order.file_hash)
        if f is None:
            return False
        seg = next(s for s in f.segments if frag_hash in s.fragment_hashes)
        row = seg.fragment_hashes.index(frag_hash)
        cfg = self.pipeline.config
        holders: dict[int, MinerAgent] = {}
        for j, h in enumerate(seg.fragment_hashes):
            if j == row:
                continue
            for peer in peers:
                if h in peer.store:
                    holders[j] = peer
                    break
            if len(holders) == cfg.k:
                break
        if len(holders) < cfg.k:
            return False
        present = tuple(holders)
        mode = self.repair_mode
        via_symbols = False
        ingress0 = self.repair_ingress_bytes
        with trace.span("offchain.repair", sys="offchain",
                        miner=self.account, row=row,
                        survivors=len(present), mode=mode):
            blob = None
            if mode == "symbols":
                blob = self._repair_via_symbols(seg, row, present,
                                                holders, cfg)
                if blob is not None and fragment_hash(blob) == frag_hash:
                    via_symbols = True
                else:
                    self.repair_fallbacks += 1
                    _flight.note("repair", "fallback",
                                 miner=self.account, row=row,
                                 reason="broken-chain" if blob is None
                                 else "bad-hash")
                    blob = None
            if blob is None:
                blob = self._repair_via_fragments(seg, row, present,
                                                  holders, cfg)
        if fragment_hash(blob) != frag_hash:
            return False
        self.store[frag_hash] = blob
        self.repair_recovered_bytes += len(blob)
        if via_symbols:
            self.repair_symbol_repairs += 1
        else:
            self.repair_whole_repairs += 1
        for peer in peers:
            if frag_hash in peer.tags:
                self.tags[frag_hash] = peer.tags[frag_hash]
                break
        else:
            for gw in (gateways or self.gateways):
                if frag_hash in gw.tag_store:
                    self.tags[frag_hash] = gw.tag_store[frag_hash]
                    break
        self.node.submit_extrinsic(self.account,
                                   "file_bank.claim_restoral_order",
                                   frag_hash)
        self.node.submit_extrinsic(self.account,
                                   "file_bank.restoral_order_complete",
                                   frag_hash)
        # custody restoral: the fragment's custodian is this miner now
        # (the ledger clears the loss and re-scores the margin)
        _flight.note("custody", "repair", miner=self.account,
                     frag=frag_hash,
                     mode="symbols" if via_symbols else "fragments",
                     ingress=self.repair_ingress_bytes - ingress0)
        return True


@codec.register
@dataclasses.dataclass(frozen=True)
class Proof:
    """The aggregated PoDR2 proof: ONE (mu, sigma) folded over every
    owed fragment with PRF coefficients (podr2.aggregate_coeffs). The
    chain sees only the codec-encoded bytes and caps the REAL wire
    size at SIGMA_MAX (runtime/src/lib.rs:992). Sizing is stated
    authoritatively ONCE, at podr2.PROOF_BYTES: raw payload 1032 B at
    the defaults, plus this codec framing's constant overhead
    (proof_wire_bytes() below computes the framed total — 1058 B at
    the defaults), constant in the number of fragments.

    Both fields are FIXED-WIDTH uint32 ndarrays. sigma used to be a
    tuple of Python ints, whose varint encoding shrank whenever a limb
    value happened to be small — so the wire size depended on the
    (F-dependent) fold values and test_aggregate_proof_wire_size_constant
    caught a 1-byte drift between F=1 and F=50. An ndarray encodes as
    dtype + shape + raw bytes: byte-for-byte constant in F."""
    mu: np.ndarray              # [sectors] uint32
    sigma: np.ndarray           # [limbs] uint32 F_p^limbs element


def proof_wire_bytes(limbs: int | None = None,
                     sectors: int = podr2.SECTORS) -> int:
    """The exact framed wire size of an aggregated proof: the raw
    payload (podr2.PROOF_BYTES — the ONE authoritative size statement)
    plus this codec framing's constant overhead, computed from an
    actual encode so it can never drift from the codec."""
    if limbs is None:
        limbs = podr2.LIMBS
    return len(codec.encode(Proof(
        mu=np.zeros((sectors,), np.uint32),
        sigma=np.zeros((limbs,), np.uint32))))


def build_proof(seed: bytes, owed: list[bytes],
                store: dict[bytes, bytes],
                tags: dict[bytes, np.ndarray],
                limbs: int | None = None, engine=None,
                tenant: str | None = None) -> bytes:
    """Miner-side: aggregated proof over the owed set, as wire bytes.
    Fragments the miner no longer holds simply can't contribute — the
    fold then fails TEE verification (that's the audit). ``tenant``
    tags the engine submit (the proving miner's account) for
    per-tenant accounting."""
    held = [h for h in owed if h in store]
    # the limb WIDTH is a deployment parameter: callers pass it from
    # their PoDR2 key (hardwiring 2 broke limbs=3 deployments; and an
    # EMPTY tags map must not silently fall back to the module default
    # — a fillerless miner in a limbs=3 deployment would emit a
    # wrong-width zero sigma and fail an audit it should pass; both
    # review-caught, r05)
    if limbs is None:
        limbs = next(iter(tags.values())).shape[-1] if tags \
            else podr2.LIMBS
    if not held:
        return codec.encode(Proof(
            mu=np.zeros((podr2.SECTORS,), np.uint32),
            sigma=np.zeros((limbs,), np.uint32)))
    frags = np.stack([np.frombuffer(store[h], dtype=np.uint8)
                      for h in held])
    tag_arr = np.stack([tags[h] for h in held])
    blocks = tag_arr.shape[1]
    idx, nu = podr2.gen_challenge(seed, blocks)
    ids = np.stack([podr2.fragment_id_from_hash(h) for h in held])
    r = podr2.aggregate_coeffs(seed, ids)
    if engine is not None and engine.audit is not None:
        # submission-engine path: miners answering the same round
        # coalesce in the engine's prove queue (bit-identical fold)
        mu, sigma = engine.prove_aggregate(frags, tag_arr,
                                           np.asarray(idx),
                                           np.asarray(nu), np.asarray(r),
                                           tenant=tenant)
    else:
        mu, sigma = podr2.prove_aggregate(jnp.asarray(frags),
                                          jnp.asarray(tag_arr), idx, nu,
                                          r)
    return codec.encode(Proof(
        mu=np.ascontiguousarray(np.asarray(mu, dtype=np.uint32)),
        sigma=np.ascontiguousarray(np.asarray(sigma, dtype=np.uint32))))


class TeeAgent:
    """Holds the PoDR2 secret; certifies fillers and verifies queued
    proofs on device."""

    def __init__(self, node: Node, controller: str, key: podr2.Podr2Key,
                 blocks_per_fragment: int, bls_seed: bytes | None = None,
                 engine=None):
        self.node = node
        self.controller = controller
        self.key = key
        self.blocks = blocks_per_fragment
        # optional submission engine (cess_tpu/serve): aggregated-proof
        # checks route through its verify queue — the highest-priority
        # class, so audit verification preempts bulk encode/tag work.
        # The engine's AuditBackend must hold THIS TEE's key.
        self.engine = engine
        if engine is not None and engine.audit is not None \
                and not podr2.keys_equal(engine.audit.key, key):
            raise ValueError("engine AuditBackend key is not this "
                             "TEE's PoDR2 key")
        self.account_key = node.spec.account_key(controller)
        self._submitted: set[tuple[str, int]] = set()
        # BLS verdict master key: registered on chain (with a PoP) so
        # every submit_verify_result is publicly re-verifiable
        if bls_seed is not None:
            self.bls_sk, self.bls_pk = bls12381.keygen(bls_seed)
        else:
            self.bls_sk, self.bls_pk = None, b""

    def bls_registration(self) -> tuple[bytes, bytes]:
        """(bls_pk, proof-of-possession) for tee_worker.register."""
        if self.bls_sk is None:
            return b"", b""
        return self.bls_pk, bls12381.prove_possession(self.bls_sk,
                                                      self.bls_pk)

    # -- filler certification -------------------------------------------------
    def certify_fillers(self, miner: str, indices: list[int],
                        blobs: list[bytes]):
        """Check each blob IS the canonical full-size PRF stream for
        (miner, index), tag it, and sign the hash batch bound to the
        miner's on-chain cert nonce — the attestation
        file_bank.upload_filler verifies (and consumes) on chain."""
        expected_size = self.blocks * podr2.BLOCK_BYTES
        if len(indices) != len(blobs) or len(set(indices)) != len(indices):
            raise ValueError("indices/blobs mismatch")
        for i, blob in zip(indices, blobs):
            if len(blob) != expected_size \
                    or blob != filler_bytes(miner, i, expected_size):
                raise ValueError(f"filler {i} content not canonical")
        return self._tag_and_sign(miner, blobs)

    def certify_pois_fillers(self, miner: str, secret: bytes,
                             indices: list[int],
                             work: int = SLOW_FILLER_WORK):
        """PoIS-direction variant (see slow_filler_bytes): the miner
        hands its filler seed to the ENCLAVE; the TEE checks it against
        the miner's on-chain commitment, derives the secret-seeded
        sequential content itself, tags and signs the batch through
        the SAME cert flow. Returns (hashes, tags, sig, blobs) — the
        derived blobs, so callers need not re-plot."""
        commitment = self.node.runtime.sminer.filler_seed_commitment_of(
            miner)
        if commitment is None \
                or filler_seed_commitment(secret) != commitment:
            raise ValueError("filler seed does not match the miner's "
                             "on-chain commitment")
        if len(set(indices)) != len(indices):
            raise ValueError("duplicate filler indices")
        expected_size = self.blocks * podr2.BLOCK_BYTES
        blobs = [slow_filler_bytes(secret, i, expected_size, work)
                 for i in indices]
        hashes, tags, sig = self._tag_and_sign(miner, blobs)
        return hashes, tags, sig, blobs

    def _tag_and_sign(self, miner: str, blobs: list[bytes]):
        from ..chain.file_bank import FileBank

        hashes = [fragment_hash(b) for b in blobs]
        ids = np.stack([podr2.fragment_id_from_hash(h) for h in hashes])
        tags = np.asarray(podr2.tag_fragments(
            self.key, jnp.asarray(ids),
            jnp.asarray(np.stack([np.frombuffer(b, dtype=np.uint8)
                                  for b in blobs]))))
        nonce = self.node.runtime.file_bank.filler_cert_nonce(miner)
        sig = self.account_key.sign(
            FileBank.FILLER_CERT_CONTEXT
            + codec.encode((miner, tuple(hashes), nonce)))
        return hashes, tags, sig

    # -- proof verification ----------------------------------------------------
    def on_block(self, node: Node) -> None:
        rt = node.runtime
        missions = rt.state.get("audit", "unverify", self.controller,
                                default=())
        ch = rt.audit.challenge()
        if not missions or ch is None:
            return
        seed = b"".join(ch.net.randoms)
        # challenge derivation is round-constant: hoist out of _verify
        idx, nu = podr2.gen_challenge(seed, self.blocks)
        for mission in missions:
            if (mission.miner, ch.start) in self._submitted:
                continue  # result already queued, not yet applied
            snap = mission.snapshot   # owed sets frozen at round start
            with trace.span("offchain.verify", sys="offchain",
                            tee=self.controller, miner=mission.miner,
                            round=ch.start) as vspan:
                service_ok = self._verify(mission.service_proof,
                                          list(snap.service_frags), seed,
                                          idx, nu)
                idle_ok = self._verify(mission.idle_proof,
                                       list(snap.fillers), seed, idx, nu)
                vspan.set(service_ok=service_ok, idle_ok=idle_ok)
                self._submitted.add((mission.miner, ch.start))
                bls_sig = b""
                if self.bls_sk is not None:
                    from ..chain import audit as audit_mod
                    bls_sig = bls12381.sign(
                        self.bls_sk, audit_mod.verdict_message(
                            self.controller,
                            audit_mod.mission_digest(mission),
                            idle_ok, service_ok))
                node.submit_extrinsic(self.controller,
                                      "audit.submit_verify_result",
                                      mission.miner, idle_ok, service_ok,
                                      bls_sig)
                # custody verdict: the frozen owed set is exactly the
                # fragment list the audit outcome covers
                _flight.note("custody", "verdict", miner=mission.miner,
                             round=ch.start, service=service_ok,
                             idle=idle_ok, frags=snap.service_frags)

    def _verify(self, blob, owed: list[bytes], seed: bytes,
                idx, nu) -> bool:
        """Decode the (untrusted) aggregated proof bytes and check them
        against the snapshot owed set — the miner proves exactly its
        obligations, or fails. Malformed bytes are a failed audit,
        never an exception."""
        try:
            proof = codec.decode(blob)
        except (codec.CodecError, TypeError, ValueError):
            return False
        if not (isinstance(proof, Proof) and isinstance(proof.mu, np.ndarray)
                and proof.mu.shape == (podr2.SECTORS,)
                and proof.mu.dtype == np.uint32
                and isinstance(proof.sigma, np.ndarray)
                and proof.sigma.shape == (self.key.limbs,)
                and proof.sigma.dtype == np.uint32
                and bool((proof.sigma < pf.P).all())):
            return False
        if not owed:
            return not proof.sigma.any() and not proof.mu.any()
        ids = np.stack([podr2.fragment_id_from_hash(h) for h in owed])
        r = podr2.aggregate_coeffs(seed, ids)
        # getattr: tests construct partial TeeAgents via __new__
        engine = getattr(self, "engine", None)
        if engine is not None and engine.audit is not None:
            return engine.verify_aggregate(
                ids, self.blocks, np.asarray(idx), np.asarray(nu),
                np.asarray(r), np.asarray(proof.mu),
                np.asarray(proof.sigma, dtype=np.uint32),
                tenant=self.controller)
        ok = podr2.verify_aggregate(self.key, jnp.asarray(ids), self.blocks,
                                    idx, nu, r,
                                    jnp.asarray(proof.mu),
                                    jnp.asarray(proof.sigma, dtype=jnp.uint32))
        return bool(np.asarray(ok))


class ValidatorOcw:
    """The audit offchain worker (audit lib.rs:347-369). Holds the
    validator's session SIGNING key: proposals carry an ed25519
    signature over the snapshot digest, verified on chain against the
    session-key registry (the reference's validate_unsigned,
    lib.rs:739-772)."""

    def __init__(self, account: str, session_key):
        self.account = account
        self.session_key = session_key
        self._proposed_at: int = -1
        self._mined_era: int = -1

    def on_block(self, node: Node) -> None:
        self._maybe_propose_challenge(node)
        self._maybe_mine_election(node)

    def _maybe_propose_challenge(self, node: Node) -> None:
        from ..chain.audit import SESSION_SIGNING_CONTEXT, Audit

        rt = node.runtime
        if self.account not in rt.audit.keys():
            return
        if rt.audit.challenge() is not None:
            return
        if rt.state.block == self._proposed_at:
            return
        net, miners = rt.audit.generation_challenge()
        if not miners:
            return
        digest = Audit.snapshot_digest(net, miners)
        sig = self.session_key.sign(SESSION_SIGNING_CONTEXT + digest)
        node.submit_extrinsic(self.account, "audit.save_challenge_info",
                              net, miners, sig)
        self._proposed_at = rt.state.block

    def _maybe_mine_election(self, node: Node) -> None:
        """The reference's unsigned election phase (lib.rs:834-863):
        during the OCW window each validator mines a solution locally
        and submits it feeless; on-chain admission verifies the
        session signature and the exact score (election.py)."""
        from .consensus import elect_validators

        rt = node.runtime
        el = rt.election
        era = rt.state.block // el.era_blocks
        if not el.in_unsigned_phase() or era == self._mined_era:
            return
        if self.account not in rt.staking.validators():
            return
        # mine over the SAME stake-bounded snapshot admission verifies
        # against (election._candidates) — the full roster would pick
        # out-of-snapshot validators and every submission would bounce
        # (review-caught)
        stakes = el._candidates()
        credits = rt.credit.credits()
        maxv = el.max_validators or rt.config.max_validators
        solution = elect_validators(stakes, credits, maxv)
        if not solution:
            return
        from ..chain.election import score_of

        score = score_of(solution, stakes, credits)
        queued = rt.state.get("election", "best_unsigned", default=None)
        if queued is not None and queued[2] >= score:
            self._mined_era = era       # someone already queued as good
            return
        sig = self.session_key.sign(
            el.unsigned_payload(tuple(solution), score, self.account))
        try:
            node.submit_extrinsic(self.account,
                                  "election.submit_unsigned",
                                  tuple(solution), score, sig)
        except DispatchError:
            pass   # raced by a peer's equal solution: fine
        self._mined_era = era
