"""Observability: Prometheus-style metrics + structured block logs.

The reference threads a Prometheus registry through tx-pool, consensus
and RPC and streams telemetry
(/root/reference/node/src/service.rs:109-151,227-234). Here the same
operational signals, framework-native:

- ``render_metrics(node)``: Prometheus text exposition of chain
  height / finality / tx pool / storage economy / audit state —
  served at ``GET /metrics`` by the RPC server and by the TCP
  service's status surface.
- ``BlockLogger``: structured per-block JSON lines (the
  ``log::info!`` + telemetry analog), attachable as an offchain
  agent.
"""
from __future__ import annotations

import json
import sys
import time


def collect(node) -> dict[str, float]:
    rt = node.runtime
    st = rt.state
    ch = rt.audit.challenge()
    m = {
        "cess_block_height": node.head().number,
        "cess_finalized_height": node.finalized,
        "cess_tx_pool_size": len(node.tx_pool),
        "cess_known_blocks": len(node.headers),
        "cess_authorities": len(node.authorities),
        "cess_spec_version": st.get("system", "spec_version", default=0),
        "cess_era": rt.staking.current_era(),
        "cess_total_idle_space_bytes":
            rt.storage_handler.total_idle_space(),
        "cess_total_service_space_bytes":
            rt.storage_handler.total_service_space(),
        "cess_miner_count": st.count_prefix("sminer", "miner"),
        "cess_tee_worker_count": st.count_prefix("tee_worker", "worker"),
        "cess_challenge_active": 0 if ch is None else 1,
        "cess_challenge_pending_miners":
            0 if ch is None else len(ch.miners),
    }
    # event-derived counters over the retained history window
    verifies = st.events_of("audit", "VerifyResult")
    m["cess_audit_pass_total"] = sum(
        1 for e in verifies
        if dict(e.data).get("idle") and dict(e.data).get("service"))
    m["cess_audit_fail_total"] = len(verifies) - m["cess_audit_pass_total"]
    m["cess_offences_total"] = len(st.events_of("offences"))
    m["cess_extrinsic_failed_total"] = len(
        st.events_of("system", "ExtrinsicFailed"))
    # submission-engine counters (cess_tpu/serve): queue depth, batch
    # occupancy, pad waste, latency percentiles per op class — merged
    # into the same exposition when a node has an engine attached
    engine = getattr(node, "engine", None)
    if engine is not None:
        m.update(engine.stats_metrics())
    return m


def render_metrics(node) -> str:
    """Prometheus text exposition format 0.0.4."""
    lines = []
    for name, value in sorted(collect(node).items()):
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"


class TelemetryStream:
    """Push telemetry to an external endpoint (the reference's
    telemetry worker streaming to telemetry.polkadot.io-style
    collectors, /root/reference/node/src/service.rs:227-234): one JSON
    line per imported block over a persistent TCP connection to
    ``host:port``.

    Connection failures NEVER affect the node: on_block only enqueues
    into a bounded queue; ALL network IO (blocking connects to
    firewalled hosts included — a 1 s SYN timeout on the import thread
    would eat the slot budget, review-caught) runs on a dedicated
    sender thread, and a full queue drops the oldest records."""

    RECONNECT_COOLDOWN = 5.0
    QUEUE_CAP = 256

    def __init__(self, endpoint: str):
        import queue
        import threading

        host, _, port = endpoint.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        self._q: "queue.Queue[dict | None]" = queue.Queue(self.QUEUE_CAP)
        self._sock = None
        self._next_try = 0.0
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def on_block(self, node) -> None:
        head = node.head()
        rec = {
            "ts": round(time.time(), 3),
            "node": node.name,
            "chain": node.spec.chain_id,
            "best": head.number,
            "best_hash": head.hash().hex(),
            "finalized": node.finalized,
            "txcount": len(node.tx_pool),
            "authorities": len(node.authorities),
            "version": _spec_version(node),
        }
        import queue

        try:
            self._q.put_nowait(rec)
        except queue.Full:
            try:                       # drop the OLDEST, keep current
                self._q.get_nowait()
                self._q.put_nowait(rec)
            except queue.Empty:
                pass

    # -- sender thread -------------------------------------------------------
    def _run(self) -> None:
        while True:
            rec = self._q.get()
            if rec is None:
                return
            sock = self._connect()
            if sock is None:
                continue               # endpoint down: record dropped
            try:
                sock.sendall((json.dumps(rec) + "\n").encode())
            except OSError:
                self._drop_conn()

    def _connect(self):
        import socket

        now = time.time()
        if self._sock is None and now >= self._next_try:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=1.0)
            except OSError:
                self._next_try = now + self.RECONNECT_COOLDOWN
        return self._sock

    def _drop_conn(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._next_try = time.time() + self.RECONNECT_COOLDOWN

    def close(self, timeout: float = 2.0) -> None:
        """Flush queued records (best effort) and stop the sender."""
        import queue

        try:
            self._q.put(None, timeout=timeout)
        except queue.Full:
            pass
        self._worker.join(timeout=timeout)
        self._drop_conn()
        self._next_try = 0.0


def _spec_version(node) -> int:
    from ..chain import migrations

    return migrations.spec_version(node.runtime.state)


class BlockLogger:
    """Offchain-agent-shaped structured logger: one JSON line per
    imported/authored block (height, hash, author, events, pool)."""

    def __init__(self, stream=None):
        self.stream = stream or sys.stderr

    def on_block(self, node) -> None:
        head = node.head()
        rec = {
            "ts": round(time.time(), 3),
            "node": node.name,
            "block": head.number,
            "hash": head.hash().hex()[:16],
            "author": head.author,
            "finalized": node.finalized,
            "events": len(node.runtime.state.events),
            "tx_pool": len(node.tx_pool),
        }
        print(json.dumps(rec), file=self.stream, flush=True)
