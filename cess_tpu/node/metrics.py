"""Observability: Prometheus-style metrics + structured block logs.

The reference threads a Prometheus registry through tx-pool, consensus
and RPC and streams telemetry
(/root/reference/node/src/service.rs:109-151,227-234). Here the same
operational signals, framework-native:

- ``render_metrics(node)``: Prometheus text exposition of chain
  height / finality / tx pool / storage economy / audit state —
  served at ``GET /metrics`` by the RPC server and by the TCP
  service's status surface.
- ``BlockLogger``: structured per-block JSON lines (the
  ``log::info!`` + telemetry analog), attachable as an offchain
  agent.
"""
from __future__ import annotations

import json
import sys
import time


def collect(node) -> dict[str, float]:
    rt = node.runtime
    st = rt.state
    ch = rt.audit.challenge()
    m = {
        "cess_block_height": node.head().number,
        "cess_finalized_height": node.finalized,
        "cess_tx_pool_size": len(node.tx_pool),
        "cess_known_blocks": len(node.headers),
        "cess_authorities": len(node.authorities),
        "cess_spec_version": st.get("system", "spec_version", default=0),
        "cess_era": rt.staking.current_era(),
        "cess_total_idle_space_bytes":
            rt.storage_handler.total_idle_space(),
        "cess_total_service_space_bytes":
            rt.storage_handler.total_service_space(),
        "cess_miner_count": st.count_prefix("sminer", "miner"),
        "cess_tee_worker_count": st.count_prefix("tee_worker", "worker"),
        "cess_challenge_active": 0 if ch is None else 1,
        "cess_challenge_pending_miners":
            0 if ch is None else len(ch.miners),
    }
    # event-derived counters over the retained history window
    verifies = st.events_of("audit", "VerifyResult")
    m["cess_audit_pass_total"] = sum(
        1 for e in verifies
        if dict(e.data).get("idle") and dict(e.data).get("service"))
    m["cess_audit_fail_total"] = len(verifies) - m["cess_audit_pass_total"]
    m["cess_offences_total"] = len(st.events_of("offences"))
    m["cess_extrinsic_failed_total"] = len(
        st.events_of("system", "ExtrinsicFailed"))
    # submission-engine counters (cess_tpu/serve): queue depth, batch
    # occupancy, pad waste, latency percentiles per op class — merged
    # into the same exposition when a node has an engine attached
    engine = getattr(node, "engine", None)
    if engine is not None:
        m.update(engine.stats_metrics())
    # telemetry-stream delivery counters (satellite: drops and sends
    # were previously silent — a dead collector looked identical to a
    # healthy one from the node's own metrics)
    for agent in getattr(node, "offchain_agents", ()):
        counters = getattr(agent, "telemetry_counters", None)
        if callable(counters):
            m.update(counters())
    # tracer ring-buffer evictions (ISSUE 6 satellite): a wrapped span
    # ring silently turned exports into a window — now the drop count
    # rides the scrape beside everything else
    tracer = _node_tracer(node)
    if tracer is not None:
        m["cess_trace_spans_dropped_total"] = float(tracer.dropped)
    # chain-plane observability gauges (obs/chainwatch.py): finality
    # lag / reorg / equivocation / market-ledger health when a
    # ChainWatch plane is armed (node.cli --chainwatch)
    chainwatch = getattr(node, "chainwatch", None)
    if chainwatch is not None:
        m.update(chainwatch.metrics())
    # remediation-plane gauges (serve/remediate.py): policy fires,
    # suppressions, live engagements, flaps when a RemediationPlane is
    # armed (node.cli --remediate)
    remediation = getattr(node, "remediation", None)
    if remediation is not None:
        m.update(remediation.metrics())
    # durability-plane gauges (obs/custody.py): ledger sizes, the
    # erasure-margin minimum + histogram, at-risk/lost counts when a
    # CustodyPlane is armed (node.cli --custody)
    custody = getattr(node, "custody", None)
    if custody is not None:
        m.update(custody.metrics())
    return m


def _node_tracer(node):
    """The tracer whose counters this node's scrape reports: the
    node-pinned one (node.cli --trace), else the process-armed tracer,
    else None (same resolution order as the cess_traceDump RPC)."""
    from ..obs import trace

    tracer = getattr(node, "tracer", None)
    return tracer if tracer is not None else trace.armed_tracer()


def render_metrics(node) -> str:
    """Prometheus text exposition format 0.0.4.

    TYPE lines are per-family and honest: monotonic ``*_total`` series
    declare ``counter`` (they used to claim ``gauge``, which breaks
    rate() semantics downstream), latency families from the engine
    render as real cumulative ``histogram`` buckets
    (``_bucket{le=...}``/``_sum``/``_count``), everything else stays
    ``gauge``. Labeled families (the ``cess_slo_*`` per-class gauges
    and ``cess_tenant_*`` series from an SLO board) render with
    escaped label values and exactly ONE TYPE line per family, however
    many label sets it carries. tests/test_metrics.py round-trips this
    output."""
    from ..obs import prom

    lines = []
    for name, value in sorted(collect(node).items()):
        kind = "counter" if name.endswith("_total") else "gauge"
        lines.append(f"# TYPE {name} {kind}")
        lines.append(f"{name} {value}")
    # build-info gauge (standard Prometheus practice): constant 1 with
    # the identifying facts as labels — joinable against every other
    # family, and MetricFederator relabels it like any other series
    info_labels = {"instance": node.name,
                   "version": str(_spec_version(node))}
    lines.append("# TYPE cess_build_info gauge")
    lines.append(f"cess_build_info{prom.format_labels(info_labels)} 1")
    engine = getattr(node, "engine", None)
    if engine is not None:
        for family, hist in sorted(engine.stats_histograms().items()):
            lines.extend(prom.render_histogram(family, hist))
        # labeled gauge/counter families (SLO board): group by family
        # so the TYPE line appears once, then every label set
        declared = set()
        # stable-sorted by family: the exposition format wants every
        # line of a family in one contiguous group
        for family, kind, labels, value in sorted(
                engine.labeled_series(), key=lambda s: s[0]):
            if family not in declared:
                declared.add(family)
                lines.append(f"# TYPE {family} {kind}")
            lines.append(f"{family}{prom.format_labels(labels)} {value}")
        # labeled histogram families (per-tenant latency): same
        # one-TYPE-line discipline across label sets
        hist_declared = set()
        for family, labels, hist in engine.labeled_histograms():
            lines.extend(prom.render_histogram(
                family, hist, labels=labels,
                type_line=family not in hist_declared))
            hist_declared.add(family)
    return "\n".join(lines) + "\n"


class TelemetryStream:
    """Push telemetry to an external endpoint (the reference's
    telemetry worker streaming to telemetry.polkadot.io-style
    collectors, /root/reference/node/src/service.rs:227-234): one JSON
    line per imported block over a persistent TCP connection to
    ``host:port``.

    Connection failures NEVER affect the node: on_block only enqueues
    into a bounded queue; ALL network IO (blocking connects to
    firewalled hosts included — a 1 s SYN timeout on the import thread
    would eat the slot budget, review-caught) runs on a dedicated
    sender thread, and a full queue drops the oldest records.

    Delivery is COUNTED, not silent: every record that reaches the
    endpoint increments ``sent``, every record lost (queue overflow,
    endpoint down, broken connection) increments ``dropped``, and both
    ride the /metrics exposition as ``cess_telemetry_sent_total`` /
    ``cess_telemetry_dropped_total`` — so a dead collector is visible
    from the node's own scrape. With a tracer armed
    (cess_tpu/obs), each record also carries the session trace id, so
    an external collector's rows can be joined against a trace dump."""

    RECONNECT_COOLDOWN = 5.0
    QUEUE_CAP = 256

    def __init__(self, endpoint: str):
        import queue
        import threading

        host, _, port = endpoint.rpartition(":")
        self.host = host or "127.0.0.1"
        self.port = int(port)
        # delivery counters, single-writer each so no lock is needed:
        # sent/dropped belong to the sender thread, overflow drops to
        # the import thread (a shared `+= 1` from both threads is a
        # read-modify-write race that loses counts under GIL
        # preemption); scrapes sum them read-only
        self.sent = 0
        self.dropped = 0
        self._overflow_dropped = 0
        self._q: "queue.Queue[dict | None]" = queue.Queue(self.QUEUE_CAP)
        self._sock = None
        self._next_try = 0.0
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def telemetry_counters(self) -> dict[str, float]:
        """Merged into the node /metrics exposition (collect())."""
        return {"cess_telemetry_sent_total": float(self.sent),
                "cess_telemetry_dropped_total":
                    float(self.dropped + self._overflow_dropped)}

    def on_block(self, node) -> None:
        head = node.head()
        rec = {
            "ts": round(time.time(), 3),
            "node": node.name,
            "chain": node.spec.chain_id,
            "best": head.number,
            "best_hash": head.hash().hex(),
            "finalized": node.finalized,
            "txcount": len(node.tx_pool),
            "authorities": len(node.authorities),
            "version": _spec_version(node),
        }
        _stamp_trace(rec)
        import queue

        try:
            self._q.put_nowait(rec)
        except queue.Full:
            try:                       # drop the OLDEST, keep current
                self._q.get_nowait()
                self._overflow_dropped += 1
                self._q.put_nowait(rec)
            except queue.Empty:
                pass

    # -- sender thread -------------------------------------------------------
    def _run(self) -> None:
        while True:
            rec = self._q.get()
            if rec is None:
                # the worker owns the socket exclusively: tear it
                # down HERE, not in close() — a join timeout must
                # never leave two threads touching _sock/_next_try
                self._drop_conn()
                return
            sock = self._connect()
            if sock is None:
                self.dropped += 1      # endpoint down: record dropped
                continue
            try:
                sock.sendall((json.dumps(rec) + "\n").encode())
                self.sent += 1
            except OSError:
                self.dropped += 1
                self._drop_conn()

    def _connect(self):
        import socket

        now = time.time()
        if self._sock is None and now >= self._next_try:
            try:
                self._sock = socket.create_connection(
                    (self.host, self.port), timeout=1.0)
            except OSError:
                self._next_try = now + self.RECONNECT_COOLDOWN
        return self._sock

    def _drop_conn(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        self._next_try = time.time() + self.RECONNECT_COOLDOWN

    def close(self, timeout: float = 2.0) -> None:
        """Flush queued records (best effort) and stop the sender."""
        import queue

        try:
            self._q.put(None, timeout=timeout)
        except queue.Full:
            pass
        self._worker.join(timeout=timeout)


def _spec_version(node) -> int:
    from ..chain import migrations

    return migrations.spec_version(node.runtime.state)


def _stamp_trace(rec: dict) -> None:
    """With a tracer armed (cess_tpu/obs), stamp the record with the
    trace id its head block was imported under, so telemetry rows and
    block logs join against an exported trace dump. No-op otherwise."""
    from ..obs import trace

    tracer = trace.armed_tracer()
    if tracer is not None:
        rec["trace_id"] = tracer.trace_id


class BlockLogger:
    """Offchain-agent-shaped structured logger: one JSON line per
    imported/authored block (height, hash, author, events, pool)."""

    def __init__(self, stream=None):
        self.stream = stream or sys.stderr

    def on_block(self, node) -> None:
        head = node.head()
        rec = {
            "ts": round(time.time(), 3),
            "node": node.name,
            "block": head.number,
            "hash": head.hash().hex()[:16],
            "author": head.author,
            "finalized": node.finalized,
            "events": len(node.runtime.state.events),
            "tx_pool": len(node.tx_pool),
        }
        _stamp_trace(rec)
        print(json.dumps(rec), file=self.stream, flush=True)
