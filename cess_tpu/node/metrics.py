"""Observability: Prometheus-style metrics + structured block logs.

The reference threads a Prometheus registry through tx-pool, consensus
and RPC and streams telemetry
(/root/reference/node/src/service.rs:109-151,227-234). Here the same
operational signals, framework-native:

- ``render_metrics(node)``: Prometheus text exposition of chain
  height / finality / tx pool / storage economy / audit state —
  served at ``GET /metrics`` by the RPC server and by the TCP
  service's status surface.
- ``BlockLogger``: structured per-block JSON lines (the
  ``log::info!`` + telemetry analog), attachable as an offchain
  agent.
"""
from __future__ import annotations

import json
import sys
import time


def collect(node) -> dict[str, float]:
    rt = node.runtime
    st = rt.state
    ch = rt.audit.challenge()
    m = {
        "cess_block_height": node.head().number,
        "cess_finalized_height": node.finalized,
        "cess_tx_pool_size": len(node.tx_pool),
        "cess_known_blocks": len(node.headers),
        "cess_authorities": len(node.authorities),
        "cess_spec_version": st.get("system", "spec_version", default=0),
        "cess_era": rt.staking.current_era(),
        "cess_total_idle_space_bytes":
            rt.storage_handler.total_idle_space(),
        "cess_total_service_space_bytes":
            rt.storage_handler.total_service_space(),
        "cess_miner_count": st.count_prefix("sminer", "miner"),
        "cess_tee_worker_count": st.count_prefix("tee_worker", "worker"),
        "cess_challenge_active": 0 if ch is None else 1,
        "cess_challenge_pending_miners":
            0 if ch is None else len(ch.miners),
    }
    # event-derived counters over the retained history window
    verifies = st.events_of("audit", "VerifyResult")
    m["cess_audit_pass_total"] = sum(
        1 for e in verifies
        if dict(e.data).get("idle") and dict(e.data).get("service"))
    m["cess_audit_fail_total"] = len(verifies) - m["cess_audit_pass_total"]
    m["cess_offences_total"] = len(st.events_of("offences"))
    m["cess_extrinsic_failed_total"] = len(
        st.events_of("system", "ExtrinsicFailed"))
    return m


def render_metrics(node) -> str:
    """Prometheus text exposition format 0.0.4."""
    lines = []
    for name, value in sorted(collect(node).items()):
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {value}")
    return "\n".join(lines) + "\n"


class BlockLogger:
    """Offchain-agent-shaped structured logger: one JSON line per
    imported/authored block (height, hash, author, events, pool)."""

    def __init__(self, stream=None):
        self.stream = stream or sys.stderr

    def on_block(self, node) -> None:
        head = node.head()
        rec = {
            "ts": round(time.time(), 3),
            "node": node.name,
            "block": head.number,
            "hash": head.hash().hex()[:16],
            "author": head.author,
            "finalized": node.finalized,
            "events": len(node.runtime.state.events),
            "tx_pool": len(node.tx_pool),
        }
        print(json.dumps(rec), file=self.stream, flush=True)
