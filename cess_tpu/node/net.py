"""TCP gossip transport: nodes as separate OS processes.

The reference's node talks libp2p — block announcement, tx
propagation, GRANDPA vote gossip, and catch-up sync between processes
(/root/reference/node/src/service.rs:259-274,508-537). This module is
the framework-native equivalent over plain TCP: length-prefixed
canonical-codec frames carrying (msg_type, payload) tuples,
bounded-degree peering, flood gossip with a generational seen-set, and
a walk-back sync request for missed blocks. The in-process ``Network``
driver and this transport run the SAME ``Node``: consensus, fork
choice and finality live in the node; this layer only moves bytes.

Topology is degree-limited (the libp2p role, service.rs:259-274):
each node dials its ``degree//2`` ring successors in sorted port
order (deterministic, so the union graph is a connected ring with
chords), accepts at most ``degree`` inbound connections, and every
connection owns a bounded outbound queue drained by a dedicated
sender thread — a stalled peer socket fills its queue and gets
dropped; it can never wedge the node lock shared with authoring/RPC.

Fault injection (``FaultPolicy``) drops or reorders outbound messages
deterministically — the gossip layer must converge anyway via sync
requests (tested in tests/test_net.py with real processes).

Wire frame: [4-byte LE length][codec bytes]; payload tuples:
  ("tx", SignedExtrinsic)          tx propagation
  ("block", Block)                 block announcement (body included)
  ("vote", Vote)                   finality vote gossip
  ("status", (head_n, head_hash, finalized))  keepalive / sync trigger
  ("sync_request", from_number)    catch-up ask
  ("sync_response", (Block, ...))  canonical tail (capped)
  ("just", Justification)         finality proof propagation
  ("warp_request", 0)              checkpoint-sync ask (fresh nodes)
  ("warp_response", (snapshot_payload_bytes, Justification))
                                   snapshot + finality countersignatures,
                                   verified against the GENESIS-derived
                                   authority set (never the snapshot's
                                   own), and only accepted while a
                                   warp_request is outstanding on the
                                   same connection
  ("peers", (port, ...))           peer exchange (discovery): each side
                                   shares its known listen ports; the
                                   ring-successor rule picks which get
                                   dialed
  ("contact", Contact)             DHT bootstrap: advertises this
                                   node's (gossip_port, dht_port) to
                                   seed routing tables
  ("traced", (trace_id, span_id, inner_frame))
                                   trace envelope (cess_tpu/obs): only
                                   emitted while a tracer is armed;
                                   receivers unwrap and handle the
                                   inner frame under a net.recv span
                                   that joins the sender's distributed
                                   trace (gossip dedup keys on the
                                   INNER frame, so the span context
                                   never splits the seen-set)
  ("fleet", (instance, exposition, slo_json))
                                   fleet observability gossip
                                   (obs/fleet.py): only emitted while
                                   a fleet plane is armed (node.cli
                                   --fleet), every FLEET_EVERY slots;
                                   receivers with a plane buffer the
                                   peer's scrape for their next round,
                                   everyone else drops it. Never
                                   re-gossiped. With a chain watch
                                   armed (node.cli --chainwatch) the
                                   frame's slo dict also carries the
                                   sender's consensus state under a
                                   "chain" key (obs/chainwatch.py) —
                                   chain health rides the SAME gossip,
                                   no extra frame kind.

Authority discovery is STRUCTURED (cess_tpu/node/dht.py): a Kademlia
DHT on a second OS-assigned port answers single-shot find_node /
find_value / store RPCs; validators periodically publish
session-key-signed address records keyed by authority id, and
``discover_authority`` resolves any authority in O(log n) routed
lookups without flooding — the reference's authority-discovery worker
over libp2p Kademlia (service.rs:508-537).
"""
from __future__ import annotations

import dataclasses
import queue
import socket
import struct
import threading
import time

from .. import codec
from ..chain.state import DispatchError
from ..crypto import ed25519
from ..obs import trace as obs_trace
from ..resilience import faults
from . import dht as dht_mod

_LEN = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024
SYNC_BATCH = 64
SYNC_LOOKBACK = 8   # re-request a short tail to cover small forks
WARP_THRESHOLD = 50  # finalized blocks behind which a fresh node warps
SEEN_CAP = 8192      # generational dedup-set rotation threshold
ERRORS_CAP = 256
SEND_QUEUE_CAP = 256    # outbound frames buffered per connection
SEND_TIMEOUT = 5.0      # stalled-socket kill switch (seconds)
FLEET_EVERY = 4         # slots between fleet scrape gossip rounds


@dataclasses.dataclass
class FaultPolicy:
    """Deterministic outbound faults for tests: drop every Nth
    message, optionally delay each send."""

    drop_every: int = 0     # 0 = never drop
    delay_s: float = 0.0
    _counter: int = 0

    def allow(self) -> bool:
        self._counter += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        return not (self.drop_every and self._counter % self.drop_every == 0)


class _Conn:
    """One TCP connection with a bounded outbound queue drained by its
    own sender thread. ``send`` never blocks the caller: a full queue
    (stalled peer) drops the frame; a send stalled past SEND_TIMEOUT
    kills the connection."""

    def __init__(self, sock: socket.socket, inbound: bool = False):
        self.sock = sock
        self.alive = True
        self.inbound = inbound
        self.warp_requested = False   # gate for warp_response acceptance
        self.dropped = 0
        self.rx = 0                   # frames received (dial liveness)
        self._q: queue.Queue[bytes | None] = queue.Queue(SEND_QUEUE_CAP)
        self._sender = threading.Thread(target=self._drain, daemon=True)
        self._sender.start()

    def send(self, raw: bytes) -> None:
        if not self.alive:
            return
        try:
            self._q.put_nowait(_LEN.pack(len(raw)) + raw)
        except queue.Full:
            self.dropped += 1   # overflow drop: slow peer loses frames

    def _drain(self) -> None:
        # send-ONLY stall timeout: settimeout() would poison the recv
        # side of the shared socket (recv must block indefinitely on an
        # idle link), so arm SO_SNDTIMEO for the kernel send path alone
        try:
            self.sock.setsockopt(
                socket.SOL_SOCKET, socket.SO_SNDTIMEO,
                struct.pack("ll", int(SEND_TIMEOUT),
                            int(SEND_TIMEOUT % 1 * 1_000_000)))
        except OSError:
            pass   # platform without SO_SNDTIMEO: bounded queue still caps
        while True:
            frame = self._q.get()
            if frame is None or not self.alive:
                return
            try:
                self.sock.sendall(frame)
            except (OSError, ValueError):
                self.close()
                return

    def close(self) -> None:
        # one-shot monotonic bool: both the drain thread (send error)
        # and external callers only ever store False, a single
        # GIL-atomic write with no read-modify-write — a lock would
        # buy nothing (pinned by tests/test_lint.py)
        # cesslint: disable=race
        self.alive = False
        try:
            self._q.put_nowait(None)   # unblock the sender thread
        except queue.Full:
            pass
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def _read_frame(sock: socket.socket) -> bytes | None:
    head = b""
    while len(head) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        return None
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class NodeService:
    """One node process: TCP listener + outbound peers + slot-timed
    authoring loop, all feeding a single Node under one lock."""

    def __init__(self, node, port: int, peers: list[int],
                 host: str = "127.0.0.1", slot_time: float = 0.2,
                 genesis_time: float = 0.0,
                 faults: FaultPolicy | None = None,
                 degree: int = 8, discovery_interval: float = 0.25):
        self.node = node
        # discovery runs as its OWN schedulable loop at this cadence
        # (not piggybacked on authoring slots): mesh formation then
        # converges in a bounded number of rounds regardless of slot
        # timing or host load — the seam the deterministic chain-
        # topology test (tests/test_net.py) drives
        self.discovery_interval = discovery_interval
        # all processes must agree on slot numbering (slot is signed
        # into VRF claims and drives epoch derivation): slots count
        # from a SHARED genesis wall-clock instant, not process start
        self.genesis_time = genesis_time
        self.host = host
        self.port = port
        self.peer_ports = peers
        self.slot_time = slot_time
        self.faults = faults
        self.degree = max(2, degree)
        self.lock = threading.RLock()
        self.conns: list[_Conn] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        # gossip dedup: generational pair of sets — membership checks
        # both, inserts go to the young set, rotation at SEEN_CAP keeps
        # memory bounded on a long-running node
        self._seen: set[bytes] = set()
        self._seen_old: set[bytes] = set()
        # peer-exchange state lives here (NOT start()): inbound frames
        # can arrive before start() finishes its own assignments
        self._known_peers: set[int] = set(peers)
        self._dialing: set[int] = set()
        # dead-peer cooling: a port that keeps failing is excluded from
        # ring-successor selection until its retry time, so the ring
        # SLIDES past crashed nodes instead of letting dead runs
        # partition the gossip graph (full-mesh robustness, kept)
        self._cooling: dict[int, float] = {}
        self.max_peers = 64   # discovery cap: bounds the learned set
        self.errors: list[str] = []      # swallowed faults, for tests/ops
        self.msgs_sent = 0               # transport telemetry (tests)
        self._warp_tries = 0
        self._warp_backoff = 0.0
        self._listener: socket.socket | None = None
        # authority discovery: Kademlia DHT on a second, OS-assigned
        # port (service.rs:508-537 role); wired up in start()
        self.dht_port = 0
        self.kad: dht_mod.Kademlia | None = None
        self._dht_listener: socket.socket | None = None
        self._publish_serial = 0
        self._next_publish = 0.0
        self._next_dht_maint = 0.0

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self.port))
        srv.listen(16)
        self._listener = srv
        # DHT RPC listener: OS-assigned port, advertised via the
        # "contact" frame and inside signed authority records
        dsrv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        dsrv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        dsrv.bind((self.host, 0))
        dsrv.listen(16)
        self._dht_listener = dsrv
        self.dht_port = dsrv.getsockname()[1]
        self.kad = dht_mod.Kademlia(
            dht_mod.Contact(port=self.port, dht_port=self.dht_port),
            self._verify_record)
        self._spawn(self._dht_accept_loop, dsrv)
        self._spawn(self._accept_loop, srv)
        self._redial()
        self._spawn(self._discovery_loop)
        self._spawn(self._author_loop)

    def _dial_targets(self) -> list[int]:
        """Ring-successor selection: the ``degree//2`` known LIVE ports
        that cyclically follow our own in sorted order (ports in their
        cooling window after repeated failures are skipped, so the
        ring advances past dead nodes). Every node dialing its
        successors yields a connected ring with chords at bounded
        per-node degree (out = degree//2, in <= degree//2 + slack
        under the same rule) — the structured-discovery stand-in for
        the reference's Kademlia DHT (service.rs:508-537)."""
        now = time.time()
        with self.lock:
            for p, until in list(self._cooling.items()):
                if now >= until:
                    del self._cooling[p]
            known = sorted(p for p in self._known_peers
                           if p != self.port and p not in self._cooling)
        if not known:
            return []
        d = max(1, self.degree // 2)
        after = [p for p in known if p > self.port]
        ring = after + [p for p in known if p < self.port]
        return ring[:d]

    def _redial(self) -> None:
        for p in self._dial_targets():
            with self.lock:
                if p in self._dialing:
                    continue
                self._dialing.add(p)
            self._spawn(self._dial_loop, p)

    def _discover(self, ports) -> None:
        """Peer exchange: learn listen ports, then let the ring rule
        decide which to dial. Bounded by max_peers — an
        unauthenticated frame must not grow state without limit."""
        for p in ports:
            if not (isinstance(p, int) and not isinstance(p, bool)
                    and 0 < p < 65536 and p != self.port):
                continue
            with self.lock:
                if len(self._known_peers) >= self.max_peers \
                        or p in self._known_peers:
                    continue
                self._known_peers.add(p)
        self._redial()

    def _discovery_loop(self) -> None:
        """The discovery round, on its own schedulable cadence: sweep
        dead-peer coolings + re-dial ring targets, and RE-ADVERTISE the
        known peer set on every live connection. Peer exchange is
        idempotent (receivers cap + dedup), so repetition turns mesh
        formation from a race against connection setup into a bounded
        number of deterministic rounds — a frame lost while a link was
        half-up is re-offered next round."""
        while not self._stop.wait(self.discovery_interval):
            self._redial()
            with self.lock:
                known = (self.port, *sorted(self._known_peers))
            for conn in list(self.conns):
                if conn.alive:
                    self._send(conn, ("peers", known))

    def stop(self) -> None:
        self._stop.set()
        for srv in (self._listener, self._dht_listener):
            if srv is not None:
                try:
                    srv.close()
                except OSError:
                    pass
        for c in list(self.conns):
            c.close()
        for t in self._threads:
            t.join(timeout=2.0)

    def _spawn(self, fn, *args) -> None:
        t = threading.Thread(target=fn, args=args, daemon=True)
        t.start()
        # prune finished threads (per-request DHT handlers and publish
        # cycles spawn continually; the join list must stay bounded);
        # the prune REBINDS the list, so an unguarded concurrent
        # append from another loop could vanish from the join list
        with self.lock:
            if len(self._threads) > 64:
                self._threads = [x for x in self._threads
                                 if x.is_alive()]
            self._threads.append(t)

    def _record_error(self, msg: str) -> None:
        # append+trim is two ops; recv loops and the author loop both
        # report here
        with self.lock:
            self.errors.append(msg)
            del self.errors[:-ERRORS_CAP]

    # -- connections --------------------------------------------------------
    def _accept_loop(self, srv: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = srv.accept()
            except OSError:
                return
            alive = [c for c in self.conns if c.alive]
            in_alive = sum(1 for c in alive if c.inbound)
            # inbound cap with ONE slack slot over the steady-state
            # in-degree (degree//2): a late joiner not yet in anyone's
            # ring must be able to land its first connection and get
            # its port gossiped — a hard cap at `degree` would lock
            # it out forever once the ring saturates. Total live
            # connections are therefore bounded by degree + 1.
            if in_alive > self.degree // 2 \
                    or len(alive) >= self.degree + 1:
                try:
                    sock.close()
                except OSError:
                    pass
                continue
            conn = _Conn(sock, inbound=True)
            self.conns.append(conn)
            self._spawn(self._recv_loop, conn)

    DIAL_FAILS_MAX = 20     # consecutive failures before cooling
    COOL_SECONDS = 5.0      # how long a dead port sits out of the ring

    def _dial_loop(self, port: int) -> None:
        """Keep one outbound connection to a ring peer alive (retry
        while it remains a ring target). A port that keeps failing —
        connect refused, or connections that die before delivering a
        single frame (e.g. a peer refusing us at its inbound cap) —
        goes into cooling and the ring re-targets around it."""
        fails = 0
        while not self._stop.is_set():
            if port not in self._dial_targets():
                with self.lock:
                    self._dialing.discard(port)
                return   # ring moved (new peers learned): stop dialing
            if fails >= self.DIAL_FAILS_MAX:
                with self.lock:
                    self._cooling[port] = time.time() + self.COOL_SECONDS
                    self._dialing.discard(port)
                self._redial()   # pick the next live successor
                return
            try:
                sock = socket.create_connection((self.host, port),
                                                timeout=2.0)
                sock.settimeout(None)
            except OSError:
                fails += 1
                # same schedulable wait seam as _discovery_loop: a
                # stop() wakes the backoff immediately instead of
                # draining a bare sleep
                if self._stop.wait(0.05):
                    return
                continue
            conn = _Conn(sock)
            self.conns.append(conn)
            self._send_status(conn)
            with self.lock:
                known = (self.port, *sorted(self._known_peers))
            self._send(conn, ("peers", known))
            if self.kad is not None:
                self._send(conn, ("contact", self.kad.self_contact))
            self._recv_loop(conn)   # blocks until closed
            if conn in self.conns:
                self.conns.remove(conn)
            fails = 0 if conn.rx else fails + 1
            if self._stop.wait(0.05):
                return

    def _recv_loop(self, conn: _Conn) -> None:
        while not self._stop.is_set() and conn.alive:
            try:
                raw = _read_frame(conn.sock)
            except OSError:
                break
            if raw is None:
                break
            conn.rx += 1
            try:
                msg = codec.decode(raw)
                self._handle(msg, conn)
            except (codec.CodecError, ValueError, DispatchError,
                    TypeError, KeyError, AttributeError, IndexError):
                # malformed or stale traffic from a peer must never
                # kill the service
                continue
        conn.close()
        if conn in self.conns:
            self.conns.remove(conn)

    # -- sending ------------------------------------------------------------
    @staticmethod
    def _envelope(msg):
        """Trace envelope (cess_tpu/obs): with a tracer armed, gossip
        frames travel as ``("traced", (trace_id, span_id, inner))`` so
        the receiving node's handling span joins the sender's
        distributed trace — a challenge -> prove -> verify round
        becomes ONE trace across nodes. With no tracer armed the frame
        is untouched (wire compatibility + zero cost)."""
        ctx = obs_trace.context()
        if ctx is None:
            return msg
        return ("traced", (ctx[0], ctx[1], msg))

    def _send(self, conn: _Conn, msg) -> None:
        if self.faults is not None and not self.faults.allow():
            return
        if not faults.allow("net.send"):
            return   # seeded chaos drop (cess_tpu/resilience/faults.py)
        with self.lock:
            self.msgs_sent += 1
        conn.send(codec.encode(self._envelope(msg)))

    def _mark_seen(self, digest: bytes) -> None:
        # the generation swap rebinds both sets; two threads swapping
        # concurrently would drop a whole dedup generation
        with self.lock:
            self._seen.add(digest)
            if len(self._seen) >= SEEN_CAP:
                self._seen_old = self._seen
                self._seen = set()

    def _was_seen(self, digest: bytes) -> bool:
        return digest in self._seen or digest in self._seen_old

    def broadcast(self, msg, mark_seen: bool = True) -> None:
        raw = codec.encode(msg)
        if mark_seen:
            import hashlib

            self._mark_seen(hashlib.sha256(raw).digest())
        env = self._envelope(msg)
        if env is not msg:
            # dedup identity stays the INNER frame (hash above) so a
            # message wrapped with different span contexts still
            # dedups; only the wire bytes carry the envelope
            raw = codec.encode(env)
        for conn in list(self.conns):
            if conn.alive:
                if self.faults is not None and not self.faults.allow():
                    continue
                if not faults.allow("net.send"):
                    continue   # seeded chaos drop, per conn like faults
                with self.lock:
                    self.msgs_sent += 1
                conn.send(raw)

    def _send_status(self, conn: _Conn) -> None:
        with self.lock:
            head = self.node.head()
            msg = ("status", (head.number, head.hash(),
                              self.node.finalized))
        self._send(conn, msg)

    # -- gossip handlers ----------------------------------------------------
    def _handle(self, msg, conn: _Conn) -> None:
        import hashlib

        kind, payload = msg
        if kind == "traced":
            # trace envelope (see _envelope): unwrap, then handle the
            # inner frame under a recv span that joins the sender's
            # trace. A node without an armed tracer just unwraps.
            remote_tid, remote_sid, inner = payload
            tracer = obs_trace.armed_tracer()
            if tracer is None:
                self._handle(inner, conn)
                return
            with tracer.start(f"net.recv:{inner[0]}", sys="net",
                              remote=(remote_tid, remote_sid),
                              current=True):
                self._handle(inner, conn)
            return
        raw_hash = hashlib.sha256(codec.encode(msg)).digest()
        if kind in ("tx", "block", "vote", "just"):
            if self._was_seen(raw_hash):
                return
            self._mark_seen(raw_hash)
        if kind == "tx":
            with self.lock:
                try:
                    self.node.submit_signed(payload)
                except DispatchError:
                    return   # invalid or duplicate: do not re-gossip
            self.broadcast(msg, mark_seen=False)
        elif kind == "block":
            ok = self._import(payload, conn)
            if ok:
                self.broadcast(msg, mark_seen=False)
                self._after_chain_move()
        elif kind == "vote":
            with self.lock:
                self.node.finality.on_vote(payload)
            self.broadcast(msg, mark_seen=False)
        elif kind == "just":
            with self.lock:
                if payload.target_number > self.node.finalized \
                        and self.node.finality.verify_justification(payload):
                    self.node.finality.justifications[payload.round] = payload
                    self.node.on_justification(payload)
        elif kind == "peers":
            if isinstance(payload, tuple):
                self._discover(payload)
        elif kind == "contact":
            # DHT bootstrap: gossip neighbors seed each other's routing
            # tables; one reciprocal reply, then the tables grow through
            # lookups (Kademlia's implicit maintenance)
            if self.kad is not None \
                    and isinstance(payload, dht_mod.Contact) \
                    and payload.port != self.port:
                self.kad.note(payload)
                if not getattr(conn, "contact_sent", False):
                    conn.contact_sent = True
                    self._send(conn, ("contact", self.kad.self_contact))
        elif kind == "fleet":
            # fleet observability gossip (obs/fleet.py): a peer's
            # scrape contribution, buffered into the local plane's
            # next round when one is armed (node.cli --fleet) —
            # one attribute load + None check otherwise. Malformed
            # payloads are dropped inside ingest_frame; never
            # re-gossiped (point-in-time data, not chain state).
            plane = getattr(self.node, "fleet", None)
            if plane is not None:
                plane.ingest_frame(payload)
            # the frame's slo dict may carry the sender's consensus
            # state under a "chain" key: hand the SAME frame to an
            # armed chain watch (obs/chainwatch.py) so peer finality
            # lag feeds the anomaly detectors too
            watch = getattr(self.node, "chainwatch", None)
            if watch is not None:
                watch.ingest_frame(payload)
        elif kind == "status":
            peer_head, _, peer_fin = payload
            now = time.time()
            offer_just = None
            with self.lock:
                ours = self.node.head().number
                warp_viable = (ours == 0 and peer_fin > WARP_THRESHOLD
                               and self._warp_tries < 3)
                fire_warp = warp_viable and now >= self._warp_backoff
                if fire_warp:
                    # one attempt per backoff window, not per status
                    # tick — a large snapshot takes time to arrive
                    self._warp_tries += 1
                    self._warp_backoff = now + 1.0
                if peer_fin < self.node.finalized:
                    # finality healing, pull side: a peer behind on
                    # finality gets our newest justification directly
                    # (it finalizes ancestors transitively)
                    offer_just = \
                        self.node.finality.newest_justification()
            if offer_just is not None:
                self._send(conn, ("just", offer_just))
            if fire_warp:
                # fresh node far behind a finalized peer: checkpoint
                # sync instead of replaying the whole chain; bounded
                # attempts then fall back to full replay sync
                conn.warp_requested = True
                self._send(conn, ("warp_request", 0))
            elif peer_head > ours and not warp_viable:
                self._send(conn, ("sync_request",
                                  max(1, ours - SYNC_LOOKBACK)))
        elif kind == "warp_request":
            from . import store as _store

            with self.lock:
                if not self.node.finality.justifications:
                    return
                rnd = max(self.node.finality.justifications)
                just = self.node.finality.justifications[rnd]
                payload_bytes = _store.snapshot_payload(self.node)
            self._send(conn, ("warp_response", (payload_bytes, just)))
        elif kind == "warp_response":
            snap_bytes, just = payload
            from .finality import Justification

            if not conn.warp_requested:
                return   # unsolicited snapshot push: refuse
            conn.warp_requested = False
            if not isinstance(snap_bytes, bytes) \
                    or not isinstance(just, Justification):
                return
            with self.lock:
                self._try_warp(snap_bytes, just)
        elif kind == "sync_request":
            with self.lock:
                blocks = []
                for n in range(payload, payload + SYNC_BATCH):
                    b = self.node.block_bodies.get(n)
                    if b is None:
                        break
                    blocks.append(b)
            if blocks:
                self._send(conn, ("sync_response", tuple(blocks)))
        elif kind == "sync_response":
            moved = False
            for b in payload:
                if self._import(b, conn):
                    moved = True
            if moved:
                self._after_chain_move()

    def _import(self, block, conn: _Conn) -> bool:
        want_sync_from = None
        with self.lock:
            try:
                self.node.import_block(block)
                return True
            except ValueError as e:
                if "unknown parent" in str(e):
                    if self.node.head().number == 0 \
                            and self._warp_tries < 3:
                        # fresh node with warp still plausible: stay
                        # quiet — the status exchange (every slot)
                        # drives checkpoint-vs-replay policy in ONE
                        # place; requesting a replay here would race
                        # the in-flight snapshot adoption
                        pass
                    else:
                        want_sync_from = max(
                            1, self.node.head().number - SYNC_LOOKBACK)
                ok = False
        # send OUTSIDE the node lock: a stalled peer must not hold it
        if want_sync_from is not None:
            self._send(conn, ("sync_request", want_sync_from))
        return ok

    def _try_warp(self, snap_bytes: bytes, just) -> bool:
        """Verify + adopt a checkpoint (caller holds the lock): the ONE
        shared trust path, store.verify_and_adopt_warp — justification
        verified against OUR genesis-derived authority set (never the
        snapshot's own), genesis-anchored header chain, state-root-
        proven KV. Fails closed (-> full replay sync) if the authority
        set has rotated since genesis."""
        from . import store as _store
        from .network import Node as _Node

        node = self.node
        return _store.verify_and_adopt_warp(
            node, snap_bytes, just,
            lambda: _Node(node.spec, f"{node.name}-warp", {}))

    def _after_chain_move(self) -> None:
        """Cast + gossip finality votes and any new justification.
        Signing happens OUTSIDE the node lock (up to VOTE_TAIL slow
        pure-python signatures after a sync batch must not stall
        recv/RPC/authoring)."""
        with self.lock:
            # a justification may have arrived before its block did;
            # now that the chain moved, act on any that became usable
            self.node.finality.apply_pending()
            jobs = self.node.finality.vote_jobs()
        votes = self.node.finality.sign_jobs(jobs)
        with self.lock:
            self.node.finality.ingest_own(votes)
            fin = self.node.finalized
            just = self.node.finality.justifications.get(fin)
        for v in votes:
            self.broadcast(("vote", v))
        if just is not None:
            self.broadcast(("just", just))

    # -- authoring ----------------------------------------------------------
    def _author_loop(self) -> None:
        """Wall-clock slots shared across processes on one host: each
        process independently computes the slot index, authors when its
        key wins, commits immediately and gossips — competing blocks
        are resolved by fork choice at import, votes settle finality."""
        last_slot = -1
        while not self._stop.is_set():
            slot = int((time.time() - self.genesis_time) / self.slot_time)
            if slot < 1:
                time.sleep(self.slot_time / 10)
                continue
            if slot == last_slot:
                time.sleep(self.slot_time / 10)
                continue
            last_slot = slot
            blk = None
            with self.lock:
                new_beats = self.node.queue_heartbeats()
                try:
                    blk = self.node.try_author(slot)
                    if blk is not None:
                        self.node.commit_proposal()
                except Exception as e:   # noqa: BLE001 — author loop must survive
                    self._record_error(f"author slot {slot}: {e!r}")
                    if self.node._proposal is not None:
                        self.node.abort_proposal()
                    blk = None
            for xt in new_beats:
                # a validator that never wins a slot still needs its
                # heartbeat IN PEERS' blocks — gossip it like any tx
                self.broadcast(("tx", xt))
            if blk is not None:
                self.broadcast(("block", blk))
                self._after_chain_move()
            for conn in list(self.conns):
                if conn.alive:
                    self._send_status(conn)
            # fleet observability (obs/fleet.py): every FLEET_EVERY
            # slots an armed plane gossips this node's scrape to
            # peers and seals a local round over whatever peers
            # gossiped in since the last one. Disarmed cost: one
            # attribute load + None check per slot.
            # chain-plane observability (obs/chainwatch.py): every
            # FLEET_EVERY slots an armed watch scans this node's own
            # chain + market state and seals a detector round (also
            # folding per-node finality lag into an attached fleet
            # plane's straggler windows). Disarmed cost: one
            # attribute load + None check per slot.
            watch = getattr(self.node, "chainwatch", None)
            if watch is not None and slot % FLEET_EVERY == 0:
                try:
                    with self.lock:
                        watch.scan_node(self.node)
                    watch.seal_round()
                except Exception as e:   # noqa: BLE001 — best-effort
                    # observability must never kill authoring
                    self._record_error(
                        f"chainwatch round slot {slot}: {e!r}")
            plane = getattr(self.node, "fleet", None)
            if plane is not None and slot % FLEET_EVERY == 0:
                try:
                    with self.lock:
                        frame = plane.self_frame()
                    if frame is not None:
                        self.broadcast(("fleet", frame), mark_seen=False)
                        plane.ingest_frame(frame)
                    plane.seal_round()
                except Exception as e:   # noqa: BLE001 — peer frames
                    # must never kill authoring (ingest validates, but
                    # the observability plane is best-effort anyway)
                    self._record_error(f"fleet round slot {slot}: {e!r}")
            # finality healing: gossip is fire-and-forget and sync
            # re-fetches blocks, never votes — a vote relayed into a
            # partially-formed mesh is lost forever, which stalls
            # finality and feeds the conflicting-quorum window the
            # vote lock (finality._locked) guards. Re-offer own
            # unfinalized votes + the newest justification each slot;
            # receivers dedup, so repetition costs bytes only.
            with self.lock:
                own_votes = self.node.finality.own_unfinalized_votes()
                newest_just = self.node.finality.newest_justification()
                fin = self.node.finalized
            for v in own_votes:
                self.broadcast(("vote", v), mark_seen=False)
            if newest_just is not None \
                    and newest_just.target_number >= fin:
                self.broadcast(("just", newest_just), mark_seen=False)
            # periodic authority-record publication, off this thread
            # (publication does blocking DHT RPCs; authoring must not)
            now = time.time()
            if now >= self._next_publish \
                    and not getattr(self, "_publishing", False):
                with self.lock:
                    self._next_publish = now + 10 * self.slot_time
                    self._publishing = True
                self._spawn(self._publish_once)
            # DHT upkeep: record expiry + stale-bucket refresh lookups
            # (libp2p Kademlia's periodic maintenance), off this thread
            if now >= self._next_dht_maint \
                    and not getattr(self, "_dht_mainting", False):
                with self.lock:
                    self._next_dht_maint = now + 20 * self.slot_time
                    self._dht_mainting = True
                self._spawn(self._dht_maintenance)

    # -- authority discovery (Kademlia; service.rs:508-537 role) -------------
    def _verify_record(self, rec: "dht_mod.AuthorityRecord") -> bool:
        """A record is valid iff its authority is in the CURRENT
        authority set and the signature verifies against that
        authority's on-chain session key — the registry finality votes
        already trust."""
        if not (isinstance(rec.authority, str)
                and isinstance(rec.signature, bytes)
                and isinstance(rec.port, int) and 0 < rec.port < 65536
                and isinstance(rec.dht_port, int)
                and 0 < rec.dht_port < 65536
                and isinstance(rec.serial, int) and rec.serial >= 0):
            return False
        with self.lock:
            if rec.authority not in self.node.authorities:
                return False
            pub = self.node.runtime.state.get("system", "session_key",
                                              rec.authority)
        if pub is None:
            return False
        return ed25519.verify(pub, rec.signing_payload(), rec.signature)

    def _dht_accept_loop(self, srv: socket.socket) -> None:
        """One short-lived request/response exchange per connection —
        DHT RPCs never occupy gossip inbound slots."""
        while not self._stop.is_set():
            try:
                sock, _ = srv.accept()
            except OSError:
                return
            self._spawn(self._dht_serve_one, sock)

    def _dht_serve_one(self, sock: socket.socket) -> None:
        try:
            sock.settimeout(2.0)
            raw = _read_frame(sock)
            if raw is None or self.kad is None:
                return
            resp = self.kad.handle(codec.decode(raw))
            raw_out = codec.encode(resp)
            sock.sendall(_LEN.pack(len(raw_out)) + raw_out)
        except (OSError, codec.CodecError, ValueError, TypeError):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _dht_call(self, contact: "dht_mod.Contact", req,
                  timeout: float = 1.0):
        """Client half of one DHT RPC; None on any failure."""
        try:
            with socket.create_connection((self.host, contact.dht_port),
                                          timeout=timeout) as sock:
                sock.settimeout(timeout)
                raw = codec.encode(req)
                sock.sendall(_LEN.pack(len(raw)) + raw)
                resp = _read_frame(sock)
            return None if resp is None else codec.decode(resp)
        except (OSError, codec.CodecError, ValueError, TypeError):
            return None

    def _iter_lookup(self, key: bytes, want_value: bool):
        """Iterative Kademlia lookup: query the ALPHA closest unqueried
        contacts per round, absorb returned contacts, stop when no
        round improves. Returns (record | None, closest_contacts)."""
        kad = self.kad
        shortlist = {c.port: c for c in kad.closest(key)}
        queried: set[int] = set()
        op = "find_value" if want_value else "find_node"
        # Kademlia termination: stop only once every still-unqueried
        # shortlist contact has been asked (bounded by MAX_QUERIED, not
        # by a no-new-contacts heuristic — a round that adds nothing
        # may still leave the record-holder unqueried)
        MAX_QUERIED = 4 * dht_mod.K
        while len(queried) < MAX_QUERIED and not self._stop.is_set():
            cands = sorted(
                (c for c in shortlist.values() if c.port not in queried),
                key=lambda c: dht_mod.distance(c.node_id(), key))
            cands = cands[:dht_mod.ALPHA]
            if not cands:
                break
            for c in cands:
                if self._stop.is_set():
                    break
                queried.add(c.port)
                resp = self._dht_call(c, (op, kad.self_contact, key))
                if not (isinstance(resp, tuple) and len(resp) == 2):
                    continue
                kad.note(c)
                if resp[0] == "value" and want_value:
                    if kad.store_record(resp[1]):   # verifies
                        return resp[1], list(shortlist.values())
                    continue                        # forged: keep looking
                if resp[0] == "nodes" and isinstance(resp[1], tuple):
                    for n in resp[1][:2 * dht_mod.K]:
                        if isinstance(n, dht_mod.Contact) \
                                and n.port != self.port \
                                and n.port not in shortlist:
                            shortlist[n.port] = n
                            kad.note(n)
        closest = sorted(shortlist.values(),
                         key=lambda c: dht_mod.distance(c.node_id(), key))
        return None, closest[:dht_mod.K]

    def _publish_once(self) -> None:
        try:
            self.publish_authorities()
        finally:
            with self.lock:
                self._publishing = False

    def _dht_maintenance(self) -> None:
        try:
            if self.kad is None:
                return
            self.kad.expire()
            for target in self.kad.refresh_targets():
                if self._stop.is_set():
                    return
                self._iter_lookup(target, want_value=False)
        finally:
            with self.lock:
                self._dht_mainting = False

    def publish_authorities(self) -> None:
        """Publish a signed address record for every authority whose
        session key this node operates, to the K closest nodes (the
        reference's authority-discovery publish half)."""
        if self.kad is None:
            return
        with self.lock:
            serial = self._publish_serial = max(self._publish_serial + 1,
                                                int(time.time()))
            mine = [a for a in self.node.keystore
                    if a in self.node.authorities]
        for account in mine:
            # sign with the key the node actually HOLDS (finality signs
            # with keystore values too): the on-chain registry peers
            # verify against can rotate away from the dev-spec
            # derivation, and a spec-derived signature would then fail
            # _verify_record on every peer
            rec = dht_mod.sign_record(self.node.keystore[account],
                                      account, self.port, self.dht_port,
                                      serial)
            self.kad.store_record(rec)          # serve it ourselves too
            _, closest = self._iter_lookup(dht_mod.record_key(account),
                                           want_value=False)
            for c in closest[:dht_mod.K]:
                if self._stop.is_set():
                    return
                self._dht_call(c, ("store", self.kad.self_contact, rec))

    def discover_authority(self, authority: str
                           ) -> "dht_mod.AuthorityRecord | None":
        """Resolve an authority's address through the DHT (verified
        record or None); a hit also feeds the gossip ring's peer set."""
        if self.kad is None:
            return None
        key = dht_mod.record_key(authority)
        rec = self.kad.record(key)
        if rec is None:
            rec, _ = self._iter_lookup(key, want_value=True)
        if rec is not None:
            self.kad.note(rec.contact())
            self._discover([rec.port])
        return rec

    # -- client surface ------------------------------------------------------
    def submit(self, xt) -> None:
        with self.lock:
            self.node.submit_signed(xt)
        self.broadcast(("tx", xt))
