"""TCP gossip transport: nodes as separate OS processes.

The reference's node talks libp2p — block announcement, tx
propagation, GRANDPA vote gossip, and catch-up sync between processes
(/root/reference/node/src/service.rs:259-274,508-537). This module is
the framework-native equivalent over plain TCP: length-prefixed
canonical-codec frames carrying (msg_type, payload) tuples, full-mesh
peering, flood gossip with seen-set dedup, and a walk-back sync
request for missed blocks. The in-process ``Network`` driver and this
transport run the SAME ``Node``: consensus, fork choice and finality
live in the node; this layer only moves bytes.

Fault injection (``FaultPolicy``) drops or reorders outbound messages
deterministically — the gossip layer must converge anyway via sync
requests (tested in tests/test_net.py with real processes).

Wire frame: [4-byte LE length][codec bytes]; payload tuples:
  ("tx", SignedExtrinsic)          tx propagation
  ("block", Block)                 block announcement (body included)
  ("vote", Vote)                   finality vote gossip
  ("status", (head_n, head_hash, finalized))  keepalive / sync trigger
  ("sync_request", from_number)    catch-up ask
  ("sync_response", (Block, ...))  canonical tail (capped)
  ("just", Justification)         finality proof propagation
  ("warp_request", 0)              checkpoint-sync ask (fresh nodes)
  ("warp_response", (snapshot_payload_bytes, Justification))
                                   snapshot + finality countersignatures
                                   (verified by Node.warp_sync logic)
  ("peers", (port, ...))           peer exchange (discovery): each side
                                   shares its known listen ports; unknown
                                   ones get dialed — the reference's
                                   Kademlia authority-discovery role
                                   (service.rs:508-537), flood-simple
"""
from __future__ import annotations

import dataclasses
import socket
import struct
import threading
import time

from .. import codec
from ..chain.state import DispatchError

_LEN = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024
SYNC_BATCH = 64
SYNC_LOOKBACK = 8   # re-request a short tail to cover small forks
WARP_THRESHOLD = 50  # finalized blocks behind which a fresh node warps


@dataclasses.dataclass
class FaultPolicy:
    """Deterministic outbound faults for tests: drop every Nth
    message, optionally delay each send."""

    drop_every: int = 0     # 0 = never drop
    delay_s: float = 0.0
    _counter: int = 0

    def allow(self) -> bool:
        self._counter += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        return not (self.drop_every and self._counter % self.drop_every == 0)


class _Conn:
    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.send_lock = threading.Lock()
        self.alive = True

    def send(self, raw: bytes) -> None:
        with self.send_lock:
            self.sock.sendall(_LEN.pack(len(raw)) + raw)

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def _read_frame(sock: socket.socket) -> bytes | None:
    head = b""
    while len(head) < _LEN.size:
        chunk = sock.recv(_LEN.size - len(head))
        if not chunk:
            return None
        head += chunk
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME:
        return None
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(65536, n - len(buf)))
        if not chunk:
            return None
        buf += chunk
    return bytes(buf)


class NodeService:
    """One node process: TCP listener + outbound peers + slot-timed
    authoring loop, all feeding a single Node under one lock."""

    def __init__(self, node, port: int, peers: list[int],
                 host: str = "127.0.0.1", slot_time: float = 0.2,
                 genesis_time: float = 0.0,
                 faults: FaultPolicy | None = None):
        self.node = node
        # all processes must agree on slot numbering (slot is signed
        # into VRF claims and drives epoch derivation): slots count
        # from a SHARED genesis wall-clock instant, not process start
        self.genesis_time = genesis_time
        self.host = host
        self.port = port
        self.peer_ports = peers
        self.slot_time = slot_time
        self.faults = faults
        self.lock = threading.RLock()
        self.conns: list[_Conn] = []
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._seen: set[bytes] = set()   # gossip dedup (frame hashes)
        # peer-exchange state lives here (NOT start()): inbound frames
        # can arrive before start() finishes its own assignments
        self._known_peers: set[int] = set(peers)
        self.max_peers = 64   # discovery cap: bounds dial threads
        self.errors: list[str] = []      # swallowed faults, for tests/ops
        self._warp_tries = 0
        self._warp_backoff = 0.0
        self._listener: socket.socket | None = None

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((self.host, self.port))
        srv.listen(16)
        self._listener = srv
        self._spawn(self._accept_loop, srv)
        for p in self.peer_ports:
            self._spawn(self._dial_loop, p)
        self._spawn(self._author_loop)

    def _discover(self, ports) -> None:
        """Peer exchange: dial newly learned listen ports. Bounded by
        max_peers — an unauthenticated frame must not be able to spawn
        unbounded dial threads. Membership check+add runs under the
        service lock (concurrent recv threads must not double-dial)."""
        for p in ports:
            if not (isinstance(p, int) and not isinstance(p, bool)
                    and 0 < p < 65536 and p != self.port):
                continue
            with self.lock:
                if len(self._known_peers) >= self.max_peers \
                        or p in self._known_peers:
                    continue
                self._known_peers.add(p)
            self._spawn(self._dial_loop, p)

    def stop(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for c in list(self.conns):
            c.close()
        for t in self._threads:
            t.join(timeout=2.0)

    def _spawn(self, fn, *args) -> None:
        t = threading.Thread(target=fn, args=args, daemon=True)
        t.start()
        self._threads.append(t)

    # -- connections --------------------------------------------------------
    def _accept_loop(self, srv: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                sock, _ = srv.accept()
            except OSError:
                return
            conn = _Conn(sock)
            self.conns.append(conn)
            self._spawn(self._recv_loop, conn)

    def _dial_loop(self, port: int) -> None:
        """Keep one outbound connection to a peer alive (retry)."""
        while not self._stop.is_set():
            try:
                sock = socket.create_connection((self.host, port),
                                                timeout=2.0)
                sock.settimeout(None)
            except OSError:
                time.sleep(0.05)
                continue
            conn = _Conn(sock)
            self.conns.append(conn)
            self._send_status(conn)
            self._send(conn, ("peers",
                              (self.port, *sorted(self._known_peers))))
            self._recv_loop(conn)   # blocks until closed
            if conn in self.conns:
                self.conns.remove(conn)
            time.sleep(0.05)

    def _recv_loop(self, conn: _Conn) -> None:
        while not self._stop.is_set() and conn.alive:
            try:
                raw = _read_frame(conn.sock)
            except OSError:
                break
            if raw is None:
                break
            try:
                msg = codec.decode(raw)
                self._handle(msg, conn)
            except (codec.CodecError, ValueError, DispatchError,
                    TypeError, KeyError, AttributeError, IndexError):
                # malformed or stale traffic from a peer must never
                # kill the service
                continue
        conn.close()

    # -- sending ------------------------------------------------------------
    def _send(self, conn: _Conn, msg) -> None:
        if self.faults is not None and not self.faults.allow():
            return
        try:
            conn.send(codec.encode(msg))
        except OSError:
            conn.close()

    def broadcast(self, msg, mark_seen: bool = True) -> None:
        raw = codec.encode(msg)
        if mark_seen:
            import hashlib

            self._seen.add(hashlib.sha256(raw).digest())
        for conn in list(self.conns):
            if conn.alive:
                if self.faults is not None and not self.faults.allow():
                    continue
                try:
                    conn.send(raw)
                except OSError:
                    conn.close()

    def _send_status(self, conn: _Conn) -> None:
        with self.lock:
            head = self.node.head()
            msg = ("status", (head.number, head.hash(),
                              self.node.finalized))
        self._send(conn, msg)

    # -- gossip handlers ----------------------------------------------------
    def _handle(self, msg, conn: _Conn) -> None:
        import hashlib

        kind, payload = msg
        raw_hash = hashlib.sha256(codec.encode(msg)).digest()
        if kind in ("tx", "block", "vote", "just"):
            if raw_hash in self._seen:
                return
            self._seen.add(raw_hash)
        if kind == "tx":
            with self.lock:
                try:
                    self.node.submit_signed(payload)
                except DispatchError:
                    return   # invalid or duplicate: do not re-gossip
            self.broadcast(msg, mark_seen=False)
        elif kind == "block":
            ok = self._import(payload, conn)
            if ok:
                self.broadcast(msg, mark_seen=False)
                self._after_chain_move()
        elif kind == "vote":
            with self.lock:
                self.node.finality.on_vote(payload)
            self.broadcast(msg, mark_seen=False)
        elif kind == "just":
            with self.lock:
                if payload.target_number > self.node.finalized \
                        and self.node.finality.verify_justification(payload):
                    self.node.finality.justifications[payload.round] = payload
                    self.node.on_justification(payload)
        elif kind == "peers":
            if isinstance(payload, tuple):
                self._discover(payload)
        elif kind == "status":
            peer_head, _, peer_fin = payload
            now = time.time()
            with self.lock:
                ours = self.node.head().number
                warp_viable = (ours == 0 and peer_fin > WARP_THRESHOLD
                               and self._warp_tries < 3)
                fire_warp = warp_viable and now >= self._warp_backoff
                if fire_warp:
                    # one attempt per backoff window, not per status
                    # tick — a large snapshot takes time to arrive
                    self._warp_tries += 1
                    self._warp_backoff = now + 1.0
            if fire_warp:
                # fresh node far behind a finalized peer: checkpoint
                # sync instead of replaying the whole chain; bounded
                # attempts then fall back to full replay sync
                self._send(conn, ("warp_request", 0))
            elif peer_head > ours and not warp_viable:
                self._send(conn, ("sync_request",
                                  max(1, ours - SYNC_LOOKBACK)))
        elif kind == "warp_request":
            from . import store as _store

            with self.lock:
                if not self.node.finality.justifications:
                    return
                rnd = max(self.node.finality.justifications)
                just = self.node.finality.justifications[rnd]
                payload_bytes = _store.snapshot_payload(self.node)
            self._send(conn, ("warp_response", (payload_bytes, just)))
        elif kind == "warp_response":
            snap_bytes, just = payload
            from .finality import Justification

            if not isinstance(snap_bytes, bytes) \
                    or not isinstance(just, Justification):
                return
            with self.lock:
                self._try_warp(snap_bytes, just)
        elif kind == "sync_request":
            with self.lock:
                blocks = []
                for n in range(payload, payload + SYNC_BATCH):
                    b = self.node.block_bodies.get(n)
                    if b is None:
                        break
                    blocks.append(b)
            if blocks:
                self._send(conn, ("sync_response", tuple(blocks)))
        elif kind == "sync_response":
            moved = False
            for b in payload:
                if self._import(b, conn):
                    moved = True
            if moved:
                self._after_chain_move()

    def _import(self, block, conn: _Conn) -> bool:
        with self.lock:
            try:
                self.node.import_block(block)
                return True
            except ValueError as e:
                if "unknown parent" in str(e):
                    if self.node.head().number == 0 \
                            and self._warp_tries < 3:
                        # fresh node with warp still plausible: stay
                        # quiet — the status exchange (every slot)
                        # drives checkpoint-vs-replay policy in ONE
                        # place; requesting a replay here would race
                        # the in-flight snapshot adoption
                        pass
                    else:
                        self._send(conn, (
                            "sync_request",
                            max(1, self.node.head().number
                                - SYNC_LOOKBACK)))
                return False

    def _try_warp(self, snap_bytes: bytes, just) -> bool:
        """Verify + adopt a checkpoint (caller holds the lock): same
        trust model as Node.warp_sync_from, over the wire."""
        from . import store as _store
        from .network import Node as _Node

        node = self.node
        if node.head().number != 0:
            return False
        probe = _Node(node.spec, f"{node.name}-warp", {})
        if not _store.restore_snapshot_payload(probe, snap_bytes):
            return False
        chain = probe.chain
        if chain[0].hash() != node.chain[0].hash():
            return False
        for parent, child in zip(chain, chain[1:]):
            if child.parent != parent.hash()                     or child.number != parent.number + 1:
                return False
        if not (0 < just.target_number < len(chain)
                and chain[just.target_number].hash() == just.target_hash):
            return False
        if not probe.finality.verify_justification(just):
            return False
        if not _store.restore_snapshot_payload(node, snap_bytes):
            return False
        node.finality.justifications[just.round] = just
        node.finalized = max(node.finalized, just.target_number)
        if node.store is not None:
            _store.write_snapshot(node.base_path, node)
        return True

    def _after_chain_move(self) -> None:
        """Cast + gossip finality votes and any new justification."""
        with self.lock:
            votes = self.node.finality.cast_votes()
            fin = self.node.finalized
            just = self.node.finality.justifications.get(fin)
        for v in votes:
            self.broadcast(("vote", v))
        if just is not None:
            self.broadcast(("just", just))

    # -- authoring ----------------------------------------------------------
    def _author_loop(self) -> None:
        """Wall-clock slots shared across processes on one host: each
        process independently computes the slot index, authors when its
        key wins, commits immediately and gossips — competing blocks
        are resolved by fork choice at import, votes settle finality."""
        last_slot = -1
        while not self._stop.is_set():
            slot = int((time.time() - self.genesis_time) / self.slot_time)
            if slot < 1:
                time.sleep(self.slot_time / 10)
                continue
            if slot == last_slot:
                time.sleep(self.slot_time / 10)
                continue
            last_slot = slot
            blk = None
            with self.lock:
                new_beats = self.node.queue_heartbeats()
                try:
                    blk = self.node.try_author(slot)
                    if blk is not None:
                        self.node.commit_proposal()
                except Exception as e:   # noqa: BLE001 — author loop must survive
                    self.errors.append(f"author slot {slot}: {e!r}")
                    if self.node._proposal is not None:
                        self.node.abort_proposal()
                    blk = None
            for xt in new_beats:
                # a validator that never wins a slot still needs its
                # heartbeat IN PEERS' blocks — gossip it like any tx
                self.broadcast(("tx", xt))
            if blk is not None:
                self.broadcast(("block", blk))
                self._after_chain_move()
            for conn in list(self.conns):
                if conn.alive:
                    self._send_status(conn)

    # -- client surface ------------------------------------------------------
    def submit(self, xt) -> None:
        with self.lock:
            self.node.submit_signed(xt)
        self.broadcast(("tx", xt))
