"""Node layer: consensus, block production/import, offchain ecosystem.

Mirrors the reference's L4/L5/L6 (SURVEY.md §1): RRSC-style VRF slot
lottery with epoch randomness and credit-weighted validator election
(consensus.py), a block production/import/finality harness over the
chain runtime (network.py), the validator offchain audit worker plus
the OSS-gateway / storage-miner / TEE-worker agents the reference
delegates to external repos (offchain.py) — here they drive the TPU
data plane (cess_tpu.models.pipeline) directly — and a JSON-RPC
surface (rpc.py) with chain-spec genesis config (chain_spec.py).
"""
