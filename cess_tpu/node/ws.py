"""WebSocket push subscriptions (the EthPubSub role, reference
node/src/rpc.rs:229-328 wiring EthPubSubApiServer over jsonrpsee's WS
transport).

A minimal RFC 6455 server endpoint mounted at ``GET /ws`` on the
JSON-RPC HTTP server: handshake, masked client text frames in,
unmasked server text frames out, ping/pong, close. Over it speaks
JSON-RPC 2.0 with:

  eth_subscribe ["newHeads"] | ["logs", criteria]  -> subscription id
  eth_unsubscribe [id]                             -> bool

and pushes ``eth_subscription`` notifications. Delivery is POLLED off
the node head (no cross-thread hooks into consensus): each connection
thread checks for new blocks every POLL_S while waiting for client
frames, so push latency is ~POLL_S and a dead client costs one thread
+ one socket until it times out. Log criteria reuse the EthFilter
normalizer, so validation/semantics match eth_newFilter exactly; the
cursor is reorg-checked the same way (rewind to finalized, redeliver)."""
from __future__ import annotations

import base64
import hashlib
import json
import socket
import struct

_GUID = b"258EAFA5-E914-47DA-95CA-C5AB0DC85B11"
POLL_S = 0.15
SEND_TIMEOUT_S = 10.0      # slow readers get a real stall budget, not
                           # the 150 ms poll tick (review finding)
MAX_WS_FRAME = 1 << 20


class _Gone(Exception):
    """Peer unreachable mid-send: unwind the connection quietly."""


def accept_key(client_key: str) -> str:
    return base64.b64encode(
        hashlib.sha1(client_key.encode() + _GUID).digest()).decode()


class SockReader:
    """recv() facade that drains a prefix buffer first — frames the
    client PIPELINED behind the HTTP upgrade were already pulled into
    the handler's buffered reader and must not be lost."""

    def __init__(self, sock: socket.socket, initial: bytes = b""):
        self.sock = sock
        self.buf = initial

    def recv(self, n: int) -> bytes:
        if self.buf:
            out, self.buf = self.buf[:n], self.buf[n:]
            return out
        return self.sock.recv(n)


def read_frame(sock) -> tuple[int, bytes] | None:
    """One frame -> (opcode, payload); None on close/EOF; raises
    socket.timeout only while IDLE (before any header byte), so the
    caller's poll loop wakes without tearing the connection down.
    Client frames MUST be masked (RFC 6455 §5.1)."""
    hdr = _read_exact(sock, 2, idle_timeout_ok=True)
    if hdr is None:
        return None
    b0, b1 = hdr
    opcode = b0 & 0x0F
    masked = bool(b1 & 0x80)
    length = b1 & 0x7F
    if length == 126:
        ext = _read_exact(sock, 2)
        if ext is None:
            return None
        length = struct.unpack(">H", ext)[0]
    elif length == 127:
        ext = _read_exact(sock, 8)
        if ext is None:
            return None
        length = struct.unpack(">Q", ext)[0]
    if length > MAX_WS_FRAME or not masked:
        return None
    mask = _read_exact(sock, 4)
    if mask is None:
        return None
    payload = _read_exact(sock, length)
    if payload is None:
        return None
    return opcode, bytes(b ^ mask[i % 4] for i, b in enumerate(payload))


def _read_exact(sock, n: int,
                idle_timeout_ok: bool = False) -> bytes | None:
    """n bytes or None. socket.timeout is an OSError subclass, so it
    needs explicit handling: with no bytes buffered and
    ``idle_timeout_ok`` it propagates (poll-loop wakeup); mid-frame it
    retries a bounded number of short waits before giving up on the
    stalled peer."""
    buf = b""
    stalls = 0
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if not buf and idle_timeout_ok:
                raise
            stalls += 1
            if stalls > 200:       # ~30 s at POLL_S: dead mid-frame
                return None
            continue
        except OSError:
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def write_frame(sock: socket.socket, payload: bytes,
                opcode: int = 0x1) -> None:
    n = len(payload)
    if n < 126:
        hdr = bytes([0x80 | opcode, n])
    elif n < 1 << 16:
        hdr = bytes([0x80 | opcode, 126]) + struct.pack(">H", n)
    else:
        hdr = bytes([0x80 | opcode, 127]) + struct.pack(">Q", n)
    sock.sendall(hdr + payload)


def serve_connection(server, handler) -> None:
    """Run one upgraded WS connection until close. ``server`` is the
    RpcServer (lock + node + criteria normalizer); ``handler`` the
    http request handler whose socket we take over."""
    sock = handler.connection
    sock.settimeout(POLL_S)
    reader = SockReader(sock, getattr(handler, "ws_initial", b""))
    subs: dict[str, dict] = {}     # id -> {kind, crit, cursor, hash}

    def send_raw(payload: bytes, opcode: int = 0x1) -> None:
        # every send swaps to the send budget and back; any failure
        # raises _Gone so each call site unwinds the same way
        sock.settimeout(SEND_TIMEOUT_S)
        try:
            write_frame(sock, payload, opcode)
        except OSError as e:
            raise _Gone from e
        finally:
            try:
                sock.settimeout(POLL_S)
            except OSError:
                pass

    def send_json(obj) -> None:
        send_raw(json.dumps(obj).encode())

    def snapshot_head():
        with server.lock:
            head = server.node.head()
            return head.number, head.hash()

    try:
        _serve(server, reader, subs, send_raw, send_json, snapshot_head)
    except _Gone:
        return


def _serve(server, sock, subs, send_raw, send_json, snapshot_head):
    seq = 0
    while True:
        # 1) pump any due notifications
        _push_updates(server, subs, send_json)
        # 2) wait briefly for a client frame
        try:
            frame = read_frame(sock)
        except socket.timeout:
            continue
        except OSError:
            return
        if frame is None:
            return
        opcode, payload = frame
        if opcode == 0x8:                    # close
            try:
                send_raw(b"", opcode=0x8)
            except _Gone:
                pass
            return
        if opcode == 0x9:                    # ping -> pong
            send_raw(payload, opcode=0xA)
            continue
        if opcode != 0x1:
            continue
        try:
            req = json.loads(payload)
            rid = req.get("id")
            method = req.get("method", "")
            params = req.get("params", [])
        except (json.JSONDecodeError, UnicodeDecodeError, AttributeError):
            continue
        if method == "eth_subscribe" and isinstance(params, list) \
                and params:
            kind = params[0]
            if kind == "newHeads":
                crit = None
            elif kind == "logs":
                flt = params[1] if len(params) > 1 \
                    and isinstance(params[1], dict) else {}
                try:
                    crit = server._norm_criteria(flt)
                except (ValueError, TypeError) as e:
                    send_json({"jsonrpc": "2.0", "id": rid, "error": {
                        "code": -32602,
                        "message": f"bad criteria: {e}"}})
                    continue
            else:
                send_json({"jsonrpc": "2.0", "id": rid, "error": {
                    "code": -32602,
                    "message": f"unknown subscription {kind!r}"}})
                continue
            if len(subs) >= 64:
                send_json({"jsonrpc": "2.0", "id": rid, "error": {
                    "code": -32000, "message": "subscription cap"}})
                continue
            seq += 1
            sid = hex(seq)
            num, hsh = snapshot_head()
            subs[sid] = {"kind": kind, "crit": crit, "cursor": num,
                         "hash": hsh}
            send_json({"jsonrpc": "2.0", "id": rid, "result": sid})
        elif method == "eth_unsubscribe" and isinstance(params, list) \
                and params:
            ok = subs.pop(params[0], None) is not None
            send_json({"jsonrpc": "2.0", "id": rid, "result": ok})
        else:
            send_json({"jsonrpc": "2.0", "id": rid, "error": {
                "code": -32601, "message": f"unknown {method!r}"}})


def _push_updates(server, subs: dict, send_json) -> None:
    """Deliver new heads/logs since each subscription's cursor; the
    cursor is reorg-checked like EthFilter polls (rewind to finalized
    and redeliver rather than silently skip)."""
    if not subs:
        return
    from .rpc import _encode

    with server.lock:
        node = server.node
        for sid, sub in subs.items():
            since, head = server.cursor_window(node, sub["cursor"],
                                               sub["hash"])
            if since >= head.number:
                continue
            if sub["kind"] == "newHeads":
                out = [{"number": n,
                        "hash": "0x" + node.chain[n].hash().hex(),
                        "parentHash": "0x" + node.chain[n].parent.hex(),
                        "author": node.chain[n].author}
                       for n in range(since + 1, head.number + 1)]
            else:
                out = [_encode(lg) for lg in
                       server._eth_logs(node.runtime, sub["crit"],
                                        frm=since + 1)]
            sub["cursor"], sub["hash"] = head.number, head.hash()
            sub["_due"] = out
    # send OUTSIDE the node lock: a slow client must not stall the node
    for sid, sub in list(subs.items()):
        for item in sub.pop("_due", []):
            send_json({"jsonrpc": "2.0", "method": "eth_subscription",
                       "params": {"subscription": sid, "result": item}})
