"""RRSC consensus: VRF slot lottery + credit-weighted election.

The reference's RRSC ("Random Rotational Selection Consensus") is a
BABE fork: primary slots are claimed by validators whose VRF output on
(epoch randomness, slot) falls under c = 1/4, with deterministic
secondary slots so every slot has an author; the validator set is
elected per era by a VrfSolver weighted by scheduler credit over a
stake floor (SURVEY.md §2.3 forked-Substrate row;
/root/reference/runtime/src/lib.rs:181-185,240-241,764-786).

Epoch randomness follows BABE: R_{e+1} = H(R_e || e || vrf outputs of
epoch e) — bias-resistant enough for the framework's purposes and
fully deterministic for replay.
"""
from __future__ import annotations

import dataclasses
import hashlib

from .. import codec, constants
from ..crypto import ed25519
from ..crypto.vrf import VrfProof, output_below, vrf_sign, vrf_verify


@codec.register
@dataclasses.dataclass(frozen=True)
class SlotClaim:
    slot: int
    authority: str
    vrf: VrfProof | None      # None => secondary (fallback) claim


class Rrsc:
    def __init__(self, epoch_blocks: int = constants.EPOCH_DURATION_BLOCKS,
                 c=(constants.RRSC_C_NUM, constants.RRSC_C_DEN)):
        self.epoch_blocks = epoch_blocks
        self.c = c
        self.randomness: dict[int, bytes] = {0: b"genesis-randomness"}
        self._epoch_vrf: dict[int, list[bytes]] = {}
        # epoch numbering is ANCHORED at the chain's first block slot
        # (BABE records the genesis slot the same way): wall-clock slot
        # numbers are huge (unix_time / slot_time), so absolute-slot
        # epochs would be astronomically distant from epoch 0. The node
        # pins this from block #1's claim; until then it floats with
        # the trial slot so every pre-genesis claim sits in epoch 0.
        self.genesis_slot: int | None = None

    # -- epochs ---------------------------------------------------------------
    def epoch_of(self, slot: int) -> int:
        return max(0, slot - (self.genesis_slot or 0)) // self.epoch_blocks

    def epoch_randomness(self, epoch: int) -> bytes:
        """Randomness for an epoch; derived lazily (and iteratively —
        never recursion-bound) from collected VRF outputs of epoch-1
        (deterministic chain if none collected)."""
        if epoch not in self.randomness:
            start = epoch
            while start not in self.randomness:
                start -= 1
            for e in range(start + 1, epoch + 1):
                outs = b"".join(sorted(self._epoch_vrf.get(e - 1, [])))
                self.randomness[e] = hashlib.sha256(
                    self.randomness[e - 1] + e.to_bytes(8, "little")
                    + outs).digest()
        return self.randomness[epoch]

    def note_vrf(self, slot: int, output: bytes) -> None:
        self._epoch_vrf.setdefault(self.epoch_of(slot), []).append(output)

    # -- slot claims ------------------------------------------------------------
    def _slot_input(self, slot: int) -> bytes:
        r = self.epoch_randomness(self.epoch_of(slot))
        return r + slot.to_bytes(8, "little")

    def claim_slot(self, slot: int, authority: str,
                   key: ed25519.SigningKey,
                   authorities: tuple[str, ...]) -> SlotClaim | None:
        """Primary claim if the VRF lottery hits; else secondary if this
        authority is the round-robin fallback for the slot."""
        if authority not in authorities:
            return None
        proof = vrf_sign(key, self._slot_input(slot))
        if output_below(proof.output, *self.c):
            return SlotClaim(slot=slot, authority=authority, vrf=proof)
        if self.secondary_author(slot, authorities) == authority:
            return SlotClaim(slot=slot, authority=authority, vrf=None)
        return None

    def secondary_author(self, slot: int, authorities: tuple[str, ...]) -> str:
        """PrimaryAndSecondaryVRFSlots fallback: deterministic from the
        epoch randomness (every slot has an author)."""
        h = hashlib.sha256(self._slot_input(slot) + b"secondary").digest()
        return authorities[int.from_bytes(h[:4], "little") % len(authorities)]

    def verify_claim(self, claim: SlotClaim, public_key: bytes,
                     authorities: tuple[str, ...]) -> bool:
        if claim.authority not in authorities:
            return False
        if claim.vrf is None:
            return self.secondary_author(claim.slot, authorities) \
                == claim.authority
        return vrf_verify(public_key, self._slot_input(claim.slot), claim.vrf) \
            and output_below(claim.vrf.output, *self.c)

    def block_randomness(self, claim: SlotClaim) -> bytes:
        """Per-block randomness for the runtime (ParentBlockRandomness):
        the VRF output, or a derived value for secondary slots."""
        if claim.vrf is not None:
            return claim.vrf.output
        return hashlib.sha256(self._slot_input(claim.slot)
                              + claim.authority.encode()).digest()


def elect_validators(candidates: dict[str, int], credits: dict[str, int],
                     max_validators: int,
                     stake_floor: int = constants.MIN_ELECTABLE_STAKE
                     ) -> tuple[str, ...]:
    """The VrfSolver election: stake floor filter, then scheduler-credit
    weighting (higher credit wins; stake tie-breaks)
    (runtime/src/lib.rs:764-786)."""
    eligible = [(v, s) for v, s in candidates.items() if s >= stake_floor]
    ranked = sorted(eligible,
                    key=lambda vs: (-credits.get(vs[0], 0), -vs[1], vs[0]))
    return tuple(v for v, _ in ranked[:max_validators])
