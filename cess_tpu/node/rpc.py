"""JSON-RPC surface over a Node (reference: node/src/rpc.rs).

The reference exposes System/Chain/State/Author (+ Eth namespaces) over
jsonrpsee; here a threaded stdlib HTTP server speaks JSON-RPC 2.0 with
the equivalent core namespaces. Bytes are hex-encoded with an "0x"
prefix; structured extrinsic args are JSON (the wire codec of this
framework — the reference uses SCALE).

Methods:
  system_chain, system_health, system_properties
  chain_getHeader [number?], chain_getBlock [number?],
  chain_getFinalizedHead, chain_getBlockNumber
  state_getStorage [pallet, item, key-parts...], state_getEvents [pallet?]
  author_submitExtrinsic [origin, call, args...]   (dev-signed)
  author_submitSignedExtrinsic [hex codec-encoded SignedExtrinsic]
  system_accountNextIndex [account]
  payment_queryInfo [hex extrinsic]   (TransactionPayment role)
  rrsc_epoch, grandpa_roundState, grandpa_proveFinality [round],
  sync_state_genSyncSpec, net_peerCount, net_listening
  mmr_root, mmr_generateProof [number], mmr_verifyProof [...]
  (header-inclusion proofs; pallet-mmr role)
  cess_minerInfo [account], cess_fileInfo [hex hash], cess_challenge
  cess_engineStats   (submission-engine queue/batch/latency counters)
  cess_traceDump [trace_id?, limit?]
                     (Chrome trace-event JSON dump of the armed
                      request tracer, Perfetto-loadable, optionally
                      scoped to one trace / the newest N spans;
                      cess_tpu/obs)
  cess_sloStatus     (SLO board snapshot: per-class burn rates/states/
                      transitions, per-tenant accounting, adaptive
                      knobs + admission state; obs/slo.py)
  cess_incidentDump [limit?]
                     (flight-recorder postmortems: incident bundles +
                      retention counters; obs/flight.py + incident.py,
                      armed via node.cli --flight)
  cess_fleetStatus   (fleet observability plane: federated metrics,
                      global SLO views, stitched cross-node traces,
                      straggler state; obs/fleet.py, armed via
                      node.cli --fleet)
  cess_profileDump   (continuous-profiling plane: per-shape stage
                      breakdowns, pad/compile ledgers, watchdog
                      states + transitions; obs/profile.py, armed via
                      node.cli --profile)
  cess_chainStatus   (chain-plane observability: per-node consensus
                      health, equivocation evidence, the storage-
                      market ledger and anomaly transitions;
                      obs/chainwatch.py, armed via node.cli
                      --chainwatch)
  cess_remediationStatus
                     (remediation plane: the policy table, live
                      engagements, detector-health evidence and the
                      action journal; serve/remediate.py, armed via
                      node.cli --remediate)
  cess_custodyStatus (durability plane: per-segment custody lineage,
                      erasure margins + histogram, at-risk/lost
                      lists and per-fragment timelines;
                      obs/custody.py, armed via node.cli --custody)
  eth_* read subset + eth_sendRawTransaction + the EthFilter namespace
  (eth_newFilter / eth_newBlockFilter / eth_getFilterChanges /
  eth_getFilterLogs / eth_uninstallFilter) — polling filters with
  exactly-once delivery (ref node/src/rpc.rs:229-328)
  GET /ws upgrades to WebSocket for the EthPubSub role:
  eth_subscribe ["newHeads" | "logs", criteria] push notifications
  (cess_tpu/node/ws.py)
"""
from __future__ import annotations

import dataclasses
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .network import Node

MAX_BODY = 1 * 1024 * 1024   # request size cap (jsonrpsee-style limit)


class RpcError(Exception):
    """Typed JSON-RPC 2.0 error (code + message)."""

    def __init__(self, code: int, message: str):
        self.code = code
        self.message = message
        super().__init__(message)


METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
INVALID_REQUEST = -32600
PARSE_ERROR = -32700
SERVER_ERROR = -32000   # dispatch/application errors


def _encode(obj):
    if isinstance(obj, bytes):
        return "0x" + obj.hex()
    if isinstance(obj, (list, tuple)):
        return [_encode(o) for o in obj]
    if isinstance(obj, frozenset):
        return sorted(_encode(o) for o in obj)
    if isinstance(obj, dict):
        return {str(k): _encode(v) for k, v in obj.items()}
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return {f.name: _encode(getattr(obj, f.name))
                for f in dataclasses.fields(obj)}
    return obj


def _eth_chain_id(spec) -> int:
    from .chain_spec import eth_chain_id

    return eth_chain_id(spec.chain_id)


def _decode(obj):
    if isinstance(obj, str) and obj.startswith("0x"):
        return bytes.fromhex(obj[2:])
    if isinstance(obj, list):
        return [_decode(o) for o in obj]
    return obj


class RpcServer:
    def __init__(self, node: Node, host: str = "127.0.0.1",
                 port: int = 9944, lock=None, service=None):
        self.node = node
        # optional NodeService backref: live peer/listening telemetry
        # for the system/net namespaces
        self.service = service
        # the block-producing side must hold the SAME lock while
        # mutating node/runtime state (cli loop, NodeService): RPC
        # reads iterate live dicts and would otherwise race
        self.lock = lock if lock is not None else threading.Lock()
        # Eth filter table (EthFilter namespace): id -> {type,
        # criteria, cursor}; bounded at MAX_FILTERS
        self._filters: dict[str, dict] = {}
        self._filter_seq = 0
        from .mmr import HeaderMmr
        self._header_mmr = HeaderMmr()
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_GET(self):
                if self.path == "/ws" and "websocket" in \
                        self.headers.get("Upgrade", "").lower():
                    # EthPubSub endpoint: RFC 6455 upgrade, then the
                    # connection belongs to the subscription loop
                    from . import ws as ws_mod

                    key = self.headers.get("Sec-WebSocket-Key", "")
                    if not key:
                        self.send_response(400)
                        self.end_headers()
                        return
                    self.send_response(101)
                    self.send_header("Upgrade", "websocket")
                    self.send_header("Connection", "Upgrade")
                    self.send_header("Sec-WebSocket-Accept",
                                     ws_mod.accept_key(key))
                    self.end_headers()
                    self.close_connection = True
                    # frames pipelined behind the upgrade were already
                    # pulled into rfile's buffer; hand them to the WS
                    # reader (read1 serves buffered bytes without a
                    # blocking raw read — the 1 ms timeout covers the
                    # empty-buffer case)
                    import socket as _socket

                    self.connection.settimeout(0.001)
                    try:
                        self.ws_initial = self.rfile.read1(65536) or b""
                    except (_socket.timeout, OSError):
                        self.ws_initial = b""
                    ws_mod.serve_connection(server, self)
                    return
                if self.path != "/metrics":
                    self.send_response(404)
                    self.end_headers()
                    return
                from .metrics import render_metrics

                with server.lock:
                    data = render_metrics(server.node).encode()
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                req_id = None
                try:
                    try:
                        length = int(self.headers.get("Content-Length", 0))
                    except (TypeError, ValueError):
                        raise RpcError(INVALID_REQUEST,
                                       "bad Content-Length") from None
                    if length < 0:
                        # read(-1) would block until EOF, hanging the
                        # handler thread on a kept-open socket
                        raise RpcError(INVALID_REQUEST,
                                       "bad Content-Length")
                    if length > MAX_BODY:
                        # drain (bounded) so the client can read the
                        # error envelope instead of a broken pipe
                        left = length
                        while left > 0:
                            chunk = self.rfile.read(min(left, 65536))
                            if not chunk:
                                break
                            left -= len(chunk)
                        raise RpcError(INVALID_REQUEST,
                                       f"request exceeds {MAX_BODY} bytes")
                    try:
                        req = json.loads(self.rfile.read(length))
                    except (json.JSONDecodeError, UnicodeDecodeError) as e:
                        raise RpcError(PARSE_ERROR, str(e)) from None
                    if not isinstance(req, dict):
                        raise RpcError(INVALID_REQUEST, "not an object")
                    req_id = req.get("id")
                    params = req.get("params", [])
                    if not isinstance(params, list):
                        raise RpcError(INVALID_PARAMS, "params: not a list")
                    with server.lock:
                        result = server.handle(req.get("method", ""),
                                               params)
                    body = {"jsonrpc": "2.0", "id": req_id,
                            "result": _encode(result)}
                except RpcError as e:
                    body = {"jsonrpc": "2.0", "id": req_id,
                            "error": {"code": e.code,
                                      "message": e.message}}
                except Exception as e:  # application-level failure
                    body = {"jsonrpc": "2.0", "id": req_id,
                            "error": {"code": SERVER_ERROR,
                                      "message": str(e)}}
                data = json.dumps(body).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_port
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)

    def start(self) -> "RpcServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()

    # -- method dispatch ------------------------------------------------------
    def handle(self, method: str, params: list):
        node = self.node
        rt = node.runtime
        if method == "system_chain":
            return node.spec.name
        if method == "system_health":
            peers = self._peer_count()
            return {"peers": peers, "isSyncing": False,
                    "shouldHavePeers": self.service is not None}
        if method == "system_properties":
            return {"chainId": node.spec.chain_id,
                    "fragmentCount": node.spec.fragment_count}
        if method == "chain_getBlockNumber":
            return rt.state.block
        if method == "chain_getFinalizedHead":
            return node.finalized
        if method == "chain_getHeader":
            n = params[0] if params else len(node.chain) - 1
            if not isinstance(n, int) or not 0 <= n < len(node.chain):
                raise RpcError(INVALID_PARAMS,
                               f"block number out of range: {n!r}")
            return node.chain[n]
        if method == "state_getStorage":
            key = tuple(_decode(p) for p in params)
            return rt.state.get(*key)
        if method == "state_getEvents":
            pallet = params[0] if params else None
            events = rt.state.events if pallet is None \
                else rt.state.events_of(pallet)
            return events[-100:]
        if method == "author_submitExtrinsic":
            # dev convenience: server-side signing with spec dev keys
            origin, call, *args = params
            node.submit_extrinsic(origin, call, *[_decode(a) for a in args])
            return True
        if method == "author_submitSignedExtrinsic":
            # production path: client-built SignedExtrinsic, codec-encoded hex
            from .. import codec as _codec

            xt = self._decode_extrinsic_param(params)
            node.submit_signed(xt)
            return True
        if method == "system_accountNextIndex":
            if not params or not isinstance(params[0], str):
                raise RpcError(INVALID_PARAMS, "expected [account]")
            return node.runtime.system.nonce(params[0])
        if method == "cess_minerInfo":
            return rt.sminer.miner(params[0])
        if method == "cess_fileInfo":
            return rt.file_bank.file(_decode(params[0]))
        if method == "cess_teeVerdicts":
            # the BLS-sealed verdict log plus each TEE's on-chain
            # pubkey: everything an external auditor needs to re-run
            # audit.reverify_verdict offline (public verifiability)
            recs = rt.audit.verdicts()
            # the FULL key history per TEE (live + retired eras): a
            # worker that exited — even one that re-registered with a
            # new key — leaves its sealed history verifiable, and
            # records' stamped keys are checked against this set
            keys = {t: list(rt.tee_worker.bls_keys_of(t))
                    for t in sorted({r.tee for r in recs})}
            return {"verdicts": list(recs), "blsKeys": keys}
        if method == "cess_challenge":
            return rt.audit.challenge()
        if method == "cess_engineStats":
            # submission-engine debug surface (cess_tpu/serve): live
            # queue depths + per-class batching/latency counters;
            # null when the node runs without an engine
            engine = getattr(node, "engine", None)
            return None if engine is None else engine.stats_snapshot()
        if method == "cess_traceDump":
            # request-scoped tracing dump (cess_tpu/obs): the node's
            # pinned tracer (node.cli --trace) or the process-armed
            # one, exported as Chrome trace-event JSON — save the
            # result and open it in Perfetto. Null when no tracer.
            # Optional params [trace_id?, limit?] scope the dump (a
            # poller no longer has to pull the whole 4096-span ring);
            # no params = the whole ring, unchanged.
            from ..obs import trace as obs_trace

            tracer = getattr(node, "tracer", None)
            if tracer is None:
                tracer = obs_trace.armed_tracer()
            if tracer is None:
                return None
            trace_id = params[0] if len(params) > 0 else None
            limit = params[1] if len(params) > 1 else None
            for v in (trace_id, limit):
                if v is not None and not isinstance(v, int):
                    raise RpcError(INVALID_PARAMS,
                                   "expected [trace_id?, limit?] ints")
            return tracer.export_chrome(trace_id=trace_id, limit=limit)
        if method == "cess_incidentDump":
            # flight-recorder postmortems (obs/incident.py): reporter
            # counters, retention snapshot and the newest bundles
            # (pinned traces, journal tails, metric deltas, fault
            # log). Optional [limit] caps the bundle count. Null when
            # the node runs without a reporter (node.cli --flight).
            reporter = getattr(node, "incidents", None)
            if reporter is None:
                return None
            limit = params[0] if params else None
            if limit is not None and not isinstance(limit, int):
                raise RpcError(INVALID_PARAMS, "expected [limit?] int")
            return reporter.dump(limit=limit)
        if method == "cess_fleetStatus":
            # fleet observability plane (obs/fleet.py): the federated
            # metric view, global SLO board, stitched cross-node
            # traces and straggler scan state. Null when the node runs
            # without a fleet plane (node.cli --fleet).
            plane = getattr(node, "fleet", None)
            return None if plane is None else plane.snapshot()
        if method == "cess_profileDump":
            # continuous-profiling plane (obs/profile.py): per-(class,
            # bucket, device) stage breakdowns, the unified pad
            # ledger, compile events and the bench-anchored watchdog
            # state. Null when the node runs without a profile plane
            # (node.cli --profile).
            plane = getattr(node, "profile", None)
            return None if plane is None else plane.snapshot()
        if method == "cess_chainStatus":
            # chain-plane observability (obs/chainwatch.py): per-node
            # consensus views, equivocation evidence records, the
            # storage-market ledger and the anomaly transition log.
            # Null when the node runs without a chain watch
            # (node.cli --chainwatch).
            plane = getattr(node, "chainwatch", None)
            return None if plane is None else plane.snapshot()
        if method == "cess_remediationStatus":
            # remediation plane (serve/remediate.py): the policy
            # table, live engagements, detector-health evidence and
            # the action journal. Null when the node runs without a
            # remediation plane (node.cli --remediate).
            plane = getattr(node, "remediation", None)
            return None if plane is None else plane.snapshot()
        if method == "cess_custodyStatus":
            # durability plane (obs/custody.py): per-segment custody
            # lineage timelines, the erasure-margin fold + histogram,
            # the at-risk/lost lists and the anomaly transition log.
            # Null when the node runs without a custody plane
            # (node.cli --custody).
            plane = getattr(node, "custody", None)
            return None if plane is None else plane.snapshot()
        if method == "cess_sloStatus":
            # SLO observability debug surface (obs/slo.py): per-class
            # burn rates / states / transition log + per-tenant
            # accounting, plus the adaptive knobs and admission state
            # when configured. Null when the engine has no board.
            engine = getattr(node, "engine", None)
            board = None if engine is None else engine.slo
            if board is None:
                return None
            out = board.snapshot()
            if engine.adaptive is not None:
                out["adaptive"] = engine.adaptive.snapshot()
            if engine.admission is not None:
                out["admission"] = engine.admission.snapshot()
            return out
        if method == "system_version":
            from ..chain import migrations as _mig

            return {"specVersion": _mig.spec_version(rt.state),
                    "storageVersions": {
                        p: _mig.storage_version(rt.state, p)
                        for p in sorted({m[0] for m in _mig.MIGRATIONS})}}
        if method == "system_metrics":
            from .metrics import collect

            return collect(node)
        if method == "chain_getBlock":
            n = params[0] if params else node.head().number
            if not isinstance(n, int):
                raise RpcError(INVALID_PARAMS, "expected [number]")
            blk = node.block_bodies.get(n)
            if blk is None and 0 <= n < len(node.chain):
                blk = node.bodies.get(node.chain[n].hash())
            if blk is None:
                return None   # pruned by warp sync, or unknown
            return {"header": blk.header,
                    "extrinsics": list(blk.extrinsics)}
        if method == "payment_queryInfo":
            # TransactionPayment analog (ref rpc.rs TransactionPayment):
            # fee breakdown for an encoded signed extrinsic
            from ..chain.runtime import CALL_WEIGHTS

            xt = self._decode_extrinsic_param(params)
            return {"weight": CALL_WEIGHTS.get(xt.call, 0),
                    "partialFee": rt.tx_fee(xt)}
        # -- consensus namespaces (RRSC/Grandpa/SyncState analogs;
        # ref node/src/rpc.rs:148-227) -----------------------------------
        if method == "rrsc_epoch":
            head = node.head()
            slot = head.claim.slot if head.claim else 0
            epoch = node.rrsc.epoch_of(slot)
            return {"epoch": epoch,
                    "epochLength": node.rrsc.epoch_blocks,
                    "randomness": node.rrsc.epoch_randomness(epoch),
                    "authorities": list(node.authorities)}
        if method == "grandpa_roundState":
            rounds = sorted(node.finality.justifications)
            return {"finalized": node.finalized,
                    "bestRound": rounds[-1] if rounds else 0,
                    "authorities": list(node.authorities)}
        if method == "grandpa_proveFinality":
            # newest justification at-or-above the asked round (newer
            # justifications imply older finality)
            want = params[0] if params else 0
            if not isinstance(want, int):
                raise RpcError(INVALID_PARAMS, "expected [round]")
            from .. import codec as _codec

            rounds = sorted(r for r in node.finality.justifications
                            if r >= want)
            if not rounds:
                return None
            return _codec.encode(node.finality.justifications[rounds[0]])
        if method == "sync_state_genSyncSpec":
            # the warp/light sync bootstrap document (ref
            # cessc-sync-state-rpc role): chain spec + finalized anchor
            from .chain_spec import spec_to_json

            return {"spec": spec_to_json(node.spec),
                    "lightSyncState": {
                        "finalizedNumber": node.finalized,
                        "finalizedHash": node.chain[node.finalized].hash()
                        if node.finalized < len(node.chain) else None}}
        if method == "net_peerCount":
            return hex(self._peer_count())
        if method == "net_listening":
            return self.service is not None
        # -- Mmr namespace (pallet-mmr role, ref runtime/src/lib.rs
        # :1270-1274,1492; node Mmr RPC) ---------------------------------
        if method == "mmr_root":
            return self._header_mmr.sync(node.chain).root()
        if method == "mmr_generateProof":
            if not params or not isinstance(params[0], int) \
                    or isinstance(params[0], bool):
                raise RpcError(INVALID_PARAMS, "expected [block number]")
            n = params[0]
            if not 0 <= n < len(node.chain):
                raise RpcError(INVALID_PARAMS, f"unknown block {n}")
            from .. import codec as _codec

            mmr = self._header_mmr.sync(node.chain)
            return {"blockNumber": n,
                    "headerHash": node.chain[n].hash(),
                    "root": mmr.root(),
                    "proof": _codec.encode(mmr.proof(n))}
        if method == "mmr_verifyProof":
            # stateless check (the light-client half exposed for tools)
            from .. import codec as _codec
            from . import mmr as mmr_mod

            if len(params) < 4:
                raise RpcError(INVALID_PARAMS,
                               "expected [root, number, hash, proof]")
            root, number, hh = (_decode(params[0]), params[1],
                                _decode(params[2]))
            if not (isinstance(root, bytes) and isinstance(hh, bytes)
                    and isinstance(number, int)
                    and not isinstance(number, bool) and number >= 0):
                raise RpcError(INVALID_PARAMS,
                               "expected [0x-root, int number, 0x-hash]")
            try:
                proof = _codec.decode(_decode(params[3]))
            except (ValueError, _codec.CodecError) as e:
                raise RpcError(INVALID_PARAMS, str(e)) from e
            return mmr_mod.verify_proof(root, number, hh, proof)
        # -- Eth namespace (Frontier RPC compat surface over the EVM
        # boundary module; ref node/src/rpc.rs:229-328) ------------------
        if method == "web3_clientVersion":
            return "cess-tpu/evm-boundary"
        if method == "web3_sha3":
            # the EVM boundary's SHA3 family (documented sha3_256
            # deviation, chain/evm_interp.py)
            from ..chain.evm_interp import sha3 as _sha3

            try:
                data = _decode(params[0]) if params else None
                if not isinstance(data, bytes):
                    raise ValueError("data must be 0x-prefixed hex")
            except (ValueError, TypeError, IndexError) as e:
                raise RpcError(INVALID_PARAMS, str(e)) from e
            return "0x" + _sha3(data).hex()
        if method == "net_version":
            return str(_eth_chain_id(node.spec))
        if method == "eth_syncing":
            return False            # replicas import synchronously here
        if method == "eth_accounts":
            return []               # keys never live in the node
        if method == "eth_getBlockTransactionCountByNumber":
            if not params:
                raise RpcError(INVALID_PARAMS, "expected [number]")
            try:
                n = self._blocknum(params[0], node.head().number)
            except (ValueError, TypeError) as e:
                raise RpcError(INVALID_PARAMS, str(e)) from e
            if not 0 <= n <= node.head().number:
                return None
            count = rt.state.get("ethereum", "count", n)
            if count is None:
                # receipts pruned out of state for old blocks — the
                # retained block BODY is the correct source there; a
                # node without the body (warp-synced) answers null
                # rather than fabricating "empty"
                body = node.block_bodies.get(n)
                if body is None:
                    return None
                count = len(body.extrinsics)
            return hex(count)
        if method == "eth_chainId":
            return hex(_eth_chain_id(node.spec))
        if method == "eth_blockNumber":
            return hex(node.head().number)
        if method == "eth_gasPrice":
            return hex(rt.evm.base_fee())
        if method == "eth_feeHistory":
            try:
                count = params[0] if params else 4
                if isinstance(count, str):
                    count = int(count, 16)
                newest = rt.state.block - 1
                if len(params) > 1 and params[1] not in ("latest",
                                                        "pending", None):
                    newest = min(newest,
                                 self._blocknum(params[1], newest))
                if not isinstance(count, int) or isinstance(count, bool) \
                        or count < 0:
                    raise ValueError("count must be a non-negative int")
            except (ValueError, TypeError) as e:
                raise RpcError(INVALID_PARAMS,
                               f"expected [count, newest?]: {e}") from e
            return rt.evm.fee_history(count, newest)
        if method == "eth_getBalance":
            if not params or not isinstance(params[0], str):
                raise RpcError(INVALID_PARAMS, "expected [account]")
            # serves both 0x EVM addresses and native account names
            return hex(rt.evm.balance(_decode(params[0])))
        if method == "eth_getCode":
            if not params:
                raise RpcError(INVALID_PARAMS, "expected [address]")
            code = rt.evm.code_at(_decode(params[0]))
            return "0x" + (code.hex() if code else "")
        if method == "eth_call":
            if len(params) < 2:
                raise RpcError(INVALID_PARAMS,
                               "expected [address, calldata, caller?]")
            caller = params[2] if len(params) > 2 else ""
            return "0x" + rt.evm.query(_decode(params[0]),
                                       _decode(params[1]),
                                       caller=caller).hex()
        if method == "eth_sendRawTransaction":
            # Frontier accepts RLP Ethereum txs (fp_self_contained,
            # runtime/src/lib.rs:1576-1579); the framework-native wire
            # is a codec-encoded SignedExtrinsic carrying an evm.* call
            from .. import codec as _codec

            xt = self._decode_extrinsic_param(params)
            if not xt.call.startswith("evm."):
                raise RpcError(INVALID_PARAMS,
                               "raw tx must carry an evm.* call")
            node.submit_signed(xt)
            import hashlib as _hl

            return "0x" + _hl.sha256(_codec.encode(xt)).hexdigest()
        if method == "eth_getLogs":
            flt = params[0] if params and isinstance(params[0], dict) \
                else {}
            try:
                crit = self._norm_criteria(flt)
            except (ValueError, TypeError) as e:
                raise RpcError(INVALID_PARAMS,
                               f"bad filter criteria: {e}") from e
            return self._eth_logs(rt, crit)
        if method == "eth_newFilter":
            flt = params[0] if params and isinstance(params[0], dict) \
                else {}
            return self._new_filter("log", flt)
        if method == "eth_newBlockFilter":
            return self._new_filter("block", {})
        if method == "eth_getFilterChanges":
            return self._filter_changes(node, rt, params)
        if method == "eth_getFilterLogs":
            f = self._get_filter(params)
            if f["type"] != "log":
                raise RpcError(INVALID_PARAMS, "not a log filter")
            return self._eth_logs(rt, f["criteria"])
        if method == "eth_uninstallFilter":
            if not params or not isinstance(params[0], str):
                raise RpcError(INVALID_PARAMS, "expected [filter id]")
            return self._filters.pop(params[0], None) is not None
        if method == "eth_getTransactionCount":
            if not params or not isinstance(params[0], str):
                raise RpcError(INVALID_PARAMS, "expected [account]")
            return hex(rt.system.nonce(params[0]))
        if method == "eth_getStorageAt":
            if len(params) < 2:
                raise RpcError(INVALID_PARAMS, "expected [address, slot]")
            slot = params[1]
            slot = int(slot, 16) if isinstance(slot, str) else int(slot)
            return hex(rt.evm.storage_at(_decode(params[0]), slot))
        # -- tx lifecycle (fc-rpc Eth: receipts / tx objects / blocks,
        #    ref node/src/rpc.rs:229-328) --------------------------------
        if method == "eth_getTransactionReceipt":
            loc = self._txloc(rt, params)
            if loc is None:
                return None
            return self._receipt_obj(node, rt, *loc)
        if method == "eth_getTransactionByHash":
            loc = self._txloc(rt, params)
            if loc is None:
                return None
            block, idx = loc
            body = node.block_bodies.get(block)
            if body is None or idx >= len(body.extrinsics):
                return None
            return self._tx_obj(node, rt, body.extrinsics[idx], block,
                                idx)
        if method == "eth_getBlockByNumber":
            if not params:
                raise RpcError(INVALID_PARAMS, "expected [number, full?]")
            try:
                n = self._blocknum(params[0], node.head().number)
            except (ValueError, TypeError) as e:
                raise RpcError(INVALID_PARAMS, str(e)) from e
            full = bool(params[1]) if len(params) > 1 else False
            return self._eth_block(node, rt, n, full)
        if method == "eth_getBlockByHash":
            if not params or not isinstance(params[0], str):
                raise RpcError(INVALID_PARAMS, "expected [hash, full?]")
            h = _decode(params[0])
            header = node.headers.get(h)
            if header is None or not node._is_canonical(h):
                return None
            full = bool(params[1]) if len(params) > 1 else False
            return self._eth_block(node, rt, header.number, full)
        if method == "eth_getTransactionByBlockNumberAndIndex":
            if len(params) < 2:
                raise RpcError(INVALID_PARAMS, "expected [number, idx]")
            try:
                n = self._blocknum(params[0], node.head().number)
                i = params[1]
                i = int(i, 16) if isinstance(i, str) else int(i)
            except (ValueError, TypeError) as e:
                raise RpcError(INVALID_PARAMS, str(e)) from e
            body = node.block_bodies.get(n)
            if body is None or not 0 <= i < len(body.extrinsics):
                return None
            return self._tx_obj(node, rt, body.extrinsics[i], n, i)
        if method == "eth_getBlockReceipts":
            if not params:
                raise RpcError(INVALID_PARAMS, "expected [number]")
            try:
                n = self._blocknum(params[0], node.head().number)
            except (ValueError, TypeError) as e:
                raise RpcError(INVALID_PARAMS, str(e)) from e
            if not 0 <= n <= node.head().number:
                return None
            count = rt.state.get("ethereum", "count", n)
            if count is None:
                # the 'count' key is only written when a receipt lands,
                # so a canonical in-retention block with no signed
                # extrinsics has none — the spec shape for an existing
                # empty block is [], not null. null stays reserved for
                # blocks pruned out of state / outside retention,
                # which tooling must treat as unknown
                pruned_to = rt.state.get("ethereum", "pruned_to",
                                         default=0)
                if n >= pruned_to and n < len(node.chain):
                    return []
                return None
            cumulative = 0
            out = []
            for i in range(count):
                rc = rt.state.get("ethereum", "receipt", n, i)
                if rc is None:
                    continue
                cumulative += rc[5]
                out.append(self._receipt_obj(node, rt, n, i,
                                             _cumulative=cumulative))
            return out
        if method == "eth_estimateGas":
            if not params or not isinstance(params[0], dict):
                raise RpcError(INVALID_PARAMS, "expected [call object]")
            return self._estimate_gas(rt, params[0])
        raise RpcError(METHOD_NOT_FOUND, f"unknown method {method!r}")

    @staticmethod
    def _decode_extrinsic_param(params) -> "object":
        """One decode contract for every hex-extrinsic parameter:
        malformed input is INVALID_PARAMS, never a server error."""
        from .. import codec as _codec
        from ..chain.extrinsic import SignedExtrinsic

        if not params or not isinstance(params[0], str):
            raise RpcError(INVALID_PARAMS, "expected [hex extrinsic]")
        try:
            raw = _decode(params[0])
            if not isinstance(raw, bytes):
                raise ValueError("hex must be 0x-prefixed")
            xt = _codec.decode(raw)
        except (ValueError, _codec.CodecError) as e:
            raise RpcError(INVALID_PARAMS, str(e)) from e
        if not isinstance(xt, SignedExtrinsic):
            raise RpcError(INVALID_PARAMS,
                           "bytes do not decode to a SignedExtrinsic")
        return xt

    def _peer_count(self) -> int:
        if self.service is None:
            return 0
        return sum(1 for c in self.service.conns if c.alive)

    # -- Eth tx lifecycle (receipts / tx objects / blocks) -----------------
    def _txloc(self, rt, params):
        if not params or not isinstance(params[0], str):
            raise RpcError(INVALID_PARAMS, "expected [tx hash]")
        h = _decode(params[0])
        if not isinstance(h, bytes) or len(h) != 32:
            raise RpcError(INVALID_PARAMS, "tx hash must be 32 bytes")
        return rt.state.get("ethereum", "txloc", h)

    @staticmethod
    def _canonical_hash(node, n: int) -> bytes:
        return node.chain[n].hash() if 0 <= n < len(node.chain) \
            else b"\0" * 32

    @staticmethod
    def _block_base_fee(rt, block: int) -> int:
        """The base fee IN FORCE at ``block``: recorded by the NEXT
        block's fee-market roll, live for the head."""
        rec = rt.state.get("evm", "fee_hist", block)
        return rec[0] if rec is not None else rt.evm.base_fee()

    def _tx_obj(self, node, rt, xt, block: int, idx: int) -> dict:
        import hashlib as _hl

        from .. import codec as _codec
        from ..chain.evm import GAS_CAP, eth_address

        txhash = _hl.sha256(_codec.encode(xt)).digest()
        call = getattr(xt, "call", "")
        args = getattr(xt, "args", ())
        kw = dict(getattr(xt, "kwargs", ()) or ())
        to, value, gas, data = None, 0, GAS_CAP, b""
        if call == "evm.call":
            to = args[0] if args else None
            data = args[1] if len(args) > 1 else b""
            gas = args[2] if len(args) > 2 else kw.get("gas_limit",
                                                      GAS_CAP)
            value = args[3] if len(args) > 3 else kw.get("value", 0)
        elif call == "evm.deploy":
            data = args[0] if args else b""
            gas = args[1] if len(args) > 1 else kw.get("gas_limit",
                                                      GAS_CAP)
            value = args[2] if len(args) > 2 else kw.get("value", 0)
        return {
            "hash": "0x" + txhash.hex(),
            "nonce": hex(getattr(xt, "nonce", 0)),
            "blockNumber": hex(block), "transactionIndex": hex(idx),
            "blockHash": "0x" + self._canonical_hash(node, block).hex(),
            "from": "0x" + eth_address(getattr(xt, "signer", "")).hex(),
            "to": "0x" + to.hex() if isinstance(to, bytes) else None,
            "value": hex(value if isinstance(value, int) else 0),
            "gas": hex(gas if isinstance(gas, int) else GAS_CAP),
            "gasPrice": hex(self._block_base_fee(rt, block)),
            "input": "0x" + (data.hex() if isinstance(data, bytes)
                             else ""),
            "call": call,                   # framework extension
        }

    def _receipt_obj(self, node, rt, block: int, idx: int,
                     _cumulative: int | None = None):
        from ..chain.evm import eth_address

        rc = rt.state.get("ethereum", "receipt", block, idx)
        if rc is None:
            return None
        (txhash, signer, call, status, error, gas_used, contract,
         log_start, log_count) = rc
        bh = "0x" + self._canonical_hash(node, block).hex()
        # whole-block serving passes the running sum; the single-tx
        # path pays one prefix scan (an O(count^2) whole-block loop
        # through this path was review-caught)
        cumulative = _cumulative
        if cumulative is None:
            cumulative = 0
            for i in range(idx + 1):
                r2 = rt.state.get("ethereum", "receipt", block, i)
                if r2 is not None:
                    cumulative += r2[5]
        logs = []
        for seq in range(log_start, log_start + log_count):
            lg = rt.evm.log_at(block, seq)
            if lg is None:
                continue
            addr, topics, data = lg
            logs.append({
                "address": "0x" + addr.hex(),
                "topics": ["0x" + t.hex() for t in topics],
                "data": "0x" + data.hex(),
                "blockNumber": hex(block), "logIndex": hex(seq),
                "transactionIndex": hex(idx),
                "transactionHash": "0x" + txhash.hex(),
                "blockHash": bh, "removed": False})
        to = None
        body = node.block_bodies.get(block)
        if body is not None and idx < len(body.extrinsics):
            bxt = body.extrinsics[idx]
            if getattr(bxt, "call", "") == "evm.call" \
                    and getattr(bxt, "args", ()):
                to = bxt.args[0]
        return {
            "transactionHash": "0x" + txhash.hex(),
            "transactionIndex": hex(idx),
            "blockNumber": hex(block), "blockHash": bh,
            "from": "0x" + eth_address(signer).hex(),
            "to": "0x" + to.hex() if isinstance(to, bytes) else None,
            "status": hex(status), "error": error or None,
            "gasUsed": hex(gas_used),
            "cumulativeGasUsed": hex(cumulative),
            "contractAddress": "0x" + contract.hex() if contract
            else None,
            "logs": logs, "logsBloom": "0x" + "00" * 256,
            "effectiveGasPrice": hex(self._block_base_fee(rt, block)),
            "type": "0x2", "call": call}

    def _eth_block(self, node, rt, n, full: bool):
        from .. import constants
        from ..chain.evm import GAS_CAP, eth_address

        if not isinstance(n, int) or n < 0 or n >= len(node.chain):
            return None
        header = node.chain[n]
        count = rt.state.get("ethereum", "count", n, default=0)
        receipts = [rt.state.get("ethereum", "receipt", n, i)
                    for i in range(count)]
        body = node.block_bodies.get(n)
        txs = []
        for i, rc in enumerate(receipts):
            if rc is None:
                continue
            if full and body is not None and i < len(body.extrinsics):
                txs.append(self._tx_obj(node, rt, body.extrinsics[i],
                                        n, i))
            else:
                txs.append("0x" + rc[0].hex())
        return {
            "number": hex(n), "hash": "0x" + header.hash().hex(),
            "parentHash": "0x" + header.parent.hex(),
            "stateRoot": "0x" + header.state_root.hex(),
            "miner": "0x" + eth_address(header.author).hex(),
            "author": header.author,       # framework extension
            # identical to the TIMESTAMP opcode env: the chain clock is
            # DERIVED (block * slot duration, runtime.init_block), so
            # this formula IS system.now_ms for block n
            "timestamp": hex(n * constants.MILLISECS_PER_BLOCK // 1000),
            "baseFeePerGas": hex(self._block_base_fee(rt, n)),
            "gasUsed": hex(sum(rc[5] for rc in receipts
                               if rc is not None)),
            "gasLimit": hex(GAS_CAP), "transactions": txs,
            "logsBloom": "0x" + "00" * 256, "extraData": "0x"}

    def _estimate_gas(self, rt, call_obj: dict) -> str:
        from ..chain.state import DispatchError

        try:
            to = call_obj.get("to")
            to_b = _decode(to) if to else None
            data_b = _decode(call_obj.get("data")
                             or call_obj.get("input") or "0x")
            value = call_obj.get("value", 0)
            if isinstance(value, str):
                value = int(value, 16)
            caller = call_obj.get("from", "")
            # simulation needs a NATIVE account identity for funding;
            # a bare 0x address has no reverse mapping, so it
            # estimates as the anonymous caller
            if not isinstance(caller, str) or caller.startswith("0x"):
                caller = ""
            if to_b is not None and (not isinstance(to_b, bytes)
                                     or len(to_b) != 20):
                raise ValueError("to must be a 20-byte address")
            if not isinstance(data_b, bytes):
                raise ValueError("data must be 0x hex")
        except (ValueError, TypeError) as e:
            raise RpcError(INVALID_PARAMS, str(e)) from e
        try:
            return hex(rt.evm.estimate(to_b, data_b, caller=caller,
                                       value=value))
        except DispatchError as e:
            raise RpcError(SERVER_ERROR, str(e)) from e

    # -- Eth filters (the EthFilter namespace, node/src/rpc.rs:229-328) ----
    @staticmethod
    def _blocknum(v, default):
        # standard Eth block tags + hex strings + plain ints
        if v is None or v in ("latest", "pending"):
            return default
        if v == "earliest":
            return 0
        return int(v, 16) if isinstance(v, str) else int(v)

    def _norm_criteria(self, flt: dict) -> dict:
        """Decode + validate filter criteria ONCE (at eth_newFilter /
        per eth_getLogs call, where the spec reports errors) — polls
        then work with pre-decoded values. Raises ValueError/TypeError
        on malformed input."""
        crit = {"frm": self._blocknum(flt.get("fromBlock"), 0),
                "to": flt.get("toBlock")}
        self._blocknum(crit["to"], 0)           # parse-check now
        addr = flt.get("address")
        def as_bytes(v):
            # 0x-hex strings or raw bytes ONLY — bytes(int) would
            # allocate attacker-sized zero buffers under the node lock,
            # and a prefixless hex string would silently never match
            if isinstance(v, str):
                got = _decode(v)
                if not isinstance(got, bytes):
                    raise ValueError(f"hex string must be 0x-prefixed: "
                                     f"{v[:16]!r}")
                return got
            if isinstance(v, (bytes, bytearray)):
                return bytes(v)
            raise ValueError(f"expected hex string, got {type(v).__name__}")

        if isinstance(addr, str):
            crit["addrs"] = frozenset({as_bytes(addr)})
        elif isinstance(addr, list):            # arrays are valid per spec
            crit["addrs"] = frozenset(as_bytes(a) for a in addr)
        elif addr is None:
            crit["addrs"] = None
        else:
            raise ValueError("address must be a hex string or array")
        tops = flt.get("topics")
        if tops:
            norm = []
            for want in tops:
                if want is None:
                    norm.append(None)           # wildcard position
                else:
                    opts = want if isinstance(want, list) else [want]
                    norm.append([as_bytes(o) for o in opts])
            crit["topics"] = norm
        else:
            crit["topics"] = None
        return crit

    def _eth_logs(self, rt, crit, frm=None):
        """Shared by eth_getLogs / eth_getFilterLogs / filter polling.
        ``crit`` is normalized; ``frm`` (poll cursor) only ever
        narrows the client's fromBlock, never widens it."""
        lo = crit["frm"] if frm is None else max(frm, crit["frm"])
        # clamp: an attacker-chosen huge toBlock must not spin the
        # range loop while holding the node lock
        to = min(self._blocknum(crit["to"], rt.state.block),
                 rt.state.block)
        logs = rt.evm.logs_in_range(lo, to)
        if crit["addrs"] is not None:
            logs = [lg for lg in logs if lg["address"] in crit["addrs"]]
        if crit["topics"]:
            def tmatch(lg):
                lt = lg["topics"]
                for i, opts in enumerate(crit["topics"]):
                    if opts is None:
                        continue
                    if i >= len(lt) or lt[i] not in opts:
                        return False
                return True

            logs = [lg for lg in logs if tmatch(lg)]
        return logs

    MAX_FILTERS = 256
    FILTER_IDLE_TTL = 300.0    # unpolled filters are evictable (s)

    def _new_filter(self, kind: str, criteria: dict) -> str:
        import time as _time

        now = _time.time()
        if len(self._filters) >= self.MAX_FILTERS:
            # evict idle filters first (the reference's EthFilter pool
            # expires them); only a table full of LIVE filters errors
            for fid in [fid for fid, f in self._filters.items()
                        if now - f["touched"] > self.FILTER_IDLE_TTL]:
                del self._filters[fid]
            if len(self._filters) >= self.MAX_FILTERS:
                raise RpcError(SERVER_ERROR, "filter table full")
        crit = None
        if kind == "log":
            try:
                crit = self._norm_criteria(criteria)
            except (ValueError, TypeError) as e:
                raise RpcError(INVALID_PARAMS,
                               f"bad filter criteria: {e}") from e
        head = self.node.head()           # handle() runs under the lock
        self._filter_seq += 1
        fid = hex(self._filter_seq)
        self._filters[fid] = {"type": kind, "criteria": crit,
                              "cursor": head.number,
                              "cursor_hash": head.hash(),
                              "touched": now}
        return fid

    def _get_filter(self, params) -> dict:
        import time as _time

        if not params or not isinstance(params[0], str) \
                or params[0] not in self._filters:
            raise RpcError(INVALID_PARAMS, "unknown filter id")
        f = self._filters[params[0]]
        f["touched"] = _time.time()
        return f

    @staticmethod
    def cursor_window(node, cursor: int, cursor_hash: bytes):
        """Reorg-checked poll window shared by EthFilter polls and the
        WS EthPubSub pusher: returns (since, head). A cursor whose
        block hash vanished (reorg) rewinds to the finalized block —
        reorgs never cross finality — so events on the new canonical
        branch are redelivered rather than silently lost:
        at-least-once across reorgs, exactly-once on a stable chain."""
        head = node.head()
        if cursor > head.number \
                or node.chain[cursor].hash() != cursor_hash:
            cursor = min(node.finalized, head.number)
        return cursor, head

    def _filter_changes(self, node, rt, params):
        """New matches since the last poll (see cursor_window)."""
        f = self._get_filter(params)
        since, head = self.cursor_window(node, f["cursor"],
                                         f["cursor_hash"])
        if f["type"] == "block":
            out = ["0x" + node.chain[n].hash().hex()
                   for n in range(since + 1, head.number + 1)]
        else:
            out = self._eth_logs(rt, f["criteria"], frm=since + 1)
        # commit the cursor only after a successful read
        f["cursor"], f["cursor_hash"] = head.number, head.hash()
        return out
