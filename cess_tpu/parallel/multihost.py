"""Multi-host data plane: jax.distributed + global mesh + corpus runs.

BASELINE.json config 5 ("tee-worker e2e: segment+encode+tag 1 TiB
corpus, pmap across v5e-16") needs more than one host: a v5e-16 slice
spans multiple host VMs, and a 1 TiB corpus must stream through
host-sharded ingest. The reference scales the analogous work by
process-level replication over libp2p (SURVEY.md §2.4); the TPU-native
equivalent is:

- ``init_multihost``: one ``jax.distributed.initialize`` per host
  process (coordinator address + process id from args or the standard
  env), after which ``jax.devices()`` is the GLOBAL device set and
  XLA collectives ride ICI within a slice / DCN across hosts.
- ``global_mesh``: the same (seg, byte) mesh as parallel.mesh but over
  the global device set — per-device programs are unchanged; only the
  sharding spans hosts.
- ``run_corpus``: streams a corpus through the sharded pipeline step
  in global batches; each host feeds ONLY its local shard
  (``jax.make_array_from_process_local_data``) so no host ever holds
  the full batch — the 1 TiB corpus is ingested host-parallel.

Exercised at BOTH process counts: single-process on the 8-device CPU
test mesh (tests/test_mesh.py) and as two real OS processes running
jax.distributed with gloo CPU collectives — init_multihost +
make_array_from_process_local_data crossing an actual process boundary
(tests/test_multiproc.py), the lines that differ in deployment.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Callable, Iterator

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.pipeline import StoragePipeline
from . import mesh as _mesh


def init_multihost(coordinator_address: str | None = None,
                   num_processes: int | None = None,
                   process_id: int | None = None) -> int:
    """Initialize the multi-host runtime; returns the process count.

    No-op for single-process runs (nothing configured). Arguments
    default to the standard JAX coordination env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID) —
    the same bootstrap contract as any jax.distributed deployment.
    """
    coordinator_address = coordinator_address \
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return 1
    if num_processes is None:
        num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if process_id is None:
        process_id = int(os.environ.get("JAX_PROCESS_ID", "0"))
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    return num_processes


def global_mesh(seg: int | None = None, byte: int = 1) -> Mesh:
    """The (seg, byte) mesh over the GLOBAL device set (all hosts)."""
    return _mesh.make_mesh(jax.devices(), seg=seg, byte=byte)


@dataclasses.dataclass(frozen=True)
class CorpusPlan:
    """How a corpus streams through the mesh: global batches of
    ``batch_segments`` segments, each host contributing its local
    slice of the 'seg' axis."""

    total_bytes: int
    segment_size: int
    batch_segments: int

    @property
    def total_segments(self) -> int:
        return -(-self.total_bytes // self.segment_size)

    @property
    def num_batches(self) -> int:
        return -(-self.total_segments // self.batch_segments)


def run_corpus(pipeline: StoragePipeline, mesh: Mesh, plan: CorpusPlan,
               local_batch_fn: Callable[[int, int], np.ndarray],
               challenge_seed: bytes = b"corpus-round",
               ) -> Iterator[dict]:
    """Stream ``plan`` through the sharded encode+tag+prove+verify
    step (parallel.mesh.sharded_pipeline_step) in global batches.

    ``local_batch_fn(batch_index, local_segments)`` returns THIS
    host's [local_segments, k, n_local_bytes] uint8 slice — reading
    from local disk/object store; the global array is assembled
    across hosts without any host materializing the full batch.

    Yields one summary dict per global batch (verified counts + light
    checksums), never the bulk data — host memory stays O(batch/hosts).
    """
    import jax.numpy as jnp

    from ..ops import podr2

    cfg = pipeline.config
    step = _mesh.sharded_pipeline_step(pipeline, mesh)
    idx, nu = podr2.gen_challenge(challenge_seed, cfg.blocks_per_fragment)
    seg_shards = mesh.shape["seg"]
    byte_shards = mesh.shape["byte"]
    procs = jax.process_count()
    if plan.batch_segments % seg_shards or plan.batch_segments % procs:
        raise ValueError(
            f"batch_segments {plan.batch_segments} must divide by both "
            f"the seg axis ({seg_shards}) and process count ({procs})")
    frag_bytes = cfg.fragment_size
    local_segs = plan.batch_segments // procs
    data_sharding = NamedSharding(mesh, P("seg", None, "byte"))
    ids_sharding = NamedSharding(mesh, P("seg", None))
    # the verified count is reduced INSIDE jit to a fully-replicated
    # scalar: with multiple processes, per-host numpy reads of a
    # sharded global array are not addressable
    count_ok = jax.jit(
        lambda ok, w: jnp.sum(ok * w[:, None], dtype=jnp.int32),
        out_shardings=NamedSharding(mesh, P()))
    rows = cfg.k + cfg.m
    done = 0
    for b in range(plan.num_batches):
        want = min(plan.batch_segments, plan.total_segments - done)
        # hosts own fixed contiguous [i*local_segs, (i+1)*local_segs)
        # slots of the global batch; real segments fill the prefix
        start = jax.process_index() * local_segs
        local_want = min(local_segs, max(0, want - start))
        local = local_batch_fn(b, local_want) if local_want else \
            np.zeros((0, cfg.k, frag_bytes), dtype=np.uint8)
        assert local.shape == (local_want, cfg.k, frag_bytes), \
            f"host batch shape {local.shape}"
        # the FINAL batch may be partial: pad to the static batch shape
        # (shapes are compiled-in) and mask padded segments out of the
        # verified count
        pad = local_segs - local_want
        if pad:
            local = np.concatenate(
                [local, np.zeros((pad, cfg.k, frag_bytes),
                                 dtype=np.uint8)])
        weights_local = np.concatenate(
            [np.ones(local_want, np.int32), np.zeros(pad, np.int32)])
        data = jax.make_array_from_process_local_data(data_sharding, local)
        ids_local = (np.arange(local_segs * rows, dtype=np.int32)
                     .reshape(local_segs, rows)
                     + (b * procs + jax.process_index())
                     * plan.batch_segments * rows)
        ids = jax.make_array_from_process_local_data(ids_sharding,
                                                     ids_local)
        w = jax.make_array_from_process_local_data(
            NamedSharding(mesh, P("seg")), weights_local)
        shards, tags, ok = step(data, ids, idx, nu)
        done += want
        yield {
            "batch": b,
            "segments": want,
            "verified": int(np.asarray(count_ok(ok, w))),
            "expected": want * rows,
        }
