"""Version-guarded jax compatibility shims for the parallel layer.

The repo targets a range of jax releases and two APIs it depends on
moved across them:

- ``shard_map``: ``jax.shard_map`` on new jax; on jax 0.4.x it lives
  at ``jax.experimental.shard_map.shard_map`` (same signature for the
  mesh/in_specs/out_specs kwargs we use).
- CPU device-count override: new jax has the
  ``jax_num_cpu_devices`` config; older releases only honor the
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` env var, and
  ONLY if it is set before the (lazy) CPU backend initializes.

Everything version-dependent that parallel/mesh.py,
parallel/multihost.py, tests/conftest.py and the multiprocess test
scripts need lives here, so a jax upgrade is a one-file audit.
"""
from __future__ import annotations

import os

import jax


def resolve_shard_map():
    """The shard_map entry point for this jax version."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    from jax.experimental.shard_map import shard_map as exp_shard_map

    return exp_shard_map


shard_map = resolve_shard_map()


def set_cpu_device_count(n: int) -> None:
    """Ask for ``n`` CPU devices. Must run before any jax call that
    initializes the backend (jax.devices(), first trace, ...)."""
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        # replace (not append) any inherited device-count flag: test
        # subprocesses inherit the parent pytest's XLA_FLAGS and must
        # still be able to ask for a different mesh size
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if "xla_force_host_platform_device_count" not in f]
        flags.append(f"--xla_force_host_platform_device_count={n}")
        os.environ["XLA_FLAGS"] = " ".join(flags)
