"""Device mesh + sharded storage-pipeline steps.

Axes:
- ``seg``  — the segment batch axis (data parallel; the reference's
  "embarrassingly parallel along the segment axis" structure,
  SURVEY.md §5 long-context note).
- ``byte`` — the intra-fragment byte/block axis. GF column operations
  are columnwise-independent so encode shards cleanly; PoDR2 proof
  aggregation (mu, sigma) reduces over this axis with ``psum`` — the
  audit-path collective.

The data plane runs under ``shard_map`` so the per-device program is
exactly the single-chip program (including Pallas kernels), with
explicit collectives where the math needs them — the idiomatic
JAX/TPU framing of the reference's work-distribution parallelism.

Topology invariance: PoDR2 PRF values are always generated for the
full block range and sliced locally, so tags/proofs are bit-identical
on any mesh shape (protocol invariant).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.pipeline import StoragePipeline
from ..ops import pfield as pf
from ..ops import podr2
from .compat import shard_map


def make_mesh(devices=None, seg: int | None = None, byte: int = 1) -> Mesh:
    """Build a (seg, byte) mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if seg is None:
        seg = n // byte
    if seg * byte != n:
        raise ValueError(f"mesh {seg}x{byte} != {n} devices")
    arr = np.array(devices).reshape(seg, byte)
    return Mesh(arr, axis_names=("seg", "byte"))


def sharded_pipeline_step(pipeline: StoragePipeline, mesh: Mesh):
    """jit the FULL pipeline step sharded over (seg, byte).

    Per step: RS-encode the segment batch, PoDR2-tag every fragment,
    build an aggregated challenge proof (mu, sigma) per fragment with
    cross-device psum over the sharded block axis, and TEE-verify it.

    Inputs: segments [B, k, n] uint8 (fragment-major; B % mesh.seg == 0,
            n % (byte * BLOCK_BYTES) == 0); fragment ids [B, k+m] int32
            (protocol-level identifiers, sharded over 'seg'); challenge
            (idx [c], nu [c]) from podr2.gen_challenge — a fresh one per
            audit round (replicated traced inputs, NOT baked into the
            program: a fixed challenge would let a prover store only the
            challenged blocks).
    Output: fragments [B, k+m, n] (sharded same as input),
            tags [B, k+m, blocks, 2] (block axis sharded over 'byte';
            trailing axis = the two F_p^2 MAC limbs, replicated),
            ok [B, k+m] bool verification verdicts (replicated).
    """
    cfg = pipeline.config
    key = pipeline.podr2_key
    sectors = key.alpha.shape[0]
    byte_shards = mesh.shape["byte"]
    blocks_total = cfg.blocks_per_fragment
    assert blocks_total % byte_shards == 0, (
        f"{blocks_total} blocks not divisible by byte axis {byte_shards}")
    blocks_local = blocks_total // byte_shards

    def step(data, ids2d, idx, nu):
        b, k, n_local = data.shape
        parity = pipeline._parity(data)
        shards = jnp.concatenate([data, parity], axis=-2)      # [b, k+m, n_local]
        rows = shards.shape[-2]
        frag_ids = ids2d.reshape(b * rows)

        # --- tag: global PRF, local slice --------------------------------
        off = jax.lax.axis_index("byte") * blocks_local
        m = podr2.fragment_to_elems(shards.reshape(b * rows, n_local),
                                    sectors)                   # [F, bl_local, s]
        f_all = jax.vmap(
            lambda i: podr2.prf_elems(key.prf_key, i, blocks_total,
                                      key.limbs))(frag_ids)
        f_loc = jax.lax.dynamic_slice_in_dim(f_all, off, blocks_local, axis=1)
        tags = jax.vmap(podr2.tag_from_elems, in_axes=(None, 0, 0))(
            key.alpha, f_loc, m)                               # [F, bl_local, 2]

        # --- prove: masked local partials, psum over 'byte' ---------------
        in_range = (idx >= off) & (idx < off + blocks_local)
        local_idx = jnp.clip(idx - off, 0, blocks_local - 1)
        w = jnp.where(in_range, nu, 0).astype(jnp.uint32)      # [c]
        m_c = jnp.take(m, local_idx, axis=1)                   # [F, c, s]
        t_c = jnp.take(tags, local_idx, axis=1)                # [F, c, 2]
        mu_part = pf.summod(pf.mulmod(w[None, :, None], m_c), axis=1)   # [F, s]
        sg_part = pf.summod(pf.mulmod(w[None, :, None], t_c), axis=1)   # [F, 2]
        mu = pf.psum_mod(mu_part, "byte")
        sigma = pf.psum_mod(sg_part, "byte")

        # --- verify (TEE role) -------------------------------------------
        ok = jax.vmap(
            lambda fa, u, s: podr2.verify_from_f(key.alpha, fa, idx, nu, u, s)
        )(f_all, mu, sigma)

        return (shards, tags.reshape(b, rows, blocks_local, 2),
                ok.reshape(b, rows))

    mapped = shard_map(        # compat: jax.shard_map moved across versions
        step,
        mesh=mesh,
        in_specs=(P("seg", None, "byte"), P("seg", None), P(), P()),
        out_specs=(P("seg", None, "byte"), P("seg", None, "byte", None),
                   P("seg", None)),
    )
    return jax.jit(mapped)
