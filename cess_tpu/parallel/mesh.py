"""Device mesh + sharded storage-pipeline steps.

Axes:
- ``seg``  — the segment batch axis (data parallel; the reference's
  "embarrassingly parallel along the segment axis" structure,
  SURVEY.md §5 long-context note).
- ``byte`` — the intra-fragment byte/block axis. GF column operations
  are columnwise-independent so encode shards cleanly; PoDR2 proof
  aggregation (mu, sigma) reduces over this axis with ``psum`` — the
  audit-path collective.

The data plane runs under ``shard_map`` so the per-device program is
exactly the single-chip program (including Pallas kernels), with
explicit collectives where the math needs them — the idiomatic
JAX/TPU framing of the reference's work-distribution parallelism.

Topology invariance: PoDR2 PRF values are always generated for the
full block range and sliced locally, so tags/proofs are bit-identical
on any mesh shape (protocol invariant).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.pipeline import StoragePipeline
from ..ops import pfield as pf
from ..ops import podr2
from .compat import shard_map


def make_mesh(devices=None, seg: int | None = None, byte: int = 1) -> Mesh:
    """Build a (seg, byte) mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if seg is None:
        seg = n // byte
    if seg * byte != n:
        raise ValueError(f"mesh {seg}x{byte} != {n} devices")
    arr = np.array(devices).reshape(seg, byte)
    return Mesh(arr, axis_names=("seg", "byte"))


def sharded_pipeline_step(pipeline: StoragePipeline, mesh: Mesh):
    """jit the FULL pipeline step sharded over (seg, byte).

    Per step: RS-encode the segment batch, PoDR2-tag every fragment,
    build an aggregated challenge proof (mu, sigma) per fragment with
    cross-device psum over the sharded block axis, and TEE-verify it.

    Inputs: segments [B, k, n] uint8 (fragment-major; B % mesh.seg == 0,
            n % (byte * BLOCK_BYTES) == 0); fragment ids [B, k+m] int32
            (protocol-level identifiers, sharded over 'seg'); challenge
            (idx [c], nu [c]) from podr2.gen_challenge — a fresh one per
            audit round (replicated traced inputs, NOT baked into the
            program: a fixed challenge would let a prover store only the
            challenged blocks).
    Output: fragments [B, k+m, n] (sharded same as input),
            tags [B, k+m, blocks, 2] (block axis sharded over 'byte';
            trailing axis = the two F_p^2 MAC limbs, replicated),
            ok [B, k+m] bool verification verdicts (replicated).
    """
    cfg = pipeline.config
    key = pipeline.podr2_key
    sectors = key.alpha.shape[0]
    byte_shards = mesh.shape["byte"]
    blocks_total = cfg.blocks_per_fragment
    assert blocks_total % byte_shards == 0, (
        f"{blocks_total} blocks not divisible by byte axis {byte_shards}")
    blocks_local = blocks_total // byte_shards

    def step(data, ids2d, idx, nu):
        b, k, n_local = data.shape
        parity = pipeline._parity(data)
        shards = jnp.concatenate([data, parity], axis=-2)      # [b, k+m, n_local]
        rows = shards.shape[-2]
        frag_ids = ids2d.reshape(b * rows)

        # --- tag: global PRF, local slice --------------------------------
        off = jax.lax.axis_index("byte") * blocks_local
        m = podr2.fragment_to_elems(shards.reshape(b * rows, n_local),
                                    sectors)                   # [F, bl_local, s]
        f_all = jax.vmap(
            lambda i: podr2.prf_elems(key.prf_key, i, blocks_total,
                                      key.limbs))(frag_ids)
        f_loc = jax.lax.dynamic_slice_in_dim(f_all, off, blocks_local, axis=1)
        tags = jax.vmap(podr2.tag_from_elems, in_axes=(None, 0, 0))(
            key.alpha, f_loc, m)                               # [F, bl_local, 2]

        # --- prove: masked local partials, psum over 'byte' ---------------
        in_range = (idx >= off) & (idx < off + blocks_local)
        local_idx = jnp.clip(idx - off, 0, blocks_local - 1)
        w = jnp.where(in_range, nu, 0).astype(jnp.uint32)      # [c]
        m_c = jnp.take(m, local_idx, axis=1)                   # [F, c, s]
        t_c = jnp.take(tags, local_idx, axis=1)                # [F, c, 2]
        mu_part = pf.summod(pf.mulmod(w[None, :, None], m_c), axis=1)   # [F, s]
        sg_part = pf.summod(pf.mulmod(w[None, :, None], t_c), axis=1)   # [F, 2]
        mu = pf.psum_mod(mu_part, "byte")
        sigma = pf.psum_mod(sg_part, "byte")

        # --- verify (TEE role) -------------------------------------------
        ok = jax.vmap(
            lambda fa, u, s: podr2.verify_from_f(key.alpha, fa, idx, nu, u, s)
        )(f_all, mu, sigma)

        return (shards, tags.reshape(b, rows, blocks_local, 2),
                ok.reshape(b, rows))

    mapped = shard_map(        # compat: jax.shard_map moved across versions
        step,
        mesh=mesh,
        in_specs=(P("seg", None, "byte"), P("seg", None), P(), P()),
        out_specs=(P("seg", None, "byte"), P("seg", None, "byte", None),
                   P("seg", None)),
    )
    return jax.jit(mapped)


def sharded_stream_step(pipeline: StoragePipeline, mesh: Mesh,
                        pair_ids: bool = False):
    """The fused encode+tag step (no prove/verify) as ONE shard_map
    program over (seg, byte) — the multi-chip program behind
    :func:`stream_entry`. Same topology-invariance contract as
    sharded_pipeline_step: PRF values are generated for the full block
    range and sliced locally, so tags are bit-identical to the
    single-device fused forward on any mesh shape.

    In: data [B, k, n] uint8 (fragment-major), ids [B, k+m] int32
    (or [B, k+m, 2] uint32 hash word pairs when ``pair_ids``).
    Out: {"fragments" [B, k+m, n], "tags" [B, k+m, blocks, limbs]} —
    the StoragePipeline.forward shape contract.
    """
    cfg = pipeline.config
    key = pipeline.podr2_key
    sectors = key.alpha.shape[0]
    byte_shards = mesh.shape["byte"]
    blocks_total = cfg.blocks_per_fragment
    assert blocks_total % byte_shards == 0, (
        f"{blocks_total} blocks not divisible by byte axis {byte_shards}")
    blocks_local = blocks_total // byte_shards

    def step(data, ids):
        b, k, n_local = data.shape
        parity = pipeline._parity(data)
        shards = jnp.concatenate([data, parity], axis=-2)
        rows = shards.shape[-2]
        frag_ids = ids.reshape((b * rows, 2) if pair_ids else (b * rows,))
        off = jax.lax.axis_index("byte") * blocks_local
        m = podr2.fragment_to_elems(shards.reshape(b * rows, n_local),
                                    sectors)
        f_all = jax.vmap(
            lambda i: podr2.prf_elems(key.prf_key, i, blocks_total,
                                      key.limbs))(frag_ids)
        f_loc = jax.lax.dynamic_slice_in_dim(f_all, off, blocks_local,
                                             axis=1)
        tags = jax.vmap(podr2.tag_from_elems, in_axes=(None, 0, 0))(
            key.alpha, f_loc, m)
        return shards, tags.reshape(b, rows, blocks_local, key.limbs)

    ids_spec = P("seg", None, None) if pair_ids else P("seg", None)
    mapped = shard_map(
        step,
        mesh=mesh,
        in_specs=(P("seg", None, "byte"), ids_spec),
        out_specs=(P("seg", None, "byte"), P("seg", None, "byte", None)),
    )
    jitted = jax.jit(mapped)

    def run(data, ids):
        shards, tags = jitted(data, ids)
        return {"fragments": shards, "tags": tags}

    return run


def stream_entry(pipeline: StoragePipeline, mesh: Mesh, batch: int,
                 pair_ids: bool = False):
    """Build the (program, put, put_ids) kwargs that point a
    StreamingIngest (cess_tpu/serve/stream.py) at a device mesh:

        ing = StreamingIngest(pipe, batch,
                              **stream_entry(pipe, mesh, batch))

    ``put`` reshapes each staged [batch, segment_size] host chunk to
    fragment-major [batch, k, fragment_size] and places it sharded
    over (seg, byte) in ONE device_put; ``put_ids`` places the id
    batch sharded over 'seg'. The driver itself stays
    topology-agnostic.
    """
    cfg = pipeline.config
    rows = cfg.k + cfg.m
    program = sharded_stream_step(pipeline, mesh, pair_ids)
    data_sh = NamedSharding(mesh, P("seg", None, "byte"))
    ids_sh = NamedSharding(
        mesh, P("seg", None, None) if pair_ids else P("seg", None))

    def put(chunk):
        chunk = np.asarray(chunk).reshape(batch, cfg.k,
                                          cfg.fragment_size)
        return jax.device_put(chunk, data_sh)

    def put_ids(ids):
        ids = np.asarray(ids)
        if pair_ids and ids.size != batch * rows * 2:
            # the driver's default (None) ids are a flat scalar arange
            # — there is no sensible pair-shaped default, so demand
            # explicit ids at the layer whose contract is violated
            raise ValueError(
                "stream_entry(pair_ids=True) requires explicit "
                "[N, k+m, 2] fragment_ids passed to run()/ingest()")
        ids = ids.reshape((batch, rows, 2) if pair_ids
                          else (batch, rows))
        return jax.device_put(ids, ids_sh)

    return {"program": program, "put": put, "put_ids": put_ids}


def pool_stream_entry(pipeline: StoragePipeline, devices, batch: int,
                      pair_ids: bool = False):
    """:func:`stream_entry` against a DevicePool's lane devices
    (cess_tpu/serve/pool.py ``stream_entry`` delegates here): an
    (n_lanes, 1) mesh over exactly the pool's devices in lane order,
    so each staged batch fans its segment axis across every lane.
    ``batch`` must be divisible by the lane count (the seg-axis
    sharding constraint); byte axis stays 1 so any
    ``blocks_per_fragment`` divides it. Tags remain bit-identical to
    the single-device fused program — the topology-invariance
    contract above."""
    devices = list(devices)
    if batch % len(devices) != 0:
        raise ValueError(
            f"stream batch {batch} not divisible by the pool's "
            f"{len(devices)} lanes")
    mesh = make_mesh(devices, seg=len(devices), byte=1)
    return stream_entry(pipeline, mesh, batch, pair_ids)

