"""Device mesh + sharded storage-pipeline steps.

Axes:
- ``seg``  — the segment batch axis (data parallel; the reference's
  "embarrassingly parallel along the segment axis" structure,
  SURVEY.md §5 long-context note).
- ``byte`` — the intra-fragment byte/chunk axis. GF column operations
  are columnwise-independent, so encode shards cleanly; PoDR2
  aggregation reduces over this axis with ``psum``.

The data plane runs under ``shard_map`` so the per-device program is
exactly the single-chip program (including Pallas kernels), with
explicit collectives where the math needs them — the idiomatic
JAX/TPU framing of the reference's work-distribution parallelism.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..models.pipeline import StoragePipeline


def make_mesh(devices=None, seg: int | None = None, byte: int = 1) -> Mesh:
    """Build a (seg, byte) mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if seg is None:
        seg = n // byte
    if seg * byte != n:
        raise ValueError(f"mesh {seg}x{byte} != {n} devices")
    arr = np.array(devices).reshape(seg, byte)
    return Mesh(arr, axis_names=("seg", "byte"))


def sharded_pipeline_step(pipeline: StoragePipeline, mesh: Mesh):
    """jit a pipeline step sharded over (seg, byte).

    Input: segments [B, k, n] uint8 (fragment-major layout; B divisible
    by mesh 'seg', n by 128*'byte'). Output: fragments [B, k+m, n] with
    the same sharding, plus a psum'd checksum exercising the audit-style
    cross-'byte' reduction path.
    """

    def step(data):
        out = pipeline._parity(data)
        shards = jnp.concatenate([data, out], axis=-2)
        # audit-style collective: per-segment byte checksum reduced over
        # the sharded byte axis (placeholder for PoDR2 sigma/mu psum)
        local = jnp.sum(shards.astype(jnp.int32), axis=-1)
        total = jax.lax.psum(local, axis_name="byte")
        return shards, total

    mapped = jax.shard_map(
        step,
        mesh=mesh,
        in_specs=P("seg", None, "byte"),
        out_specs=(P("seg", None, "byte"), P("seg", None)),
    )
    return jax.jit(mapped)
