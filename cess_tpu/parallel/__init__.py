"""Multi-chip scale-out: mesh construction + sharded data-plane steps.

The reference scales by process-level replication over libp2p
(SURVEY.md §2.4); the TPU framework's data plane instead shards the
segment batch across a ``jax.sharding.Mesh`` and lets XLA insert ICI
collectives. The segment axis is embarrassingly parallel for encode;
audit aggregation reduces with psum; repair gathers survivors.
"""
from .mesh import make_mesh, sharded_pipeline_step  # noqa: F401
