"""Structured TEE attestation (round-2 VERDICT item #6 done-criteria):
forged-field and wrong-chain registrations must fail; parsing, not
substring matching (ref primitives/enclave-verify/src/lib.rs:46-219).
"""
import dataclasses

import pytest

from cess_tpu import constants
from cess_tpu.chain.attestation import (ATTESTATION_TIME,
                                        AttestationReport, issue_cert,
                                        issue_report)
from cess_tpu.chain.runtime import Runtime, RuntimeConfig
from cess_tpu.chain.state import DispatchError
from cess_tpu.crypto.rsa import generate_rsa_keypair

D = constants.DOLLARS
MR = b"\x07" * 32
PK = b"podr2-key-bytes"


@pytest.fixture
def env():
    rt = Runtime(RuntimeConfig(era_blocks=1000))
    rt.fund("stash1", 3_000_000 * D)
    rt.apply_extrinsic("stash1", "staking.bond", 2_000_000 * D)
    root_kp = generate_rsa_keypair(1024, seed=11)
    signer_kp = generate_rsa_keypair(1024, seed=12)
    rt.apply_extrinsic("root", "tee_worker.update_whitelist", MR)
    rt.apply_extrinsic("root", "tee_worker.pin_ias_signer", root_kp.public)
    cert = issue_cert(root_kp, "ias-signer", signer_kp.public)
    return rt, root_kp, signer_kp, cert


def register(rt, report, sig, chain, controller="tee1"):
    rt.apply_extrinsic(controller, "tee_worker.register", "stash1",
                       b"peer", PK, report, sig, chain)


def test_valid_chain_registers(env):
    rt, _, signer_kp, cert = env
    report, sig = issue_report(signer_kp, MR, PK, "tee1")
    register(rt, report, sig, (cert,))
    assert rt.tee_worker.worker("tee1").podr2_pk == PK
    # two-link chain (root -> intermediate -> signer) also verifies
    inter_kp = generate_rsa_keypair(1024, seed=13)
    leaf_kp = generate_rsa_keypair(1024, seed=14)
    root_kp = env[1]
    c1 = issue_cert(root_kp, "intermediate", inter_kp.public)
    c2 = issue_cert(inter_kp, "leaf", leaf_kp.public)
    report2, sig2 = issue_report(leaf_kp, MR, PK, "tee2")
    register(rt, report2, sig2, (c1, c2), controller="tee2")
    assert rt.tee_worker.worker("tee2") is not None


def test_unpinned_root_rejected(env):
    rt, _, _, _ = env
    rogue_root = generate_rsa_keypair(1024, seed=21)
    rogue_signer = generate_rsa_keypair(1024, seed=22)
    cert = issue_cert(rogue_root, "rogue", rogue_signer.public)
    report, sig = issue_report(rogue_signer, MR, PK, "tee1")
    with pytest.raises(DispatchError, match="UntrustedSigner"):
        register(rt, report, sig, (cert,))


def test_broken_chain_link_rejected(env):
    rt, root_kp, _, _ = env
    inter_kp = generate_rsa_keypair(1024, seed=23)
    leaf_kp = generate_rsa_keypair(1024, seed=24)
    c1 = issue_cert(root_kp, "intermediate", inter_kp.public)
    # leaf signed by an UNRELATED key, not the intermediate
    other = generate_rsa_keypair(1024, seed=25)
    c2 = issue_cert(other, "leaf", leaf_kp.public)
    report, sig = issue_report(leaf_kp, MR, PK, "tee1")
    with pytest.raises(DispatchError, match="BrokenCertChain"):
        register(rt, report, sig, (c1, c2))


def test_expired_cert_rejected(env):
    rt, root_kp, signer_kp, _ = env
    stale = issue_cert(root_kp, "stale", signer_kp.public,
                       not_after=ATTESTATION_TIME - 1)
    report, sig = issue_report(signer_kp, MR, PK, "tee1")
    with pytest.raises(DispatchError, match="CertExpired"):
        register(rt, report, sig, (stale,))


def test_forged_report_fields_rejected(env):
    rt, _, signer_kp, cert = env
    report, sig = issue_report(signer_kp, MR, PK, "tee1")
    # any mutated field breaks the report signature (parsed + signed
    # as a whole — no substring tricks possible)
    for field, value in [("mrenclave", b"\x08" * 32),
                         ("report_data", b"\x09" * 32),
                         ("timestamp", 123)]:
        forged = dataclasses.replace(report, **{field: value})
        with pytest.raises(DispatchError,
                           match="VerifyCertFailed|NonTeeWorker"):
            register(rt, forged, sig, (cert,))


def test_wrong_binding_rejected(env):
    rt, _, signer_kp, cert = env
    # validly-signed report but for a DIFFERENT podr2 key
    report, sig = issue_report(signer_kp, MR, b"other-key", "tee1")
    with pytest.raises(DispatchError, match="report_data"):
        register(rt, report, sig, (cert,))
    # validly-signed report bound to a DIFFERENT controller
    report2, sig2 = issue_report(signer_kp, MR, PK, "someone-else")
    with pytest.raises(DispatchError, match="report_data"):
        register(rt, report2, sig2, (cert,))


def test_non_whitelisted_mrenclave_rejected(env):
    rt, _, signer_kp, cert = env
    report, sig = issue_report(signer_kp, b"\x0a" * 32, PK, "tee1")
    with pytest.raises(DispatchError, match="NonTeeWorker"):
        register(rt, report, sig, (cert,))


def test_malformed_shapes_rejected(env):
    rt, _, signer_kp, cert = env
    report, sig = issue_report(signer_kp, MR, PK, "tee1")
    with pytest.raises(DispatchError, match="MalformedReport"):
        register(rt, "not-a-report", sig, (cert,))
    short = dataclasses.replace(report, mrenclave=b"\x07" * 16)
    with pytest.raises(DispatchError, match="MalformedReport"):
        register(rt, short, sig, (cert,))
    with pytest.raises(DispatchError, match="MalformedCertChain"):
        register(rt, report, sig, ())
    with pytest.raises(DispatchError, match="MalformedCertChain"):
        register(rt, report, sig, (cert, "junk"))
