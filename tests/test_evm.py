"""EVM execution engine: ERC-20-style round trip, gas bounds, logs,
and the eth_* RPC surface (VERDICT r3 Missing #3 done-criteria:
deploy -> transfer -> balanceOf via eth_call, eth_sendRawTransaction,
eth_getLogs; ref runtime/src/lib.rs:1310-1380, node/src/rpc.rs:229-328).
"""
import numpy as np
import pytest

from cess_tpu import constants
from cess_tpu.chain import evm_interp
from cess_tpu.chain.evm import eth_address
from cess_tpu.chain.evm_interp import asm, initcode
from cess_tpu.chain.runtime import Runtime, RuntimeConfig
from cess_tpu.chain.state import DispatchError

D = constants.DOLLARS
SUPPLY = 1_000_000

# -- a hand-assembled ERC-20-style token ----------------------------------
# calldata ABI (32-byte words): [method][arg1][arg2]
#   method 1: transfer(to_word, amount)  -> LOG1(topic=to, data=amount)
#   method 2: balanceOf(addr_word)       -> returns balance word
# storage: slot sha3(addr_word) = balance; slot 0 = totalSupply

TOKEN_RUNTIME = asm(
    0, "CALLDATALOAD", 1, "EQ", ("push_label", "transfer"), "JUMPI",
    0, "CALLDATALOAD", 2, "EQ", ("push_label", "balof"), "JUMPI",
    0, 0, "REVERT",

    ("label", "transfer"),
    "CALLER", 0, "MSTORE",
    32, 0, "SHA3",                     # [sf]
    "DUP1", "SLOAD",                   # [sf, bf]
    "DUP1", 64, "CALLDATALOAD",        # [sf, bf, bf, amt]
    "SWAP1", "LT",                     # [sf, bf, bf<amt]
    ("push_label", "fail"), "JUMPI",   # [sf, bf]
    64, "CALLDATALOAD",                # [sf, bf, amt]
    "SWAP1", "SUB",                    # [sf, bf-amt]
    "SWAP1", "SSTORE",                 # debit sender
    32, "CALLDATALOAD", 0, "MSTORE",
    32, 0, "SHA3",                     # [st]
    "DUP1", "SLOAD",                   # [st, bt]
    64, "CALLDATALOAD", "ADD",         # [st, bt+amt]
    "SWAP1", "SSTORE",                 # credit recipient
    64, "CALLDATALOAD", 0, "MSTORE",   # data = amount
    32, "CALLDATALOAD",                # topic = to
    32, 0, "LOG1",
    "STOP",

    ("label", "fail"), 0, 0, "REVERT",

    ("label", "balof"),
    32, "CALLDATALOAD", 0, "MSTORE",
    32, 0, "SHA3", "SLOAD",
    0, "MSTORE",
    32, 0, "RETURN",
)

# constructor: mint SUPPLY to the deployer, record totalSupply
TOKEN_CTOR = asm(
    "CALLER", 0, "MSTORE",
    32, 0, "SHA3",           # [slot(caller)]
    SUPPLY, "SWAP1", "SSTORE",
    SUPPLY, 0, "SSTORE",
)

TOKEN_INIT = initcode(TOKEN_RUNTIME, ctor=TOKEN_CTOR)


def word(v) -> bytes:
    if isinstance(v, bytes):
        return v.rjust(32, b"\0")
    return int(v).to_bytes(32, "big")


def calldata(method: int, *args) -> bytes:
    return word(method) + b"".join(word(a) for a in args)


@pytest.fixture
def rt():
    rt = Runtime(RuntimeConfig(era_blocks=1000))
    for who in ("dev", "bob"):
        rt.fund(who, 1_000 * D)
    return rt


def test_token_deploy_transfer_balance(rt):
    addr = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    assert rt.evm.code_at(addr) == TOKEN_RUNTIME
    dev_w = eth_address("dev")
    bob_w = eth_address("bob")
    # constructor minted to deployer
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, dev_w)), "big") == SUPPLY
    # transfer 250 dev -> bob
    rt.apply_extrinsic("dev", "evm.call", addr, calldata(1, bob_w, 250))
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, dev_w)), "big") == SUPPLY - 250
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, bob_w)), "big") == 250
    # overdraw reverts and changes nothing
    with pytest.raises(DispatchError, match="Reverted"):
        rt.apply_extrinsic("bob", "evm.call", addr,
                           calldata(1, dev_w, 9_999_999))
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, bob_w)), "big") == 250
    # logs archived for eth_getLogs
    logs = rt.evm.logs_in_range(0, rt.state.block, address=addr)
    assert len(logs) == 1
    assert logs[0]["topics"][0] == word(bob_w)
    assert int.from_bytes(logs[0]["data"], "big") == 250


def test_query_is_read_only(rt):
    addr = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    bob_w = eth_address("bob")
    # a transfer run through query (eth_call) must not commit
    rt.evm.query(addr, calldata(1, bob_w, 10), caller="dev")
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, bob_w)), "big") == 0


def test_infinite_loop_cannot_stall_block_production(rt):
    looper = initcode(asm(("label", "spin"),
                          ("push_label", "spin"), "JUMP"))
    addr = rt.apply_extrinsic("dev", "evm.deploy", looper)
    with pytest.raises(DispatchError, match="ExecutionFailed"):
        rt.apply_extrinsic("dev", "evm.call", addr, b"", 100_000)
    # dispatch failed but the chain advances: nothing is wedged
    before = rt.state.block
    rt.advance_blocks(2)
    assert rt.state.block == before + 2


def test_interp_primitives():
    # arithmetic + memory + return
    res = evm_interp.execute(asm(7, 5, "ADD", 0, "MSTORE", 32, 0, "RETURN"))
    assert int.from_bytes(res.output, "big") == 12
    # revert carries data
    with pytest.raises(evm_interp.EvmRevert) as e:
        evm_interp.execute(asm(0xDEAD, 0, "MSTORE", 32, 0, "REVERT"))
    assert int.from_bytes(e.value.data, "big") == 0xDEAD
    # jump to a non-JUMPDEST is an exceptional halt
    with pytest.raises(evm_interp.EvmError):
        evm_interp.execute(asm(3, "JUMP", "STOP"))


def test_eth_rpc_surface():
    """deploy -> eth_sendRawTransaction(transfer) -> eth_call(balanceOf)
    -> eth_getLogs, all through the RPC server."""
    import json
    import urllib.request

    from cess_tpu import codec
    from cess_tpu.chain.extrinsic import sign_extrinsic
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.rpc import RpcServer

    spec = dev_spec()
    node = Node(spec, "rpc-evm", {"alice": spec.session_key("alice")})
    srv = RpcServer(node, port=0)
    srv.start()
    try:
        port = srv.port

        def rpc(method, *params):
            req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                              "params": list(params)}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}", data=req,
                    headers={"Content-Type": "application/json"}),
                    timeout=10) as resp:
                out = json.loads(resp.read())
            if "error" in out:
                raise RuntimeError(out["error"])
            return out["result"]

        rpc("author_submitExtrinsic", "alice", "evm.deploy",
            "0x" + TOKEN_INIT.hex())
        node.try_author(1) and node.commit_proposal()
        addr = [k[0] for k, _ in
                node.runtime.state.iter_prefix("evm", "code")][0]
        assert rpc("eth_getCode", "0x" + addr.hex()) \
            == "0x" + TOKEN_RUNTIME.hex()

        # eth_sendRawTransaction: client-built, codec-encoded signed tx
        bob_w = eth_address("bob")
        xt = sign_extrinsic(
            spec.account_key("alice"), node.runtime.genesis_hash(),
            "alice", node.runtime.system.nonce("alice"),
            "evm.call",
            ([k[0] for k, _ in
              node.runtime.state.iter_prefix("evm", "code")][0],
             calldata(1, bob_w, 77)), ())
        assert rpc("eth_sendRawTransaction",
                   "0x" + codec.encode(xt).hex())
        node.try_author(2) and node.commit_proposal()

        got = rpc("eth_call", "0x" + addr.hex(),
                  "0x" + calldata(2, bob_w).hex())
        assert int(got, 16) == 77
        logs = rpc("eth_getLogs", {"fromBlock": 0,
                                   "address": "0x" + addr.hex()})
        assert len(logs) == 1
        assert int.from_bytes(codec_bytes(logs[0]["data"]), "big") == 77
    finally:
        srv.stop()


def codec_bytes(v) -> bytes:
    """RPC values arrive JSON-encoded; bytes may come back hex-coded."""
    if isinstance(v, bytes):
        return v
    if isinstance(v, str) and v.startswith("0x"):
        return bytes.fromhex(v[2:])
    if isinstance(v, str):
        return bytes.fromhex(v)
    if isinstance(v, list):
        return bytes(v)
    raise TypeError(type(v))
