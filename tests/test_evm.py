"""EVM execution engine: ERC-20-style round trip, gas bounds, logs,
and the eth_* RPC surface (VERDICT r3 Missing #3 done-criteria:
deploy -> transfer -> balanceOf via eth_call, eth_sendRawTransaction,
eth_getLogs; ref runtime/src/lib.rs:1310-1380, node/src/rpc.rs:229-328).
"""
import numpy as np
import pytest

from cess_tpu import constants
from cess_tpu.chain import evm_interp
from cess_tpu.chain.evm import eth_address
from cess_tpu.chain.evm_interp import asm, initcode
from cess_tpu.chain.runtime import Runtime, RuntimeConfig
from cess_tpu.chain.state import DispatchError

D = constants.DOLLARS
SUPPLY = 1_000_000

# -- a hand-assembled ERC-20-style token ----------------------------------
# calldata ABI (32-byte words): [method][arg1][arg2]
#   method 1: transfer(to_word, amount)  -> LOG1(topic=to, data=amount)
#   method 2: balanceOf(addr_word)       -> returns balance word
# storage: slot sha3(addr_word) = balance; slot 0 = totalSupply

TOKEN_RUNTIME = asm(
    0, "CALLDATALOAD", 1, "EQ", ("push_label", "transfer"), "JUMPI",
    0, "CALLDATALOAD", 2, "EQ", ("push_label", "balof"), "JUMPI",
    0, 0, "REVERT",

    ("label", "transfer"),
    "CALLER", 0, "MSTORE",
    32, 0, "SHA3",                     # [sf]
    "DUP1", "SLOAD",                   # [sf, bf]
    "DUP1", 64, "CALLDATALOAD",        # [sf, bf, bf, amt]
    "SWAP1", "LT",                     # [sf, bf, bf<amt]
    ("push_label", "fail"), "JUMPI",   # [sf, bf]
    64, "CALLDATALOAD",                # [sf, bf, amt]
    "SWAP1", "SUB",                    # [sf, bf-amt]
    "SWAP1", "SSTORE",                 # debit sender
    32, "CALLDATALOAD", 0, "MSTORE",
    32, 0, "SHA3",                     # [st]
    "DUP1", "SLOAD",                   # [st, bt]
    64, "CALLDATALOAD", "ADD",         # [st, bt+amt]
    "SWAP1", "SSTORE",                 # credit recipient
    64, "CALLDATALOAD", 0, "MSTORE",   # data = amount
    32, "CALLDATALOAD",                # topic = to
    32, 0, "LOG1",
    "STOP",

    ("label", "fail"), 0, 0, "REVERT",

    ("label", "balof"),
    32, "CALLDATALOAD", 0, "MSTORE",
    32, 0, "SHA3", "SLOAD",
    0, "MSTORE",
    32, 0, "RETURN",
)

# constructor: mint SUPPLY to the deployer, record totalSupply
TOKEN_CTOR = asm(
    "CALLER", 0, "MSTORE",
    32, 0, "SHA3",           # [slot(caller)]
    SUPPLY, "SWAP1", "SSTORE",
    SUPPLY, 0, "SSTORE",
)

TOKEN_INIT = initcode(TOKEN_RUNTIME, ctor=TOKEN_CTOR)


def word(v) -> bytes:
    if isinstance(v, bytes):
        return v.rjust(32, b"\0")
    return int(v).to_bytes(32, "big")


def calldata(method: int, *args) -> bytes:
    return word(method) + b"".join(word(a) for a in args)


@pytest.fixture
def rt():
    rt = Runtime(RuntimeConfig(era_blocks=1000))
    for who in ("dev", "bob"):
        rt.fund(who, 1_000 * D)
    return rt


def test_token_deploy_transfer_balance(rt):
    addr = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    assert rt.evm.code_at(addr) == TOKEN_RUNTIME
    dev_w = eth_address("dev")
    bob_w = eth_address("bob")
    # constructor minted to deployer
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, dev_w)), "big") == SUPPLY
    # transfer 250 dev -> bob
    rt.apply_extrinsic("dev", "evm.call", addr, calldata(1, bob_w, 250))
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, dev_w)), "big") == SUPPLY - 250
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, bob_w)), "big") == 250
    # overdraw reverts and changes nothing
    with pytest.raises(DispatchError, match="Reverted"):
        rt.apply_extrinsic("bob", "evm.call", addr,
                           calldata(1, dev_w, 9_999_999))
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, bob_w)), "big") == 250
    # logs archived for eth_getLogs
    logs = rt.evm.logs_in_range(0, rt.state.block, address=addr)
    assert len(logs) == 1
    assert logs[0]["topics"][0] == word(bob_w)
    assert int.from_bytes(logs[0]["data"], "big") == 250


def test_query_is_read_only(rt):
    addr = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    bob_w = eth_address("bob")
    # a transfer run through query (eth_call) must not commit
    rt.evm.query(addr, calldata(1, bob_w, 10), caller="dev")
    assert int.from_bytes(
        rt.evm.query(addr, calldata(2, bob_w)), "big") == 0


def test_infinite_loop_cannot_stall_block_production(rt):
    looper = initcode(asm(("label", "spin"),
                          ("push_label", "spin"), "JUMP"))
    addr = rt.apply_extrinsic("dev", "evm.deploy", looper)
    with pytest.raises(DispatchError, match="ExecutionFailed"):
        rt.apply_extrinsic("dev", "evm.call", addr, b"", 100_000)
    # dispatch failed but the chain advances: nothing is wedged
    before = rt.state.block
    rt.advance_blocks(2)
    assert rt.state.block == before + 2


def test_interp_primitives():
    # arithmetic + memory + return
    res = evm_interp.execute(asm(7, 5, "ADD", 0, "MSTORE", 32, 0, "RETURN"))
    assert int.from_bytes(res.output, "big") == 12
    # revert carries data
    with pytest.raises(evm_interp.EvmRevert) as e:
        evm_interp.execute(asm(0xDEAD, 0, "MSTORE", 32, 0, "REVERT"))
    assert int.from_bytes(e.value.data, "big") == 0xDEAD
    # jump to a non-JUMPDEST is an exceptional halt
    with pytest.raises(evm_interp.EvmError):
        evm_interp.execute(asm(3, "JUMP", "STOP"))


def test_eth_rpc_surface():
    """deploy -> eth_sendRawTransaction(transfer) -> eth_call(balanceOf)
    -> eth_getLogs, all through the RPC server."""
    import json
    import urllib.request

    from cess_tpu import codec
    from cess_tpu.chain.extrinsic import sign_extrinsic
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.rpc import RpcServer

    spec = dev_spec()
    node = Node(spec, "rpc-evm", {"alice": spec.session_key("alice")})
    srv = RpcServer(node, port=0)
    srv.start()
    try:
        port = srv.port

        def rpc(method, *params):
            req = json.dumps({"jsonrpc": "2.0", "id": 1, "method": method,
                              "params": list(params)}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                    f"http://127.0.0.1:{port}", data=req,
                    headers={"Content-Type": "application/json"}),
                    timeout=10) as resp:
                out = json.loads(resp.read())
            if "error" in out:
                raise RuntimeError(out["error"])
            return out["result"]

        rpc("author_submitExtrinsic", "alice", "evm.deploy",
            "0x" + TOKEN_INIT.hex())
        node.try_author(1) and node.commit_proposal()
        addr = [k[0] for k, _ in
                node.runtime.state.iter_prefix("evm", "code")][0]
        assert rpc("eth_getCode", "0x" + addr.hex()) \
            == "0x" + TOKEN_RUNTIME.hex()

        # eth_sendRawTransaction: client-built, codec-encoded signed tx
        bob_w = eth_address("bob")
        xt = sign_extrinsic(
            spec.account_key("alice"), node.runtime.genesis_hash(),
            "alice", node.runtime.system.nonce("alice"),
            "evm.call",
            ([k[0] for k, _ in
              node.runtime.state.iter_prefix("evm", "code")][0],
             calldata(1, bob_w, 77)), ())
        assert rpc("eth_sendRawTransaction",
                   "0x" + codec.encode(xt).hex())
        node.try_author(2) and node.commit_proposal()

        got = rpc("eth_call", "0x" + addr.hex(),
                  "0x" + calldata(2, bob_w).hex())
        assert int(got, 16) == 77
        logs = rpc("eth_getLogs", {"fromBlock": 0,
                                   "address": "0x" + addr.hex()})
        assert len(logs) == 1
        assert int.from_bytes(codec_bytes(logs[0]["data"]), "big") == 77

        # EthFilter namespace (ref node/src/rpc.rs:229-328): polling
        # filters deliver each event exactly once
        fid = rpc("eth_newFilter", {"address": "0x" + addr.hex()})
        bfid = rpc("eth_newBlockFilter")
        assert rpc("eth_getFilterChanges", fid) == []   # nothing yet
        assert len(rpc("eth_getFilterLogs", fid)) == 1  # full history
        xt2 = sign_extrinsic(
            spec.account_key("alice"), node.runtime.genesis_hash(),
            "alice", node.runtime.system.nonce("alice"),
            "evm.call", (addr, calldata(1, bob_w, 5)), ())
        rpc("eth_sendRawTransaction", "0x" + codec.encode(xt2).hex())
        node.try_author(3) and node.commit_proposal()
        changes = rpc("eth_getFilterChanges", fid)
        assert len(changes) == 1
        assert int.from_bytes(codec_bytes(changes[0]["data"]), "big") == 5
        assert rpc("eth_getFilterChanges", fid) == []   # exactly once
        blocks = rpc("eth_getFilterChanges", bfid)
        assert blocks == ["0x" + node.head().hash().hex()]
        assert rpc("eth_uninstallFilter", fid) is True
        assert rpc("eth_uninstallFilter", fid) is False
        try:
            rpc("eth_getFilterChanges", fid)
            raise AssertionError("uninstalled filter still answered")
        except RuntimeError:
            pass
    finally:
        srv.stop()


def codec_bytes(v) -> bytes:
    """RPC values arrive JSON-encoded; bytes may come back hex-coded."""
    if isinstance(v, bytes):
        return v
    if isinstance(v, str) and v.startswith("0x"):
        return bytes.fromhex(v[2:])
    if isinstance(v, str):
        return bytes.fromhex(v)
    if isinstance(v, list):
        return bytes(v)
    raise TypeError(type(v))


def test_eth_filter_hardening():
    """Review findings: address arrays honored, bad criteria rejected
    at creation, idle filters evicted at the cap, reorg-safe cursors
    rewind to finality instead of dropping events."""
    import pytest

    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.rpc import RpcError, RpcServer

    spec = dev_spec()
    node = Node(spec, "flt", {"alice": spec.session_key("alice")})
    srv = RpcServer(node, port=0)   # handle() used directly, no HTTP

    node.submit_extrinsic("alice", "evm.deploy", TOKEN_INIT)
    node.try_author(1) and node.commit_proposal()
    addr = [k[0] for k, _ in
            node.runtime.state.iter_prefix("evm", "code")][0]

    # malformed criteria fail at eth_newFilter, not at poll time
    with pytest.raises(RpcError, match="bad filter criteria"):
        srv.handle("eth_newFilter", [{"toBlock": "0xzz"}])
    with pytest.raises(RpcError, match="bad filter criteria"):
        srv.handle("eth_newFilter", [{"address": "0xnothex"}])

    # address ARRAYS select exactly the named contracts
    fid = srv.handle("eth_newFilter",
                     [{"address": ["0x" + addr.hex(),
                                   "0x" + (b"\x99" * 20).hex()]}])
    node.submit_extrinsic(
        "alice", "evm.call", addr, calldata(1, eth_address("bob"), 9))
    node.try_author(2) and node.commit_proposal()
    assert len(srv.handle("eth_getFilterChanges", [fid])) == 1
    miss = srv.handle("eth_newFilter",
                      [{"address": ["0x" + (b"\x99" * 20).hex()]}])
    assert srv.handle("eth_getFilterLogs", [miss]) == []

    # cap + idle eviction: stale filters make room, live ones do not
    for _ in range(srv.MAX_FILTERS - len(srv._filters)):
        srv.handle("eth_newBlockFilter", [])
    with pytest.raises(RpcError, match="filter table full"):
        srv.handle("eth_newBlockFilter", [])
    for f in [f for k, f in srv._filters.items()
              if k not in (fid, miss)][:10]:
        f["touched"] -= srv.FILTER_IDLE_TTL + 1
    assert srv.handle("eth_newBlockFilter", [])   # evicted 10, added 1

    # reorg safety: a cursor pointing at a vanished block rewinds to
    # finality and redelivers instead of silently skipping
    f = srv._filters[fid]
    f["cursor"], f["cursor_hash"] = 2, b"\x00" * 32   # simulate reorg
    redelivered = srv.handle("eth_getFilterChanges", [fid])
    assert len(redelivered) == 1                      # block-2 log again


def test_eth_filter_criteria_semantics():
    """Review findings: topics validated at creation; fromBlock bounds
    the poll window (cursor only narrows, never widens)."""
    import pytest

    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.rpc import RpcError, RpcServer

    spec = dev_spec()
    node = Node(spec, "fc", {"alice": spec.session_key("alice")})
    srv = RpcServer(node, port=0)
    node.submit_extrinsic("alice", "evm.deploy", TOKEN_INIT)
    node.try_author(1) and node.commit_proposal()
    addr = [k[0] for k, _ in
            node.runtime.state.iter_prefix("evm", "code")][0]

    # malformed TOPICS rejected at creation, not first poll
    with pytest.raises(RpcError, match="bad filter criteria"):
        srv.handle("eth_newFilter", [{"topics": ["0xzz"]}])
    with pytest.raises(RpcError, match="bad filter criteria"):
        srv.handle("eth_getLogs", [{"address": 42}])

    # fromBlock in the future excludes earlier logs from polls
    fut = srv.handle("eth_newFilter",
                     [{"fromBlock": hex(10), "address": "0x" + addr.hex()}])
    now = srv.handle("eth_newFilter", [{"address": "0x" + addr.hex()}])
    node.submit_extrinsic("alice", "evm.call", addr,
                          calldata(1, eth_address("bob"), 3))
    node.try_author(2) and node.commit_proposal()
    assert srv.handle("eth_getFilterChanges", [fut]) == []   # block 2 < 10
    assert len(srv.handle("eth_getFilterChanges", [now])) == 1
    # topic selection with pre-decoded options
    tf = srv.handle("eth_newFilter",
                    [{"fromBlock": 0,
                      "topics": [["0x" + word(eth_address("bob")).hex()]]}])
    assert len(srv.handle("eth_getFilterLogs", [tf])) == 1
    tmiss = srv.handle("eth_newFilter",
                       [{"fromBlock": 0,
                         "topics": [["0x" + word(b"\x01" * 20).hex()]]}])
    assert srv.handle("eth_getFilterLogs", [tmiss]) == []


# -- inter-contract calls ------------------------------------------------------

def _mk_caller(token_addr: bytes, op: str) -> bytes:
    """A contract that forwards its calldata to the token via CALL /
    STATICCALL / DELEGATECALL and returns (success_word, returndata)."""
    return initcode(asm(
        # copy our calldata to memory 0
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        # outOff=64 outSize=32, inOff=0 inSize=CALLDATASIZE
        *( [32, 64, "CALLDATASIZE", 0]
           + ([0] if op == "CALL" else [])
           + [int.from_bytes(token_addr, "big"), 100_000, op] ),
        # store success word at 32
        32, "MSTORE",
        # return mem[32:96] = [success, ret word]
        64, 32, "RETURN",
    ))


def test_call_staticcall_between_contracts(rt):
    token = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    bob_w = eth_address("bob")
    for op, mutate in (("CALL", True), ("STATICCALL", False)):
        proxy = rt.apply_extrinsic("dev", "evm.deploy",
                                   _mk_caller(token, op))
        # balanceOf through the proxy: success=1, inner return surfaces
        out = rt.evm.query(proxy, calldata(2, eth_address("dev")),
                           caller="dev")
        assert int.from_bytes(out[:32], "big") == 1, op
        assert int.from_bytes(out[32:64], "big") \
            == (SUPPLY if op == "CALL" else SUPPLY)
        if mutate:
            # transfer THROUGH the proxy commits: but the token debits
            # CALLER = the proxy (which has balance 0) -> inner revert
            # -> success=0 while the proxy itself completes fine
            out = rt.apply_extrinsic("dev", "evm.call", proxy,
                                     calldata(1, bob_w, 10))
            # (call() returns the proxy's output via dispatch result)
            assert int.from_bytes(out[:32], "big") == 0
            assert int.from_bytes(
                rt.evm.query(token, calldata(2, bob_w)), "big") == 0
        else:
            # STATICCALL into a transfer = inner SSTORE violation ->
            # success=0, and nothing committed
            out = rt.apply_extrinsic("dev", "evm.call", proxy,
                                     calldata(1, bob_w, 10))
            assert int.from_bytes(out[:32], "big") == 0
            assert int.from_bytes(
                rt.evm.query(token, calldata(2, bob_w)), "big") == 0


def test_delegatecall_uses_caller_storage(rt):
    token = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    proxy = rt.apply_extrinsic("dev", "evm.deploy",
                               _mk_caller(token, "DELEGATECALL"))
    dev_w = eth_address("dev")
    # through DELEGATECALL the token code reads the PROXY's storage:
    # nothing was ever minted there, balance must be 0 (not SUPPLY)
    out = rt.evm.query(proxy, calldata(2, dev_w), caller="dev")
    assert int.from_bytes(out[:32], "big") == 1
    assert int.from_bytes(out[32:64], "big") == 0
    # and the token's own state is untouched
    assert int.from_bytes(
        rt.evm.query(token, calldata(2, dev_w)), "big") == SUPPLY


def test_inner_revert_unwinds_only_inner_writes(rt):
    token = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    # proxy that writes its own slot 7, then CALLs token.transfer
    # (which reverts: proxy has no balance), then returns its slot 7
    proxy_code = initcode(asm(
        99, 7, "SSTORE",                       # own write BEFORE call
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        0, 0, "CALLDATASIZE", 0, 0,
        int.from_bytes(token, "big"), 100_000, "CALL",
        "POP",                                 # ignore success
        7, "SLOAD", 0, "MSTORE", 32, 0, "RETURN",
    ))
    proxy = rt.apply_extrinsic("dev", "evm.deploy", proxy_code)
    out = rt.apply_extrinsic("dev", "evm.call", proxy,
                             calldata(1, eth_address("bob"), 5))
    # outer write survives the inner revert
    assert int.from_bytes(out, "big") == 99
    assert rt.evm.storage_at(proxy, 7) == 99


def test_query_with_inner_calls_never_writes_state(rt):
    """Review finding (confirmed leak, now fixed): eth_call through a
    proxy whose inner CALL succeeds must leave chain state untouched —
    all writes, inner frames included, land in session overlays."""
    token = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    proxy = rt.apply_extrinsic("dev", "evm.deploy",
                               _mk_caller(token, "CALL"))
    # fund the proxy inside the token so the simulated inner transfer
    # SUCCEEDS (a reverting inner call would mask the leak)
    rt.apply_extrinsic("dev", "evm.call", token, calldata(1, proxy, 500))
    bob_w = eth_address("bob")
    out = rt.evm.query(proxy, calldata(1, bob_w, 40), caller="dev")
    assert int.from_bytes(out[:32], "big") == 1   # simulated success
    assert int.from_bytes(
        rt.evm.query(token, calldata(2, bob_w)), "big") == 0
    assert int.from_bytes(
        rt.evm.query(token, calldata(2, proxy)), "big") == 500


def test_middle_frame_revert_unwinds_grandchild_writes(rt):
    """Review-confirmed flaw (now fixed): A -> B -> token where the
    token transfer SUCCEEDS, then B reverts — the token's storage
    write must vanish with B's frame."""
    token = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    bob_w = eth_address("bob")
    # B: forward calldata to the token, then REVERT unconditionally
    b_code = initcode(asm(
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        0, 0, "CALLDATASIZE", 0, 0,
        int.from_bytes(token, "big"), 200_000, "CALL",
        "POP", 0, 0, "REVERT",
    ))
    b = rt.apply_extrinsic("dev", "evm.deploy", b_code)
    # fund B inside the token so its inner transfer SUCCEEDS
    rt.apply_extrinsic("dev", "evm.call", token, calldata(1, b, 500))
    # A: call B, IGNORE its failure, return cleanly
    a_code = initcode(asm(
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        0, 0, "CALLDATASIZE", 0, 0,
        int.from_bytes(b, "big"), 300_000, "CALL",
        0, "MSTORE", 32, 0, "RETURN",
    ))
    a = rt.apply_extrinsic("dev", "evm.deploy", a_code)
    out = rt.apply_extrinsic("dev", "evm.call", a,
                             calldata(1, bob_w, 40))
    assert int.from_bytes(out, "big") == 0        # B reverted
    # the token transfer B's frame contained was unwound with it
    assert int.from_bytes(
        rt.evm.query(token, calldata(2, bob_w)), "big") == 0
    assert int.from_bytes(
        rt.evm.query(token, calldata(2, b)), "big") == 500


def test_base_fee_market_tracks_demand(rt):
    """The pallet_base_fee/dynamic_fee role: the per-block base fee
    rises under gas demand and decays toward the floor when idle."""
    from cess_tpu.chain import evm as evm_mod

    addr = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    start = rt.evm.base_fee()
    # a busy block (several calls) pushes the NEXT base fee up only if
    # gas used exceeds the target; these small calls stay below it, so
    # the fee DECAYS — assert the rule, not a direction guess
    for i in range(3):
        rt.apply_extrinsic("dev", "evm.call", addr,
                           calldata(1, eth_address("bob"), 1))
    used = rt.state.get("evm", "block_gas", default=0)
    rt.advance_blocks(1)
    expect = evm_mod.next_base_fee(start, used)
    assert rt.evm.base_fee() == expect
    # idle blocks decay toward (and clamp at) the floor
    for _ in range(5):
        rt.advance_blocks(1)
    assert evm_mod.MIN_BASE_FEE <= rt.evm.base_fee() < expect
    # synthetic high demand raises the fee
    assert evm_mod.next_base_fee(1000, evm_mod.GAS_CAP) > 1000


def test_eth_gasprice_and_feehistory_rpc(rt):
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Network, Node
    from cess_tpu.node.rpc import RpcServer

    spec = dev_spec()
    node = Node(spec, "fee", {"alice": spec.session_key("alice")})
    Network([node]).run_slots(4)
    srv = RpcServer(node, port=0)
    assert int(srv.handle("eth_gasPrice", []), 16) >= 7
    hist = srv.handle("eth_feeHistory", [3])
    assert len(hist["baseFeePerGas"]) == len(hist["gasUsedRatio"]) + 1
    assert all(r == 0.0 for r in hist["gasUsedRatio"])   # idle chain


def test_failed_execution_still_moves_fee_market(rt):
    """ADVICE r4: reverting/trapping executions consume gas the fee
    side charged for; they must count toward block_gas so sustained
    reverting load moves the EIP-1559 base fee upward too."""
    addr = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    base = rt.state.get("evm", "block_gas", default=0)
    with pytest.raises(DispatchError, match="Reverted"):
        rt.apply_extrinsic("dev", "evm.call", addr,
                           calldata(1, eth_address("bob"), 9_999_999))
    after_revert = rt.state.get("evm", "block_gas", default=0)
    assert after_revert > base
    # an exceptional halt consumes the full limit
    looper = initcode(asm(("label", "spin"), ("push_label", "spin"),
                          "JUMP"))
    la = rt.apply_extrinsic("dev", "evm.deploy", looper)
    with pytest.raises(DispatchError, match="ExecutionFailed"):
        rt.apply_extrinsic("dev", "evm.call", la, b"", 50_000)
    assert rt.state.get("evm", "block_gas", default=0) \
        >= after_revert + 50_000


# -- value, CREATE/CREATE2, precompiles (VERDICT r4 Missing #2) -----------

def test_value_transfer_and_selfbalance(rt):
    vault = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        "SELFBALANCE", 0, "MSTORE", 32, 0, "RETURN")))
    rt.apply_extrinsic("dev", "evm.deposit", 100 * D)
    out = rt.apply_extrinsic("dev", "evm.call", vault, b"", 100_000,
                             30)
    # the callee observes its balance ALREADY credited
    assert int.from_bytes(out, "big") == 30
    assert rt.evm.balance_of(vault) == 30
    assert rt.evm.balance("dev") == 100 * D - 30
    # overdraw fails closed
    with pytest.raises(DispatchError, match="InsufficientBalance"):
        rt.apply_extrinsic("dev", "evm.call", vault, b"", 100_000,
                           200 * D)


def test_value_revert_returns_funds(rt):
    bomb = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        0, 0, "REVERT")))
    rt.apply_extrinsic("dev", "evm.deposit", 10 * D)
    with pytest.raises(DispatchError, match="Reverted"):
        rt.apply_extrinsic("dev", "evm.call", bomb, b"", 100_000, 5)
    assert rt.evm.balance("dev") == 10 * D     # transfer unwound
    assert rt.evm.balance_of(bomb) == 0


def test_inner_call_forwards_value(rt):
    """A CALL from bytecode carries value: the forwarder keeps half
    and sends half to the address in calldata."""
    sink = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm("STOP")))
    fwd = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        0, 0, 0, 0,                    # outSize outOff inSize inOff
        2, "CALLVALUE", "DIV",         # value = CALLVALUE / 2
        0, "CALLDATALOAD",             # to
        100_000, "CALL",
        0, "MSTORE", 32, 0, "RETURN")))
    rt.apply_extrinsic("dev", "evm.deposit", 10 * D)
    out = rt.apply_extrinsic("dev", "evm.call", fwd, word(sink),
                             200_000, 40)
    assert int.from_bytes(out, "big") == 1     # inner call succeeded
    assert rt.evm.balance_of(sink) == 20
    assert rt.evm.balance_of(fwd) == 20
    # value to a CODELESS address is a plain transfer, still a success
    nobody = b"\xaa" * 20
    rt.apply_extrinsic("dev", "evm.call", fwd, word(nobody),
                       200_000, 6)
    assert rt.evm.balance_of(nobody) == 3


def test_create2_factory_at_predicted_address(rt):
    """VERDICT r4 #2 done-criteria: a factory CREATE2-deploys a child
    at the predicted address and calls it."""
    from cess_tpu.chain.evm import create2_address

    child_runtime = asm(7, 0, "MSTORE", 32, 0, "RETURN")
    child_init = initcode(child_runtime)
    factory = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        0x42,                          # salt
        "CALLDATASIZE", 0,             # size, offset
        0,                             # value
        "CREATE2",
        # call the new child and return ITS output
        "DUP1", 0, "MSTORE",           # remember addr at mem 0
        32, 32, 0, 0, 0,               # outSize=32 @32, no input
        "DUP6", 100_000, "CALL", "POP",
        64, 0, "RETURN")))             # [addr, child_out]
    out = rt.apply_extrinsic("dev", "evm.call", factory, child_init,
                             2_000_000)
    predicted = create2_address(factory, (0x42).to_bytes(32, "big"),
                                child_init)
    assert out[12:32] == predicted
    assert int.from_bytes(out[32:64], "big") == 7
    assert rt.evm.code_at(predicted) == child_runtime
    # and the child answers direct calls at that address
    assert int.from_bytes(rt.evm.query(predicted, b""), "big") == 7
    # redeploying the same (salt, init) collides -> CREATE2 fails (0)
    out2 = rt.apply_extrinsic("dev", "evm.call", factory, child_init,
                              2_000_000)
    assert int.from_bytes(out2[:32], "big") == 0


def test_create_from_bytecode(rt):
    child_runtime = asm(9, 0, "MSTORE", 32, 0, "RETURN")
    child_init = initcode(child_runtime)
    factory = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        "CALLDATASIZE", 0,             # size, offset
        0,                             # value
        "CREATE",
        0, "MSTORE", 32, 0, "RETURN")))
    out = rt.apply_extrinsic("dev", "evm.call", factory, child_init,
                             2_000_000)
    addr = out[12:32]
    assert int(out[:12].hex(), 16) == 0 and addr != b"\0" * 20
    assert rt.evm.code_at(addr) == child_runtime
    # two CREATEs from the same factory land at DIFFERENT addresses
    out2 = rt.apply_extrinsic("dev", "evm.call", factory, child_init,
                              2_000_000)
    assert out2[12:32] != addr


# a proxy that forwards calldata[32:] to the address in word 0 and
# returns the call's first output word
PC_PROXY = initcode(asm(
    32, "CALLDATASIZE", "SUB",         # n = CDS - 32
    "DUP1",
    32, 0, "CALLDATACOPY",             # mem[0:n] = calldata[32:]
    32, 0x100, "SWAP1", "SWAP2",       # [outSize=32, outOff=256, n]
    0,                                 # inOff
    0,                                 # value
    0, "CALLDATALOAD",                 # to
    100_000, "CALL",
    "POP", 32, 0x100, "RETURN"))


def test_precompiles_through_contract_call(rt):
    """VERDICT r4 #2 done-criteria: a contract verifies an ecrecover
    signature; sha256/ripemd160/identity answer at 0x2-0x4."""
    import hashlib

    from cess_tpu.crypto import secp256k1 as k1

    proxy = rt.apply_extrinsic("dev", "evm.deploy", PC_PROXY)
    # 0x1 ecrecover
    secret = 0x5EC0_5EC0_5EC0
    h = hashlib.sha256(b"authorize the thing").digest()
    v, r, s = k1.sign(secret, h)
    out = rt.evm.query(proxy, word(1) + h + word(v) + word(r) + word(s))
    assert out[12:32] == k1.address_of(secret)
    # a corrupted signature recovers NOTHING (empty returndata -> 0s)
    out = rt.evm.query(
        proxy, word(1) + h + word(v) + word(r ^ 1) + word(s))
    assert out == b"\0" * 32 or out[12:32] != k1.address_of(secret)
    # 0x2 sha256
    out = rt.evm.query(proxy, word(2) + b"abc")
    assert out == hashlib.sha256(b"abc").digest()
    # 0x3 ripemd160 (left-padded to a word)
    out = rt.evm.query(proxy, word(3) + b"abc")
    assert out[12:] == hashlib.new("ripemd160", b"abc").digest()
    # 0x4 identity
    out = rt.evm.query(proxy, word(4) + b"echo" + b"\0" * 28)
    assert out[:4] == b"echo"


def test_eth_tx_lifecycle_rpc():
    """VERDICT r4 Missing #1 done-criteria: ERC-20 deploy -> transfer
    -> receipt -> logs purely through RPC (ref node/src/rpc.rs:229-328
    Eth namespace: receipts, tx objects, blocks, estimateGas)."""
    from cess_tpu import codec
    from cess_tpu.chain.extrinsic import sign_extrinsic
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.rpc import RpcServer

    spec = dev_spec()
    node = Node(spec, "rcpt", {"alice": spec.session_key("alice")})
    srv = RpcServer(node, port=0)

    def raw_tx(call, args):
        return "0x" + codec.encode(sign_extrinsic(
            spec.account_key("alice"), node.runtime.genesis_hash(),
            "alice", node.runtime.system.nonce("alice"), call, args,
            ())).hex()

    # gas estimate for the deploy, via RPC, before sending anything
    est = srv.handle("eth_estimateGas", [{"data": "0x"
                                          + TOKEN_INIT.hex()}])
    assert int(est, 16) > 0

    # deploy through eth_sendRawTransaction; follow the hash to the
    # receipt; pick up the contract address from it
    h1 = srv.handle("eth_sendRawTransaction",
                    [raw_tx("evm.deploy", (TOKEN_INIT,))])
    assert srv.handle("eth_getTransactionReceipt", [h1]) is None  # pending
    node.try_author(1) and node.commit_proposal()
    rc1 = srv.handle("eth_getTransactionReceipt", [h1])
    assert rc1["status"] == "0x1"
    assert rc1["blockNumber"] == "0x1"
    assert int(rc1["gasUsed"], 16) > 0
    token = rc1["contractAddress"]
    assert token and srv.handle("eth_getCode", [token]) \
        == "0x" + TOKEN_RUNTIME.hex()

    # transfer; the receipt carries the LOG1 with its topics/data.
    # Estimate FIRST: the gas schedule is deterministic, so estimating
    # against the same state the tx will execute in is exact.
    bob_w = eth_address("bob")
    est2 = srv.handle("eth_estimateGas",
                      [{"from": "alice", "to": token,
                        "data": "0x" + calldata(1, bob_w, 250).hex()}])
    h2 = srv.handle("eth_sendRawTransaction",
                    [raw_tx("evm.call",
                            (bytes.fromhex(token[2:]),
                             calldata(1, bob_w, 250)))])
    node.try_author(2) and node.commit_proposal()
    rc2 = srv.handle("eth_getTransactionReceipt", [h2])
    assert rc2["status"] == "0x1" and rc2["to"] == token
    assert len(rc2["logs"]) == 1
    lg = rc2["logs"][0]
    assert lg["address"] == token
    assert lg["topics"] == ["0x" + word(bob_w).hex()]
    assert int(lg["data"], 16) == 250
    assert lg["transactionHash"] == h2

    # the tx object round-trips: to/input/nonce/blockHash all present
    tx2 = srv.handle("eth_getTransactionByHash", [h2])
    assert tx2["to"] == token
    assert tx2["input"] == "0x" + calldata(1, bob_w, 250).hex()
    assert tx2["blockNumber"] == "0x2"
    assert tx2["blockHash"] == rc2["blockHash"]

    # blocks: hashes-only and full-object forms agree
    blk = srv.handle("eth_getBlockByNumber", ["0x2", False])
    assert blk["hash"] == rc2["blockHash"]
    assert blk["transactions"] == [h2]
    assert int(blk["gasUsed"], 16) == int(rc2["gasUsed"], 16)
    full = srv.handle("eth_getBlockByNumber", ["0x2", True])
    assert full["transactions"][0]["hash"] == h2
    by_hash = srv.handle("eth_getBlockByHash", [blk["hash"], False])
    assert by_hash["number"] == "0x2"
    assert srv.handle("eth_getBlockByNumber", ["0x99"]) is None

    # the pre-send estimate matches the measured receipt exactly
    assert int(est2, 16) == int(rc2["gasUsed"], 16)

    # a FAILED dispatch still yields a receipt, status 0x0 + error
    h3 = srv.handle("eth_sendRawTransaction",
                    [raw_tx("evm.call",
                            (bytes.fromhex(token[2:]),
                             calldata(1, bob_w, 10**9)))])
    node.try_author(3) and node.commit_proposal()
    rc3 = srv.handle("eth_getTransactionReceipt", [h3])
    assert rc3["status"] == "0x0"
    assert rc3["error"] == "evm.Reverted"
    assert rc3["logs"] == []
    # unknown hash -> null, bad hash -> error
    assert srv.handle("eth_getTransactionReceipt",
                      ["0x" + "ab" * 32]) is None
    import pytest as _pytest

    from cess_tpu.node.rpc import RpcError
    with _pytest.raises(RpcError):
        srv.handle("eth_getTransactionReceipt", ["0x1234"])


def test_negative_value_cannot_mint(rt):
    """Review-reproduced pot drain (fixed): a negative value passed
    'have < amount' and CREDITED the attacker; the pot then paid the
    minted balance out of other users' deposits."""
    rt.apply_extrinsic("dev", "evm.deposit", 100 * D)   # fund the pot
    sink = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm("STOP")))
    with pytest.raises(DispatchError, match="InvalidAmount"):
        rt.apply_extrinsic("bob", "evm.deploy", initcode(asm("STOP")),
                           100_000, -50 * D)
    with pytest.raises(DispatchError, match="Invalid"):
        rt.apply_extrinsic("bob", "evm.call", sink, b"", 100_000,
                           -50 * D)
    assert rt.evm.balance("bob") == 0
    with pytest.raises(DispatchError, match="InvalidAmount"):
        rt.apply_extrinsic("bob", "evm.withdraw", 1)


def test_ripemd160_fallback_matches_hashlib():
    """The 0x3 precompile must be platform-independent: the pure
    fallback and hashlib (when the OpenSSL build has it) agree, so
    differently-built nodes can't diverge on a consensus result."""
    import hashlib

    from cess_tpu.crypto import ripemd160 as pure

    for m in (b"", b"abc", b"message digest", b"a" * 1000,
              bytes(range(256)) * 3):
        assert pure.digest(m).hex() \
            == "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc".replace(
                "8eb208f7e05d987a9b044a8e98c6b087f15a0bfc",
                hashlib.new("ripemd160", m).hexdigest())


def test_delegatecall_to_precompile_moves_no_value(rt):
    """Review-reproduced drain (fixed): DELEGATECALL to 0x1-0x4 with a
    nonzero apparent callvalue must not transfer anything — mainnet
    DELEGATECALL never moves value."""
    # delegate calldata to 0x4 (identity), then return SELFBALANCE
    dlg = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        32, 0x100, "CALLDATASIZE", 0, 4, 100_000, "DELEGATECALL",
        "POP",
        "SELFBALANCE", 0, "MSTORE", 32, 0, "RETURN")))
    rt.apply_extrinsic("dev", "evm.deposit", 10 * D)
    out = rt.apply_extrinsic("dev", "evm.call", dlg, b"xyz", 300_000,
                             50)
    # the contract still holds its full callvalue after delegating
    assert int.from_bytes(out, "big") == 50
    assert rt.evm.balance_of(dlg) == 50
    assert rt.evm.balance_of((4).to_bytes(20, "big")) == 0


def test_eth_history_pruned_incrementally():
    """Receipts/logs/txlocs expire out of STATE after the retention
    window, one block's worth per block (bounded state growth; older
    data is recomputable from block archives by replay)."""
    from cess_tpu.chain.extrinsic import sign_extrinsic
    from cess_tpu.crypto import ed25519

    rt = Runtime(RuntimeConfig(era_blocks=10 ** 6))
    rt.ETH_HISTORY_BLOCKS = 5          # small window for the test
    rt.fund("dev", 1_000 * D)
    key = ed25519.SigningKey.generate(b"dev-prune")
    rt.init_block()
    addr = rt.apply_extrinsic("dev", "evm.deploy", TOKEN_INIT)
    hashes = []
    for i in range(8):
        rt.init_block()
        xt = sign_extrinsic(key, rt.genesis_hash(), "dev",
                            rt.system.nonce("dev"), "evm.call",
                            (addr, calldata(1, eth_address("bob"), 1)),
                            ())
        import hashlib as _hl

        from cess_tpu import codec as _codec

        rt.apply_in_block(xt)
        hashes.append((_hl.sha256(_codec.encode(xt)).digest(),
                       rt.state.block))
    head = rt.state.block
    for h, blk in hashes:
        loc = rt.state.get("ethereum", "txloc", h)
        nlogs = rt.state.get("evm", "log_seq", blk, default=0)
        if blk <= head - rt.ETH_HISTORY_BLOCKS:
            assert loc is None, f"block {blk} receipt not pruned"
            assert nlogs == 0, f"block {blk} logs not pruned"
            assert rt.state.get("ethereum", "count", blk, default=0) == 0
        else:
            assert loc == (blk, 0)
            assert rt.state.get("ethereum", "receipt", blk, 0) is not None
            assert nlogs == 1


def test_eth_misc_tooling_probes():
    """The small eth-namespace probes wallets/tooling fire on connect:
    syncing, accounts, web3_sha3, per-block tx counts."""
    import hashlib

    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.rpc import RpcServer

    spec = dev_spec()
    node = Node(spec, "misc", {"alice": spec.session_key("alice")})
    srv = RpcServer(node, port=0)
    node.submit_extrinsic("alice", "evm.deploy", TOKEN_INIT)
    node.try_author(1) and node.commit_proposal()
    assert srv.handle("eth_syncing", []) is False
    assert srv.handle("eth_accounts", []) == []
    assert srv.handle("web3_sha3", ["0x" + b"abc".hex()]) \
        == "0x" + hashlib.sha3_256(b"abc").hexdigest()
    assert srv.handle("eth_getBlockTransactionCountByNumber",
                      ["0x1"]) == "0x1"
    assert srv.handle("eth_getBlockTransactionCountByNumber",
                      ["0x99"]) is None
    # malformed web3_sha3 input is INVALID_PARAMS, never a server error
    import pytest as _pytest

    from cess_tpu.node.rpc import RpcError
    for bad in (["0xzz"], ["abc"], []):
        with _pytest.raises(RpcError) as e:
            srv.handle("web3_sha3", bad)
        assert e.value.code == -32602
    # a pruned-out old block falls back to the retained body's count
    node.runtime.state.delete("ethereum", "count", 1)
    assert srv.handle("eth_getBlockTransactionCountByNumber",
                      ["0x1"]) == "0x1"
    # no body either (warp-synced node): null, never a fabricated 0x0
    node.block_bodies.pop(1)
    assert srv.handle("eth_getBlockTransactionCountByNumber",
                      ["0x1"]) is None


def test_reentrant_value_call_cannot_double_spend(rt):
    """A contract that re-enters its caller mid-value-flow cannot
    mint: every frame's transfers live in its own overlay, and the
    total EVM-domain balance is conserved across arbitrary CALL
    nesting."""
    rt.apply_extrinsic("dev", "evm.deposit", 100 * D)
    # ping: on call, CALLs the address in calldata forwarding half its
    # callvalue; the callee is pong, which calls BACK into ping. The
    # chain ends naturally when a deep frame's empty calldata targets
    # the zero address (the host depth cap has its own tests)
    pong = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        0, 0, 0, 0,
        2, "CALLVALUE", "DIV",
        0, "CALLDATALOAD",
        50_000, "CALL", "POP", "STOP")))
    ping = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        0, 0, "CALLDATASIZE", 0,
        2, "CALLVALUE", "DIV",
        int.from_bytes(pong, "big"),
        200_000, "CALL", "POP", "STOP")))
    def ledger_total():
        return sum(v for _, v in
                   rt.state.iter_prefix("evm", "balance"))

    assert ledger_total() == 100 * D
    rt.apply_extrinsic("dev", "evm.call", ping, word(ping), 500_000, 64)
    # the WHOLE ledger is conserved — including the zero address,
    # where a deep frame's empty calldata makes CALLDATALOAD(0) target
    # 0x00 and strand a few units (faithful EVM semantics)
    assert ledger_total() == 100 * D
    assert rt.evm.balance("dev") == 100 * D - 64
    burned = rt.evm.balance_of(b"\x00" * 20)
    assert rt.evm.balance_of(ping) + rt.evm.balance_of(pong) \
        + burned == 64
    assert burned < 64 // 8      # only the deep tail strands


def test_call_depth_cap_bounds_self_recursion(rt):
    """The host caps nested CALL frames at Evm.MAX_CALL_DEPTH: a
    self-recursive contract executes exactly 1 + MAX_CALL_DEPTH frames
    (the attempt FROM the deepest frame fails cleanly, success=0)."""
    from cess_tpu.chain.evm import Evm

    # increment slot 0, CALL self (address from calldata), store the
    # inner success flag at slot 1, STOP
    rec = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        0, "SLOAD", 1, "ADD", 0, "SSTORE",
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        0, 0, "CALLDATASIZE", 0, 0,
        0, "CALLDATALOAD", 500_000, "CALL",
        1, "SSTORE", "STOP")))
    rt.apply_extrinsic("dev", "evm.call", rec, word(rec), 5_000_000)
    assert rt.evm.storage_at(rec, 0) == 1 + Evm.MAX_CALL_DEPTH
    # the cap failure is CLEAN: the depth-8 frame's failed CALL pushed
    # 0 without reverting, so its own slot-0 increment committed (the
    # count above proves it) and the outermost frame's success flag —
    # the last slot-1 write to commit — reads 1
    assert rt.evm.storage_at(rec, 1) == 1


def test_eth_block_receipts_and_tx_by_index():
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.rpc import RpcServer

    spec = dev_spec()
    node = Node(spec, "br", {"alice": spec.session_key("alice")})
    srv = RpcServer(node, port=0)
    node.submit_extrinsic("alice", "evm.deploy", TOKEN_INIT)
    node.submit_extrinsic("alice", "system.remark", b"x")
    node.try_author(1) and node.commit_proposal()
    rcs = srv.handle("eth_getBlockReceipts", ["0x1"])
    assert len(rcs) == 2
    assert rcs[0]["contractAddress"] and rcs[0]["status"] == "0x1"
    assert rcs[1]["call"] == "system.remark"
    assert srv.handle("eth_getBlockReceipts", ["0x99"]) is None
    # cumulative gas accumulates across the block
    assert int(rcs[1]["cumulativeGasUsed"], 16) \
        == int(rcs[0]["gasUsed"], 16) + int(rcs[1]["gasUsed"], 16)
    # a canonical in-retention block with NO signed extrinsics answers
    # [] (the spec shape for an existing empty block), never null
    node.try_author(2) and node.commit_proposal()
    assert node.head().number == 2
    assert srv.handle("eth_getBlockReceipts", ["0x2"]) == []
    # pruned-out receipt state answers null, never a fabricated []:
    # real pruning deletes the count key AND advances the pruned_to
    # cursor past the block, which is what distinguishes "pruned" from
    # "canonical but empty"
    node.runtime.state.delete("ethereum", "count", 1)
    node.runtime.state.put("ethereum", "pruned_to", 2)
    assert srv.handle("eth_getBlockReceipts", ["0x1"]) is None
    tx0 = srv.handle("eth_getTransactionByBlockNumberAndIndex",
                     ["0x1", "0x0"])
    assert tx0["hash"] == rcs[0]["transactionHash"]
    assert tx0["transactionIndex"] == "0x0"
    assert srv.handle("eth_getTransactionByBlockNumberAndIndex",
                      ["0x1", "0x9"]) is None


def test_create_nonce_persists_after_init_revert(rt):
    """Mainnet semantics: a CREATE whose init reverts still bumps the
    creator's nonce in the PARENT frame — a retried create derives a
    fresh address instead of deterministically reusing the old one."""
    from cess_tpu.chain.evm import create_address

    factory = rt.apply_extrinsic("dev", "evm.deploy", initcode(asm(
        "CALLDATASIZE", 0, 0, "CALLDATACOPY",
        "CALLDATASIZE", 0,             # size, offset
        0,                             # value
        "CREATE",
        0, "MSTORE", 32, 0, "RETURN")))
    # init that reverts: the child overlay is discarded...
    out = rt.apply_extrinsic("dev", "evm.call", factory,
                             asm(0, 0, "REVERT"), 2_000_000)
    assert int.from_bytes(out, "big") == 0          # create failed
    # ...but the nonce bump persists in the parent world
    assert rt.state.get("evm", "nonce", factory, default=0) == 1
    # the retry lands at the nonce-1 address, NOT a reuse of nonce 0
    child_runtime = asm(5, 0, "MSTORE", 32, 0, "RETURN")
    out2 = rt.apply_extrinsic("dev", "evm.call", factory,
                              initcode(child_runtime), 2_000_000)
    addr = out2[12:32]
    assert addr == create_address(factory, 1)
    assert addr != create_address(factory, 0)
    assert rt.evm.code_at(addr) == child_runtime
    assert rt.state.get("evm", "nonce", factory, default=0) == 2


def test_call_to_empty_runtime_code_is_value_transfer(rt):
    """A contract whose init returned EMPTY runtime code is a plain
    account (mainnet): calls to it are pure value transfers, so value
    parked there stays reachable — previously evm.call raised
    NoContract because code_at conflated b"" with 'no entry'."""
    # init = STOP: returns no output -> empty runtime code stored
    empty = rt.apply_extrinsic("dev", "evm.deploy", asm("STOP"))
    assert rt.evm.code_at(empty) == b""
    rt.apply_extrinsic("dev", "evm.deposit", 100)
    out = rt.apply_extrinsic("dev", "evm.call", empty, b"", 100_000, 40)
    assert out == b""
    assert rt.evm.balance_of(empty) == 40
    assert rt.evm.balance("dev") == 60
    # eth_call / estimate agree: success with empty output, zero gas
    assert rt.evm.query(empty, b"xyz") == b""
    assert rt.evm.estimate(empty, b"") == 0
    # a truly nonexistent code entry still refuses: None != b""
    with pytest.raises(DispatchError, match="NoContract"):
        rt.apply_extrinsic("dev", "evm.call", b"\x01" * 20, b"")


def test_txloc_first_write_wins_on_replayed_extrinsic():
    """A stale-nonce duplicate re-included by a later block author
    must not re-point eth_getTransactionReceipt at its failed
    dispatch: the original inclusion's (block, idx) stays canonical."""
    import hashlib as _hl

    from cess_tpu import codec as _codec
    from cess_tpu.chain.extrinsic import sign_extrinsic
    from cess_tpu.crypto import ed25519

    rt = Runtime(RuntimeConfig(era_blocks=10 ** 6))
    rt.fund("dev", 1_000 * D)
    key = ed25519.SigningKey.generate(b"dev-dup")
    rt.init_block()
    xt = sign_extrinsic(key, rt.genesis_hash(), "dev",
                        rt.system.nonce("dev"), "balances.transfer",
                        ("bob", 1 * D), None)
    h = _hl.sha256(_codec.encode(xt)).digest()
    rt.apply_in_block(xt)
    blk1 = rt.state.block
    assert rt.state.get("ethereum", "txloc", h) == (blk1, 0)
    assert rt.state.get("ethereum", "receipt", blk1, 0)[3] == 1
    # the duplicate: same bytes, later block, fails with BadNonce
    rt.init_block()
    blk2 = rt.state.block
    failed_before = len(rt.state.events_of("system", "ExtrinsicFailed"))
    rt.apply_in_block(xt)
    assert len(rt.state.events_of("system", "ExtrinsicFailed")) \
        == failed_before + 1
    # first write wins: location AND receipt untouched by the replay
    assert rt.state.get("ethereum", "txloc", h) == (blk1, 0)
    assert rt.state.get("ethereum", "receipt", blk2, 0) is None
    assert rt.state.get("ethereum", "count", blk2, default=0) == 0


def test_txloc_failed_first_inclusion_superseded_by_success():
    """The dual of first-write-wins: a tx whose FIRST inclusion failed
    without consuming the nonce (unfunded signer) and is later
    re-included successfully must get its receipt re-pointed at the
    success — not forever report failure for a transfer that ran."""
    import hashlib as _hl

    from cess_tpu import codec as _codec
    from cess_tpu.chain.extrinsic import sign_extrinsic
    from cess_tpu.crypto import ed25519

    rt = Runtime(RuntimeConfig(era_blocks=10 ** 6))
    key = ed25519.SigningKey.generate(b"dev-retry")
    rt.init_block()
    xt = sign_extrinsic(key, rt.genesis_hash(), "dev", 0,
                        "balances.transfer", ("bob", 1 * D), None)
    h = _hl.sha256(_codec.encode(xt)).digest()
    rt.apply_in_block(xt)           # unfunded: CannotPayFee, nonce kept
    blk1 = rt.state.block
    assert rt.state.get("ethereum", "txloc", h) == (blk1, 0)
    assert rt.state.get("ethereum", "receipt", blk1, 0)[3] == 0
    assert rt.system.nonce("dev") == 0
    rt.fund("dev", 1_000 * D)
    rt.init_block()
    blk2 = rt.state.block
    rt.apply_in_block(xt)           # re-included: succeeds this time
    assert rt.balances.free("bob") == 1 * D
    # the mapping moved to the success; the old block keeps its honest
    # failed-attempt receipt row
    assert rt.state.get("ethereum", "txloc", h) == (blk2, 0)
    assert rt.state.get("ethereum", "receipt", blk2, 0)[3] == 1
    assert rt.state.get("ethereum", "receipt", blk1, 0)[3] == 0
    # and a FAILED replay after the success never re-points it back
    rt.init_block()
    rt.apply_in_block(xt)           # stale nonce now: fails
    assert rt.state.get("ethereum", "txloc", h) == (blk2, 0)
    # pruning the block holding the SUPERSEDED failed receipt must not
    # destroy the mapping to the still-retained successful receipt
    rt._prune_eth_block(blk1)
    assert rt.state.get("ethereum", "receipt", blk1, 0) is None
    assert rt.state.get("ethereum", "txloc", h) == (blk2, 0)
    # pruning the success's own block finally drops the mapping
    rt._prune_eth_block(blk2)
    assert rt.state.get("ethereum", "txloc", h) is None
