"""ISSUE 16 acceptance drill: the remediation plane turns detector
edges into journaled, replayable recovery actions.

The drill: in a seeded tampered world (a) a perf regression is
auto-pinned to the reference backend and auto-released on recovery /
re-probed on count; (b) an injected vote equivocation is auto-filed
via ``offences.report_equivocation`` with the offender slashed and
chilled on-chain; (c) a repair-ingress regression flips the miner's
``repair_mode``. Two same-seed runs produce byte-identical
``witness()`` action logs, a ``dry_run`` replay journals identically
while applying nothing, and both ``remediation`` invariants provably
fire on a world whose responsible policy is disabled.
"""
import dataclasses
import threading
import types

import pytest

from cess_tpu import constants
from cess_tpu.chain.offences import sign_vote
from cess_tpu.node.chain_spec import ChainSpec, ValidatorGenesis
from cess_tpu.node.network import Network, Node
from cess_tpu.node.offchain import MinerAgent
from cess_tpu.obs import flight
from cess_tpu.resilience import ResilienceConfig
from cess_tpu.serve import make_engine
from cess_tpu.serve.remediate import (ACTIONS, Policy, RemediationPlane,
                                      default_policies)
from cess_tpu.sim import (SCENARIOS, InvariantViolation, run_checks,
                          run_scenario)

D = constants.DOLLARS


def note(plane, seq, sys, kind, **detail):
    plane.on_note(seq, sys, kind, detail)


@pytest.fixture()
def engine():
    eng = make_engine(4, 8, rs_backend="jax",
                      resilience=ResilienceConfig())
    yield eng
    eng.close()


# ---------------------------------------------------------------------------
# the policy table
# ---------------------------------------------------------------------------
class TestPolicyTable:
    def test_unknown_action_and_bad_bounds_are_loud(self):
        with pytest.raises(ValueError, match="unknown action"):
            Policy(name="x", trigger=("a", "b"), action="reboot-it")
        with pytest.raises(ValueError, match="max_fires"):
            Policy(name="x", trigger=("a", "b"),
                   action="pin-reference", max_fires=0)
        with pytest.raises(ValueError, match="cooldown"):
            Policy(name="x", trigger=("a", "b"),
                   action="pin-reference", cooldown=-1)

    def test_duplicate_policy_names_are_rejected(self):
        p = default_policies()
        with pytest.raises(ValueError, match="duplicate"):
            RemediationPlane(b"x", p + (p[0],))

    def test_default_table_covers_every_detector_altitude(self):
        pols = {p.name: p for p in default_policies()}
        assert set(pols) == {"perf-pin", "breaker-pin",
                             "straggler-quarantine",
                             "equivocation-report", "repair-ingress",
                             "custody-repair"}
        # every shipped action verb is exercised by some default row
        assert {p.action for p in pols.values()} == set(ACTIONS)
        # rows are JSON-shaped for the RPC snapshot
        row = pols["perf-pin"].row()
        assert row["trigger"] == ["perf", "regression"]
        assert row["match"] == [["to", "regressed"]]
        assert row["release_match"] == [["to", "ok"]]


# ---------------------------------------------------------------------------
# drill (a): perf regression -> pin-reference -> release / re-probe
# ---------------------------------------------------------------------------
class TestPerfPinDrill:
    def test_regression_pins_and_recovery_releases(self, engine):
        plane = RemediationPlane(b"drill-pin")
        plane.bind_engine(engine)
        assert engine.monitors["codec"].state != "held"
        note(plane, 1, "perf", "regression", metric="encode",
             frm="ok", to="regressed", window=2)
        plane.tick()
        # the pin latched the class's reference-backend monitor
        assert engine.monitors["codec"].state == "held"
        fire = plane.journal()[-1]
        assert fire["event"] == "fire" and fire["applied"] is True
        assert fire["policy"] == "perf-pin" and fire["key"] == "encode"
        assert "perf-pin:encode" in plane.engagements()
        # the recovery edge releases the hold
        note(plane, 2, "perf", "regression", metric="encode",
             frm="regressed", to="ok", window=3)
        plane.tick()
        assert engine.monitors["codec"].state != "held"
        rel = plane.journal()[-1]
        assert rel["event"] == "release" and rel["reason"] == "recovered"
        assert plane.engagements() == {}

    def test_cooldown_suppression_then_flap_then_reprobe(self, engine):
        plane = RemediationPlane(b"drill-flap")
        plane.bind_engine(engine)
        flaps = []
        note(plane, 1, "perf", "regression", metric="encode",
             frm="ok", to="regressed", window=1)
        plane.tick()                                   # fire @ tick 1
        note(plane, 2, "perf", "regression", metric="encode",
             frm="regressed", to="ok", window=2)
        plane.tick()                                   # release @ tick 2
        # a refire inside the per-key cooldown window is suppressed
        note(plane, 3, "perf", "regression", metric="encode",
             frm="ok", to="regressed", window=3)
        plane.tick()                                   # tick 3
        sup = plane.journal()[-1]
        assert sup["event"] == "suppress" and sup["reason"] == "cooldown"
        assert engine.monitors["codec"].state != "held"
        # past the fire cooldown but within cooldown of the RELEASE:
        # the refire succeeds and is journaled as a flap, and the flap
        # flight note feeds the incident plane's remediation-flap
        # trigger
        rec = flight.FlightRecorder(b"flap-notes")
        rec.add_listener(lambda q, s, k, d: flaps.append((s, k, dict(d)))
                         if s == "remediation" else None)
        plane.tick()                                   # tick 4
        plane.tick()                                   # tick 5
        note(plane, 4, "perf", "regression", metric="encode",
             frm="ok", to="regressed", window=6)
        with flight.armed(rec):
            plane.tick()                               # tick 6: fire+flap
        events = [e["event"] for e in plane.journal()]
        assert events[-2:] == ["fire", "flap"]
        assert plane.journal()[-1]["reason"] == "refire-inside-cooldown"
        assert ("remediation", "flap",
                {"policy": "perf-pin", "action": "pin-reference",
                 "key": "encode", "gap": 4}) in flaps
        # no recovery edge: the count-based re-probe releases the
        # engagement release_after ticks after the fire
        for _ in range(8):
            plane.tick()
        rel = plane.journal()[-1]
        assert rel["event"] == "release" and rel["reason"] == "re-probe"
        assert engine.monitors["codec"].state != "held"

    def test_breaker_trip_latches_the_named_monitor(self, engine):
        plane = RemediationPlane(b"drill-breaker")
        plane.bind_engine(engine)
        note(plane, 1, "breaker", "trip", name="codec", window=5)
        plane.tick()
        assert engine.monitors["codec"].state == "held"
        assert plane.journal()[-1]["policy"] == "breaker-pin"

    def test_quarantine_holds_every_breaker_on_the_lane(self):
        eng = make_engine(4, 8, rs_backend="jax",
                          resilience=ResilienceConfig(), pool=2)
        try:
            plane = RemediationPlane(b"drill-lane")
            plane.bind_engine(eng)
            note(plane, 1, "fleet", "outlier", instance="bench.d1",
                 metric="encode_p99_ms")
            plane.tick()
            lane = next(l for l in eng.pool.lanes if l.index == 1)
            other = next(l for l in eng.pool.lanes if l.index == 0)
            assert all(m.state == "held"
                       for m in lane.monitors.values())
            assert all(m.state != "held"
                       for m in other.monitors.values())
            # a key naming a foreign host resolves to nothing: the
            # intent is journaled, honestly marked not-applied
            note(plane, 2, "fleet", "outlier", instance="otherhost",
                 metric="encode_p99_ms")
            plane.tick()
            ent = plane.journal()[-1]
            assert ent["key"] == "otherhost" and not ent["applied"]
        finally:
            eng.close()


# ---------------------------------------------------------------------------
# drill (b): injected equivocation -> offences.report_equivocation
# ---------------------------------------------------------------------------
def make_chain(n=3, chain_id="remediate-equiv"):
    spec = ChainSpec(
        name="t", chain_id=chain_id,
        endowed=(("alice", 1_000_000_000 * D),),
        validators=tuple(ValidatorGenesis(f"v{i}", 4_000_000 * D)
                         for i in range(n)),
        era_blocks=1000, epoch_blocks=1000, sudo="alice")
    nodes = [Node(spec, f"node{i}",
                  {f"v{i}": spec.session_key(f"v{i}")})
             for i in range(n)]
    return spec, nodes


class TestEquivocationDrill:
    def test_injected_equivocation_is_filed_slashed_and_chilled(self):
        spec, nodes = make_chain()
        net = Network(nodes)
        net.run_slots(2)
        node, evil = nodes[0], "v2"
        key = spec.session_key(evil)
        g = node.runtime.genesis_hash()
        rnd = node.chain[-1].number + 50
        node.finality.on_vote(
            sign_vote(key, g, evil, rnd, b"\xaa" * 32, rnd))
        node.finality.on_vote(
            sign_vote(key, g, evil, rnd, b"\xbb" * 32, rnd))
        assert node.finality.equivocations
        bond0 = node.runtime.staking.bonded(evil)

        # a dry-run plane journals the decision but files NOTHING
        dry = RemediationPlane(b"drill-equiv", dry_run=True,
                               reporter="alice")
        dry.bind_node(node)
        note(dry, 1, "chain", "anomaly", cls="equivocation",
             key=f"{evil}@{rnd}", to="active")
        dry.tick()
        ent = dry.journal()[-1]
        assert ent["event"] == "fire" and ent["applied"] is False
        net.run_slots(1)
        assert node.runtime.staking.bonded(evil) == bond0

        # the acting plane matches the anomaly key against the node's
        # own signed vote evidence and submits the extrinsic
        plane = RemediationPlane(b"drill-equiv", reporter="alice")
        plane.bind_node(node)
        note(plane, 1, "chain", "anomaly", cls="equivocation",
             key=f"{evil}@{rnd}", to="active")
        plane.tick()
        ent = plane.journal()[-1]
        assert ent["event"] == "fire" and ent["applied"] is True
        assert ent["action"] == "file-offence"
        # one-shot: nothing stays engaged, nothing to release
        assert plane.engagements() == {}
        net.run_slots(1)
        for n_ in nodes:
            assert n_.runtime.staking.bonded(evil) == bond0 * 9 // 10
            assert evil not in n_.runtime.staking.validators()
            ev = n_.runtime.state.events_of("offences",
                                            "EquivocationReported")
            assert dict(ev[-1].data)["offender"] == evil
        # a duplicate anomaly edge is suppressed by the huge per-key
        # cooldown (the on-chain AlreadyReported dedup is the backstop)
        note(plane, 2, "chain", "anomaly", cls="equivocation",
             key=f"{evil}@{rnd}", to="active")
        plane.tick()
        sup = plane.journal()[-1]
        assert sup["event"] == "suppress" and sup["reason"] == "cooldown"

    def test_anomaly_without_local_evidence_is_not_applied(self):
        spec, nodes = make_chain(chain_id="remediate-noev")
        Network(nodes).run_slots(1)
        plane = RemediationPlane(b"drill-noev", reporter="alice")
        plane.bind_node(nodes[0])
        note(plane, 1, "chain", "anomaly", cls="equivocation",
             key="v1@99", to="active")
        plane.tick()
        ent = plane.journal()[-1]
        # the intent is journaled; the seam honestly reports no-op
        assert ent["event"] == "fire" and ent["applied"] is False


# ---------------------------------------------------------------------------
# drill (c): repair-ingress regression -> flip-repair-mode
# ---------------------------------------------------------------------------
class StubMiner:
    """The MinerAgent surface the plane touches, nothing else."""

    def __init__(self, account):
        self.account = account
        self.repair_mode = "symbols"
        self.repair_ingress_bytes = 0
        self.repair_recovered_bytes = 0
        self.modes = []

    def set_repair_mode(self, mode):
        self.repair_mode = mode
        self.modes.append(mode)


class TestRepairModeDrill:
    def test_ingress_regression_flips_and_reprobe_flips_back(self):
        plane = RemediationPlane(b"drill-ingress")
        m = StubMiner("m1")
        plane.bind_miners([m])
        # 4 ingressed bytes per recovered byte: past the 1.5x bound
        m.repair_ingress_bytes = 4000
        m.repair_recovered_bytes = 1000
        plane.tick()
        ent = plane.journal()[-1]
        assert ent["policy"] == "repair-ingress"
        assert ent["event"] == "fire" and ent["applied"] is True
        assert ent["detail"]["ratio"] == 4.0
        assert m.repair_mode == "fragments"
        assert plane.intended_mode("m1") == "fragments"
        # while engaged the sampler stays quiet (mode gate), and the
        # count-based re-probe flips the miner back to symbols
        for _ in range(12):
            plane.tick()
        assert plane.journal()[-1]["event"] == "release"
        assert m.repair_mode == "symbols"
        assert m.modes == ["fragments", "symbols"]

    def test_healthy_ratio_never_fires(self):
        plane = RemediationPlane(b"drill-healthy")
        m = StubMiner("m1")
        plane.bind_miners([m])
        m.repair_ingress_bytes = 1100
        m.repair_recovered_bytes = 1000
        plane.tick()
        assert plane.journal() == [] and m.repair_mode == "symbols"

    def test_real_miner_set_repair_mode_is_threadsafe_and_noted(self):
        m = MinerAgent(None, "m9", [], None)
        with pytest.raises(ValueError, match="repair_mode"):
            m.set_repair_mode("bogus")
        seen = []
        rec = flight.FlightRecorder(b"mode-notes")
        rec.add_listener(
            lambda q, s, k, d: seen.append((s, k, dict(d))))
        with flight.armed(rec):
            m.set_repair_mode("symbols")
            m.set_repair_mode("symbols")     # no-op flip stays silent
        assert seen == [("repair", "mode",
                         {"miner": "m9", "frm": "fragments",
                          "to": "symbols"})]
        # concurrent flippers never tear the mode
        def flip(mode):
            for _ in range(200):
                m.set_repair_mode(mode)
        threads = [threading.Thread(target=flip, args=(mode,))
                   for mode in ("symbols", "fragments") * 4]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert m.repair_mode in ("symbols", "fragments")


# ---------------------------------------------------------------------------
# the replay contract: same seed => byte-identical witness, dry or not
# ---------------------------------------------------------------------------
def _drive(dry_run):
    """One scripted tampered-world episode: a perf regression, an
    ingress regression, a recovery edge, quiet re-probe rounds, then
    an equivocation anomaly (unfiled: no node bound — the journal
    decision is what replays)."""
    plane = RemediationPlane(b"drill-replay", dry_run=dry_run)
    m = StubMiner("m1")
    plane.bind_miners([m])
    seq = 0

    def n(sys, kind, **detail):
        nonlocal seq
        seq += 1
        plane.on_note(seq, sys, kind, detail)

    n("perf", "regression", metric="encode", frm="ok", to="regressed",
      window=1)
    plane.tick()
    m.repair_ingress_bytes += 4000
    m.repair_recovered_bytes += 1000
    plane.tick()
    n("perf", "regression", metric="encode", frm="regressed", to="ok",
      window=3)
    for _ in range(12):
        plane.tick()
    n("chain", "anomaly", cls="equivocation", key="v2@9", to="active")
    plane.tick()
    return plane, m


class TestWitnessReplay:
    def test_same_seed_runs_are_byte_identical(self):
        a, _ = _drive(dry_run=False)
        b, _ = _drive(dry_run=False)
        assert a.witness() == b.witness()
        # the witness is non-trivial: fires, releases and a suppress-
        # free ingress decision all made it in
        events = [e["event"] for e in a.journal()]
        assert events.count("fire") >= 3
        assert events.count("release") >= 2

    def test_dry_run_journals_identically_and_applies_nothing(self):
        act, m_act = _drive(dry_run=False)
        dry, m_dry = _drive(dry_run=True)
        # byte-identical witness: ``applied`` is bookkeeping, not
        # part of the replay contract
        assert dry.witness() == act.witness()
        assert all(e["applied"] is False for e in dry.journal())
        assert any(e["applied"] for e in act.journal())
        # the acting run really flipped the miner; the dry run
        # tracked the same INTENDED trajectory without touching it
        assert m_act.modes == ["fragments", "symbols"]
        assert m_dry.modes == []
        assert m_dry.repair_mode == "symbols"
        assert dry.snapshot()["counters"]["applied"] == 0

    def test_snapshot_metrics_and_rpc_shape(self):
        plane, _ = _drive(dry_run=False)
        snap = plane.snapshot()
        assert snap["policies"] and snap["journal"]
        assert snap["health"]["perf"]["encode"] == "ok"
        m = plane.metrics()
        assert m["cess_remediation_policies"] == 6
        assert m["cess_remediation_fires_total"] >= 3
        assert m["cess_remediation_dry_run"] == 0
        assert all(k.startswith("cess_remediation_") for k in m)


# ---------------------------------------------------------------------------
# satellite 1: the autopilot scenario replays bit-identically
# ---------------------------------------------------------------------------
class TestAutopilotScenario:
    def test_same_seed_action_logs_at_20_and_100_nodes(self):
        sc = SCENARIOS["perf_regression_autopilot"]
        for n_nodes in (20, 100):
            a = run_scenario(sc, b"autopilot", n_nodes=n_nodes)
            b = run_scenario(sc, b"autopilot", n_nodes=n_nodes)
            assert a.witness() == b.witness(), n_nodes
            assert a.remediation.witness() == b.remediation.witness()
        # the scripted regressions were pinned AND released, applied
        # for real (the scenario runs the acting plane)
        journal = a.remediation.journal()
        fired = [(e["policy"], e["key"]) for e in journal
                 if e["event"] == "fire"]
        assert ("perf-pin", "encode") in fired
        assert ("perf-pin", "decode") in fired
        assert all(e["applied"] for e in journal
                   if e["event"] == "fire")
        released = [e["key"] for e in journal
                    if e["event"] == "release"]
        assert "encode" in released and "decode" in released
        # a later incident bundle embeds a non-empty journal tail
        tails = [b_["snapshots"]["remediation"]["journal"]
                 for b_ in a.reporter.bundles()
                 if "remediation" in b_["snapshots"]]
        assert tails and any(tails)


# ---------------------------------------------------------------------------
# invariant tripwires: both remediation checkers provably fire
# ---------------------------------------------------------------------------
class TestRemediationInvariantTripwires:
    def _regressed_world(self, enabled):
        pols = tuple(dataclasses.replace(p, enabled=enabled)
                     if p.name == "perf-pin" else p
                     for p in default_policies())
        plane = RemediationPlane(b"tripwire", pols)
        note(plane, 1, "perf", "regression", metric="encode",
             frm="ok", to="regressed", window=1)
        plane.tick()
        plane.tick()
        return types.SimpleNamespace(remediation=plane), plane

    def test_coverage_fires_on_a_disabled_policy_world(self):
        world, plane = self._regressed_world(enabled=False)
        assert plane.edge_log()       # the edge WAS matched + recorded
        with pytest.raises(InvariantViolation,
                           match="remediation-coverage.*DISABLED"):
            run_checks(world, ("remediation-coverage",))

    def test_effective_fires_on_a_disabled_policy_world(self):
        world, _ = self._regressed_world(enabled=False)
        with pytest.raises(InvariantViolation,
                           match="remediation-effective.*regressed"):
            run_checks(world, ("remediation-effective",))

    def test_both_hold_on_the_enabled_world(self):
        world, _ = self._regressed_world(enabled=True)
        run_checks(world, ("remediation-coverage",
                           "remediation-effective"))

    def test_effective_fires_when_the_hold_is_tampered_away(self, engine):
        plane = RemediationPlane(b"tamper")
        plane.bind_engine(engine)
        note(plane, 1, "perf", "regression", metric="encode",
             frm="ok", to="regressed", window=1)
        plane.tick()
        world = types.SimpleNamespace(remediation=plane)
        run_checks(world, ("remediation-effective",))  # holds pre-tamper
        # someone releases the monitor behind the plane's back
        engine.monitors["codec"].release()
        with pytest.raises(InvariantViolation,
                           match="remediation-effective.*not held"):
            run_checks(world, ("remediation-effective",))

    def test_absent_plane_is_a_no_op(self):
        world = types.SimpleNamespace(remediation=None)
        run_checks(world, ("remediation-coverage",
                           "remediation-effective"))
