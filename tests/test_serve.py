"""Device submission engine (cess_tpu/serve): batch coalescing
determinism, bucket padding, priority, backpressure/timeout contracts,
and the stats surface through node/metrics.py + RPC.

The hard invariant throughout: engine-mediated results are
BIT-IDENTICAL to the direct ErasureCodec / AuditBackend calls —
the engine decides WHEN and HOW BATCHED device work runs, never what
it computes (protocol determinism, like the codec gate itself).
"""
import threading

import numpy as np
import pytest

from cess_tpu.ops import podr2, rs
from cess_tpu.serve import (AdmissionPolicy, EngineClosed,
                            EngineSaturated, EngineTimeout, make_engine)

K, M = 2, 1
FRAG = 1024               # bytes per fragment -> 2 PoDR2 blocks


@pytest.fixture(scope="module")
def pkey():
    return podr2.Podr2Key.generate(21)


@pytest.fixture()
def engine(pkey):
    eng = make_engine(K, M, podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=0.005))
    yield eng
    eng.close()


def rnd(shape, seed=0, dtype=np.uint8):
    rng = np.random.default_rng(seed)
    return rng.integers(0, np.iinfo(dtype).max, shape, dtype=dtype)


# -- determinism: engine == direct, per op class ---------------------------

def test_encode_bit_identical_and_padded(engine):
    codec = rs.make_codec(K, M, backend="cpu")
    for b, seed in ((1, 1), (3, 2), (5, 3)):       # odd sizes force pads
        data = rnd((b, K, 256), seed)
        assert np.array_equal(engine.encode(data), codec.encode(data))
    # 2-D submit round-trips without a batch axis
    one = rnd((K, 256), 9)
    out = engine.encode(one)
    assert out.shape == (K + M, 256)
    assert np.array_equal(out, codec.encode(one[None])[0])
    st = engine.stats_snapshot()["classes"]["encode"]
    assert st["pad_waste"] > 0          # 3- and 5-row batches padded


def test_reconstruct_and_decode_match_direct(engine):
    codec = rs.make_codec(K, M, backend="cpu")
    data = rnd((4, K, 512), 5)
    coded = codec.encode(data)
    # drop row 0: survivors are rows (1, 2)
    surv = coded[:, [1, 2]]
    rec = engine.reconstruct(surv, (1, 2), (0,))
    assert np.array_equal(rec, codec.reconstruct(surv, (1, 2), (0,)))
    assert np.array_equal(rec[:, 0], coded[:, 0])
    dec = engine.decode_data(surv, (1, 2))
    assert np.array_equal(dec, data)


def test_tag_prove_verify_bit_identical(engine, pkey):
    frags = rnd((5, FRAG), 7)
    hashes = [bytes([i]) * 32 for i in range(5)]
    ids = np.stack([podr2.fragment_id_from_hash(h) for h in hashes])
    tags = engine.tag_fragments(ids, frags)
    direct = np.asarray(podr2.tag_fragments(pkey, ids, frags))
    assert np.array_equal(tags, direct)
    blocks = tags.shape[1]
    idx, nu = podr2.gen_challenge(b"round-1", blocks)
    r = np.asarray(podr2.aggregate_coeffs(b"round-1", ids))
    mu, sigma = engine.prove_aggregate(frags, tags, idx, nu, r)
    dmu, dsigma = podr2.prove_aggregate(frags, tags, idx, nu, r)
    assert np.array_equal(mu, np.asarray(dmu))
    assert np.array_equal(sigma, np.asarray(dsigma))
    assert engine.verify_aggregate(ids, blocks, idx, nu, r, mu, sigma)
    # per-fragment checks coalesce along F and agree with the direct op
    mu_b, sigma_b = podr2.prove_batch(frags, tags, idx, nu)
    ok = engine.verify_batch(ids, blocks, idx, nu, np.asarray(mu_b),
                             np.asarray(sigma_b))
    dok = np.asarray(podr2.verify_batch(pkey, ids, blocks, idx, nu,
                                        mu_b, sigma_b))
    assert np.array_equal(ok, dok) and ok.all()


def test_verify_aggregate_coalesces_ragged_missions(pkey):
    """Missions with DIFFERENT owed-set sizes coalesce into one
    F-padded vmap batch; verdicts match the direct per-mission calls,
    including a tampered proof rejected inside the same batch."""
    eng = make_engine(K, M, podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=0.25))
    try:
        blocks = FRAG // podr2.BLOCK_BYTES
        idx, nu = podr2.gen_challenge(b"round-2", blocks)
        missions = []
        for i, f in enumerate((2, 3, 5)):        # ragged owed sets
            frags = rnd((f, FRAG), 30 + i)
            hashes = [bytes([40 + i, j]) * 16 for j in range(f)]
            ids = np.stack([podr2.fragment_id_from_hash(h)
                            for h in hashes])
            tags = np.asarray(podr2.tag_fragments(pkey, ids, frags))
            r = np.asarray(podr2.aggregate_coeffs(b"round-2", ids))
            mu, sigma = podr2.prove_aggregate(frags, tags, idx, nu, r)
            mu, sigma = np.asarray(mu), np.asarray(sigma)
            if i == 1:                           # tamper one mission
                sigma = (sigma + 1) % (2 ** 31 - 1)
            missions.append((ids, r, mu, sigma))
        # submit back-to-back (inputs prepared above, so all three
        # land in the queue within the coalescing window)
        futs = [eng.submit_verify_aggregate(ids, blocks, idx, nu, r,
                                            mu, sigma)
                for ids, r, mu, sigma in missions]
        want = [bool(np.asarray(podr2.verify_aggregate(
            pkey, ids, blocks, idx, nu, r, mu, sigma)))
            for ids, r, mu, sigma in missions]
        got = [bool(f.result(timeout=30)) for f in futs]
        assert got == want == [True, False, True]
        st = eng.stats_snapshot()["classes"]["verify"]
        assert st["batch_occupancy"] > 1        # they really coalesced
    finally:
        eng.close()


# -- zero-copy device handoff ----------------------------------------------

def test_engine_zero_copy_device_arrays(pkey):
    """jax.Array in -> jax.Array out (no forced np.asarray anywhere on
    the device submitter's path), values bit-identical to direct; host
    (numpy) submitters keep getting numpy back."""
    import jax
    import jax.numpy as jnp

    eng = make_engine(K, M, rs_backend="jax", podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=0.005))
    try:
        codec = rs.make_codec(K, M, backend="cpu")
        host = rnd((2, K, 256), 1)
        dev = jnp.asarray(host)
        out = eng.encode(dev)
        assert isinstance(out, jax.Array)
        assert np.array_equal(np.asarray(out), codec.encode(host))
        np_out = eng.encode(host)
        assert isinstance(np_out, np.ndarray)
        assert np.array_equal(np_out, np.asarray(out))
        # tag + verify classes round-trip on device too
        frags = jnp.asarray(rnd((3, FRAG), 2))
        ids = jnp.asarray(rnd((3, 2), 3, dtype=np.uint32))
        tags = eng.tag_fragments(ids, frags)
        assert isinstance(tags, jax.Array)
        direct = np.asarray(podr2.tag_fragments(pkey, ids, frags))
        assert np.array_equal(np.asarray(tags), direct)
        blocks = tags.shape[1]
        idx, nu = podr2.gen_challenge(b"round-zc", blocks)
        mu_b, sigma_b = podr2.prove_batch(frags, tags, idx, nu)
        ok = eng.verify_batch(jnp.asarray(ids), blocks, idx, nu,
                              jnp.asarray(mu_b), jnp.asarray(sigma_b))
        assert isinstance(ok, jax.Array) and np.asarray(ok).all()
    finally:
        eng.close()


def test_mixed_host_device_batch_coalesces(pkey):
    """A device submitter and a host submitter coalesce into ONE
    batch; each gets its own domain back and both match direct."""
    import jax
    import jax.numpy as jnp

    codec = rs.make_codec(K, M, backend="cpu")
    eng = make_engine(K, M, rs_backend="jax",
                      policy=AdmissionPolicy(max_delay=0.25))
    try:
        host = rnd((2, K, 128), 4)
        dev = jnp.asarray(rnd((3, K, 128), 5))
        f_host = eng.submit_encode(host)
        f_dev = eng.submit_encode(dev)
        out_host = f_host.result(timeout=30)
        out_dev = f_dev.result(timeout=30)
        assert isinstance(out_host, np.ndarray)
        assert isinstance(out_dev, jax.Array)
        assert np.array_equal(out_host, codec.encode(host))
        assert np.array_equal(np.asarray(out_dev),
                              codec.encode(np.asarray(dev)))
        st = eng.stats_snapshot()["classes"]["encode"]
        assert st["batches"] == 1 and st["batch_occupancy"] == 2
    finally:
        eng.close()


def test_pipeline_engine_path_returns_device_arrays(pkey):
    """StoragePipeline -> engine -> device is one handoff: the engine
    path hands back jax.Array results identical to the direct path."""
    import jax
    import jax.numpy as jnp

    from cess_tpu.models.pipeline import PipelineConfig, StoragePipeline

    cfg = PipelineConfig(k=K, m=M, segment_size=K * FRAG)
    eng = make_engine(K, M, rs_backend="jax", podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=0.005))
    try:
        piped = StoragePipeline(cfg, podr2_key=pkey, engine=eng)
        direct = StoragePipeline(cfg, podr2_key=pkey)
        # host segments: the fused direct path donates its staged
        # device copy on accelerators, so the shared input stays numpy
        segs = rnd((2, K * FRAG), 6)
        ids = jnp.asarray(rnd((2, K + M, 2), 7, dtype=np.uint32))
        out = piped.forward(segs, ids)
        assert isinstance(out["fragments"], jax.Array)
        assert isinstance(out["tags"], jax.Array)
        ref = direct.forward(segs, ids)
        assert np.array_equal(np.asarray(out["fragments"]),
                              np.asarray(ref["fragments"]))
        assert np.array_equal(np.asarray(out["tags"]),
                              np.asarray(ref["tags"]))
    finally:
        eng.close()


# -- pipeline + offchain wiring --------------------------------------------

def test_pipeline_engine_matches_direct(pkey):
    from cess_tpu.models.pipeline import PipelineConfig, StoragePipeline

    cfg = PipelineConfig(k=K, m=M, segment_size=K * FRAG)
    direct = StoragePipeline(cfg, podr2_key=pkey)
    eng = make_engine(K, M, podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=0.005))
    try:
        piped = StoragePipeline(cfg, podr2_key=pkey, engine=eng)
        segs = rnd((3, K * FRAG), 11)
        a = np.asarray(direct.encode_step(segs))
        b = np.asarray(piped.encode_step(segs))
        assert np.array_equal(a, b)
        ids = rnd((3, K + M, 2), 12, dtype=np.uint32)
        ta = np.asarray(direct.tag_step(a, ids))
        tb = np.asarray(piped.tag_step(b, ids))
        assert np.array_equal(ta, tb)
    finally:
        eng.close()
    # a mismatched audit key is refused loudly (silent tag divergence)
    other = podr2.Podr2Key.generate(99)
    eng2 = make_engine(K, M, podr2_key=other,
                       policy=AdmissionPolicy(max_delay=0.005))
    try:
        with pytest.raises(ValueError, match="key"):
            StoragePipeline(cfg, podr2_key=pkey, engine=eng2)
    finally:
        eng2.close()


def test_build_proof_engine_path_identical(engine, pkey):
    from cess_tpu.node.offchain import build_proof

    frags = rnd((4, FRAG), 17)
    hashes = [bytes([60 + i]) * 32 for i in range(4)]
    ids = np.stack([podr2.fragment_id_from_hash(h) for h in hashes])
    tags = np.asarray(podr2.tag_fragments(pkey, ids, frags))
    store = {h: frags[i].tobytes() for i, h in enumerate(hashes)}
    tagmap = {h: tags[i] for i, h in enumerate(hashes)}
    direct = build_proof(b"round-3", hashes, store, tagmap,
                         limbs=pkey.limbs)
    via_engine = build_proof(b"round-3", hashes, store, tagmap,
                             limbs=pkey.limbs, engine=engine)
    assert direct == via_engine       # identical wire bytes


def test_tee_agent_verify_engine_path(engine, pkey):
    """TeeAgent._verify routes through the engine's verify class when
    one is configured, with verdicts identical to the direct path —
    including malformed-blob rejection (never an exception)."""
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.offchain import TeeAgent, build_proof

    node = Node(dev_spec(), "tee-host", {})
    blocks = FRAG // podr2.BLOCK_BYTES
    direct_tee = TeeAgent(node, "alice", pkey, blocks)
    engine_tee = TeeAgent(node, "alice", pkey, blocks, engine=engine)
    frags = rnd((3, FRAG), 55)
    hashes = [bytes([70 + i]) * 32 for i in range(3)]
    ids = np.stack([podr2.fragment_id_from_hash(h) for h in hashes])
    tags = np.asarray(podr2.tag_fragments(pkey, ids, frags))
    store = {h: frags[i].tobytes() for i, h in enumerate(hashes)}
    tagmap = {h: tags[i] for i, h in enumerate(hashes)}
    seed = b"round-5"
    blob = build_proof(seed, hashes, store, tagmap, limbs=pkey.limbs)
    idx, nu = podr2.gen_challenge(seed, blocks)
    for owed in (hashes, hashes[:2]):       # honest + wrong owed set
        assert engine_tee._verify(blob, owed, seed, idx, nu) \
            == direct_tee._verify(blob, owed, seed, idx, nu)
    assert engine_tee._verify(blob, hashes, seed, idx, nu) is True
    assert engine_tee._verify(b"garbage", hashes, seed, idx, nu) is False
    # a mismatched engine audit key is refused at construction
    other = make_engine(K, M, podr2_key=podr2.Podr2Key.generate(98),
                        policy=AdmissionPolicy(max_delay=0.005))
    try:
        with pytest.raises(ValueError, match="key"):
            TeeAgent(node, "alice", pkey, blocks, engine=other)
    finally:
        other.close()


# -- contention: coalescing + priority --------------------------------------

def test_concurrent_submitters_coalesce(pkey):
    """>= 8 concurrent submitters (the acceptance-criteria contention
    shape): their requests coalesce into shared device batches (mean
    occupancy > 1) and every result is bit-identical to direct."""
    codec = rs.make_codec(K, M, backend="cpu")
    eng = make_engine(K, M, podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=0.3))
    n_threads = 8
    datas = [rnd((2, K, 256), 100 + i) for i in range(n_threads)]
    outs: list = [None] * n_threads
    barrier = threading.Barrier(n_threads)

    def submit(i):
        barrier.wait()
        outs[i] = eng.encode(datas[i], timeout=30)

    try:
        threads = [threading.Thread(target=submit, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(60)
        for i in range(n_threads):
            assert np.array_equal(outs[i], codec.encode(datas[i])), i
        st = eng.stats_snapshot()["classes"]["encode"]
        assert st["submitted"] == st["completed"] == n_threads
        assert st["batch_occupancy"] > 1, st
    finally:
        eng.close()


def test_verify_preempts_queued_encode(pkey):
    """Per-class priority: once a drain triggers, the verify class
    goes to the device before bulk encode that queued EARLIER —
    challenge verification preempts upload work (policy.py)."""
    import time

    eng = make_engine(K, M, podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=0.4))
    order: list[str] = []
    real_encode, real_verify = eng._op_encode, eng._op_verify_batch
    eng._op_encode = lambda b, d=False: (order.append("encode"),
                                         real_encode(b, d))[1]
    eng._op_verify_batch = lambda b, d=False: (order.append("verify"),
                                               real_verify(b, d))[1]
    try:
        f_enc = eng.submit_encode(rnd((1, K, 256), 1))
        time.sleep(0.05)          # verify arrives LATER...
        blocks = FRAG // podr2.BLOCK_BYTES
        idx, nu = podr2.gen_challenge(b"round-4", blocks)
        f_ver = eng.submit_verify_batch(
            np.zeros((1, 2), np.uint32), blocks, idx, nu,
            np.zeros((1, podr2.SECTORS), np.uint32),
            np.zeros((1, podr2.LIMBS), np.uint32))
        f_ver.result(timeout=30)
        f_enc.result(timeout=30)
        assert order == ["verify", "encode"]     # ...but runs FIRST
    finally:
        eng.close()


# -- backpressure / timeout / shutdown contracts ----------------------------

def test_saturation_is_explicit(pkey):
    eng = make_engine(K, M, policy=AdmissionPolicy(
        queue_cap=2, max_delay=30.0))
    try:
        data = rnd((1, K, 64), 3)
        eng.submit_encode(data)
        eng.submit_encode(data)
        with pytest.raises(EngineSaturated):
            eng.submit_encode(data)
        st = eng.stats_snapshot()["classes"]["encode"]
        assert st["saturated"] == 1 and st["queue_depth"] == 2
    finally:
        eng.close()


def test_deadline_expiry_cancels(pkey):
    eng = make_engine(K, M, policy=AdmissionPolicy(max_delay=30.0))
    try:
        fut = eng.submit_encode(rnd((1, K, 64), 4), timeout=0.05)
        with pytest.raises(EngineTimeout):
            fut.result(timeout=10)
        st = eng.stats_snapshot()["classes"]["encode"]
        assert st["timeouts"] == 1 and st["completed"] == 0
    finally:
        eng.close()


def test_deadline_expiry_crosses_classes(pkey):
    """An expired request in a LOW-priority class cancels promptly
    even while a higher-priority class holds queued (untriggered)
    work — expiry is a queue sweep, not a drain side-effect."""
    eng = make_engine(K, M, podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=30.0))
    try:
        blocks = FRAG // podr2.BLOCK_BYTES
        idx, nu = podr2.gen_challenge(b"round-6", blocks)
        f_ver = eng.submit_verify_batch(        # higher class, queued
            np.zeros((1, 2), np.uint32), blocks, idx, nu,
            np.zeros((1, podr2.SECTORS), np.uint32),
            np.zeros((1, podr2.LIMBS), np.uint32))
        f_enc = eng.submit_encode(rnd((1, K, 64), 7), timeout=0.05)
        with pytest.raises(EngineTimeout):
            f_enc.result(timeout=10)
        st = eng.stats_snapshot()["classes"]
        assert st["encode"]["timeouts"] == 1
        # the verify request was NOT force-drained by the dead encode
        # (no spurious occupancy-1 batches); it completes on close
        eng.close()
        assert f_ver.result(timeout=10).shape == (1,)
    finally:
        eng.close()


def test_stacked_ops_cap_pad_spread(pkey):
    """One huge prove request must not drag tiny same-round peers
    into its row bucket: requests whose buckets differ more than
    PAD_SPREAD split into separate batches."""
    eng = make_engine(K, M, podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=0.25,
                                             max_batch_rows=512))
    try:
        blocks = FRAG // podr2.BLOCK_BYTES
        idx, nu = podr2.gen_challenge(b"round-7", blocks)
        sets = []
        for i, f in enumerate((64, 1, 1)):       # 64-row + two tiny
            frags = rnd((f, FRAG), 80 + i)
            ids = np.stack([podr2.fragment_id_from_hash(
                bytes([90 + i, j % 256]) * 16) for j in range(f)])
            tags = np.asarray(podr2.tag_fragments(pkey, ids, frags))
            r = np.asarray(podr2.aggregate_coeffs(b"round-7", ids))
            sets.append((frags, tags, r))
        futs = [eng.submit_prove_aggregate(f, t, idx, nu, r)
                for f, t, r in sets]
        for (f, t, r), fut in zip(sets, futs):
            mu, sigma = fut.result(timeout=60)
            dmu, dsigma = podr2.prove_aggregate(f, t, idx, nu, r)
            assert np.array_equal(mu, np.asarray(dmu))
            assert np.array_equal(sigma, np.asarray(dsigma))
        st = eng.stats_snapshot()["classes"]["prove"]
        assert st["batches"] == 2        # big solo, two tiny together
    finally:
        eng.close()


def test_closed_engine_refuses(pkey):
    eng = make_engine(K, M)
    eng.close()
    with pytest.raises(EngineClosed):
        eng.submit_encode(rnd((1, K, 64), 5))


def test_close_drains_pending(pkey):
    """close() is graceful: already-queued work completes."""
    codec = rs.make_codec(K, M, backend="cpu")
    eng = make_engine(K, M, policy=AdmissionPolicy(max_delay=30.0))
    data = rnd((2, K, 64), 6)
    fut = eng.submit_encode(data)
    eng.close()
    assert np.array_equal(fut.result(timeout=10), codec.encode(data))


def test_flush_waits_for_quiescence(pkey):
    """flush() returns only once every queued request has resolved
    (including in-flight batches), and respects its own timeout."""
    import time

    codec = rs.make_codec(K, M, backend="cpu")
    eng = make_engine(K, M, policy=AdmissionPolicy(max_delay=10.0))
    real = eng._op_encode
    eng._op_encode = lambda b, d=False: (time.sleep(0.3), real(b, d))[1]
    try:
        datas = [rnd((1, K, 64), s) for s in (1, 2)]
        futs = [eng.submit_encode(d) for d in datas]
        assert eng.flush(timeout=0.01) is False     # still working
        assert eng.flush(timeout=30) is True
        for f, d in zip(futs, datas):
            assert f.done()
            assert np.array_equal(f.result(), codec.encode(d))
    finally:
        eng.close()


def test_close_timeout_rejects_still_queued(pkey):
    """A close() whose drain outlives its timeout rejects every
    still-queued future with EngineClosed — no caller hangs forever
    on a future that will never fire."""
    import time

    eng = make_engine(K, M, policy=AdmissionPolicy(max_delay=30.0))
    real = eng._op_encode
    eng._op_encode = lambda b, d=False: (time.sleep(1.5), real(b, d))[1]
    # different shapes -> two batches: the first goes in flight (and
    # sleeps), the second is still queued when close() gives up
    f1 = eng.submit_encode(rnd((1, K, 64), 1))
    f2 = eng.submit_encode(rnd((1, K, 128), 2))
    time.sleep(0.3)                     # let batch 1 enter the runner
    eng.close(timeout=0.1)
    with pytest.raises(EngineClosed):
        f2.result(timeout=10)
    # the in-flight batch still resolves (process is alive)
    assert f1.result(timeout=10).shape == (1, K + M, 64)


def test_miner_agent_rejects_mismatched_engine_geometry(pkey):
    from cess_tpu.models.pipeline import PipelineConfig, StoragePipeline
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.offchain import MinerAgent

    node = Node(dev_spec(), "mm", {})
    pipe = StoragePipeline(PipelineConfig(k=K, m=M,
                                          segment_size=K * FRAG),
                           podr2_key=pkey)
    other = make_engine(4, 8, policy=AdmissionPolicy(max_delay=0.005))
    try:
        with pytest.raises(ValueError, match="RS"):
            MinerAgent(node, "m1", [], pipe, engine=other)
    finally:
        other.close()


def test_program_cache_lru_bounded():
    from cess_tpu.serve.buckets import ProgramCache

    cache = ProgramCache(capacity=3)
    for i in range(5):
        cache.get(("op", i), lambda i=i: (lambda: i))
    assert len(cache) == 3               # oldest two evicted
    # hot keys survive: touch ("op", 2) then insert -> 3 goes, 2 stays
    cache.get(("op", 2), lambda: (lambda: None))
    cache.get(("op", 9), lambda: (lambda: None))
    assert len(cache) == 3
    built = []
    cache.get(("op", 2), lambda: built.append(1))
    assert not built                     # still cached


# -- buckets + program cache -------------------------------------------------

def test_bucket_padding_and_program_reuse(pkey):
    from cess_tpu.serve.buckets import bucket_rows

    assert [bucket_rows(n) for n in (1, 2, 3, 5, 9)] == [1, 2, 4, 8, 16]
    # even a request past the row budget stays on the power-of-two
    # grid (bounded program count beats exact-size one-off compiles)
    assert bucket_rows(600) == 1024
    eng = make_engine(K, M, policy=AdmissionPolicy(max_delay=0.005))
    try:
        codec = rs.make_codec(K, M, backend="cpu")
        for seed in (1, 2, 3):
            data = rnd((3, K, 128), seed)   # same bucket every time
            assert np.array_equal(eng.encode(data), codec.encode(data))
        snap = eng.stats_snapshot()
        assert snap["programs_built"] == 1
        assert snap["programs_reused"] == 2
    finally:
        eng.close()


def test_mixed_shapes_do_not_cross_coalesce(pkey):
    """Requests with different geometry keys never share a batch but
    all complete correctly (the drain splits by key)."""
    codec = rs.make_codec(K, M, backend="cpu")
    eng = make_engine(K, M, policy=AdmissionPolicy(max_delay=0.2))
    try:
        a, b = rnd((2, K, 128), 1), rnd((2, K, 256), 2)
        fa, fb = eng.submit_encode(a), eng.submit_encode(b)
        assert np.array_equal(fa.result(timeout=30), codec.encode(a))
        assert np.array_equal(fb.result(timeout=30), codec.encode(b))
        assert eng.stats_snapshot()["classes"]["encode"]["batches"] == 2
    finally:
        eng.close()


# -- observability surface ---------------------------------------------------

def test_engine_stats_via_node_metrics_and_rpc(pkey):
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.metrics import collect, render_metrics
    from cess_tpu.node.network import Node
    from cess_tpu.node.rpc import RpcServer

    node = Node(dev_spec(), "eng-node",
                {"alice": dev_spec().session_key("alice")})
    srv = RpcServer(node, port=0)
    try:
        # no engine attached: RPC answers null, metrics stay clean
        assert srv.handle("cess_engineStats", []) is None
        assert not any(k.startswith("cess_engine_") for k in collect(node))
        eng = make_engine(K, M, podr2_key=pkey,
                          policy=AdmissionPolicy(max_delay=0.005))
        node.engine = eng
        try:
            eng.encode(rnd((2, K, 128), 8))
            m = collect(node)
            assert m["cess_engine_encode_completed"] == 1
            assert m["cess_engine_encode_batches"] == 1
            assert "cess_engine_verify_queue_depth" in m
            text = render_metrics(node)
            assert "cess_engine_encode_batch_occupancy" in text
            snap = srv.handle("cess_engineStats", [])
            assert snap["classes"]["encode"]["completed"] == 1
            assert set(snap["classes"]) \
                == {"verify", "prove", "tag", "repair", "encode"}
        finally:
            eng.close()
    finally:
        srv.httpd.server_close()
