"""Golden tests: JAX codec strategies vs the NumPy oracle, byte-exact.

Byte-exact determinism between the CPU default path and the device path
is a protocol invariant — fragment hashes go on chain (SURVEY.md §7
hard part 4). Runs on the virtual CPU mesh; the same code path runs on
TPU hardware via bench.py.
"""
import numpy as np
import pytest

from cess_tpu.ops import gf
from cess_tpu.ops.rs import TPUCodec, make_codec
from cess_tpu.ops.rs_ref import ReferenceCodec

GEOMETRIES = [(2, 1), (4, 8), (4, 2), (10, 4)]
STRATEGIES = ["gather", "bitmatrix", "pallas"]


def rand(shape, seed=0):
    return np.random.default_rng(seed).integers(0, 256, shape, dtype=np.uint8)


@pytest.mark.parametrize("k,m", GEOMETRIES)
@pytest.mark.parametrize("strategy", STRATEGIES)
def test_encode_matches_oracle(k, m, strategy):
    ref = ReferenceCodec(k, m)
    tpu = TPUCodec(k, m, strategy=strategy)
    data = rand((k, 512), seed=k * 31 + m)
    want = ref.encode(data)
    got = np.asarray(tpu.encode(data))
    np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("strategy", STRATEGIES)
def test_encode_batched(strategy):
    k, m = 4, 8
    ref = ReferenceCodec(k, m)
    tpu = TPUCodec(k, m, strategy=strategy)
    data = rand((3, 5, k, 256), seed=7)
    np.testing.assert_array_equal(np.asarray(tpu.encode(data)), ref.encode(data))


@pytest.mark.parametrize("k,m", [(2, 1), (4, 8)])
@pytest.mark.parametrize("strategy", STRATEGIES)
def test_reconstruct_all_erasure_patterns(k, m, strategy):
    """Any k survivors recover every missing shard exactly."""
    import itertools

    ref = ReferenceCodec(k, m)
    tpu = TPUCodec(k, m, strategy=strategy)
    data = rand((2, k, 128), seed=99)
    shards = ref.encode(data)
    patterns = list(itertools.combinations(range(k + m), k))
    if len(patterns) > 12:  # keep runtime sane for (4,8): sample across the space
        rng = np.random.default_rng(k * 100 + m)
        patterns = [patterns[i] for i in rng.choice(len(patterns), 12, replace=False)]
    for present in patterns:
        missing = tuple(i for i in range(k + m) if i not in present)
        survivors = shards[:, list(present), :]
        got = np.asarray(tpu.reconstruct(survivors, present))
        np.testing.assert_array_equal(got, shards[:, list(missing), :])
        got_data = np.asarray(tpu.decode_data(survivors, present))
        np.testing.assert_array_equal(got_data, data)


@pytest.mark.parametrize("strategy", STRATEGIES)
def test_segment_sized_shards(strategy):
    """One real-geometry shard column count (scaled-down fragment)."""
    k, m = 4, 8
    tpu = TPUCodec(k, m, strategy=strategy)
    ref = ReferenceCodec(k, m)
    data = rand((k, 64 * 1024), seed=3)
    np.testing.assert_array_equal(np.asarray(tpu.encode_parity(data)),
                                  ref.encode_parity(data))


def test_make_codec_backends():
    cpu = make_codec(2, 1, backend="cpu")
    dev = make_codec(2, 1, backend="jax")
    assert isinstance(cpu, ReferenceCodec) and isinstance(dev, TPUCodec)
    data = rand((2, 64), seed=1)
    np.testing.assert_array_equal(np.asarray(dev.encode(data)), cpu.encode(data))


@pytest.mark.parametrize("use_int8", [True, False])
def test_pallas_kernel_matches_oracle(use_int8):
    """Fused Pallas kernel (interpret mode on CPU) vs oracle, incl. padding."""
    from cess_tpu.ops.rs_pallas import apply_bitmatrix

    k, m = 4, 8
    ref = ReferenceCodec(k, m)
    bmat = gf.expand_bitmatrix(ref.parity)
    for n in (512, 700):  # 700 exercises the pad-to-tile path
        data = rand((2, k, n), seed=n)
        got = np.asarray(apply_bitmatrix(bmat, data, tile_n=512, use_int8=use_int8))
        np.testing.assert_array_equal(got, ref.encode_parity(data))


def test_bitmatrix_expansion_roundtrip():
    """expand_bitmatrix really is the GF multiply, for all 256 constants."""
    xs = np.arange(256, dtype=np.uint8).reshape(1, 256)
    for c in [0, 1, 2, 3, 0x1D, 0x80, 0xFF]:
        bm = gf.expand_bitmatrix(np.array([[c]], dtype=np.uint8))
        bits = ((xs[:, None, :] >> np.arange(8)[None, :, None]) & 1).reshape(8, 256)
        obits = (bm.astype(np.int64) @ bits) & 1
        got = np.zeros(256, dtype=np.uint8)
        for a in range(8):
            got |= (obits[a] << a).astype(np.uint8)
        want = np.array([gf.gf_mul(c, int(x)) for x in range(256)], dtype=np.uint8)
        np.testing.assert_array_equal(got, want)


def test_property_encode_corrupt_repair_random_patterns():
    """SURVEY §4 implication: property tests for encode->corrupt->
    repair. Random geometries and random erasure sets across all
    three backends, byte-exact against the oracle."""
    import numpy as np

    from cess_tpu.ops import rs, rs_ref

    rng = np.random.default_rng(1234)
    for trial in range(12):
        k = int(rng.integers(1, 6))
        m = int(rng.integers(1, 6))
        n = int(rng.integers(1, 5)) * 64
        data = rng.integers(0, 256, (2, k, n), dtype=np.uint8)
        ref = rs_ref.ReferenceCodec(k, m)
        coded = ref.encode(data)
        # lose a random subset of up to m shards
        n_lose = int(rng.integers(1, m + 1))
        missing = tuple(sorted(rng.choice(k + m, size=n_lose,
                                          replace=False).tolist()))
        present = tuple(i for i in range(k + m) if i not in missing)[:k]
        surv = coded[:, list(present)]
        expect = coded[:, list(missing)]
        for backend in ("cpu", "native", "jax"):
            codec = rs.make_codec(k, m, backend=backend)
            got = np.asarray(codec.reconstruct(surv, present, missing))
            assert np.array_equal(got, expect), \
                (trial, backend, k, m, missing)
            got_data = np.asarray(codec.decode_data(surv, present))
            assert np.array_equal(got_data, data), (trial, backend)
