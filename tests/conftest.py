"""Test configuration: force an 8-device virtual CPU platform.

Tests never require TPU hardware; multi-chip sharding is exercised on a
virtual 8-device CPU mesh (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Must run before jax is imported anywhere.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
