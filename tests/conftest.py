"""Test configuration: force an 8-device virtual CPU platform.

Tests never require TPU hardware; multi-chip sharding is exercised on a
virtual 8-device CPU mesh (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this box's axon sitecustomize registers the TPU plugin and
overrides JAX_PLATFORMS env at interpreter start, so env vars alone
don't stick — the programmatic config update below does.
"""
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)
