"""Test configuration: force an 8-device virtual CPU platform.

Tests never require TPU hardware; multi-chip sharding is exercised on a
virtual 8-device CPU mesh (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this box's axon sitecustomize registers the TPU plugin and
overrides JAX_PLATFORMS env at interpreter start, so env vars alone
don't stick — the programmatic config update below does. The
version-guarded device-count shim (``jax_num_cpu_devices`` on newer
jax, XLA_FLAGS before the lazy CPU backend init on older — never
both; newer jax rejects the combination) lives in
cess_tpu.parallel.compat so the subprocess-based multihost tests use
the identical logic.
"""
import os
import sys

import jax

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from cess_tpu.parallel import compat  # noqa: E402

jax.config.update("jax_platforms", "cpu")
compat.set_cpu_device_count(8)


def pytest_configure(config):
    # tier-1 runs with -m 'not slow' (ROADMAP): anything slow-marked
    # (the 1000-node sim world) is outside the gate
    config.addinivalue_line(
        "markers", "slow: outside the tier-1 gate (large worlds)")
