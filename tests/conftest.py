"""Test configuration: force an 8-device virtual CPU platform.

Tests never require TPU hardware; multi-chip sharding is exercised on a
virtual 8-device CPU mesh (the driver separately dry-run-compiles the
multi-chip path via __graft_entry__.dryrun_multichip).

Note: this box's axon sitecustomize registers the TPU plugin and
overrides JAX_PLATFORMS env at interpreter start, so env vars alone
don't stick — the programmatic config update below does. The
``jax_num_cpu_devices`` option only exists on newer jax; older
installs fall back to XLA_FLAGS, which the (lazy) CPU backend init
reads later. The two knobs must NEVER both be set — newer jax
rejects the combination — so the env fallback lives strictly inside
the AttributeError branch.
"""
import os

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:      # pre-0.5 jax: the XLA flag is the only way
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8").strip()
