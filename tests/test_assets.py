"""Fungible assets + fee payment in assets (reference pallet_assets /
pallet_asset_tx_payment, runtime/src/lib.rs ids 12-13): lifecycle,
team permissions, min_balance dust rules, freezing, and the
AssetTxPayment account preference charging real dispatch fees."""
import pytest

from cess_tpu import constants
from cess_tpu.chain.extrinsic import sign_extrinsic
from cess_tpu.chain.runtime import TREASURY, Runtime, RuntimeConfig
from cess_tpu.chain.state import DispatchError
from cess_tpu.crypto import ed25519

D = constants.DOLLARS


@pytest.fixture
def rt():
    rt = Runtime(RuntimeConfig(era_blocks=1000))
    for who in ("alice", "bob", "carol"):
        rt.fund(who, 1_000 * D)
    return rt


def test_create_mint_transfer_burn_roundtrip(rt):
    rt.apply_extrinsic("alice", "assets.create", 7, 10)
    rt.apply_extrinsic("alice", "assets.set_metadata", 7, "Gold", "GLD", 6)
    assert rt.assets.metadata(7).symbol == "GLD"
    rt.apply_extrinsic("alice", "assets.mint", 7, "bob", 500)
    assert rt.assets.balance(7, "bob") == 500
    assert rt.assets.asset(7).supply == 500
    rt.apply_extrinsic("bob", "assets.transfer", 7, "carol", 100)
    assert rt.assets.balance(7, "carol") == 100
    rt.apply_extrinsic("alice", "assets.burn", 7, "carol", 50)
    assert rt.assets.balance(7, "carol") == 50
    assert rt.assets.asset(7).supply == 450
    # duplicate id refused; unknown asset refused
    with pytest.raises(DispatchError, match="InUse"):
        rt.apply_extrinsic("bob", "assets.create", 7)
    with pytest.raises(DispatchError, match="Unknown"):
        rt.apply_extrinsic("bob", "assets.transfer", 99, "carol", 1)


def test_min_balance_dust_rules(rt):
    rt.apply_extrinsic("alice", "assets.create", 1, 10)
    rt.apply_extrinsic("alice", "assets.mint", 1, "bob", 100)
    # cannot create a destination below min_balance
    with pytest.raises(DispatchError, match="BelowMinimum"):
        rt.apply_extrinsic("bob", "assets.transfer", 1, "carol", 5)
    # a transfer leaving the SENDER with dust burns the remainder
    rt.apply_extrinsic("bob", "assets.transfer", 1, "carol", 95)
    assert rt.assets.balance(1, "bob") == 0          # 5 dust burned
    assert rt.assets.balance(1, "carol") == 95
    assert rt.assets.asset(1).supply == 95


def test_team_permissions_and_freezing(rt):
    rt.apply_extrinsic("alice", "assets.create", 2, 1)
    rt.apply_extrinsic("alice", "assets.set_team", 2, "bob", "carol",
                       "carol")
    # old owner is no longer issuer
    with pytest.raises(DispatchError, match="NoPermission"):
        rt.apply_extrinsic("alice", "assets.mint", 2, "alice", 10)
    rt.apply_extrinsic("bob", "assets.mint", 2, "alice", 10)
    # freezer freezes the account; admin thaws
    rt.apply_extrinsic("carol", "assets.freeze", 2, "alice")
    with pytest.raises(DispatchError, match="Frozen"):
        rt.apply_extrinsic("alice", "assets.transfer", 2, "bob", 1)
    rt.apply_extrinsic("carol", "assets.thaw", 2, "alice")
    rt.apply_extrinsic("alice", "assets.transfer", 2, "bob", 1)
    # whole-asset freeze
    rt.apply_extrinsic("carol", "assets.freeze_asset", 2)
    with pytest.raises(DispatchError, match="Frozen"):
        rt.apply_extrinsic("bob", "assets.transfer", 2, "alice", 1)
    # ownership transfer moves owner-only rights
    rt.apply_extrinsic("alice", "assets.transfer_ownership", 2, "bob")
    with pytest.raises(DispatchError, match="NoPermission"):
        rt.apply_extrinsic("alice", "assets.set_metadata", 2, "x", "X", 0)


def _signed(rt, key, signer, call, args):
    return sign_extrinsic(key, rt.genesis_hash(), signer,
                          rt.system.nonce(signer), call, args, None)


def test_fees_charged_in_chosen_asset(rt):
    """The AssetTxPayment role end-to-end: an account opted into an
    asset with a root-set rate pays REAL dispatch fees in it, split
    80/20 treasury/author like native fees."""
    rt.apply_extrinsic("alice", "assets.create", 5, 1)
    rt.apply_extrinsic("alice", "assets.mint", 5, "bob", 10_000_000_000)
    rt.apply_extrinsic("root", "assets.set_fee_rate", 5, 2, 1)  # 2x
    rt.apply_extrinsic("bob", "assets.set_fee_asset", 5)
    key = ed25519.SigningKey.generate(b"bob-key")
    rt.init_block(author="val0")
    xt = _signed(rt, key, "bob", "balances.transfer", ("carol", 1 * D))
    native_before = rt.balances.free("bob")
    fee = rt.tx_fee(xt)
    rt.apply_signed(xt)
    # native balance only moved by the TRANSFER amount, not the fee
    assert rt.balances.free("bob") == native_before - 1 * D
    asset_fee = 2 * fee
    assert rt.assets.balance(5, "bob") == 10_000_000_000 - asset_fee
    assert rt.assets.balance(5, TREASURY) == asset_fee * 8 // 10
    assert rt.assets.balance(5, "val0") == asset_fee - asset_fee * 8 // 10
    # opting out restores native charging
    rt.apply_extrinsic("bob", "assets.set_fee_asset", None)
    xt2 = _signed(rt, key, "bob", "balances.transfer", ("carol", 1 * D))
    before = rt.balances.free("bob")
    rt.apply_signed(xt2)
    assert rt.balances.free("bob") == before - 1 * D - rt.tx_fee(xt2)


def test_asset_fee_makes_broke_account_viable(rt):
    """An account with NO native tokens but a covering fee asset can
    still transact (the point of asset-tx-payment); a stale preference
    falls back to native rather than bricking the account."""
    rt.apply_extrinsic("alice", "assets.create", 6, 1)
    rt.apply_extrinsic("alice", "assets.mint", 6, "dave", 10**12)
    rt.apply_extrinsic("root", "assets.set_fee_rate", 6, 1, 1)
    rt.apply_extrinsic("dave", "assets.set_fee_asset", 6)
    key = ed25519.SigningKey.generate(b"dave-key")
    # dave holds zero native tokens
    assert rt.balances.free("dave") == 0
    xt = _signed(rt, key, "dave", "system.remark", (b"hi",))
    rt.apply_signed(xt)                       # fee paid in asset 6
    assert rt.assets.balance(6, "dave") < 10**12
    # drain the asset: affordability check fails closed
    rt.apply_extrinsic("alice", "assets.burn", 6, "dave",
                       rt.assets.balance(6, "dave"))
    xt2 = _signed(rt, key, "dave", "system.remark", (b"again",))
    with pytest.raises(DispatchError, match="CannotPayFee"):
        rt.apply_signed(xt2)


def test_self_transfer_is_identity(rt):
    """Review-reproduced inflation bug (fixed): transferring to
    yourself must not mint — balance and supply are invariant."""
    rt.apply_extrinsic("alice", "assets.create", 9, 1)
    rt.apply_extrinsic("alice", "assets.mint", 9, "bob", 100)
    rt.apply_extrinsic("bob", "assets.transfer", 9, "bob", 100)
    assert rt.assets.balance(9, "bob") == 100
    assert rt.assets.asset(9).supply == 100
    for _ in range(3):
        rt.apply_extrinsic("bob", "assets.transfer", 9, "bob", 40)
    assert rt.assets.balance(9, "bob") == 100
    assert rt.assets.asset(9).supply == 100


def test_create_reserves_deposit_destroy_refunds(rt):
    """ADVICE r4: permissionless create reserves ASSET_DEPOSIT so id
    squatting isn't free; destroy (supply == 0 only) refunds it."""
    from cess_tpu.chain.assets import ASSET_DEPOSIT

    free0 = rt.balances.free("alice")
    rt.apply_extrinsic("alice", "assets.create", 11, 1)
    assert rt.balances.reserved("alice") == ASSET_DEPOSIT
    assert rt.balances.free("alice") == free0 - ASSET_DEPOSIT
    # a broke account cannot squat ids
    with pytest.raises(DispatchError, match="InsufficientBalance"):
        rt.apply_extrinsic("eve", "assets.create", 12)
    # destroy is owner-only and requires all units burned first
    rt.apply_extrinsic("alice", "assets.mint", 11, "bob", 100)
    with pytest.raises(DispatchError, match="InUse"):
        rt.apply_extrinsic("alice", "assets.destroy", 11)
    rt.apply_extrinsic("alice", "assets.burn", 11, "bob", 100)
    with pytest.raises(DispatchError, match="NoPermission"):
        rt.apply_extrinsic("bob", "assets.destroy", 11)
    rt.apply_extrinsic("alice", "assets.destroy", 11)
    assert rt.assets.asset(11) is None
    assert rt.balances.reserved("alice") == 0
    assert rt.balances.free("alice") == free0
    # the id is reusable after destroy
    rt.apply_extrinsic("bob", "assets.create", 11)


def test_self_transfer_never_burns_dust(rt):
    """ADVICE r4: balance 10, min_balance 5, self-transfer 7 — the
    debit path would burn the 3-unit remainder as dust; a self-transfer
    is the identity after validation."""
    rt.apply_extrinsic("alice", "assets.create", 10, 5)
    rt.apply_extrinsic("alice", "assets.mint", 10, "bob", 10)
    rt.apply_extrinsic("bob", "assets.transfer", 10, "bob", 7)
    assert rt.assets.balance(10, "bob") == 10
    assert rt.assets.asset(10).supply == 10
    # overdrawn self-transfer still fails
    with pytest.raises(DispatchError, match="BalanceLow"):
        rt.apply_extrinsic("bob", "assets.transfer", 10, "bob", 11)
