"""Chain-plane observability (ISSUE 14): consensus health, the
storage-market ledger, byzantine anomaly detection — and the two
contracts everything in ``cess_tpu/obs`` lives by:

- zero-cost-when-off: a node that never armed ``--chainwatch`` has
  ``chainwatch`` unset/None, exports no ``cess_chain_*`` gauges, and
  a scenario without ``chainwatch=True`` leaves the chain slot of the
  sim witness empty — the disarmed paths are byte-identical;
- count-sequenced determinism: two same-seed ``equivocating_validator``
  runs replay every chain-plane witness byte-for-byte.

Plus the detector units (reorg-depth inference, BABE-shaped
block-equivocation evidence, the audit-failure-spike window, the
fake-capacity heuristic, edge-triggered anomaly transitions) and
hostile-input hardening for the gossip-frame ingest path.
"""
import json

import pytest

from cess_tpu import obs
from cess_tpu.obs import flight as _obs_flight
from cess_tpu.obs.chainwatch import (ChainAnomalyDetector, ChainWatch,
                                     ConsensusWatch, MarketWatch,
                                     lag_state)
from cess_tpu.sim.scenarios import SCENARIOS, run_scenario


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    obs.disarm()
    _obs_flight.disarm()


def _state(head, finalized, *, tail=None, blocks=(), locks=(),
           votes=(), slot=0, era=0, forks=0):
    return {
        "head": head, "finalized": finalized, "slot": slot,
        "era": era, "forks": forks,
        "tail": tail if tail is not None
        else {str(n): f"h{n}" for n in range(head + 1)},
        "blocks": list(blocks), "locks": list(locks),
        "vote_equivocations": list(votes),
    }


# -- consensus units ---------------------------------------------------------
class TestConsensusWatch:
    def test_lag_state_grading(self):
        assert lag_state(0) == "ok"
        assert lag_state(3) == "ok"
        assert lag_state(4) == "warn"
        assert lag_state(9) == "warn"
        assert lag_state(10) == "burning"

    def test_reorg_depth_is_inferred_from_the_tail_diff(self):
        w = ConsensusWatch()
        w.observe("n0", _state(5, 3))
        # pure extension: same hashes below, new head on top
        ext = {str(n): f"h{n}" for n in range(6)}
        ext["6"] = "h6"
        w.observe("n0", _state(6, 4, tail=ext))
        assert w.views()["n0"]["reorg_depth"] == 0
        # blocks 5..6 replaced by a side branch: depth = old head (6)
        # minus the deepest common height (4)
        reorg = {str(n): f"h{n}" for n in range(5)}
        reorg["5"] = "h5'"
        reorg["6"] = "h6'"
        w.observe("n0", _state(6, 4, tail=reorg))
        assert w.views()["n0"]["reorg_depth"] == 2
        snap = w.snapshot()
        assert snap["reorgs"] == 1 and snap["max_reorg_depth"] == 2

    def test_block_equivocation_needs_two_hashes_one_slot(self):
        w = ConsensusWatch()
        w.observe("n0", _state(3, 2, blocks=[["v1", 7, "aa"]]))
        assert w.evidence() == ()
        # a second DISTINCT hash for the same (author, slot) — seen
        # via a different node's view — is the BABE equivocation shape
        w.observe("n1", _state(3, 2, blocks=[["v1", 7, "bb"]]))
        ev = w.evidence()
        assert len(ev) == 1
        assert ev[0] == {"kind": "block-equivocation", "offender": "v1",
                         "round": 7, "hashes": ["aa", "bb"]}
        # the same pair reported again does not duplicate evidence
        w.observe("n2", _state(3, 2, blocks=[["v1", 7, "aa"],
                                             ["v1", 7, "bb"]]))
        assert len(w.evidence()) == 1

    def test_vote_equivocation_and_lock_ages(self):
        w = ConsensusWatch()
        w.observe("n0", _state(10, 8, locks=[["acct", 4]],
                               votes=[["v2", 5, "cc", "dd"]]))
        v = w.views()["n0"]
        assert v["locks"] == 1 and v["max_lock_age"] == 6
        ev = w.evidence()
        assert ev[0]["kind"] == "vote-equivocation"
        assert ev[0]["offender"] == "v2"
        assert ev[0]["hashes"] == ["cc", "dd"]

    def test_malformed_state_is_dropped_whole(self):
        w = ConsensusWatch()
        w.observe("n0", _state(3, 2))
        for garbage in (None, 42, {}, {"head": "x"},
                        {"head": 1, "finalized": 0, "tail": 7},
                        {"head": 1, "finalized": 0, "tail": {},
                         "blocks": [["only-two", 1]]}):
            w.observe("n0", garbage)
        # the good view survives untouched; nothing partially applied
        assert w.views()["n0"]["head"] == 3
        assert w.snapshot()["scans"] == 1


# -- market units ------------------------------------------------------------
def _market(verdicts, *, service=0, audited=0):
    return {
        "miners": {"m0": {"idle": 100, "service": service, "lock": 0,
                          "state": "positive", "audited": audited}},
        "verdicts": {"m0": verdicts},
        "restoral": {"open": 1, "claimed": 1, "generated": 2,
                     "claims": 3, "completed": 1},
    }


class TestMarketWatch:
    def test_audit_failure_spike_window(self):
        w = MarketWatch(spike_window=4, spike_fails=3)
        # 3 fails, but only 2 inside the last-4 window: no spike
        w.observe(_market([0, 1, 1, 0, 1, 0, 1, 1]))
        assert w.spikes() == ()
        # 3 fails inside the window: spike
        w.observe(_market([1, 1, 0, 0, 1, 0]))
        assert w.spikes() == ("m0",)
        m = w.snapshot()["miners"]["m0"]
        assert m["passes"] == 3 and m["fails"] == 3 and m["spike"]

    def test_fake_capacity_is_declared_vs_audited_drift(self):
        w = MarketWatch()
        w.observe(_market([1], service=100, audited=49))
        m = w.snapshot()["miners"]["m0"]
        assert m["drift"] == 51 and m["fake_capacity"]
        # recompute-and-replace is idempotent: audits catching up
        # clears the flag on the next scan, no cursor state left over
        w.observe(_market([1], service=100, audited=80))
        m = w.snapshot()["miners"]["m0"]
        assert m["drift"] == 20 and not m["fake_capacity"]
        assert w.snapshot()["space"]["drift"] == 20

    def test_malformed_market_is_dropped_whole(self):
        w = MarketWatch()
        w.observe(_market([1], service=8, audited=8))
        for garbage in (None, [], {"miners": {"m1": {}}},
                        {"miners": {"m1": {"idle": "x", "service": 0}}}):
            w.observe(garbage)
        snap = w.snapshot()
        assert list(snap["miners"]) == ["m0"] and snap["scans"] == 1


# -- anomaly detector units --------------------------------------------------
class TestChainAnomalyDetector:
    def test_transitions_are_edge_triggered(self):
        det = ChainAnomalyDetector()
        det.update("finality-stall", "n0", True, lag=5)
        det.update("finality-stall", "n0", True, lag=6)   # no new edge
        det.update("finality-stall", "n0", False, lag=0)
        det.update("finality-stall", "n0", False, lag=0)  # no new edge
        assert det.transition_log() == (
            (1, "finality-stall", "n0", "ok", "bad"),
            (2, "finality-stall", "n0", "bad", "ok"))
        snap = det.snapshot()
        assert snap["seq"] == 2 and snap["anomalies"] == 1
        assert snap["active"]["finality-stall"] == []

    def test_each_bad_edge_announces_one_flight_note(self):
        from cess_tpu.obs import flight
        rec = flight.arm(flight.FlightRecorder(b"t"))
        det = ChainAnomalyDetector()
        det.update("deep-reorg", "n3", True, depth=4)
        det.update("deep-reorg", "n3", True, depth=5)
        notes = [e for e in rec.journal_tail("chain")
                 if e["kind"] == "anomaly"]
        assert len(notes) == 1
        d = notes[0]["detail"]
        assert d["cls"] == "deep-reorg" and d["key"] == "n3"
        assert d["frm"] == "ok" and d["to"] == "bad" and d["depth"] == 4

    def test_witness_is_canonical_bytes(self):
        a, b = ChainAnomalyDetector(), ChainAnomalyDetector()
        for det in (a, b):
            det.update("equivocation", "v1@7", True)
            det.update("finality-stall", "n0", True)
            det.update("finality-stall", "n0", False)
        assert a.witness() == b.witness()
        canon = json.loads(a.witness())
        assert canon["active"] == [["equivocation", "v1@7"]]
        assert len(canon["transitions"]) == 3


# -- the composed plane ------------------------------------------------------
class TestChainWatch:
    def test_seal_round_runs_every_detector(self):
        w = ChainWatch("probe", stall_lag=4)
        w.ingest_state("n0", _state(9, 3))           # lag 6: stall
        w.ingest_state("n1", _state(9, 8))           # lag 1: fine
        w.ingest_state("n0", _state(9, 3, blocks=[["v1", 7, "aa"]]))
        w.ingest_state("n1", _state(9, 8, blocks=[["v1", 7, "bb"]]))
        w.ingest_market(_market([0, 0, 0]))
        w.seal_round()
        active = w.anomalies.active()
        assert active["finality-stall"] == ["n0"]
        assert active["equivocation"] == ["v1@7"]
        assert active["audit-failure-spike"] == ["m0"]
        m = w.metrics()
        assert m["cess_chain_rounds"] == 1.0
        assert m["cess_chain_nodes"] == 2.0
        assert m["cess_chain_equivocations_total"] == 1.0
        assert m["cess_chain_stalled_nodes"] == 1.0
        assert m["cess_chain_audit_fail_spikes"] == 1.0
        # recovery clears the stall edge on the next seal
        w.ingest_state("n0", _state(9, 9, blocks=[["v1", 7, "aa"]]))
        w.seal_round()
        assert w.anomalies.active().get("finality-stall", []) == []

    def test_ingest_frame_survives_hostile_peers(self):
        w = ChainWatch("probe")
        for frame in (None, 42, ("inst",), ("inst", None, "not-json"),
                      ("inst", None, json.dumps(["not", "a", "dict"])),
                      ("inst", None, json.dumps({"chain": "bogus"})),
                      ("inst", None, json.dumps({"targets": {}}))):
            w.ingest_frame(frame)
        assert w.consensus.views() == {}
        good = ("n9", None, json.dumps({"chain": _state(4, 2)}))
        w.ingest_frame(good)
        assert w.consensus.views()["n9"]["lag"] == 2

    def test_snapshot_is_json_safe(self):
        w = ChainWatch("probe")
        w.ingest_state("n0", _state(3, 2))
        w.ingest_market(_market([1]))
        w.seal_round()
        snap = w.snapshot()
        json.dumps(snap)
        assert snap["instance"] == "probe" and snap["rounds"] == 1
        assert set(snap) == {"instance", "rounds", "consensus",
                             "market", "anomalies"}


# -- zero-cost-when-off pins -------------------------------------------------
class TestDisarmedIsFree:
    def test_node_has_no_chain_gauges_when_disarmed(self):
        from cess_tpu.node.chain_spec import dev_spec
        from cess_tpu.node.metrics import collect, render_metrics
        from cess_tpu.node.network import Node

        node = Node(dev_spec(), "cold-node", {})
        assert getattr(node, "chainwatch", None) is None
        m = collect(node)
        assert not any(k.startswith("cess_chain_") for k in m)
        # ...and the build-info gauge is there regardless (ISSUE 14
        # satellite): value 1, instance + version labels
        expo = render_metrics(node)
        lines = [l for l in expo.splitlines()
                 if l.startswith("cess_build_info")]
        assert len(lines) == 1
        assert 'instance="cold-node"' in lines[0]
        assert 'version=' in lines[0]
        assert lines[0].endswith(" 1")

    def test_rpc_returns_none_when_disarmed(self):
        from cess_tpu.node.chain_spec import dev_spec
        from cess_tpu.node.network import Node
        from cess_tpu.node.rpc import RpcServer

        node = Node(dev_spec(), "rpc-node", {})
        rpc = RpcServer(node, port=0).start()
        try:
            assert rpc.handle("cess_chainStatus", []) is None
            node.chainwatch = ChainWatch("rpc-node")
            node.chainwatch.ingest_state("rpc-node", _state(2, 1))
            dump = rpc.handle("cess_chainStatus", [])
            assert dump["consensus"]["nodes"]["rpc-node"]["lag"] == 1
            json.dumps(dump)
        finally:
            rpc.stop()

    def test_armed_node_exports_chain_gauges(self):
        from cess_tpu.node.chain_spec import dev_spec
        from cess_tpu.node.metrics import collect
        from cess_tpu.node.network import Node

        node = Node(dev_spec(), "hot-node", {})
        node.chainwatch = ChainWatch("hot-node")
        node.chainwatch.ingest_state("hot-node", _state(5, 2))
        node.chainwatch.seal_round()
        m = collect(node)
        assert m["cess_chain_head"] == 5.0
        assert m["cess_chain_finality_lag"] == 3.0

    def test_build_info_is_relabeled_by_the_federator(self):
        from cess_tpu.node.chain_spec import dev_spec
        from cess_tpu.node.metrics import render_metrics
        from cess_tpu.node.network import Node
        from cess_tpu.obs.fleet import MetricFederator

        node = Node(dev_spec(), "build-node", {})
        fed = MetricFederator()
        fed.scrape_round({"fleet-inst": render_metrics(node)})
        gauges = fed.snapshot()["gauges"]
        keys = [k for k in gauges if k.startswith("cess_build_info")]
        assert len(keys) == 1
        # the scrape instance label WINS over the node's own — one
        # series per fleet member even when nodes share a name
        assert 'instance="fleet-inst"' in keys[0]
        assert 'version=' in keys[0]
        assert gauges[keys[0]] == 1.0

    def test_unarmed_scenario_has_an_empty_chain_witness_slot(self):
        sc = SCENARIOS["partition_heal"]
        report = run_scenario(sc, b"cold", n_nodes=8)
        assert report.chainwatch is None
        w = report.witness()
        # 8-tuple since the custody plane joined the witness; every
        # optional plane is empty-bytes when unarmed
        assert len(w) == 8 and w[5] == b"" and w[6] == b"" \
            and w[7] == b""


# -- the replay drill --------------------------------------------------------
class TestSameSeedReplay:
    def test_equivocating_validator_chain_witnesses_replay(self):
        sc = SCENARIOS["equivocating_validator"]
        a = run_scenario(sc, b"drill", n_nodes=12)
        b = run_scenario(sc, b"drill", n_nodes=12)
        wa, wb = a.chainwatch.witness(), b.chainwatch.witness()
        assert isinstance(wa, bytes) and wa == wb
        assert a.chainwatch.anomalies.witness() \
            == b.chainwatch.anomalies.witness()
        assert a.witness() == b.witness()
        assert a.witness()[5] == wa
        # the witness really carries all three parts, and the run
        # really produced evidence + anomalies to replay
        canon = json.loads(wa)
        assert set(canon) == {"consensus", "market", "transitions"}
        assert canon["consensus"]["equivocations"]
        assert canon["transitions"]
        # ...and a different seed is a different chain-plane history
        c = run_scenario(sc, b"other", n_nodes=12)
        assert c.chainwatch.witness() != wa
