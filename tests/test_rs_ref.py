"""Reference CPU codec tests: encode/corrupt/repair property tests."""
import itertools

import numpy as np
import pytest

from cess_tpu.ops.rs_ref import ReferenceCodec


@pytest.mark.parametrize("k,m", [(2, 1), (4, 8), (6, 3)])
def test_encode_reconstruct_all_patterns(k, m):
    rng = np.random.default_rng(5)
    n = 64
    codec = ReferenceCodec(k, m)
    data = rng.integers(0, 256, size=(k, n)).astype(np.uint8)
    shards = codec.encode(data)
    assert shards.shape == (k + m, n)
    assert np.array_equal(shards[:k], data)  # systematic

    patterns = list(itertools.combinations(range(k + m), k))
    rng.shuffle(patterns)
    for present in patterns[:20]:
        survivors = shards[list(present)]
        rec_data = codec.decode_data(survivors, present)
        assert np.array_equal(rec_data, data), present
        missing = tuple(i for i in range(k + m) if i not in present)
        rec = codec.reconstruct(survivors, present, missing)
        assert np.array_equal(rec, shards[list(missing)]), present


def test_batched_encode():
    rng = np.random.default_rng(6)
    codec = ReferenceCodec(4, 8)
    data = rng.integers(0, 256, size=(3, 4, 32)).astype(np.uint8)
    shards = codec.encode(data)
    assert shards.shape == (3, 12, 32)
    for b in range(3):
        single = codec.encode(data[b])
        assert np.array_equal(shards[b], single)


def test_reference_geometry_2_1():
    """Reference snapshot geometry: 3 fragments = RS(2,1); parity = XOR-like combo."""
    rng = np.random.default_rng(7)
    codec = ReferenceCodec(2, 1)
    data = rng.integers(0, 256, size=(2, 128)).astype(np.uint8)
    shards = codec.encode(data)
    # lose each single shard, recover
    for lost in range(3):
        present = tuple(i for i in range(3) if i != lost)
        rec = codec.reconstruct(shards[list(present)], present, (lost,))
        assert np.array_equal(rec[0], shards[lost])


def test_erasure_beyond_m_unrecoverable_interface():
    codec = ReferenceCodec(4, 2)
    with pytest.raises(ValueError):
        codec.decode_data(np.zeros((3, 8), np.uint8), (0, 1, 2))
