"""Merkle Mountain Range header commitments (pallet-mmr role, ref
runtime/src/lib.rs:1270-1274,1492): append-only roots, inclusion
proofs at every size, tamper rejection, and the RPC surface."""
import dataclasses
import hashlib

import pytest

from cess_tpu.node import mmr


def _h(i: int) -> bytes:
    return hashlib.sha256(b"hdr%d" % i).digest()


def test_proofs_verify_at_every_size_and_index():
    m = mmr.Mmr()
    for size in range(1, 40):
        m.append(size - 1, _h(size - 1))
        root = m.root()
        for i in range(size):
            p = m.proof(i)
            assert mmr.verify_proof(root, i, _h(i), p), (size, i)


def test_root_changes_on_append_and_binds_count():
    m = mmr.Mmr()
    roots = set()
    for i in range(20):
        m.append(i, _h(i))
        roots.add(m.root())
    assert len(roots) == 20   # every append moves the root
    # a proof against an older root must fail (count is bound in)
    m2 = mmr.Mmr()
    for i in range(7):
        m2.append(i, _h(i))
    old_root = m2.root()
    p = m.proof(3)
    assert not mmr.verify_proof(old_root, 3, _h(3), p)


def test_tampered_proofs_rejected():
    m = mmr.Mmr()
    for i in range(13):
        m.append(i, _h(i))
    root = m.root()
    p = m.proof(5)
    assert mmr.verify_proof(root, 5, _h(5), p)
    assert not mmr.verify_proof(root, 5, _h(6), p)        # wrong leaf
    assert not mmr.verify_proof(root, 6, _h(5), p)        # wrong number
    if p.path:
        flipped = (p.path[0][0], not p.path[0][1])
        bad = dataclasses.replace(p, path=(flipped,) + p.path[1:])
        assert not mmr.verify_proof(root, 5, _h(5), bad)  # side flipped
    bad2 = dataclasses.replace(p, peaks_left=(b"\x00" * 32,)
                               + p.peaks_left)
    assert not mmr.verify_proof(root, 5, _h(5), bad2)     # forged peak
    assert not mmr.verify_proof(root, 5, _h(5), "junk")
    with pytest.raises(IndexError):
        m.proof(13)


def test_header_mmr_extends_and_rebuilds_on_reorg():
    class FakeHeader:
        def __init__(self, i, salt=b""):
            self.number = i
            self._salt = salt

        def hash(self):
            return hashlib.sha256(b"fh%d" % self.number
                                  + self._salt).digest()

    hm = mmr.HeaderMmr()
    chain = [FakeHeader(i) for i in range(10)]
    r1 = hm.sync(chain).root()
    chain.append(FakeHeader(10))
    r2 = hm.sync(chain).root()
    assert r1 != r2
    # reorg: replace the tip block — the cache must rebuild, matching
    # a fresh MMR over the new chain
    chain[10] = FakeHeader(10, salt=b"fork")
    r3 = hm.sync(chain).root()
    fresh = mmr.Mmr()
    for hd in chain:
        fresh.append(hd.number, hd.hash())
    assert r3 == fresh.root() != r2


def test_mmr_rpc_surface():
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Network, Node
    from cess_tpu.node.rpc import RpcServer

    spec = dev_spec()
    node = Node(spec, "mm", {"alice": spec.session_key("alice")})
    Network([node]).run_slots(5)
    srv = RpcServer(node, port=0)
    root = srv.handle("mmr_root", [])
    got = srv.handle("mmr_generateProof", [3])
    assert got["root"] == root
    assert srv.handle("mmr_verifyProof",
                      [root, 3, got["headerHash"], got["proof"]])
    # proof is stateless: verifies against the chain's header hash only
    assert not srv.handle("mmr_verifyProof",
                          [root, 4, got["headerHash"], got["proof"]])
