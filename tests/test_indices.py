"""Indices, Preimage, Timestamp-role clock, and child bounties
(reference pallet_indices/pallet_preimage/pallet_timestamp/
pallet_child_bounties, runtime/src/lib.rs:1486-1522)."""
import hashlib

import pytest

from cess_tpu import constants
from cess_tpu.chain.runtime import Runtime, RuntimeConfig
from cess_tpu.chain.state import DispatchError

D = constants.DOLLARS


@pytest.fixture
def rt():
    rt = Runtime(RuntimeConfig(era_blocks=1000))
    for who in ("alice", "bob", "c1", "c2", "c3", "curt"):
        rt.fund(who, 1_000 * D)
    rt.apply_extrinsic("root", "council.set_members", ("c1", "c2", "c3"))
    return rt


def test_indices_claim_free_transfer(rt):
    rt.apply_extrinsic("alice", "indices.claim", 42)
    assert rt.indices.lookup(42) == "alice"
    with pytest.raises(DispatchError, match="InUse"):
        rt.apply_extrinsic("bob", "indices.claim", 42)
    # deposit reserved; freeing refunds it
    free_before = rt.balances.free("alice")
    rt.apply_extrinsic("alice", "indices.transfer", 42, "bob")
    assert rt.indices.lookup(42) == "bob"
    assert rt.balances.free("alice") > free_before   # refund came back
    with pytest.raises(DispatchError, match="NotOwner"):
        rt.apply_extrinsic("alice", "indices.free", 42)
    rt.apply_extrinsic("bob", "indices.free", 42)
    assert rt.indices.lookup(42) is None


def test_preimage_note_fetch_unnote(rt):
    blob = b"a large governance call" * 10
    h = rt.apply_extrinsic("alice", "preimage.note_preimage", blob)
    assert h == hashlib.sha256(blob).digest()
    assert rt.preimage.preimage(h) == blob
    with pytest.raises(DispatchError, match="AlreadyNoted"):
        rt.apply_extrinsic("bob", "preimage.note_preimage", blob)
    with pytest.raises(DispatchError, match="NotNoter"):
        rt.apply_extrinsic("bob", "preimage.unnote_preimage", h)
    rt.apply_extrinsic("alice", "preimage.unnote_preimage", h)
    assert rt.preimage.preimage(h) is None
    with pytest.raises(DispatchError, match="TooBig"):
        rt.apply_extrinsic("alice", "preimage.note_preimage",
                           b"\0" * (128 * 1024 + 1))


def test_chain_clock_advances_with_blocks(rt):
    rt.advance_blocks(3)
    assert rt.system.now_ms() \
        == rt.state.block * constants.MILLISECS_PER_BLOCK


def _council_pass(rt, call, args):
    rt.apply_extrinsic("c1", "council.propose", call, args)
    mid = rt.state.get("council", "next_motion") - 1
    rt.apply_extrinsic("c2", "council.vote", mid, True)
    rt.apply_extrinsic("c3", "council.close", mid)


def test_child_bounties_full_flow(rt):
    rt.fund(rt.treasury_pallet.ACCOUNT
            if hasattr(rt.treasury_pallet, "ACCOUNT") else "treasury",
            10_000 * D)
    bid = rt.apply_extrinsic("alice", "treasury.propose_bounty",
                             b"build the thing", 100 * D)
    _council_pass(rt, "treasury.approve_bounty", (bid,))
    _council_pass(rt, "treasury.assign_curator", (bid, "curt"))
    # only the curator can carve children
    with pytest.raises(DispatchError, match="NotCurator"):
        rt.apply_extrinsic("bob", "treasury.add_child_bounty", bid,
                           b"sub", 10 * D)
    c0 = rt.apply_extrinsic("curt", "treasury.add_child_bounty", bid,
                            b"sub-task A", 30 * D)
    c1 = rt.apply_extrinsic("curt", "treasury.add_child_bounty", bid,
                            b"sub-task B", 20 * D)
    # children cannot carve more than the parent holds
    with pytest.raises(DispatchError, match="InsufficientBountyValue"):
        rt.apply_extrinsic("curt", "treasury.add_child_bounty", bid,
                           b"too much", 60 * D)
    # parent cannot be awarded while children are active (exercised on
    # the pallet surface the council motion dispatches into)
    with pytest.raises(DispatchError, match="HasActiveChildBounty"):
        rt.treasury_pallet.award_bounty(bid, "alice")
    rt.apply_extrinsic("curt", "treasury.award_child_bounty", bid, c0,
                       "bob")
    rt.apply_extrinsic("curt", "treasury.close_child_bounty", bid, c1)
    # closing c1 uncarves its 20: the parent remainder is 100-30 = 70
    rt.treasury_pallet.award_bounty(bid, "alice")
    approved = dict(rt.state.get("treasury", "approved", default=()))
    assert approved.get("bob") == 30 * D
    assert approved.get("alice") == 70 * D
