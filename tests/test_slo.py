"""SLO monitors + per-tenant accounting + adaptive control (ISSUE 6).

Pins, in order: SloTarget/parse_targets syntax, the multi-window
burn-rate state machine (observation-count deterministic), tenant
accounting bounds, the weighted-fair drain anchor, AdaptiveBatchPolicy
knob movement, the AdmissionController's shed + breaker-hold
responses, HealthMonitor hold/release semantics, the
zero-cost-when-off contract (the NOOP_SPAN analog for the SLO layer),
RPC/CLI wire-up — and THE acceptance drill: under a seeded FaultPlan
that slows device dispatch, the verify-class SLO transitions
ok -> burning, admission sheds encode-class load and CPU-degrades the
surviving codec traffic, verify p99 recovers (burning -> warn -> ok),
the whole episode is one connected trace with ``slo.*`` spans, and two
replays of the same seed produce the identical SLO state-transition
log.
"""
import numpy as np
import pytest

from cess_tpu import obs
from cess_tpu.obs.slo import (DEFAULT_TARGETS, OVERFLOW, SloBoard,
                              SloTarget, parse_targets)
from cess_tpu.ops import podr2
from cess_tpu.resilience import (FaultPlan, FaultSpec, HealthMonitor,
                                 ResilienceConfig, faults)
from cess_tpu.serve import (AdaptiveBatchPolicy, AdmissionController,
                            AdmissionPolicy, EngineShed, make_engine)

K, M = 2, 1


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    obs.disarm()
    faults.disarm()


def rnd(shape, seed=0, dtype=np.uint8):
    rng = np.random.default_rng(seed)
    return rng.integers(0, np.iinfo(dtype).max, shape, dtype=dtype)


# -- targets + syntax --------------------------------------------------------
class TestTargets:
    def test_target_validation(self):
        t = SloTarget("verify", 0.05, 0.01)
        assert t.budget == pytest.approx(0.02)
        with pytest.raises(ValueError):
            SloTarget("", 0.05)
        with pytest.raises(ValueError):
            SloTarget("verify", 0.0)
        with pytest.raises(ValueError):
            SloTarget("verify", 0.05, 1.0)

    def test_parse_targets_syntax(self):
        got = parse_targets("verify:p99=50ms,err=1%;encode:p99=2s")
        assert got == (SloTarget("verify", 0.05, 0.01),
                       SloTarget("encode", 2.0, 0.0))
        # bare numbers: seconds / fractions
        assert parse_targets("prove:p99=0.1,err=0.02") == \
            (SloTarget("prove", 0.1, 0.02),)
        assert parse_targets("") == DEFAULT_TARGETS
        for bad in ("verify", "verify:err=1%", "verify:p99=50ms,x=1",
                    "verify:p99"):
            with pytest.raises(ValueError):
                parse_targets(bad)

    def test_duplicate_target_class_rejected(self):
        with pytest.raises(ValueError):
            SloBoard((SloTarget("verify", 0.05),
                      SloTarget("verify", 0.10)))


# -- the burn-rate state machine ---------------------------------------------
def small_board(**kw):
    kw.setdefault("fast_window", 4)
    kw.setdefault("slow_window", 16)
    kw.setdefault("eval_every", 4)
    return SloBoard((SloTarget("verify", 0.02, 0.01),), **kw)


class TestBurnRate:
    def test_ok_to_burning_to_ok_on_observation_count(self):
        board = small_board()
        # 8 breaching observations: burning fires at the obs-4 eval
        for _ in range(8):
            board.observe("verify", 1.0)
        assert board.state("verify") == "burning"
        # recovery: fast window clears first (warn), then the slow
        # window flushes (ok) — everything at eval boundaries
        for _ in range(24):
            board.observe("verify", 0.001)
        assert board.state("verify") == "ok"
        log = board.transition_log()
        assert [(c, a, b) for c, a, b, _ in log] == [
            ("verify", "ok", "burning"),
            ("verify", "burning", "warn"),
            ("verify", "warn", "ok")]
        # transitions land on eval_every boundaries: count-determinism
        assert all(n % 4 == 0 for _, _, _, n in log)

    def test_failures_breach_like_slow_requests(self):
        board = small_board()
        for _ in range(8):
            board.observe("verify", 0.001, ok=False)   # fast but failed
        assert board.state("verify") == "burning"

    def test_no_eval_before_fast_window_fills(self):
        board = small_board()
        for _ in range(3):
            board.observe("verify", 1.0)
        assert board.state("verify") == "ok"        # len(slow) < fast

    def test_untargeted_class_is_accounted_not_evaluated(self):
        board = small_board()
        for _ in range(16):
            board.observe("encode", 99.0, tenant="t")
        assert board.state("encode") == "ok"
        assert board.transition_log() == ()
        assert board.snapshot()["tenants"]["t"]["encode"]["requests"] \
            == 16

    def test_transition_spans_ride_the_armed_tracer(self):
        board = small_board()
        tracer = obs.Tracer()
        with obs.armed(tracer):
            for _ in range(8):
                board.observe("verify", 1.0)
        spans = [s for s in tracer.finished()
                 if s["name"] == "slo.transition"]
        assert len(spans) == 1 and spans[0]["sys"] == "slo"
        assert spans[0]["attrs"]["frm"] == "ok"
        assert spans[0]["attrs"]["to"] == "burning"

    def test_listener_fires_outside_the_lock(self):
        board = small_board()
        seen = []
        board.add_listener(
            lambda cls, old, new: seen.append((cls, old, new)))
        for _ in range(8):
            board.observe("verify", 1.0)
        assert seen == [("verify", "ok", "burning")]

    def test_announcements_deliver_in_log_order_under_concurrency(self):
        # two observer threads flap the state; whatever interleaving
        # the scheduler picks, listeners must see transitions in
        # EXACTLY transition-log order — a descheduled observer
        # delivering its older transition late would leave the
        # admission controller engaged against a board that reads ok
        # (review-caught; the announce queue pins FIFO delivery)
        import threading

        board = SloBoard((SloTarget("verify", 0.01),), fast_window=4,
                         slow_window=8, eval_every=2,
                         max_transitions=65536)
        seen = []
        board.add_listener(
            lambda cls, old, new: seen.append((cls, old, new)))

        def feed(latency):
            for _ in range(400):
                board.observe("verify", latency)

        threads = [threading.Thread(target=feed, args=(lat,))
                   for lat in (1.0, 0.0, 1.0)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert seen == [(c, a, b)
                        for c, a, b, _ in board.transition_log()]
        assert len(seen) >= 1


class TestTenantAccounting:
    def test_counters_shed_and_overflow_cap(self):
        board = small_board(max_tenants=3)
        board.observe("encode", 0.001, tenant="a", rows=4)
        board.observe("encode", 0.001, ok=False, tenant="a")
        board.note_shed("encode", "a")
        board.observe("encode", 0.001)                  # untagged
        for t in ("b", "c", "d", "e"):                  # cap is 3
            board.observe("encode", 0.001, tenant=t)
        snap = board.snapshot()["tenants"]
        assert snap["a"]["encode"] == {"requests": 2, "failed": 1,
                                       "shed": 1, "rows": 4}
        assert snap["-"]["encode"]["requests"] == 1     # untagged bucket
        # a, -, b admitted; c/d/e aggregate under the overflow bucket
        assert set(snap) == {"a", "-", "b", OVERFLOW}
        assert snap[OVERFLOW]["encode"]["requests"] == 3

    def test_series_families_and_enum_state(self):
        board = small_board()
        board.observe("verify", 0.001, tenant="t")
        fams = {}
        for family, kind, labels, value in board.series():
            fams.setdefault(family, []).append((kind, labels, value))
        states = {l["state"]: v
                  for k, l, v in fams["cess_slo_state"]}
        assert states == {"ok": 1.0, "warn": 0.0, "burning": 0.0}
        assert all(k == "counter"
                   for k, _, _ in fams["cess_tenant_requests_total"])
        assert ("cess_tenant_latency_seconds", {"tenant": "t",
                                                "class": "verify"}) \
            == board.tenant_histograms()[0][:2]


# -- weighted-fair drain -----------------------------------------------------
class TestFairDrain:
    def test_anchor_prefers_the_deficit_tenant(self):
        board = SloBoard((SloTarget("verify", 0.02),))
        eng = make_engine(K, M,
                          policy=AdmissionPolicy(max_delay=30.0,
                                                 max_batch_requests=64,
                                                 max_batch_rows=4096),
                          slo=board)
        try:
            # nothing triggers a drain (huge delay, small queue), so
            # the queue is inspectable; "heavy" has served 10k rows,
            # "light" none — light's request anchors the next batch
            # even though heavy queued first
            for i in range(4):
                eng.submit_encode(rnd((2, K, 64), i), timeout=60,
                                  tenant="heavy")
            eng.submit_encode(rnd((4, K, 64), 9), timeout=60,
                              tenant="light")
            with eng._cond:
                eng._tenant_rows["encode"] = {"heavy": 10_000,
                                              "light": 0}
                q = eng._queues["encode"]
                assert eng._anchor_index("encode", q) == 4
                batch = eng._drain("encode")
            # the anchor leads the batch; same-key mates still coalesce
            assert batch[0].tenant == "light"
            assert {r.tenant for r in batch} == {"heavy", "light"}
            # resolve the popped requests so close() has nothing to kill
            for r in batch:
                r.future._resolve(None)
                r.span.finish()
        finally:
            eng.close(timeout=0.1)

    def test_over_cap_tenant_reads_the_overflow_deficit(self):
        # a tenant past the board's max_tenants cap is CHARGED to
        # "~other" (_account_batch), so the anchor choice must READ
        # its deficit from "~other" too — otherwise its raw name
        # always looks at 0 served rows and it anchors every drain
        # forever (review-caught)
        board = SloBoard((SloTarget("verify", 0.02),))
        eng = make_engine(K, M,
                          policy=AdmissionPolicy(max_delay=30.0,
                                                 max_batch_requests=64,
                                                 max_batch_rows=4096),
                          slo=board)
        try:
            eng.submit_encode(rnd((2, K, 64), 0), timeout=60,
                              tenant="newcomer")   # over-cap: aliases
            eng.submit_encode(rnd((2, K, 64), 1), timeout=60,
                              tenant="t00")        # in-cap, light
            with eng._cond:
                served = {f"t{i:02d}": 10
                          for i in range(eng.slo.max_tenants)}
                served["~other"] = 10_000          # bucket heavily fed
                eng._tenant_rows["encode"] = served
                q = eng._queues["encode"]
                assert eng._anchor_index("encode", q) == 1
                batch = eng._drain("encode")
            assert batch[0].tenant == "t00"
            for r in batch:
                r.future._resolve(None)
                r.span.finish()
        finally:
            eng.close(timeout=0.1)

    def test_without_a_board_the_oldest_anchors(self):
        eng = make_engine(K, M, policy=AdmissionPolicy(max_delay=30.0))
        try:
            eng.submit_encode(rnd((2, K, 64), 0), timeout=60,
                              tenant="b")
            eng.submit_encode(rnd((2, K, 64), 1), timeout=60,
                              tenant="a")
            with eng._cond:
                assert eng._anchor_index("encode",
                                         eng._queues["encode"]) == 0
                batch = eng._drain("encode")
            assert batch[0].tenant == "b"
            for r in batch:
                r.future._resolve(None)
                r.span.finish()
        finally:
            eng.close(timeout=0.1)


# -- adaptive batching knobs -------------------------------------------------
class TestAdaptiveBatchPolicy:
    def test_over_target_shrinks_under_target_grows(self):
        pol = AdmissionPolicy(max_delay=0.01, max_batch_rows=512)
        ad = AdaptiveBatchPolicy(pol, targets={"verify": 0.02},
                                 update_every=4, window=8,
                                 min_delay_s=0.001, min_rows=8)
        assert ad.knobs("verify") == (0.01, pol.max_batch_requests, 512)
        for _ in range(4):
            ad.note("verify", 0.05)               # p99 over target
        delay, _, rows = ad.knobs("verify")
        assert delay == pytest.approx(0.005) and rows == 256
        assert ad.p99_est("verify") == pytest.approx(0.05)
        # fast + under-occupied observations: one more shrink while
        # the slow samples are still in the window (obs-8 eval), then
        # growth once they roll out (obs-12/16 evals)
        for _ in range(12):
            ad.note("verify", 0.001, occupancy=1)
        delay2, _, rows2 = ad.knobs("verify")
        assert delay2 > 0.0025 and rows2 == 512
        log = ad.adjustment_log()
        assert [e[0] for e in log] == ["verify"] * len(log)
        assert len(log) == ad.snapshot()["verify"]["adjustments"] >= 3
        # the log records both directions
        deltas = [e[3] for e in log]
        assert min(deltas) == pytest.approx(0.0025)
        assert deltas[-1] > min(deltas)

    def test_good_occupancy_blocks_growth(self):
        ad = AdaptiveBatchPolicy(AdmissionPolicy(max_delay=0.01),
                                 targets={"encode": 1.0},
                                 update_every=4, occupancy_target=4.0)
        for _ in range(8):
            ad.note("encode", 0.001, occupancy=16)  # well-batched
        assert ad.knobs("encode")[0] == 0.01        # no change

    def test_untargeted_class_keeps_static_knobs(self):
        pol = AdmissionPolicy(max_delay=0.01)
        ad = AdaptiveBatchPolicy(pol, targets={"verify": 0.02})
        for _ in range(64):
            ad.note("encode", 123.0)
        assert ad.knobs("encode") == (pol.max_delay,
                                      pol.max_batch_requests,
                                      pol.max_batch_rows)

    def test_board_supplies_targets(self):
        board = SloBoard((SloTarget("verify", 0.07),))
        ad = AdaptiveBatchPolicy(board=board)
        assert ad.target_for("verify") == 0.07
        assert ad.target_for("encode") is None
        assert AdaptiveBatchPolicy(
            board=board, targets={"verify": 0.5}).target_for("verify") \
            == 0.5


# -- admission controller + breaker hold -------------------------------------
class TestHoldOpen:
    def test_held_breaker_admits_nothing_and_releases_clean(self):
        mon = HealthMonitor()
        assert mon.allow()
        mon.hold_open("slo:verify")
        assert mon.state == "held"
        assert not any(mon.allow() for _ in range(32))  # NO probes
        snap = mon.snapshot()
        assert snap["held_reason"] == "slo:verify"
        assert snap["holds"] == 1 and snap["trips"] == 0
        mon.release()
        assert mon.state == "closed" and mon.allow()

    def test_hold_never_masks_a_real_trip(self):
        mon = HealthMonitor(min_samples=2, probe_every=2)
        for _ in range(4):
            mon.record_error()                      # window-tripped
        assert mon.state == "open"
        mon.hold_open("slo:verify")
        assert mon.state == "held"
        mon.release()
        assert mon.state == "open"                  # the trip remains

    def test_exposition_reports_held_as_open(self):
        from cess_tpu.resilience.stats import ResilienceStats

        rs = ResilienceStats()
        mon = HealthMonitor()
        rs.register_monitor("codec", mon)
        mon.hold_open("slo:verify")
        m = rs.metrics()
        assert m["cess_resilience_breaker_codec_open"] == 1.0
        assert m["cess_resilience_breaker_codec_held"] == 1.0


class TestAdmissionController:
    def test_burning_sheds_and_holds_until_ok(self):
        board = small_board()
        ad = AdaptiveBatchPolicy(board=board)
        ctrl = AdmissionController(board, ad)

        class EngineLike:
            monitors = {"codec": HealthMonitor()}

        eng = EngineLike()
        ctrl.bind(eng)
        assert ctrl.admit("encode", 30.0) is None
        assert ctrl.admit("verify", 30.0) is None
        for _ in range(8):
            board.observe("verify", 1.0)            # -> burning
        assert ctrl.engaged
        assert eng.monitors["codec"].state == "held"
        assert ctrl.admit("encode", 30.0) == "slo-burning"
        assert ctrl.admit("verify", 30.0) is None   # protected: never
        for _ in range(8):
            board.observe("verify", 0.001)          # -> warn: still on
        assert board.state("verify") == "warn"
        assert ctrl.engaged
        for _ in range(16):
            board.observe("verify", 0.001)          # -> ok: released
        assert board.state("verify") == "ok"
        assert not ctrl.engaged
        assert eng.monitors["codec"].state == "closed"
        assert ctrl.admit("encode", 30.0) is None
        snap = ctrl.snapshot()
        assert snap["holds"] == snap["releases"] == 1
        assert snap["sheds"]["encode"]["slo-burning"] == 1
        # sheds were charged to tenant accounting
        assert board.snapshot()["tenants"]["-"]["encode"]["shed"] == 1

    def test_deadline_unmeetable_shed(self):
        board = small_board()
        ad = AdaptiveBatchPolicy(board=board, targets={"encode": 0.01},
                                 update_every=4)
        ctrl = AdmissionController(board, ad)
        for _ in range(4):
            ad.note("encode", 5.0)                  # p99 est ~5 s
        assert ctrl.admit("encode", 1.0) == "deadline-unmeetable"
        assert ctrl.admit("encode", 10.0) is None   # budget fits
        assert ctrl.admit("encode", None) is None   # no deadline
        # an IDLE class always admits: the estimate is refreshed by
        # served requests alone, so shedding with no backlog would
        # wedge a stale spike estimate forever (review-caught)
        assert ctrl.admit("encode", 1.0, queued=0) is None
        assert ctrl.admit("encode", 1.0, queued=3) == \
            "deadline-unmeetable"

    def test_engine_submit_raises_engine_shed(self):
        board = small_board()
        eng = make_engine(K, M,
                          policy=AdmissionPolicy(max_delay=0.002),
                          slo=board, adaptive=True)
        try:
            for _ in range(8):
                board.observe("verify", 1.0)        # -> burning
            with pytest.raises(EngineShed, match="slo-burning"):
                eng.encode(rnd((2, K, 64), 3), timeout=5,
                           tenant="bulk")
            snap = eng.stats_snapshot()
            assert snap["classes"]["encode"]["shed"] == 1
            assert snap["slo"]["tenants"]["bulk"]["encode"]["shed"] == 1
            assert "slo" in snap and "adaptive" in snap
            # recovery re-admits, and a served class materializes its
            # adaptive gauges on the exposition
            for _ in range(24):
                board.observe("verify", 0.001)
            assert board.state("verify") == "ok"
            eng.encode(rnd((1, K, 64), 4), timeout=30)
            assert "cess_adaptive_encode_delay_s" in eng.stats_metrics()
        finally:
            eng.close()


# -- the zero-cost-when-off contract -----------------------------------------
def test_disabled_engine_allocates_no_slo_or_tenant_objects():
    """The NOOP_SPAN analog for the SLO layer (acceptance pin): with
    no board configured, the control attributes ARE the None
    singleton, requests carry the bare None tenant default, and after
    real traffic no SLO/tenant/adaptive structure exists anywhere on
    the engine or its exposition."""
    eng = make_engine(K, M, policy=AdmissionPolicy(max_delay=0.002))
    try:
        assert eng.slo is None and eng.adaptive is None \
            and eng.admission is None
        assert eng.stats.slo is None and eng.stats.adaptive is None
        fut = eng.submit_encode(rnd((2, K, 64), 1), timeout=30)
        fut.result(30)
        eng.encode(rnd((2, K, 64), 2), timeout=30)
        # the fair-queue deficit map never materializes a tenant entry
        assert eng._tenant_rows == {}
        snap = eng.stats_snapshot()
        assert "slo" not in snap and "adaptive" not in snap
        assert not any(k.startswith(("cess_slo_", "cess_tenant_",
                                     "cess_adaptive_"))
                       for k in eng.stats_metrics())
        assert eng.labeled_series() == []
        assert eng.labeled_histograms() == []
    finally:
        eng.close()


# -- wire-up: RPC + CLI ------------------------------------------------------
def test_rpc_slo_status():
    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.network import Node
    from cess_tpu.node.rpc import RpcServer

    node = Node(dev_spec(), "slo-node", {})
    rpc = RpcServer(node, port=0)
    assert rpc.handle("cess_sloStatus", []) is None      # no engine
    board = SloBoard((SloTarget("verify", 0.05),))
    eng = make_engine(K, M, policy=AdmissionPolicy(max_delay=0.002),
                      slo=board, adaptive=True)
    node.engine = eng
    try:
        eng.encode(rnd((1, K, 64), 1), timeout=30, tenant="alice")
        out = rpc.handle("cess_sloStatus", [])
        assert out["targets"]["verify"]["state"] == "ok"
        assert out["tenants"]["alice"]["encode"]["requests"] == 1
        assert "adaptive" in out and "admission" in out
        assert out["admission"]["engaged"] is False
    finally:
        eng.close()


def test_cli_slo_flags_wire_engine():
    import argparse

    from cess_tpu.node.chain_spec import dev_spec
    from cess_tpu.node.cli import _make_cli_engine

    def ns(engine, slo=None, adaptive=False):
        return argparse.Namespace(engine=engine, resilience="off",
                                  slo=slo, adaptive=adaptive)

    eng = _make_cli_engine(ns("cpu", slo="verify:p99=40ms",
                              adaptive=True), dev_spec())
    try:
        assert eng.slo is not None and eng.adaptive is not None \
            and eng.admission is not None
        assert eng.slo.targets == (SloTarget("verify", 0.04),)
        assert eng.adaptive.target_for("verify") == 0.04
    finally:
        eng.close()
    eng = _make_cli_engine(ns("cpu", slo=""), dev_spec())  # defaults
    try:
        assert eng.slo.targets == DEFAULT_TARGETS
        assert eng.adaptive is None and eng.admission is None
    finally:
        eng.close()
    plain = _make_cli_engine(ns("cpu"), dev_spec())
    try:
        assert plain.slo is None
    finally:
        plain.close()
    with pytest.raises(SystemExit, match="slo"):
        _make_cli_engine(ns("off", slo=""), dev_spec())
    with pytest.raises(SystemExit, match="adaptive"):
        _make_cli_engine(ns("off", adaptive=True), dev_spec())
    # --adaptive without --slo would build a tuner with no targets to
    # steer toward (silently never adjusting) — refused loudly instead
    with pytest.raises(SystemExit, match="--adaptive requires --slo"):
        _make_cli_engine(ns("cpu", adaptive=True), dev_spec())


# -- THE acceptance: the SLO drill -------------------------------------------
OBJECTIVE_S = 0.30      # verify p99 objective: ~6x the CPU-jax
                        # verify dispatch floor (~50 ms) — phase-2
                        # classification must stay noise-immune even
                        # on a fully loaded box (one phase-2 breach
                        # poisons the 16-obs slow window and stalls
                        # the warn->ok walk, or re-fires burning)
FAULT_DELAY_S = 0.70    # injected dispatch slowness: ~2.3x objective


def _run_drill(seed: bytes):
    """One full drill episode; returns (board, engine stats snapshot,
    shed count, phase-2 verify latencies, spans)."""
    import time

    pkey = podr2.Podr2Key.generate(44)
    params = podr2.Podr2Params()
    blocks = params.blocks_for(512)
    ids = np.stack([np.arange(2, dtype=np.uint32),
                    np.zeros(2, dtype=np.uint32)], axis=1)
    idx, nu = podr2.gen_challenge(b"slo-drill", blocks)
    mu = np.zeros((2, params.sectors), dtype=np.uint32)
    sigma = np.zeros((2, podr2.LIMBS), dtype=np.uint32)

    board = SloBoard((SloTarget("verify", OBJECTIVE_S, 0.01),),
                     fast_window=4, slow_window=16, eval_every=4)
    adaptive = AdaptiveBatchPolicy(board=board)
    admission = AdmissionController(board, adaptive,
                                    protect=("verify",),
                                    shed=("encode",))
    tracer = obs.Tracer(capacity=65536)
    eng = make_engine(K, M, rs_backend="jax", podr2_key=pkey,
                      policy=AdmissionPolicy(max_delay=0.002),
                      resilience=ResilienceConfig(),
                      tracer=tracer, slo=board, adaptive=adaptive,
                      admission=admission)
    plan = FaultPlan.seeded(seed, {
        "engine.dispatch": (1.0, FaultSpec("delay",
                                           delay_s=FAULT_DELAY_S)),
    }, horizon=64)
    bulk = rnd((1, K, 512), 7)
    sheds = 0
    lats2 = []
    try:
        with obs.armed(tracer):
            # -- phase 1: every device dispatch is slow ---------------
            with faults.armed(plan):
                for i in range(8):
                    try:
                        eng.encode(bulk, timeout=30, tenant="bulk")
                    except EngineShed:
                        sheds += 1
                    eng.verify_batch(ids, blocks, idx, nu, mu, sigma,
                                     timeout=30, tenant="auditor")
                # the verify SLO is burning; encode is being shed and
                # the codec breaker is HELD: surviving codec traffic
                # (a repair claim) serves CPU-degraded, correct, fast
                assert board.state("verify") == "burning"
                assert eng.monitors["codec"].state == "held"
                shards = np.asarray(eng._fallback_codec.encode(bulk))
                rec = eng.reconstruct(shards[:, (0, 1)], (0, 1), (2,),
                                      timeout=30, tenant="repairer")
                assert np.array_equal(np.asarray(rec),
                                      shards[:, (2,)])
            # -- phase 2: the device is healthy again -----------------
            for i in range(20):
                try:
                    eng.encode(bulk, timeout=30, tenant="bulk")
                except EngineShed:
                    sheds += 1
                t0 = time.perf_counter()
                eng.verify_batch(ids, blocks, idx, nu, mu, sigma,
                                 timeout=30, tenant="auditor")
                lats2.append(time.perf_counter() - t0)
        snap = eng.stats_snapshot()
    finally:
        eng.close()
    return board, snap, sheds, lats2, tracer.finished()


def test_slo_drill_end_to_end_and_replay_deterministic():
    board1, snap1, sheds1, lats2, spans = _run_drill(b"slo-drill-seed")

    # the episode: ok -> burning (dispatch slowness), admission
    # response, then recovery through warn back to ok
    log1 = board1.transition_log()
    assert [(c, a, b) for c, a, b, _ in log1] == [
        ("verify", "ok", "burning"),
        ("verify", "burning", "warn"),
        ("verify", "warn", "ok")]
    assert board1.state("verify") == "ok"

    # encode-class load was shed while the SLO was at risk, and
    # admitted again after recovery (the last loop-2 encodes ran)
    assert sheds1 >= 4
    assert snap1["classes"]["encode"]["shed"] == sheds1
    assert snap1["slo"]["tenants"]["bulk"]["encode"]["shed"] == sheds1
    assert snap1["classes"]["encode"]["completed"] >= 1
    # the held breaker CPU-degraded the surviving codec traffic
    assert snap1["resilience"]["breakers"]["codec"]["holds"] == 1
    assert snap1["resilience"]["breakers"]["codec"]["state"] == "closed"
    degraded = snap1["resilience"]["degraded_batches"]
    assert degraded.get("repair", 0) >= 1
    # verify p99 recovered: the phase-2 tail sits under the objective
    tail = sorted(lats2)
    assert tail[int(0.99 * len(tail))] < OBJECTIVE_S

    # one connected trace with slo.* spans: single trace id, no
    # orphaned parents, the transition spans in episode order, and
    # the degraded repair visible on its device span
    assert {s["trace_id"] for s in spans} == {1}
    span_ids = {s["span_id"] for s in spans}
    assert [s for s in spans
            if s["parent_id"] and not s["remote_parent"]
            and s["parent_id"] not in span_ids] == []
    transitions = [(s["attrs"]["frm"], s["attrs"]["to"])
                   for s in spans if s["name"] == "slo.transition"]
    assert transitions == [("ok", "burning"), ("burning", "warn"),
                           ("warn", "ok")]
    systems = {s["sys"] for s in spans}
    assert {"engine", "device", "slo"} <= systems
    assert any(s["name"] == "device.repair"
               and s["attrs"].get("degraded") for s in spans)
    assert any(s["attrs"].get("tenant") == "auditor" for s in spans)

    # determinism: replaying the same seed reproduces the identical
    # SLO state-transition log, observation count for observation
    # count (the fired_log analog of resilience/faults.py)
    board2, snap2, sheds2, _, _ = _run_drill(b"slo-drill-seed")
    assert board2.transition_log() == log1
    assert sheds2 == sheds1
