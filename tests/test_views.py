"""Renderer smoke tests: every ``tools/*_view.py`` script drives its
real snapshot fixture end-to-end (ISSUE 14 satellite).

The fixtures under ``tests/data/`` are genuine payloads dumped from
deterministic sim runs — ``chain_status.json`` /
``fleet_status.json`` / ``incident_dump.json`` came out of one
``equivocating_validator`` run (seed ``b"fixtures"``, 20 nodes) and
``profile_dump.json`` out of ``gateway_hotspot_pool`` — so a renderer
that drifts from its plane's snapshot shape fails here, not in an
operator's terminal. Each viewer must exit 0, print its section
anchors, and refuse a payload belonging to a different RPC.
"""
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DATA = os.path.join(REPO, "tests", "data")


def _viewer(name):
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _fixture(name):
    return os.path.join(DATA, name)


class TestViewerSmoke:
    def test_chain_view_renders_the_chain_status_fixture(self, capsys):
        mod = _viewer("chain_view")
        assert mod.main([_fixture("chain_status.json")]) == 0
        out = capsys.readouterr().out
        assert "chain plane:" in out
        assert "consensus:" in out
        assert "equivocation evidence" in out
        assert "block-equivocation" in out
        assert "market:" in out
        assert "anomalies:" in out
        assert "transition log" in out

    def test_chain_view_node_table_is_capped(self, capsys):
        mod = _viewer("chain_view")
        assert mod.main([_fixture("chain_status.json"),
                         "--nodes", "3"]) == 0
        out = capsys.readouterr().out
        assert "top 3 of" in out

    def test_fleet_view_renders_the_fleet_status_fixture(self, capsys):
        mod = _viewer("fleet_view")
        assert mod.main([_fixture("fleet_status.json")]) == 0
        out = capsys.readouterr().out
        assert "fleet plane @" in out
        # the chain-plane fold is visible at fleet level: the board
        # carries the finality_lag SLO class next to head
        assert "finality_lag" in out

    def test_profile_view_renders_the_profile_dump_fixture(self,
                                                           capsys):
        mod = _viewer("profile_view")
        assert mod.main([_fixture("profile_dump.json")]) == 0
        out = capsys.readouterr().out
        assert "profile plane:" in out
        assert "pad ledger:" in out
        assert "compile ledger:" in out

    def test_incident_view_renders_the_incident_dump_fixture(self,
                                                             capsys):
        mod = _viewer("incident_view")
        assert mod.main([_fixture("incident_dump.json")]) == 0
        out = capsys.readouterr().out
        assert "incident #" in out
        assert "equivocation" in out
        assert "finality-stall" in out

    def test_remediation_view_renders_the_remediation_status_fixture(
            self, capsys):
        # fixture dumped from one perf_regression_autopilot run
        # (seed b"fixtures", 20 nodes): two perf-pin fire/release
        # episodes live in its journal tail
        mod = _viewer("remediation_view")
        assert mod.main([_fixture("remediation_status.json")]) == 0
        out = capsys.readouterr().out
        assert "remediation plane" in out
        assert "policy table (" in out
        assert "engagements (" in out
        assert "detector evidence (" in out
        assert "action journal (" in out
        assert "perf-pin" in out
        assert "pin-reference" in out

    def test_custody_view_renders_the_custody_status_fixture(
            self, capsys):
        # fixture dumped from one miner_attrition run (seed
        # b"fixtures", 20 nodes): two silent-death -> proactive-repair
        # episodes live in its timelines and transition log
        mod = _viewer("custody_view")
        assert mod.main([_fixture("custody_status.json")]) == 0
        out = capsys.readouterr().out
        assert "custody plane @" in out
        assert "margin histogram (" in out
        assert "at-risk (" in out
        assert "segments (worst" in out
        assert "fragment timelines (" in out
        assert "anomaly transition log (" in out
        # the drill's lineage is visible end-to-end: the silent death
        # surfaced as a restoral, the proactive rebuild as a repair,
        # and the at_risk edge both fired and released
        assert "restoral" in out and "repair(" in out
        assert "at_risk" in out and "ok -> bad" in out \
            and "bad -> ok" in out

    def test_custody_view_segment_table_is_capped(self, capsys):
        mod = _viewer("custody_view")
        assert mod.main([_fixture("custody_status.json"),
                         "--segments", "1", "--timelines", "2"]) == 0
        out = capsys.readouterr().out
        assert "segments (worst 1 of" in out
        assert "fragment timelines (first 2 of" in out

    def test_xor_view_renders_the_schedule_dump_fixture(self, capsys):
        # fixture collected from real engines (strategy="auto" and a
        # forced strategy="xor") after encode + warm_repair +
        # reconstruct traffic, so it carries both cost-model-chosen
        # and forced program attributions
        mod = _viewer("xor_view")
        assert mod.main([_fixture("xor_schedule_dump.json")]) == 0
        out = capsys.readouterr().out
        assert "xor-schedule dump:" in out
        assert "compiled schedules (" in out
        assert "cached programs (" in out
        assert "scratch high-water" in out
        assert "saving" in out
        # chosen-vs-forced strategy per cached program is visible
        assert "[cost-model]" in out and "[forced]" in out
        assert "strategy=auto:" in out and "strategy=xor" in out

    def test_xor_view_collect_roundtrips_a_live_engine(self, capsys):
        import numpy as np

        from cess_tpu.serve import make_engine

        mod = _viewer("xor_view")
        eng = make_engine(2, 1, rs_backend="jax", strategy="xor")
        try:
            eng.encode(np.zeros((2, 64), np.uint8))
            dump = mod.collect(eng)
        finally:
            eng.close()
        assert dump["kind"] == "xor_schedule_dump"
        assert dump["schedules"] and dump["programs"]
        assert all(p["forced"] for p in dump["programs"])

    def test_viewers_reject_foreign_payloads(self):
        # each _load names its RPC in the rejection so an operator
        # who mixes up dump files learns which file they actually got
        for viewer, wrong in (("chain_view", "fleet_status.json"),
                              ("fleet_view", "chain_status.json"),
                              ("profile_view", "chain_status.json"),
                              ("incident_view", "profile_dump.json"),
                              ("remediation_view",
                               "chain_status.json"),
                              ("custody_view",
                               "remediation_status.json"),
                              ("xor_view", "profile_dump.json")):
            mod = _viewer(viewer)
            with pytest.raises(SystemExit):
                mod.main([_fixture(wrong)])
