"""tier-1 gate for the deterministic simulation harness (ISSUE 8).

Four layers of proof:

- the primitives: virtual clock monotonicity, the seeded event
  queue's tie-breaking (same seed => bit-identical fired log), the
  topology generators;
- the world: a seeded sim TWIN of the live partition/heal finality
  test (tests/test_fork.py keeps the threaded original) that
  reproduces the identical finalized prefix on two same-seed replays;
- the scenario library: every scenario replays bit-identically
  (witness = event log + finalized prefixes + SLO transitions + fired
  faults), the full library passes at 100 nodes, and the adversarial
  scenario's audit rounds each form ONE connected trace with the
  corrupt fragment's challenge failure visible as span attributes;
- the invariant checkers: expected-violation fixtures prove each
  tripwire actually fires (a checker that can't fail checks nothing).

The 1000-node world is ``slow``-marked — outside the tier-1 gate.
"""
import time
import types

import pytest

from cess_tpu.obs import trace
from cess_tpu.resilience import faults
from cess_tpu.sim import (SCENARIOS, US, EventQueue, InvariantViolation,
                          SimClock, World, run_checks, run_scenario,
                          topology_edges)


# ---------------------------------------------------------------------------
# virtual clock + seeded event queue
# ---------------------------------------------------------------------------
class TestSimClock:
    def test_monotonic_advance(self):
        c = SimClock()
        c.advance_to_us(5 * US)
        assert c.now_us() == 5 * US and c.now() == 5.0
        with pytest.raises(ValueError):
            c.advance_to_us(4 * US)

    def test_sleep_advances_virtual_time_not_wall_time(self):
        c = SimClock()
        t0 = time.perf_counter()
        c.sleep(3600.0)            # an hour of virtual time
        assert time.perf_counter() - t0 < 0.1
        assert c.now() == 3600.0
        with pytest.raises(ValueError):
            c.sleep(-1.0)

    def test_wait_consumes_timeout_and_returns_false(self):
        c = SimClock(start_us=10)
        assert c.wait(0.5) is False
        assert c.now_us() == 10 + US // 2

    def test_deadline(self):
        c = SimClock()
        c.sleep(1.0)
        assert c.deadline(2.5) == 3.5


class TestEventQueue:
    def test_fires_in_time_order_and_logs(self):
        q = EventQueue(b"s")
        hits = []
        q.push(0.2, "b", lambda: hits.append("b"))
        q.push(0.1, "a", lambda: hits.append("a"))
        q.mark("setup")
        assert q.drain() == 2
        assert hits == ["a", "b"]
        assert q.fired_log() == ((0, "setup"), (US // 10, "a"),
                                 (US // 5, "b"))

    def test_same_time_ties_broken_by_seed_not_insertion(self):
        def order(seed, names):
            q = EventQueue(seed)
            hits = []
            for n in names:
                q.push(0.1, n, lambda n=n: hits.append(n))
            q.drain()
            return hits

        names = [f"e{i}" for i in range(12)]
        a = order(b"seed-A", names)
        # same seed, same pushes => identical order, every run
        assert order(b"seed-A", names) == a
        # a different seed shuffles the same-time ties
        assert order(b"seed-B", names) != a

    def test_cannot_schedule_in_the_past(self):
        q = EventQueue(b"s")
        q.clock.advance_to_us(100)
        with pytest.raises(ValueError):
            q.push_at_us(50, "late", lambda: None)

    def test_run_until_fires_strictly_before_and_advances(self):
        q = EventQueue(b"s")
        hits = []
        q.push_at_us(10, "in", lambda: hits.append("in"))
        q.push_at_us(20, "at", lambda: hits.append("at"))
        assert q.run_until_us(20) == 1
        assert hits == ["in"] and q.clock.now_us() == 20 and len(q) == 1

    def test_drain_guards_against_runaway_self_scheduling(self):
        q = EventQueue(b"s")

        def reschedule():
            q.push(0.001, "again", reschedule)

        q.push(0.001, "again", reschedule)
        with pytest.raises(RuntimeError):
            q.drain(max_events=100)


# ---------------------------------------------------------------------------
# topology generators
# ---------------------------------------------------------------------------
def _connected(n, edges):
    adj = {i: set() for i in range(n)}
    for a, b in edges:
        adj[a].add(b)
        adj[b].add(a)
    seen, todo = {0}, [0]
    while todo:
        for j in adj[todo.pop()]:
            if j not in seen:
                seen.add(j)
                todo.append(j)
    return len(seen) == n


class TestTopology:
    @pytest.mark.parametrize("kind", ["chain", "ring", "random-degree",
                                      "clustered"])
    def test_connected_and_deterministic(self, kind):
        edges = topology_edges(kind, 30, b"topo")
        assert _connected(30, edges)
        assert topology_edges(kind, 30, b"topo") == edges
        assert all(a < b for a, b in edges)     # canonical orientation

    def test_chain_and_ring_shapes(self):
        assert len(topology_edges("chain", 10, b"t")) == 9
        assert len(topology_edges("ring", 10, b"t")) == 10

    def test_random_degree_is_seed_sensitive(self):
        assert topology_edges("random-degree", 30, b"a") != \
            topology_edges("random-degree", 30, b"b")

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError):
            topology_edges("torus", 10, b"t")


# ---------------------------------------------------------------------------
# the sim twin of tests/test_fork.py::test_partition_diverges_then_converges
# (satellite: the live threaded original stays; this is the seeded twin)
# ---------------------------------------------------------------------------
def _partition_twin(seed):
    """The live test's phases on a seeded 5-node world: finalize,
    split 2-vs-3 (neither side reaches 2/3 of 5), diverge, heal,
    converge, finality resumes. Returns (world, fin0)."""
    world = World(seed, n_nodes=5, n_validators=5, topology="ring",
                  loss=0.0)
    world.run_rounds(3)
    fin0 = world.nodes[0].finalized
    assert fin0 > 0, "full validator set must finalize live"

    world.set_partition([[0, 1], [2, 3, 4]])
    world.run_rounds(3)
    head_a = world.nodes[0].chain[-1]
    head_b = world.nodes[2].chain[-1]
    assert head_a.hash() != head_b.hash(), "both sides must author"
    assert all(n.finalized == fin0 for n in world.nodes), \
        "a minority partition must not finalize"

    world.heal()
    run_checks(world, ("heads-converged", "finalized-prefix"))
    assert world.nodes[0].chain[-1].number >= head_b.number

    world.run_rounds(2)
    assert world.nodes[0].finalized > fin0, \
        "finality must resume past the partition"
    return world, fin0


def test_partition_twin_diverges_then_converges():
    _partition_twin(b"fork-twin")


def test_partition_twin_replays_identical_finalized_prefix():
    a, _ = _partition_twin(b"fork-twin")
    b, _ = _partition_twin(b"fork-twin")
    assert a.finalized_prefix() == b.finalized_prefix()
    assert a.queue.fired_log() == b.queue.fired_log()
    # and a different seed is a different world (the witness moves)
    c, _ = _partition_twin(b"fork-twin-2")
    assert c.queue.fired_log() != a.queue.fired_log()


# ---------------------------------------------------------------------------
# the scenario library
# ---------------------------------------------------------------------------
def _assert_scenario_behavior(name, report):
    """The per-scenario property that makes the run meaningful, pinned
    on top of the in-run invariant checks."""
    rt = report.world.nodes[0].runtime
    if name == "gateway_hotspot":
        # the hotspot's whole point: the upload SLO class breached and
        # the transition log (the replay witness) recorded it
        assert any(cls == "upload" and to != "ok"
                   for cls, _frm, to, _n in report.board.transition_log())
    elif name == "gateway_hotspot_pool":
        # ISSUE 10: the run was really served by the device pool —
        # the snapshot rides the report, lane 0 (every dispatch
        # faulted by the seeded plan) completed NOTHING and its
        # breakers tripped, the drained work landed on siblings, and
        # the storage layer still converged (checked in-run)
        snap = report.pool
        assert snap is not None and snap["n_devices"] >= 2
        lanes = {l["device"]: l for l in snap["lanes"]}
        assert lanes[0]["batches"] == 0
        assert sum(l["batches"] for l in snap["lanes"]) >= 1
        assert sum(l["requeues"] for l in snap["lanes"]) >= 1
        assert "open" in lanes[0]["breakers"].values()
        # the lane trips were journaled for the flight recorder
        trips = [e for e in report.recorder.journal_tail("breaker")
                 if e["kind"] == "trip"
                 and e["detail"]["name"].endswith(".d0")]
        assert trips, "no lane-0 breaker trip in the flight journal"
    elif name == "adversarial_audit":
        adversarial = {f"m{j}"
                       for j in report.world.storage.adversarial_miners}
        verdicts = {}
        for e in rt.state.events_of("audit", "VerifyResult"):
            d = dict(e.data)
            verdicts[d["miner"]] = d
        judged = [d for m, d in verdicts.items() if m in adversarial]
        assert judged, "no adversarial miner was ever audited"
        assert all(not d["service"] for d in judged), \
            "a corrupt fragment passed its service audit"
    elif name == "restoral_auction":
        done = [dict(e.data)
                for e in rt.state.events_of("file_bank",
                                            "RestoralComplete")]
        assert len(done) == 1, "the market must pay exactly one rescuer"
        marks = [m for _t, m in report.world.queue.fired_log()
                 if m.startswith("repair_contend:")]
        assert marks and int(marks[0].split(":")[1]) >= 2, \
            "contention needs at least two racing reconstructions"
    elif name == "repair_storm":
        # ISSUE 15: the mass-failure storm really ran in symbol mode —
        # a batch of miners died, their whole fragment custody flooded
        # the market, the rescuers drained it regeneratively (fleet
        # ingress strictly below the k-fragment baseline, zero
        # fallbacks), and the seeded lane trip mid-storm left an
        # incident bundle behind
        miners = report.world.miners
        ingress = sum(m.repair_ingress_bytes for m in miners)
        recovered = sum(m.repair_recovered_bytes for m in miners)
        assert recovered > 0, "the storm never recovered a byte"
        assert ingress < report.world.storage.k * recovered, \
            "repair ingress did not beat the whole-fragment baseline"
        assert sum(m.repair_fallbacks for m in miners) == 0
        assert sum(m.repair_symbol_repairs for m in miners) >= 2
        marks = [m for _t, m in report.world.queue.fired_log()
                 if m.startswith("storm_")]
        kills = [m for m in marks if m.startswith("storm_kill:")]
        assert len(kills) >= 2, "the storm must kill a BATCH of miners"
        assert sum(int(m.rsplit(":", 1)[1]) for m in kills) >= 4, \
            "the kills opened too few restoral orders for a storm"
        assert "breaker-trip" in [b["trigger"]
                                  for b in report.reporter.bundles()], \
            "the mid-storm lane trip left no incident bundle"
        done: dict = {}
        for e in rt.state.events_of("file_bank", "RestoralComplete"):
            d = dict(e.data)
            done.setdefault(d["fragment_hash"], []).append(d["miner"])
        assert done and all(len(v) == 1 for v in done.values()), \
            "the market must pay exactly one winner per fragment"
    elif name == "miner_churn":
        # whether a 0.12-rate drop ordinal is actually crossed depends
        # on seed and world size; what matters for replay is that the
        # lossy-fetch plan is armed with a seeded schedule — its fired
        # log (possibly empty) is already part of the witness
        assert report.plan is not None and report.plan.schedule, \
            "the lossy-fetch fault plan was never armed"
        assert report.uploads_active >= 1
    elif name == "partition_heal":
        assert max(f for f, _ in report.world.finalized_prefix()) > 0
    elif name == "miner_attrition":
        # ISSUE 20: both silent deaths fired the at-risk edge, the
        # proactive rebuild released each one, and no fragment set
        # ever crossed below k (the drill's whole point) — the deep
        # assertions live in tests/test_custody.py
        log = report.custody.detector.transition_log()
        assert all(cls != "lost" for (_s, cls, _k, _o, _t) in log)
        assert sum(1 for (_s, cls, _k, _o, to) in log
                   if cls == "at_risk" and to == "bad") == 2
        assert report.custody.detector.active() == {}
        assert any(kind == "repair" for (_s, kind, _f, _d)
                   in report.custody.ledger.log())
    elif name == "gateway_hotspot_fleet":
        # ISSUE 12: the stripe partition's head lag must be VISIBLE at
        # fleet level — both global views flipped to warn and recovered
        # after the heal, in that order, in the deterministic log...
        log = report.fleet.board.transition_log()
        assert [(v, frm, to) for _c, v, frm, to, _r in log] == [
            ("worst", "ok", "warn"), ("quorum", "ok", "warn"),
            ("worst", "warn", "ok"), ("quorum", "warn", "ok")]
        # ...the MAD detector flagged the lagging nodes as stragglers
        # and each NEW outlier produced exactly one incident bundle
        # (edge-triggered), with the scrape rounds really federated
        triggers = [b["trigger"] for b in report.reporter.bundles()]
        assert triggers.count("fleet-outlier") >= 1
        fed = report.fleet.federator.snapshot()
        assert len(fed["instances"]) == report.world.n
        assert fed["round"] >= 1
    elif name == "perf_regression_autopilot":
        # ISSUE 16: the scripted perf edges were auto-pinned and
        # auto-released by the ACTING remediation plane — every fire
        # applied, every engagement gone by the end, no flapping —
        # and a later incident bundle embeds a non-empty action
        # journal tail
        plane = report.remediation
        assert plane is not None and not plane.dry_run
        journal = plane.journal()
        fired = [(e["policy"], e["key"]) for e in journal
                 if e["event"] == "fire"]
        assert ("perf-pin", "encode") in fired
        assert ("perf-pin", "decode") in fired
        assert all(e["applied"] for e in journal
                   if e["event"] == "fire")
        released = [e["key"] for e in journal
                    if e["event"] == "release"]
        assert "encode" in released and "decode" in released
        assert plane.engagements() == {}
        assert plane.snapshot()["counters"]["flaps"] == 0
        tails = [b["snapshots"]["remediation"]["journal"]
                 for b in report.reporter.bundles()
                 if "remediation" in b["snapshots"]]
        assert tails and any(tails), \
            "no bundle embedded the remediation journal tail"
    elif name == "equivocating_validator":
        # ISSUE 14: the forged twin block is detected as BABE-shaped
        # equivocation evidence (two hashes, one author, one slot) and
        # the stripe stall + heal reorg both fired their anomaly
        # triggers through the incident plane
        triggers = [b["trigger"] for b in report.reporter.bundles()]
        assert "equivocation" in triggers
        assert "finality-stall" in triggers
        ev = report.chainwatch.consensus.evidence()
        assert any(e["kind"] == "block-equivocation"
                   and len(e["hashes"]) == 2 for e in ev)
        # the equivocation bundle embeds the chain-plane snapshot
        bundle = next(b for b in report.reporter.bundles()
                      if b["trigger"] == "equivocation")
        assert "chain" in bundle["snapshots"]
        # the stall is visible at FLEET level: the global quorum
        # finality-lag view flipped to warn and recovered on heal
        fl = [(v, frm, to)
              for c, v, frm, to, _r in report.fleet.board.transition_log()
              if c == "finality_lag"]
        assert ("quorum", "ok", "warn") in fl
        assert ("quorum", "warn", "ok") in fl
        assert fl.index(("quorum", "ok", "warn")) \
            < fl.index(("quorum", "warn", "ok"))


@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_replays_bit_identical(name):
    """ISSUE 8 acceptance: two same-seed runs of every scenario
    produce bit-identical event logs, finalized prefixes and SLO
    transition logs (plus fired faults) — and the run exhibits the
    behavior the scenario exists to exercise."""
    sc = SCENARIOS[name]
    a = run_scenario(sc, b"replay", n_nodes=20)
    b = run_scenario(sc, b"replay", n_nodes=20)
    assert a.witness() == b.witness()
    assert a.rounds_run == sc.rounds
    _assert_scenario_behavior(name, a)


def test_full_library_at_100_nodes():
    """ISSUE 8 acceptance: a 100-node world runs the full scenario
    library inside tier-1 — every in-run and final invariant check
    passes at that scale, under bounded wall-clock."""
    for name in sorted(SCENARIOS):
        report = run_scenario(SCENARIOS[name], b"ci-100", n_nodes=100)
        assert report.rounds_run == SCENARIOS[name].rounds
        assert max(f for f, _ in report.world.finalized_prefix()) > 0, \
            f"{name}: the 100-node world never finalized"


def test_adversarial_scenario_traces_connect():
    """Armed-tracer integration: each scenario round is ONE connected
    trace (single trace id, zero orphaned parents), and the corrupt
    fragment's challenge failure is visible as span attributes — the
    ``offchain.verify`` span carries ``service_ok=False``."""
    tracer = trace.Tracer(capacity=65536)
    sc = SCENARIOS["adversarial_audit"]
    report = run_scenario(sc, b"traced", n_nodes=20, tracer=tracer)
    assert tracer.dropped == 0, "ring wrapped; the analysis needs all spans"
    spans = tracer.finished()
    # single trace id: every span carries the session's
    assert {s["trace_id"] for s in spans} == {tracer.trace_id}
    # one tree per round: the ONLY roots are the per-round sim.round
    # spans, and every other span hangs off a recorded parent — no
    # orphaned parents, no stray trees
    roots = [s for s in spans if s["parent_id"] == 0]
    assert [s["name"] for s in roots] == ["sim.round"] * sc.rounds
    ids = {s["span_id"] for s in spans}
    orphans = [s for s in spans
               if s["parent_id"] != 0 and s["parent_id"] not in ids]
    assert orphans == [], f"orphaned parents: {orphans[:3]}"
    verifies = [s for s in spans if s["name"] == "offchain.verify"]
    assert verifies, "no audit verification was traced"
    adversarial = {f"m{j}" for j in report.world.storage.adversarial_miners}
    bad = [s for s in verifies
           if s["attrs"].get("miner") in adversarial]
    # the FIRST audit round predates the corrupt upload (nothing but
    # clean fillers to audit); once the corrupt fragments are stored,
    # the challenge failure must be visible as span attributes
    assert any(s["attrs"]["service_ok"] is False for s in bad), \
        "the corrupt fragment's challenge failure must be span-visible"
    assert any(s["attrs"].get("service_ok") is True for s in verifies), \
        "honest miners must still pass their audits"


# ---------------------------------------------------------------------------
# invariant tripwires: each checker provably FIRES on a violation
# ---------------------------------------------------------------------------
class TestInvariantTripwires:
    def test_finalized_prefix_fires_on_conflicting_finalized_block(self):
        world = World(b"tamper", n_nodes=5, n_validators=4,
                      topology="ring")
        world.run_rounds(3)
        node = world.nodes[1]
        assert node.finalized >= 1
        run_checks(world, ("finalized-prefix",))        # holds pre-tamper
        # tamper: node 1's finalized block is swapped for a DIFFERENT
        # header (its parent) — two conflicting finalized prefixes
        node.chain[node.finalized] = node.chain[node.finalized - 1]
        with pytest.raises(InvariantViolation, match="finalized-prefix"):
            run_checks(world, ("finalized-prefix",))

    def test_vote_locks_fires_when_horizon_filter_regresses(self):
        world = World(b"locks", n_nodes=5, n_validators=4,
                      topology="ring")
        world.run_rounds(2)
        run_checks(world, ("vote-locks",))              # holds pre-tamper
        # locked_rounds() itself enforces the horizon (finality.py
        # names this checker as its regression tripwire); simulate
        # that filter regressing on one node
        node = world.nodes[0]
        head = node.chain[-1].number
        horizon = node.finality.LOCK_HORIZON
        node.finality.locked_rounds = \
            lambda account, h: [head - horizon - 5]
        with pytest.raises(InvariantViolation, match="vote-locks"):
            run_checks(world, ("vote-locks",))

    def test_audit_soundness_fires_on_corrupt_store_with_passing_verdict(
            self):
        # a minimal duck-typed world: adversarial miner m1 holds bytes
        # that do NOT hash to their fragment id, yet the latest
        # on-chain verdict says its service audit PASSED
        event = types.SimpleNamespace(
            data=(("miner", "m1"), ("service", True), ("idle", True)))
        state = types.SimpleNamespace(
            events_of=lambda mod, name: [event])
        node = types.SimpleNamespace(
            finalized=1, runtime=types.SimpleNamespace(state=state))
        agent = types.SimpleNamespace(store={b"\x11" * 32: b"corrupt"})
        world = types.SimpleNamespace(
            n=1, alive=[True], nodes=[node],
            storage=types.SimpleNamespace(adversarial_miners=(1,)),
            agents={"m1": agent})
        with pytest.raises(InvariantViolation, match="audit-soundness"):
            run_checks(world, ("audit-soundness",))

    def test_strict_false_collects_instead_of_raising(self):
        world = World(b"collect", n_nodes=5, n_validators=4,
                      topology="ring")
        world.run_rounds(3)
        node = world.nodes[1]
        node.chain[node.finalized] = node.chain[node.finalized - 1]
        out = run_checks(world, ("finalized-prefix",), strict=False,
                         context="tampered")
        assert len(out) == 1 and out[0].startswith("[tampered]")


# ---------------------------------------------------------------------------
# satellite: fault-plan delays ride the injected virtual clock
# ---------------------------------------------------------------------------
def test_fault_delay_advances_virtual_clock_not_wall_clock():
    clock = SimClock()
    plan = faults.FaultPlan(
        {"sim.site": {0: faults.FaultSpec(kind="delay", delay_s=7.5)}},
        seed=b"d", clock=clock)
    with faults.armed(plan):
        t0 = time.perf_counter()
        faults.inject("sim.site")
        assert time.perf_counter() - t0 < 0.1
    assert clock.now() == 7.5
    assert plan.fired_log() == (("sim.site", 0, "delay"),)


# ---------------------------------------------------------------------------
# the thousand-node world (outside tier-1)
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_thousand_node_world_partitions_and_heals():
    world = World(b"kilo", n_nodes=1000, n_validators=7,
                  topology="random-degree", loss=0.0)
    world.run_rounds(2)
    run_checks(world, ("finalized-prefix", "vote-locks"))
    fin0 = max(f for f, _ in world.finalized_prefix())
    assert fin0 > 0
    world.stripe_partition(2)
    world.run_rounds(2)
    world.heal()
    run_checks(world, ("heads-converged", "finalized-prefix"))
    world.run_rounds(1)
    assert max(f for f, _ in world.finalized_prefix()) > fin0
