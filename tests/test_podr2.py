"""PoDR2 scheme tests: completeness, soundness smoke, batching, oracle parity."""
import numpy as np
import pytest

import jax.numpy as jnp

from cess_tpu.ops import pfield as pf
from cess_tpu.ops import podr2

FRAG_BYTES = 4 * podr2.BLOCK_BYTES * 4  # 16 blocks, small for tests


def make_fragments(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, (n, FRAG_BYTES), dtype=np.uint8)


def test_tag_shapes_and_determinism():
    key = podr2.Podr2Key.generate(42)
    frags = make_fragments(3)
    ids = jnp.arange(3)
    tags = podr2.tag_fragments(key, ids, frags)
    blocks = podr2.Podr2Params().blocks_for(FRAG_BYTES)
    assert tags.shape == (3, blocks, podr2.LIMBS)
    tags2 = podr2.tag_fragments(key, ids, frags)
    np.testing.assert_array_equal(np.asarray(tags), np.asarray(tags2))
    # different key -> different tags
    key2 = podr2.Podr2Key.generate(43)
    assert not np.array_equal(np.asarray(tags),
                              np.asarray(podr2.tag_fragments(key2, ids, frags)))


def test_completeness_honest_proof_verifies():
    key = podr2.Podr2Key.generate(7)
    frags = make_fragments(4, seed=1)
    ids = jnp.arange(4)
    tags = podr2.tag_fragments(key, ids, frags)
    blocks = tags.shape[1]
    idx, nu = podr2.gen_challenge(b"round-1-randomness", blocks)
    mu, sigma = podr2.prove_batch(jnp.asarray(frags), tags, idx, nu)
    ok = podr2.verify_batch(key, ids, blocks, idx, nu, mu, sigma)
    assert bool(np.all(np.asarray(ok))), "honest proofs must verify"


def test_soundness_corrupted_data_fails():
    key = podr2.Podr2Key.generate(7)
    frags = make_fragments(2, seed=2)
    ids = jnp.arange(2)
    tags = podr2.tag_fragments(key, ids, frags)
    blocks = tags.shape[1]
    idx, nu = podr2.gen_challenge(b"round-2", blocks)
    corrupted = frags.copy()
    # flip one byte inside a challenged block
    target_block = int(np.asarray(idx)[0])
    corrupted[0, target_block * podr2.BLOCK_BYTES] ^= 0xFF
    mu, sigma = podr2.prove_batch(jnp.asarray(corrupted), tags, idx, nu)
    ok = np.asarray(podr2.verify_batch(key, ids, blocks, idx, nu, mu, sigma))
    assert not ok[0], "proof over corrupted data must fail"
    assert ok[1], "untouched fragment still verifies"


def test_soundness_wrong_sigma_and_replay():
    key = podr2.Podr2Key.generate(9)
    frags = make_fragments(1, seed=3)
    ids = jnp.arange(1)
    tags = podr2.tag_fragments(key, ids, frags)
    blocks = tags.shape[1]
    idx, nu = podr2.gen_challenge(b"round-3", blocks)
    mu, sigma = podr2.prove_batch(jnp.asarray(frags), tags, idx, nu)
    bad_sigma = pf.addmod(sigma, jnp.ones_like(sigma))
    ok = podr2.verify_batch(key, ids, blocks, idx, nu, mu, bad_sigma)
    assert not bool(np.asarray(ok)[0])
    # replaying the same proof against a different round's challenge fails
    idx2, nu2 = podr2.gen_challenge(b"round-4", blocks)
    ok2 = podr2.verify_batch(key, ids, blocks, idx2, nu2, mu, sigma)
    assert not bool(np.asarray(ok2)[0])


def test_soundness_each_limb_rejects_independently():
    """The F_p^2 check is two independently-keyed base-field equations;
    a forged sigma satisfying ONE limb but not the other must fail —
    i.e. acceptance requires both, giving the ~p^-2 = 2^-62 bound
    (VERDICT r3 Weak #2 fix)."""
    key = podr2.Podr2Key.generate(21)
    frags = make_fragments(1, seed=9)
    ids = jnp.arange(1)
    tags = podr2.tag_fragments(key, ids, frags)
    blocks = tags.shape[1]
    idx, nu = podr2.gen_challenge(b"limb-round", blocks)
    mu, sigma = podr2.prove_batch(jnp.asarray(frags), tags, idx, nu)
    good = np.asarray(sigma)          # [1, 2]
    for limb in range(podr2.LIMBS):
        forged = good.copy()
        forged[0, limb] = (forged[0, limb] + 1) % pf.P
        ok = podr2.verify_batch(key, ids, blocks, idx, nu, mu,
                                jnp.asarray(forged))
        assert not bool(np.asarray(ok)[0]), \
            f"sigma valid in the other limb but forged in limb {limb} passed"
    ok = podr2.verify_batch(key, ids, blocks, idx, nu, mu, sigma)
    assert bool(np.asarray(ok)[0])


def test_hash_derived_fragment_ids():
    """Hash-pair ids: unique per fragment, full 64-bit fold, batchable."""
    import jax.numpy as jnp

    key = podr2.Podr2Key.generate(3)
    frags = make_fragments(2, seed=8)
    h1, h2 = b"\xaa" * 32, (b"\xbb" * 8 + b"\xaa" * 24)
    ids = jnp.asarray(np.stack([podr2.fragment_id_from_hash(h1),
                                podr2.fragment_id_from_hash(h2)]))
    tags = podr2.tag_fragments(key, ids, frags)
    blocks = tags.shape[1]
    idx, nu = podr2.gen_challenge(b"hash-id-round", blocks)
    mu, sigma = podr2.prove_batch(jnp.asarray(frags), tags, idx, nu)
    ok = podr2.verify_batch(key, ids, blocks, idx, nu, mu, sigma)
    assert bool(np.all(np.asarray(ok)))
    # ids differing only in the HIGH word must produce different tags
    h3 = b"\xaa" * 4 + b"\xcc" * 4 + b"\xaa" * 24
    id3 = jnp.asarray(podr2.fragment_id_from_hash(h3)[None])
    tags3 = podr2.tag_fragments(key, id3, frags[:1])
    assert not np.array_equal(np.asarray(tags[:1]), np.asarray(tags3))


def test_proof_size_within_chain_cap():
    from cess_tpu.constants import SIGMA_MAX

    assert podr2.PROOF_BYTES <= SIGMA_MAX


def test_aggregate_proof_completeness_and_soundness():
    """Cross-fragment aggregation: one (mu, sigma) proves many
    fragments; omitting or corrupting any owed fragment fails."""
    key = podr2.Podr2Key.generate(11)
    frags = make_fragments(5, seed=6)
    hashes = [bytes([i]) * 32 for i in range(5)]
    ids = jnp.asarray(np.stack([podr2.fragment_id_from_hash(h)
                                for h in hashes]))
    tags = podr2.tag_fragments(key, ids, frags)
    blocks = tags.shape[1]
    seed = b"agg-round-randomness"
    idx, nu = podr2.gen_challenge(seed, blocks)
    r = podr2.aggregate_coeffs(seed, ids)
    mu, sigma = podr2.prove_aggregate(jnp.asarray(frags), tags, idx, nu, r)
    assert bool(np.asarray(podr2.verify_aggregate(
        key, ids, blocks, idx, nu, r, mu, sigma)))
    # dropping one owed fragment from the fold fails verification
    mu4, sigma4 = podr2.prove_aggregate(jnp.asarray(frags[:4]), tags[:4],
                                        idx, nu, r[:4])
    assert not bool(np.asarray(podr2.verify_aggregate(
        key, ids, blocks, idx, nu, r, mu4, sigma4)))
    # corrupting a challenged byte of any fragment fails
    bad = frags.copy()
    bad[2, int(np.asarray(idx)[0]) * podr2.BLOCK_BYTES] ^= 1
    mu_b, sigma_b = podr2.prove_aggregate(jnp.asarray(bad), tags, idx, nu, r)
    assert not bool(np.asarray(podr2.verify_aggregate(
        key, ids, blocks, idx, nu, r, mu_b, sigma_b)))


def test_aggregate_proof_wire_size_constant():
    """The codec-encoded aggregated proof stays under SIGMA_MAX no
    matter how many fragments it covers (VERDICT Weak #3 fix)."""
    from cess_tpu import codec
    from cess_tpu.constants import SIGMA_MAX
    from cess_tpu.node.offchain import Proof, build_proof

    key = podr2.Podr2Key.generate(12)
    sizes = []
    for count in (1, 50):
        frags = make_fragments(count, seed=13)
        hashes = [bytes([i % 256]) * 16 + i.to_bytes(16, "little")
                  for i in range(count)]
        ids = jnp.asarray(np.stack([podr2.fragment_id_from_hash(h)
                                    for h in hashes]))
        tags = np.asarray(podr2.tag_fragments(key, ids, frags))
        store = {h: frags[i].tobytes() for i, h in enumerate(hashes)}
        tagmap = {h: tags[i] for i, h in enumerate(hashes)}
        blob = build_proof(b"size-round", sorted(hashes), store, tagmap)
        assert isinstance(blob, bytes) and len(blob) <= SIGMA_MAX
        proof = codec.decode(blob)
        assert isinstance(proof, Proof)
        sizes.append(len(blob))
    assert sizes[0] == sizes[1], "proof size must not grow with F"
    # the authoritative size statement (podr2.PROOF_BYTES + constant
    # codec framing, r06 satellite) matches the real wire bytes
    from cess_tpu.node.offchain import proof_wire_bytes

    assert sizes[0] == proof_wire_bytes()
    assert proof_wire_bytes() - podr2.PROOF_BYTES == 26


def test_tag_oracle_parity_numpy_bigint():
    """Tag math matches a bigint reference implementation exactly."""
    key = podr2.Podr2Key.generate(5)
    frag = make_fragments(1, seed=4)[0]
    tags = np.asarray(podr2.tag_fragment(key, 0, frag))
    alpha = np.asarray(key.alpha)
    m = np.asarray(podr2.fragment_to_elems(jnp.asarray(frag)))
    f = np.asarray(podr2.prf_elems(key.prf_key, 0, m.shape[0]))
    for b in range(m.shape[0]):
        for limb in range(podr2.LIMBS):
            want = (int(f[b, limb])
                    + sum(int(a) * int(x)
                          for a, x in zip(alpha[:, limb], m[b]))) % pf.P
            assert int(tags[b, limb]) == want


def test_audit_backend_gate():
    """The AuditBackend half of the north-star trait pair: cpu default
    and device-pinned variants compute IDENTICAL results (platform
    determinism is a protocol invariant)."""
    import numpy as np

    from cess_tpu.ops import podr2
    from cess_tpu.ops.audit_backend import make_audit_backend

    key = podr2.Podr2Key.generate(3)
    cpu = make_audit_backend(key, "cpu")
    auto = make_audit_backend(key, "auto")
    rng = np.random.default_rng(0)
    frags = rng.integers(0, 256, (4, 2048), dtype=np.uint8)
    ids = np.arange(4, dtype=np.uint32)
    blocks = 2048 // podr2.BLOCK_BYTES
    tags_a = np.asarray(cpu.tag_fragments(ids, frags))
    tags_b = np.asarray(auto.tag_fragments(ids, frags))
    assert np.array_equal(tags_a, tags_b)
    idx, nu = cpu.gen_challenge(b"round", blocks)
    mu, sigma = cpu.prove_batch(frags, tags_a, idx, nu)
    ok = np.asarray(cpu.verify_batch(ids, blocks, idx, nu, mu, sigma))
    assert ok.all()
    # aggregated constant-size proof path
    ids2 = np.stack([ids, np.zeros(4, np.uint32)], axis=1)
    r = cpu.aggregate_coeffs(b"round", ids2)
    mu_t, sg_t = cpu.prove_aggregate(frags, tags_a, idx, nu, r)
    assert bool(np.asarray(cpu.verify_aggregate(
        ids2, blocks, idx, nu, r, mu_t, sg_t)))
    import pytest

    with pytest.raises(ValueError, match="unknown AuditBackend"):
        make_audit_backend(key, "quantum")


@pytest.mark.parametrize("limbs", [2, 3])
def test_limb_count_parametrized(limbs):
    """VERDICT r4 Weak #5 / Next #8: LIMBS is a measured option —
    limbs=2 (~2^-62) is the default, limbs=3 (~2^-93) a config knob.
    Completeness, single-limb forgery rejection, and aggregation all
    hold at either width."""
    params = podr2.Podr2Params(limbs=limbs)
    key = podr2.Podr2Key.generate(11, params)
    assert key.limbs == limbs
    frags = make_fragments(4, seed=9)
    ids = jnp.arange(4)
    tags = podr2.tag_fragments(key, ids, frags)
    blocks = tags.shape[1]
    assert tags.shape == (4, blocks, limbs)
    idx, nu = podr2.gen_challenge(b"limb-round", blocks)
    mu, sigma = podr2.prove_batch(jnp.asarray(frags), tags, idx, nu)
    assert sigma.shape == (4, limbs)
    ok = podr2.verify_batch(key, ids, blocks, idx, nu, mu, sigma)
    assert np.asarray(ok).all()
    # a sigma forged in ONE limb must fail (each limb is an
    # independent MAC equation; all must hold)
    for limb in range(limbs):
        bad = np.asarray(sigma).copy()
        bad[0, limb] = (bad[0, limb] + 1) % pf.P
        ok = podr2.verify_batch(key, ids, blocks, idx, nu, mu,
                                jnp.asarray(bad))
        assert not np.asarray(ok)[0]
        assert np.asarray(ok)[1:].all()
    # aggregated proof round-trips at this width too
    r = podr2.aggregate_coeffs(b"limb-agg", np.stack(
        [np.asarray(ids, np.uint32), np.zeros(4, np.uint32)], axis=1))
    mu_a, sigma_a = podr2.prove_aggregate(jnp.asarray(frags), tags,
                                          idx, nu, r)
    ids2 = np.stack([np.asarray(ids, np.uint32),
                     np.zeros(4, np.uint32)], axis=1)
    assert np.asarray(podr2.verify_aggregate(
        key, ids2, blocks, idx, nu, r, mu_a, sigma_a))


@pytest.mark.parametrize("limbs", [2, 3])
def test_offchain_proof_wire_respects_limb_width(limbs):
    """Review finding (r05, fixed): build_proof hardwired a 2-limb
    sigma and TeeAgent._verify required len == module LIMBS, so a
    limbs=3 deployment failed every honest audit. The wire layer now
    derives the width from the TEE-issued tags / the verifier's key."""
    from cess_tpu import codec
    from cess_tpu.node.offchain import Proof, build_proof

    params = podr2.Podr2Params(limbs=limbs)
    key = podr2.Podr2Key.generate(21, params)
    frags = make_fragments(3, seed=17)
    hashes = [bytes([40 + i]) * 32 for i in range(3)]
    ids = jnp.asarray(np.stack([podr2.fragment_id_from_hash(h)
                                for h in hashes]))
    tags = np.asarray(podr2.tag_fragments(key, ids, frags))
    store = {h: frags[i].tobytes() for i, h in enumerate(hashes)}
    tagmap = {h: tags[i] for i, h in enumerate(hashes)}
    blob = build_proof(b"limb-wire", sorted(hashes), store, tagmap)
    proof = codec.decode(blob)
    assert len(proof.sigma) == limbs

    # drive the TEE-side check exactly as the agent does
    class _FakeTee:
        pass
    from cess_tpu.node.offchain import TeeAgent

    tee = object.__new__(TeeAgent)
    tee.key = key
    tee.blocks = tags.shape[1]
    blocks = tags.shape[1]
    idx, nu = podr2.gen_challenge(b"limb-wire", blocks)
    assert TeeAgent._verify(tee, blob, sorted(hashes), b"limb-wire",
                            idx, nu)
    # empty-owed path: the zero sigma matches the deployment width
    empty = build_proof(b"limb-wire", [], {}, tagmap)
    assert TeeAgent._verify(tee, empty, [], b"limb-wire", idx, nu)
    # a WRONG-width sigma is a failed audit, not an exception
    wrong = codec.encode(Proof(mu=np.zeros((podr2.SECTORS,), np.uint32),
                               sigma=np.zeros((limbs + 1,), np.uint32)))
    assert not TeeAgent._verify(tee, wrong, [], b"limb-wire", idx, nu)
    # the legacy tuple-sigma wire shape is likewise a failed audit
    legacy = codec.encode(Proof(mu=np.zeros((podr2.SECTORS,), np.uint32),
                                sigma=(0,) * limbs))
    assert not TeeAgent._verify(tee, legacy, [], b"limb-wire", idx, nu)


def test_fillerless_miner_proof_width_limbs3():
    """Review-caught (r05): with an EMPTY tags map the proof width must
    come from the caller's key, not the module default — a fillerless
    miner in a limbs=3 deployment otherwise emits a 2-limb zero sigma
    and fails an audit it should pass."""
    from cess_tpu import codec
    from cess_tpu.node.offchain import TeeAgent, build_proof

    params = podr2.Podr2Params(limbs=3)
    key = podr2.Podr2Key.generate(31, params)
    blob = build_proof(b"seed", [], {}, {}, limbs=3)
    proof = codec.decode(blob)
    assert len(proof.sigma) == 3
    tee = object.__new__(TeeAgent)
    tee.key = key
    tee.blocks = 16
    idx, nu = podr2.gen_challenge(b"seed", 16)
    assert TeeAgent._verify(tee, blob, [], b"seed", idx, nu)


def test_tag_fragments_with_traced_key_falls_back():
    """Review-caught: the fused kernel precomputes weights host-side,
    so a key passed as a TRACED jit argument must route to the jnp
    path (identical results) instead of crashing on device_get."""
    import jax

    key = podr2.Podr2Key.generate(44)
    frags = make_fragments(2, seed=23)
    ids = jnp.arange(2)

    @jax.jit
    def tag_with_key(alpha, prf_key, f):
        k = podr2.Podr2Key(alpha=alpha, prf_key=prf_key)
        return podr2.tag_fragments(k, ids, f)

    got = np.asarray(tag_with_key(key.alpha, key.prf_key,
                                  jnp.asarray(frags)))
    want = np.asarray(podr2.tag_fragments(key, ids, frags))
    np.testing.assert_array_equal(got, want)


def test_fused_envelope_is_protocol_geometry_only():
    """Only sectors == 256 (the single Mosaic-validated shape) may
    route into the kernel; everything else takes the jnp path."""
    from cess_tpu.ops import podr2_pallas

    assert podr2_pallas.supported(256, 16)
    assert podr2_pallas.supported(256, 16384)
    for sectors in (64, 96, 128, 255):
        assert not podr2_pallas.supported(sectors, 256)
    # non-256 sectors still tag correctly (jnp route)
    params = podr2.Podr2Params(sectors=128)
    key = podr2.Podr2Key.generate(45, params)
    frag = np.random.default_rng(1).integers(
        0, 256, (1, 8 * 128 * 2), dtype=np.uint8)
    tags = podr2.tag_fragments(key, jnp.arange(1), frag)
    assert tags.shape == (1, 8, 2)


def test_fused_envelope_tracks_block_tile():
    """The block gate follows DEFAULT_BLOCK_TILE (r05 retune 256->128
    shifted membership in both directions — pin it): blocks fuse iff
    they fit one tile or divide it evenly."""
    from cess_tpu.ops import podr2_pallas as pp

    tile = pp.DEFAULT_BLOCK_TILE
    assert pp.supported(256, tile)           # one tile
    assert pp.supported(256, 3 * tile)       # whole grid steps
    assert pp.supported(256, tile // 2)      # sub-tile: tile == blocks
    assert not pp.supported(256, tile + tile // 2)   # ragged grid
    assert not pp.supported(256, 3 * tile // 2)
